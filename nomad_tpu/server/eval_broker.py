"""EvalBroker: leader-side priority queue of evaluations with at-least-once
delivery (reference: nomad/eval_broker.go).

Semantics mirrored: per-scheduler-type priority queues; per-JobID
serialization (one in-flight eval per job, rest held "blocked"); Ack/Nack
with nack-timeout redelivery; delivery-limit overflow into the `_failed`
queue; wait-time deferral; token-gated requeue (a scheduler reblocking its
own eval defers until the outstanding one is Ack'd/Nack'd).

QoS extension (beyond the reference — see README "QoS & SLO serving"):
with a ``QoSConfig``, each ready queue splits into priority TIERS. High
tier drains first; a lower tier's head is promoted one effective tier per
``aging_s`` seconds queued, so saturating high-tier load can delay but
never permanently starve it. The broker also remembers each eval's FIRST
enqueue time across Nack redeliveries and blocked-eval requeues (a
requeued eval must not reset behind fresh arrivals), and converts
(first-enqueue -> ack) wait against the tier deadline into the per-tier
SLO-burn signal admission control sheds on. QoS disabled (the default)
keeps the single-heap path bit-identical to the reference behavior.
"""

from __future__ import annotations

import heapq
import itertools
import random
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Tuple

from nomad_tpu.analysis import guarded_by, requires_lock
from nomad_tpu.qos.tiers import N_TIERS, TIER_NAMES, QoSConfig, qos_enabled
from nomad_tpu.structs import Evaluation, generate_uuid
from nomad_tpu.telemetry import metrics, trace
from nomad_tpu.timerwheel import TimerHandle, wheel

FAILED_QUEUE = "_failed"

# Bound on the federation foreign-region park (see _enqueue_locked): a
# safety-net diagnostic for misdirected writes, evicted oldest-first.
FOREIGN_PARK_CAP = 4096


class NotOutstandingError(Exception):
    pass


class TokenMismatchError(Exception):
    pass


class _PriorityQueue:
    """Max-priority heap of evaluations, FIFO within a priority.

    With an enabled QoS config the queue becomes TIERED: one heap per QoS
    tier, served high-first with aging-based promotion (the head of a
    lower tier gains one effective tier per ``aging_s`` waited; effective
    ties go to the longer-waiting head, so progress is guaranteed even
    under a saturating high-tier storm). Without one — the default — the
    single-heap branch is byte-identical to the pre-QoS ordering."""

    _seq = itertools.count()

    def __init__(self, qos: Optional[QoSConfig] = None) -> None:
        self._heap: List[Tuple[int, int, int, Evaluation]] = []
        self._qos = qos if qos_enabled(qos) else None
        self._tiers: Optional[List[list]] = (
            [[] for _ in range(N_TIERS)] if self._qos is not None else None)
        self.promoted = 0  # pops served from an aged-up tier

    def push(self, ev: Evaluation, enq_time: float = 0.0) -> None:
        if self._qos is None:
            heapq.heappush(
                self._heap,
                (-ev.Priority, ev.CreateIndex, next(self._seq), ev))
            return
        tier = self._qos.tier_of(ev.Priority)
        # enq_time rides the entry (never compared: seq is unique) so the
        # aging check reads the head's ORIGINAL enqueue time — preserved
        # across Nack/blocked requeues by the broker's age map.
        heapq.heappush(
            self._tiers[tier],
            (-ev.Priority, ev.CreateIndex, next(self._seq), ev, enq_time))

    def _best_tier(self, now: float) -> Optional[Tuple[int, tuple]]:
        """(tier, sort key) of the entry pop would serve: minimize
        (effective tier, head enqueue time). Aging promotes a head one
        tier per aging_s waited; equal effective tiers go to the OLDER
        head — the anti-starvation guarantee."""
        best = None
        for tier in range(N_TIERS):
            heap = self._tiers[tier]
            if not heap:
                continue
            enq = heap[0][4] or now
            eff = tier
            if self._qos.aging_s > 0:
                eff = max(0, tier - int((now - enq) / self._qos.aging_s))
            key = (eff, enq)
            if best is None or key < best[1]:
                best = (tier, key)
        return best

    def pop(self, now: Optional[float] = None) -> Optional[Evaluation]:
        if self._qos is None:
            if not self._heap:
                return None
            return heapq.heappop(self._heap)[3]
        best = self._best_tier(now if now is not None else time.monotonic())
        if best is None:
            return None
        tier, (eff, _) = best
        if eff < tier:
            self.promoted += 1
        return heapq.heappop(self._tiers[tier])[3]

    def peek(self, now: Optional[float] = None) -> Optional[Evaluation]:
        if self._qos is None:
            if not self._heap:
                return None
            return self._heap[0][3]
        best = self._best_tier(now if now is not None else time.monotonic())
        if best is None:
            return None
        return self._tiers[best[0]][0][3]

    def peek_key(self, now: float) -> Optional[tuple]:
        """Cross-scheduler comparison key for _scan: lower sorts first."""
        if self._qos is None:
            head = self.peek()
            return None if head is None else (-head.Priority,)
        best = self._best_tier(now)
        if best is None:
            return None
        tier, key = best
        return key + (-self._tiers[tier][0][3].Priority,)

    def tier_depths(self) -> List[int]:
        if self._tiers is None:
            return [len(self._heap), 0, 0]
        return [len(h) for h in self._tiers]

    def __len__(self) -> int:
        if self._qos is None:
            return len(self._heap)
        return sum(len(h) for h in self._tiers)


@dataclass
class _Unack:
    eval: Evaluation
    token: str
    nack_timer: TimerHandle


@dataclass
class BrokerStats:
    TotalReady: int = 0
    TotalUnacked: int = 0
    TotalBlocked: int = 0
    TotalWaiting: int = 0
    ByScheduler: Dict[str, Dict[str, int]] = field(default_factory=dict)


class EvalBroker:
    _concurrency = guarded_by(
        "_lock", "_enabled", "_evals", "_job_evals", "_blocked", "_ready",
        "_unack", "_requeue", "_time_wait", "stats", "_ages",
        "_age_slack", "_slo", "_floors", "_foreign", "_region",
        "_index_source")

    def __init__(self, nack_timeout: float = 60.0, delivery_limit: int = 3,
                 qos: Optional[QoSConfig] = None):
        if nack_timeout < 0:
            raise ValueError("timeout cannot be negative")
        self.nack_timeout = nack_timeout
        self.delivery_limit = delivery_limit
        self.qos = qos
        self._enabled = False
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)

        self._evals: Dict[str, int] = {}          # eval id -> delivery count
        self._job_evals: Dict[str, str] = {}      # job id -> in-flight eval id
        self._blocked: Dict[str, _PriorityQueue] = {}  # job id -> waiting
        self._ready: Dict[str, _PriorityQueue] = {}    # scheduler -> ready
        self._unack: Dict[str, _Unack] = {}
        self._requeue: Dict[str, Evaluation] = {}  # token -> eval
        self._time_wait: Dict[str, TimerHandle] = {}
        # Queue-age memory: eval id -> FIRST enqueue (monotonic). Kept
        # across Nack redeliveries and seeded by blocked-eval requeues
        # (enqueue_all ages=), dropped at Ack/flush — so an aged eval is
        # never reset behind fresh arrivals, and ack-time wait vs the tier
        # deadline feeds the SLO-burn rings below.
        self._ages: Dict[str, float] = {}
        # Warm-failover witness slack per eval: the first-enqueue seed a
        # new leader derives from the replicated timetable errs OLDER by
        # up to one witness interval (good for ordering — the eval keeps
        # its place — but it must not count as deadline burn the eval
        # may never have suffered). ack subtracts it from the SLO-burn
        # wait, turning the burn sample into a LOWER bound of true wait.
        self._age_slack: Dict[str, float] = {}
        # Per-tier ring of recent completions: True = blew its deadline.
        self._slo: List[Deque[bool]] = [
            deque(maxlen=(qos.burn_window if qos_enabled(qos) else 1))
            for _ in range(N_TIERS)]
        # Federation (set_federation; both None/"" when federation is
        # off, leaving every path below bit-identical to pre-federation
        # behavior):
        # - _floors: eval id -> store index at the moment the eval
        #   became READY (its release point). A follower-snapshot worker
        #   only needs its replica caught up to THIS, not to the
        #   leader's global latest index: per-job serialization means no
        #   plan for the eval's job can commit after its release, so a
        #   snapshot at the floor can never double-place — the Omega
        #   soundness bound that lets a shared snapshot serve a whole
        #   storm burst.
        # - _foreign: evals whose Region differs from the local one,
        #   parked instead of served — a region must never dequeue work
        #   it has no nodes for (ingress forwarding makes these orphans
        #   by construction; parking + the counter is the safety net).
        self._index_source = None
        self._region = ""
        self._floors: Dict[str, int] = {}
        self._foreign: Dict[str, Evaluation] = {}
        self.stats = BrokerStats()

    def set_federation(self, region: str, index_source) -> None:
        """Arm federation routing: evals release-stamp a snapshot floor
        from ``index_source`` (the local store's latest_index) and evals
        of a different region park instead of entering the ready queues."""
        with self._lock:
            self._region = region
            self._index_source = index_source

    def _queue(self) -> _PriorityQueue:
        return _PriorityQueue(self.qos)

    # ------------------------------------------------------------- lifecycle
    def enabled(self) -> bool:
        with self._lock:
            return self._enabled

    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            self._enabled = enabled
        if not enabled:
            self.flush()

    def flush(self) -> None:
        """(reference: eval_broker.go Flush)"""
        with self._lock:
            for unack in self._unack.values():
                unack.nack_timer.cancel()
            for timer in self._time_wait.values():
                timer.cancel()
            self._evals.clear()
            self._job_evals.clear()
            self._blocked.clear()
            self._ready.clear()
            self._unack.clear()
            self._requeue.clear()
            self._time_wait.clear()
            self._ages.clear()
            self._age_slack.clear()
            self._floors.clear()
            self._foreign.clear()
            self.stats = BrokerStats()
            self._cond.notify_all()

    # --------------------------------------------------------------- enqueue
    def enqueue(self, ev: Evaluation) -> None:
        with self._lock:
            self._process_enqueue(ev, "")

    def enqueue_all(self, evals: Dict[str, Tuple[Evaluation, str]],
                    ages: Optional[Dict[str, float]] = None) -> None:
        """evals: eval.ID -> (eval, token) for token-gated requeues.
        ``ages`` seeds original first-enqueue times (monotonic) for evals
        re-entering from outside the broker — BlockedEvals carries them so
        a capacity-requeued eval keeps its queue age instead of resetting
        behind fresh arrivals."""
        with self._lock:
            if ages:
                for eid, ts in ages.items():
                    if ts:
                        self._ages.setdefault(eid, ts)
            for ev, token in evals.values():
                self._process_enqueue(ev, token)

    @requires_lock("_lock")
    def _process_enqueue(self, ev: Evaluation, token: str) -> None:
        # Tracing: remember the enqueuing context (one dict write when a
        # trace is active, one truthiness check otherwise) so the worker
        # that dequeues this eval — any thread, any time — can resume it,
        # and stamp the hop on the active span.
        trace.link("eval", ev.ID)
        trace.add_event("broker.enqueue", eval=ev.ID, job=ev.JobID)
        if ev.ID in self._evals:
            if token == "":
                return
            unack = self._unack.get(ev.ID)
            if unack is not None and unack.token == token:
                self._requeue[token] = ev
            return
        if self._enabled:
            self._evals[ev.ID] = 0

        if ev.Wait > 0:
            self._time_wait[ev.ID] = wheel.after(
                ev.Wait / 1e9, self._enqueue_waiting, ev)
            self.stats.TotalWaiting += 1
            return
        self._enqueue_locked(ev, ev.Type)

    def _enqueue_waiting(self, ev: Evaluation) -> None:
        with self._lock:
            self._time_wait.pop(ev.ID, None)
            self.stats.TotalWaiting -= 1
            self._enqueue_locked(ev, ev.Type)

    def _enqueue_locked(self, ev: Evaluation, queue: str) -> None:
        if not self._enabled:
            return
        if self._region and ev.Region and ev.Region != self._region:
            # Region-aware routing: this region has no nodes for the
            # eval's job — park it rather than hand it to a local
            # scheduler that can only fail it into a blocked eval no
            # capacity change here will ever unblock. Ingress forwarding
            # keeps these from existing at all; the park is the safety
            # net for pre-federation data and misdirected writes.
            if ev.ID not in self._foreign:
                self._foreign[ev.ID] = ev
                metrics.incr_counter(("nomad", "federation",
                                      "foreign_evals"))
                # The park is a bounded DIAGNOSTIC, not an authority:
                # nothing ever serves these locally, so a leader fed a
                # steady stream of misdirected writes must not grow the
                # dict (and pin dead Evaluations) for its whole term —
                # evict oldest-first past the cap (insertion-ordered).
                while len(self._foreign) > FOREIGN_PARK_CAP:
                    self._foreign.pop(next(iter(self._foreign)))
            return
        # First-enqueue memory: a Nack redelivery or blocked requeue keeps
        # the original timestamp (setdefault), so tier aging and SLO burn
        # see the eval's TRUE queue age, not its latest re-entry.
        enq_time = self._ages.setdefault(ev.ID, time.monotonic())
        pending = self._job_evals.get(ev.JobID, "")
        if pending == "":
            self._job_evals[ev.JobID] = ev.ID
        elif pending != ev.ID:
            self._blocked.setdefault(ev.JobID, _PriorityQueue()).push(ev)
            self.stats.TotalBlocked += 1
            return
        if self._index_source is not None:
            # Release floor (federation): the store index at the moment
            # this eval enters a ready queue. Overwritten on every
            # re-entry (nack redelivery, blocked promotion) — the newest
            # release point is the sound snapshot bound.
            self._floors[ev.ID] = self._index_source()
        self._ready.setdefault(queue, self._queue()).push(ev, enq_time)
        self.stats.TotalReady += 1
        sched = self.stats.ByScheduler.setdefault(
            queue, {"Ready": 0, "Unacked": 0})
        sched["Ready"] += 1
        self._cond.notify_all()

    # --------------------------------------------------------------- dequeue
    def dequeue(self, schedulers: List[str], timeout: Optional[float] = None
                ) -> Tuple[Optional[Evaluation], str]:
        """Blocking dequeue of the highest-priority eligible eval.

        timeout is in seconds; None or 0 blocks indefinitely (reference
        semantics: Dequeue with timeout 0 has no timeout channel).
        """
        import time as _time

        end = None if not timeout else _time.monotonic() + timeout
        with self._lock:
            while True:
                if not self._enabled:
                    raise RuntimeError("eval broker disabled")
                got = self._scan(schedulers)
                if got is not None:
                    return got
                if end is None:
                    self._cond.wait()
                else:
                    remaining = end - _time.monotonic()
                    if remaining <= 0 or not self._cond.wait(remaining):
                        return None, ""

    def dequeue_window(self, schedulers: List[str], count: int,
                       timeout: Optional[float] = None,
                       fill_timeout: float = 0.0
                       ) -> List[Tuple[Evaluation, str]]:
        """Batch dequeue of up to `count` evals as ONE window under a
        single lock hold (the N-worker fast path). Blocks like dequeue()
        for the first eligible eval, then drains whatever else is already
        ready; with fill_timeout > 0 it lingers that long for stragglers
        (an enqueue burst still landing) before returning a short window.

        Handing the whole window out inside one critical section gives
        each worker a DISJOINT eval set in one lock round — per-eval
        dequeue loops from two workers interleave-steal each other's
        window fills and convoy on the lock, so both end up dispatching
        half-size windows that each still pay a full device round trip."""
        import time as _time

        out: List[Tuple[Evaluation, str]] = []
        if count <= 0:
            return out
        end = None if not timeout else _time.monotonic() + timeout
        with self._lock:
            while True:
                if not self._enabled:
                    raise RuntimeError("eval broker disabled")
                got = self._scan(schedulers)
                if got is not None:
                    out.append(got)
                    break
                if end is None:
                    self._cond.wait()
                else:
                    remaining = end - _time.monotonic()
                    if remaining <= 0 or not self._cond.wait(remaining):
                        return out
            fill_end = _time.monotonic() + fill_timeout
            while len(out) < count:
                if not self._enabled:
                    break
                got = self._scan(schedulers)
                if got is not None:
                    out.append(got)
                    continue
                remaining = fill_end - _time.monotonic()
                if remaining <= 0 or not self._cond.wait(remaining):
                    break
        return out

    @requires_lock("_lock")
    def _scan(self, schedulers: List[str]
              ) -> Optional[Tuple[Evaluation, str]]:
        if qos_enabled(self.qos):
            # Tier-aware scan: pick the scheduler whose head has the best
            # (effective tier, queue age, priority) key — high tier drains
            # first, aged lower tiers promote, ties go to the oldest.
            now = time.monotonic()
            best_key = None
            eligible: List[str] = []
            for sched in schedulers:
                pending = self._ready.get(sched)
                if pending is None:
                    continue
                key = pending.peek_key(now)
                if key is None:
                    continue
                if best_key is None or key < best_key:
                    best_key = key
                    eligible = [sched]
                elif key == best_key:
                    eligible.append(sched)
            if not eligible:
                return None
            return self._dequeue_for_sched(random.choice(eligible), now=now)
        eligible = []
        eligible_priority = 0
        for sched in schedulers:
            pending = self._ready.get(sched)
            if pending is None:
                continue
            ready = pending.peek()
            if ready is None:
                continue
            if not eligible or ready.Priority > eligible_priority:
                eligible = [sched]
                eligible_priority = ready.Priority
            elif ready.Priority == eligible_priority:
                eligible.append(sched)
        if not eligible:
            return None
        return self._dequeue_for_sched(random.choice(eligible))

    @requires_lock("_lock")
    def _dequeue_for_sched(self, sched: str,
                           now: Optional[float] = None
                           ) -> Tuple[Evaluation, str]:
        ev = self._ready[sched].pop(now)
        entry = trace.linked_entry("eval", ev.ID)
        if entry is not None:
            # Synthesized queue-wait span: enqueue-link time -> now.
            trace.record_span(entry[0], "broker.wait", entry[1],
                              eval=ev.ID, scheduler=sched)
        token = generate_uuid()
        timer = wheel.after(self.nack_timeout, self.nack, ev.ID, token)
        self._unack[ev.ID] = _Unack(ev, token, timer)
        self._evals[ev.ID] = self._evals.get(ev.ID, 0) + 1
        self.stats.TotalReady -= 1
        self.stats.TotalUnacked += 1
        by = self.stats.ByScheduler[sched]
        by["Ready"] -= 1
        by["Unacked"] += 1
        return ev, token

    # --------------------------------------------------------------- ack/nack
    def outstanding(self, eval_id: str) -> Optional[str]:
        with self._lock:
            unack = self._unack.get(eval_id)
            return unack.token if unack is not None else None

    def outstanding_reset(self, eval_id: str, token: str) -> None:
        """Reset the nack timer mid-flight (reference: OutstandingReset)."""
        with self._lock:
            unack = self._unack.get(eval_id)
            if unack is None:
                raise NotOutstandingError(eval_id)
            if unack.token != token:
                raise TokenMismatchError(eval_id)
            unack.nack_timer.cancel()
            unack.nack_timer = wheel.after(self.nack_timeout, self.nack,
                                           eval_id, token)

    def outstanding_reset_batch(self, pairs: List[Tuple[str, str]]
                                ) -> set:
        """outstanding_reset for a whole window under ONE lock hold (the
        pipelined worker re-arms every live eval's nack deadline at each
        stage entry; per-eval lock rounds from N workers convoy here and
        let deadlines lapse mid-window — the redelivery storm behind the
        `stale` counter). Returns the set of eval ids no longer
        outstanding to this caller (redelivered / token rotated) instead
        of raising — one stale eval must not abort the sweep for the
        rest of the window."""
        stale: set = set()
        with self._lock:
            for eval_id, token in pairs:
                unack = self._unack.get(eval_id)
                if unack is None or unack.token != token:
                    stale.add(eval_id)
                    continue
                unack.nack_timer.cancel()
                unack.nack_timer = wheel.after(self.nack_timeout, self.nack,
                                               eval_id, token)
        return stale

    def ack(self, eval_id: str, token: str) -> None:
        """(reference: eval_broker.go:461-519)"""
        with self._lock:
            self._ack_locked(eval_id, token)

    def ack_batch(self, pairs: List[Tuple[str, str]]
                  ) -> List[Tuple[str, Exception]]:
        """Ack a whole window's evals under ONE lock hold. Per-eval
        broker races (redelivered mid-window, token rotated) are
        returned, not raised — one lost eval must not abort the acks of
        the rest of the window."""
        failures: List[Tuple[str, Exception]] = []
        with self._lock:
            for eval_id, token in pairs:
                try:
                    self._ack_locked(eval_id, token)
                except (NotOutstandingError, TokenMismatchError) as e:
                    failures.append((eval_id, e))
        return failures

    @requires_lock("_lock")
    def _ack_locked(self, eval_id: str, token: str) -> None:
        requeued = self._requeue.pop(token, None)
        unack = self._unack.get(eval_id)
        if unack is None:
            raise NotOutstandingError(f"Evaluation ID not found: {eval_id}")
        if unack.token != token:
            raise TokenMismatchError(eval_id)
        unack.nack_timer.cancel()
        job_id = unack.eval.JobID
        enq_time = self._ages.pop(eval_id, 0.0)
        slack = self._age_slack.pop(eval_id, 0.0)
        self._floors.pop(eval_id, None)
        if qos_enabled(self.qos) and enq_time:
            # SLO burn: did this eval's whole broker residency (first
            # enqueue -> ack, spanning redeliveries) blow its tier
            # deadline? Admission control sheds lower tiers on this.
            # Minus the failover witness slack: a restored eval's seed
            # errs older by up to one timetable interval, and counting
            # that as burn would saturate the rings (and shed tiers)
            # after every election on a long-lived cluster.
            tier = self.qos.tier_of(unack.eval.Priority)
            waited = time.monotonic() - enq_time - slack
            self._slo[tier].append(waited > self.qos.deadlines_s[tier])

        self.stats.TotalUnacked -= 1
        queue = unack.eval.Type
        if self._evals.get(eval_id, 0) > self.delivery_limit:
            queue = FAILED_QUEUE
        by = self.stats.ByScheduler.get(queue)
        if by is not None:
            by["Unacked"] -= 1

        self._unack.pop(eval_id, None)
        self._evals.pop(eval_id, None)
        self._job_evals.pop(job_id, None)

        blocked = self._blocked.get(job_id)
        if blocked is not None and len(blocked):
            ev = blocked.pop()
            if not len(blocked):
                self._blocked.pop(job_id, None)
            self.stats.TotalBlocked -= 1
            self._enqueue_locked(ev, ev.Type)

        if requeued is not None:
            # Token-gated deferred requeue: the SAME logical eval keeps
            # waiting, so it keeps its original queue age (the pop above
            # closed the SLO measurement for the delivery that just
            # acked; without re-seeding, the requeue would reset the
            # aging clock behind fresh arrivals).
            if enq_time:
                self._ages.setdefault(eval_id, enq_time)
            self._process_enqueue(requeued, "")

    def nack(self, eval_id: str, token: str) -> None:
        """(reference: eval_broker.go:520-560)"""
        with self._lock:
            self._requeue.pop(token, None)
            unack = self._unack.get(eval_id)
            if unack is None:
                raise NotOutstandingError(f"Evaluation ID not found: {eval_id}")
            if unack.token != token:
                raise TokenMismatchError(eval_id)
            unack.nack_timer.cancel()
            self._unack.pop(eval_id, None)
            self.stats.TotalUnacked -= 1
            by = self.stats.ByScheduler.get(unack.eval.Type)
            if by is not None:
                by["Unacked"] -= 1
            if self._evals.get(eval_id, 0) >= self.delivery_limit:
                self._enqueue_locked(unack.eval, FAILED_QUEUE)
            else:
                self._enqueue_locked(unack.eval, unack.eval.Type)

    # ------------------------------------------------- federation accessors
    def release_floor(self, eval_id: str) -> Optional[int]:
        """The store index at which this eval entered the ready queue
        (federation snapshot floor), or None when federation is off —
        callers then fall back to the pre-federation global latest
        index, keeping the disabled path bit-identical."""
        with self._lock:
            return self._floors.get(eval_id)

    def foreign_parked(self) -> List[Evaluation]:
        """Evals parked as foreign-region (never served locally)."""
        with self._lock:
            return list(self._foreign.values())

    def foreign_count(self) -> int:
        """len(foreign_parked()) without copying the dict — the stats
        loop and sched-stats endpoint only want the number."""
        with self._lock:
            return len(self._foreign)

    # ------------------------------------------------------ QoS introspection
    def seed_age_slack(self, slack: Dict[str, float]) -> None:
        """Record per-eval witness slack for restored evals (see
        _age_slack). Seeded once per eval — an existing entry (an eval
        that rode TWO elections accumulates only its first, larger
        slack) is kept."""
        with self._lock:
            for eid, s in slack.items():
                if s > 0.0:
                    self._age_slack.setdefault(eid, s)

    def queue_age(self, eval_id: str) -> Optional[float]:
        """Monotonic timestamp of the eval's FIRST enqueue (preserved
        across Nack redeliveries), or None once acked/unknown."""
        with self._lock:
            return self._ages.get(eval_id)

    def tier_depths(self) -> List[int]:
        """Ready-queue depth per QoS tier, summed over scheduler types
        (all zeros except tier 0 totals when QoS is disabled)."""
        with self._lock:
            out = [0] * N_TIERS
            for sched, pending in self._ready.items():
                if sched == FAILED_QUEUE:
                    continue
                for tier, n in enumerate(pending.tier_depths()):
                    out[tier] += n
            return out

    def tier_promotions(self) -> int:
        """Total aged-up pops (anti-starvation promotions served)."""
        with self._lock:
            return sum(q.promoted for q in self._ready.values())

    def slo_burn(self) -> List[float]:
        """Per-tier fraction of recent completions that blew their tier
        deadline (first enqueue -> ack), over the burn_window ring."""
        with self._lock:
            return [(sum(ring) / len(ring)) if ring else 0.0
                    for ring in self._slo]

    def qos_stats(self) -> Dict[str, Dict[str, float]]:
        """Named-tier snapshot for the sched-stats surface."""
        depths = self.tier_depths()
        burn = self.slo_burn()
        return {
            "TierDepths": dict(zip(TIER_NAMES, depths)),
            "SLOBurn": {name: round(b, 4)
                        for name, b in zip(TIER_NAMES, burn)},
            "Promoted": self.tier_promotions(),
        }
