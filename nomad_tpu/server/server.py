"""Server: composes the FSM, leader singletons, workers, and endpoints
(reference: nomad/server.go, nomad/leader.go, nomad/*_endpoint.go).

One Server instance is a full scheduling control plane. In dev mode it is a
single-node "cluster" (DevRaft backend, always leader); the replicated
deployment swaps the consensus backend and runs the same leadership
enable/restore sequence on failover (reference: leader.go:107-243).
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from nomad_tpu.state.state_store import StateStore
from nomad_tpu.structs import (
    Allocation,
    Evaluation,
    Job,
    JobPlanResponse,
    Node,
    PeriodicLaunch,
    generate_uuid,
)
from nomad_tpu.structs.structs import (
    CoreJobEvalGC,
    CoreJobForceGC,
    CoreJobJobGC,
    CoreJobNodeGC,
    CoreJobPriority,
    EvalStatusBlocked,
    EvalStatusCancelled,
    EvalStatusFailed,
    EvalStatusPending,
    EvalTriggerJobDeregister,
    EvalTriggerJobRegister,
    EvalTriggerNodeUpdate,
    EvalTriggerPeriodicJob,
    JobTypeCore,
    JobTypeService,
    JobTypeSystem,
    NodeStatusDown,
    NodeStatusInit,
    NodeStatusReady,
    valid_node_status,
)
from nomad_tpu.federation import (
    FederationConfig,
    FederationHealth,
    SnapshotSource,
    federation_enabled,
    health_payload,
)
from nomad_tpu.qos import (
    AdmissionController,
    QoSConfig,
    QoSCounters,
    qos_enabled,
)
from nomad_tpu.telemetry import metrics
from nomad_tpu.tensor import TensorIndex
from nomad_tpu.raft import NotLeaderError

from .blocked_evals import BlockedEvals
from .core_sched import CoreScheduler
from .eval_broker import FAILED_QUEUE, EvalBroker
from .fsm import FSM, DevRaft, MessageType
from .heartbeat import HeartbeatTimers
from .periodic import PeriodicDispatch, derive_job, derived_job_id
from .plan_apply import PlanApplier
from .plan_queue import PlanQueue
from .worker import Worker

logger = logging.getLogger("nomad.server")


@dataclass
class ServerConfig:
    """(reference: nomad/config.go)"""

    region: str = "global"
    datacenter: str = "dc1"
    num_schedulers: int = 2
    enabled_schedulers: List[str] = field(
        default_factory=lambda: ["service", "batch", "system"])
    eval_nack_timeout: float = 60.0
    eval_delivery_limit: int = 3
    min_heartbeat_ttl: float = 10.0
    heartbeat_grace: float = 10.0
    max_heartbeats_per_second: float = 50.0
    eval_gc_interval: float = 300.0
    job_gc_interval: float = 300.0
    node_gc_interval: float = 300.0
    eval_gc_threshold: float = 3600.0
    job_gc_threshold: float = 4 * 3600.0
    node_gc_threshold: float = 24 * 3600.0
    failed_eval_unblock_interval: float = 60.0
    # Windowed device-chained scheduling (server/pipelined_worker.py):
    # pure-placement evals batch through one device pipeline per window.
    pipelined_scheduling: bool = True
    scheduler_window: int = 32
    # Placement engine for generic schedulers: "tpu" (device kernels) or
    # "cpu-reference" (the reference's host iterator chain — the benchmark
    # denominator runs THROUGH the same served path with this set).
    scheduler_impl: str = "tpu"
    # Multi-chip serving: "all" shards the node tensor (and every placement
    # kernel) over all local devices with jax.sharding — the SERVED windows
    # run SPMD over the mesh, not just the bare kernels. "" = single device.
    # Device counts that aren't a power of two use the largest pow2 prefix
    # (row padding is pow2, so the node axis must divide evenly).
    scheduler_mesh: str = ""
    # Scheduling workers on follower servers, dequeuing/submitting over
    # leader RPC (reference: workers on every server, worker.go:101-130).
    distributed_workers: bool = True
    # Host fast-path placement for shallow pipelined windows (numpy mirror
    # of the device kernel — see scheduler/kernels.place_batch_host).
    # False forces every fast-path window onto the device chain; the
    # multichip dryrun uses that to prove the SPMD path compiles and runs.
    host_placement: bool = True
    # Columnar service commits: all-placed pipelined windows ride the
    # sweep-batch machinery end to end — one ApplySweepBatch raft entry +
    # one SweepSegment store scatter per plan instead of per-object
    # upserts (README "Columnar state store"). False keeps the per-object
    # commit path (the bench `service_columnar` A/B's object side).
    service_columnar: bool = True
    # Server-side coalescing of Node.UpdateAlloc: concurrent client RPCs
    # within this window share ONE raft entry / future (reference:
    # batchUpdateInterval + batchFuture, node_endpoint.go:530-593). At 10k
    # clients x task churn, one consensus apply per RPC is the
    # consensus-throughput wall. 0 disables (one apply per RPC).
    alloc_update_batch_interval: float = 0.05
    dev_mode: bool = False
    # QoS subsystem (nomad_tpu/qos/): priority-tiered broker lanes,
    # deadline-aware worker windows, admission control at submission
    # ingress, and alloc preemption for high-tier placements. None (the
    # default) keeps the served path bit-identical to pre-QoS behavior;
    # pass QoSConfig(enabled=True, ...) to opt in (README "QoS & SLO
    # serving" documents every knob).
    qos: Optional["QoSConfig"] = None
    # Federated multi-region scheduling (nomad_tpu/federation/):
    # follower-snapshot workers against staleness-bounded shared
    # snapshots, region-local placement with hardened cross-region
    # forwarding at ingress, region-aware broker routing, and the
    # per-region QoS health view. None (the default) keeps the served
    # path bit-identical to pre-federation behavior; pass
    # FederationConfig(enabled=True, ...) to opt in (README
    # "Federation" documents every knob).
    federation: Optional["FederationConfig"] = None
    # Cluster event stream (nomad_tpu/events/): ring slots retained for
    # catch-up, in applied-entry batches. 0 disables the broker entirely
    # — the FSM apply path then pays one attribute check and placements
    # are bit-identical to pre-events behavior (README "Event stream").
    event_buffer_size: int = 4096
    # Cross-replica state-digest verification (analysis/replica_digest.py):
    # every apply folds its effect into a rolling chain; every this-many
    # applies the chain value becomes a checkpoint the leader piggybacks
    # on AppendEntries for followers to verify (README "Replica
    # determinism"). 0 disables — the apply path then pays one attribute
    # check and replication carries no digest fields.
    digest_interval: int = 64
    # Replicated deployment (reference: nomad/config.go RaftConfig +
    # BootstrapExpect). node_id doubles as the raft/transport address.
    node_id: str = ""
    bootstrap_expect: int = 1


class _BatchAllocUpdate:
    """Shared future for one coalesced window of client alloc updates
    (reference: structs.BatchFuture, node_endpoint.go:530-545)."""

    __slots__ = ("event", "index", "error")

    def __init__(self) -> None:
        self.event = threading.Event()
        self.index = 0
        self.error: Optional[Exception] = None


class Server:
    def __init__(self, config: Optional[ServerConfig] = None,
                 transport=None, log_store=None,
                 peers: Optional[List[str]] = None, raft_config=None):
        """With no transport this is a dev-mode single-node control plane
        (DevRaft, reference: server.go:612-616 DevMode). With a transport it
        boots a replicated server: a RaftNode over the given peers whose
        leadership transitions drive establish/revoke (reference:
        monitorLeadership, nomad/leader.go:24-56)."""
        self.config = config or ServerConfig()
        self.fsm = FSM()
        if self.config.event_buffer_size > 0:
            from nomad_tpu.events import EventBroker

            # Region-tagged under federation only ("" otherwise — the
            # same home-region contract evaluations follow, _ev_region).
            self.fsm.events = EventBroker(
                size=self.config.event_buffer_size,
                region=(self.config.region
                        if federation_enabled(self.config.federation)
                        else ""))
        if self.config.digest_interval > 0:
            from nomad_tpu.analysis.replica_digest import ReplicaDigest

            # Folds on EVERY replica (dev mode included — sched-stats
            # shows the chain); the checkpoint exchange only happens
            # under the replicated backend.
            self.fsm.digest = ReplicaDigest(
                interval=self.config.digest_interval)
        self._leadership_lock = threading.Lock()
        if transport is not None:
            from nomad_tpu.raft import RaftBackend
            self.raft = RaftBackend(
                node_id=self.config.node_id or generate_uuid(),
                fsm=self.fsm,
                peers=peers or [],
                transport=transport,
                log_store=log_store,
                config=raft_config,
                on_leader_change=self._leadership_transition,
                # With explicit peers the node may elect immediately; with
                # none it boots dormant until gossip bootstrap-expect fires
                # or an existing cluster admits it (server/membership.py).
                electable=bool(peers))
        else:
            self.raft = DevRaft(self.fsm)
        self.state: StateStore = self.fsm.state
        self.tindex = TensorIndex.attach(self.state)
        # host_placement=False must force the DEVICE kernel everywhere —
        # including the per-eval slow path's select_batch — so the
        # multichip dry run proves the SPMD path end to end.
        self.tindex.allow_host_select = self.config.host_placement
        if self.config.scheduler_mesh:
            if self.config.scheduler_mesh != "all":
                raise ValueError(
                    f"scheduler_mesh must be \"all\" or \"\", got "
                    f"{self.config.scheduler_mesh!r}")
            from nomad_tpu.parallel import pow2_prefix, scheduling_mesh

            import jax

            self.tindex.nt.set_mesh(
                scheduling_mesh(pow2_prefix(jax.devices())))

        # QoS: tiered broker lanes + admission at ingress + preemption in
        # the scheduler, all sharing one config and one counter block.
        self.qos = self.config.qos or QoSConfig()
        self.qos_counters = QoSCounters()
        self.eval_broker = EvalBroker(self.config.eval_nack_timeout,
                                      self.config.eval_delivery_limit,
                                      qos=self.qos)
        # Federation (nomad_tpu/federation/): the shared staleness-
        # bounded snapshot source workers schedule from, the per-region
        # QoS health view, and the broker's region routing — all None /
        # disarmed when federation is off, keeping every consumer's
        # path bit-identical to pre-federation behavior.
        self.fed = self.config.federation
        if federation_enabled(self.fed):
            # follower_snapshots=False is the bench's all-on-leader
            # baseline arm: routing/forwarding/health identical, but
            # workers pin fresh live-store watermarks per window.
            self.fed_source = (SnapshotSource(self.state, self.fed)
                               if self.fed.follower_snapshots else None)
            self.fed_health = FederationHealth(self.fed)
            self.eval_broker.set_federation(self.config.region,
                                            self.state.latest_index)
        else:
            self.fed_source = None
            self.fed_health = None
        # Cross-region health poll hook: ClusterServer.enable_gossip
        # points this at the membership plane's poll (needs the WAN
        # pool); the leader loop drives it.
        self.fed_poll = None
        self.admission = AdmissionController(self.qos, self.eval_broker,
                                             self.qos_counters,
                                             fed=self.fed,
                                             fed_health=self.fed_health)
        self.blocked_evals = BlockedEvals(self.eval_broker)
        self.plan_queue = PlanQueue()
        self.plan_applier = PlanApplier(self.plan_queue, self.raft,
                                        self.eval_broker, tindex=self.tindex,
                                        qos_counters=self.qos_counters,
                                        fed=self.fed)
        # Owned by the FSM so it is persisted in snapshots and rebuilt from
        # apply on every replica (survives leader failover).
        self.timetable = self.fsm.timetable
        self.core_sched = CoreScheduler(
            self.raft, self.timetable,
            eval_gc_threshold=self.config.eval_gc_threshold,
            job_gc_threshold=self.config.job_gc_threshold,
            node_gc_threshold=self.config.node_gc_threshold)
        self.heartbeats = HeartbeatTimers(
            min_ttl=self.config.min_heartbeat_ttl,
            grace=self.config.heartbeat_grace,
            max_per_second=self.config.max_heartbeats_per_second,
            on_expire=self._invalidate_heartbeat)
        self.periodic = PeriodicDispatch(self._dispatch_periodic)
        self.workers: List[Worker] = []
        self.remote_workers: List[Worker] = []
        # Workers stopped on leadership loss keep running until their
        # current eval finishes; shutdown() must join them (their threads
        # dispatch XLA work — abandoning one at interpreter exit aborts
        # the process).
        self._retired_workers: List[Worker] = []
        self._leader = False
        self._shutdown = threading.Event()
        self._reapers: List[threading.Thread] = []
        # Coalesced Node.UpdateAlloc window (node_endpoint.go:530-593).
        self._alloc_update_cond = threading.Condition()
        self._alloc_update_pending: List[Allocation] = []
        self._alloc_update_future: Optional[_BatchAllocUpdate] = None
        self._alloc_flush_thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ leadership
    def start(self) -> None:
        """Start the consensus backend (replicated mode). Dev mode needs no
        start; callers invoke establish_leadership directly."""
        if hasattr(self.raft, "start"):
            self.raft.start()

    def is_leader(self) -> bool:
        if hasattr(self.raft, "is_leader"):
            return self.raft.is_leader()
        return self._leader

    def start_remote_workers(self, pool) -> None:
        """Run scheduling workers on this server regardless of leadership,
        resolving broker/plan operations over leader RPC (reference: workers
        on every server, nomad/worker.go:101-130). The reference's leader
        pauses 3/4 of its own workers to reserve capacity for plan
        application (leader.go:110-116); here the leader pauses ALL routed
        workers and runs its dedicated device-pipelined workers instead —
        same intent, shaped for the TPU fast path. `_core` GC evals are
        excluded: the core scheduler writes through raft directly, which is
        leader-local by construction."""
        from .worker import RemoteBackend
        for i in range(self.config.num_schedulers):
            backend = RemoteBackend(pool, self.raft,
                                    local_addr=self.config.node_id)
            w = Worker(self.raft, None, None, None, self.tindex,
                       schedulers=list(self.config.enabled_schedulers),
                       backend=backend)
            w.qos = self.qos
            w.qos_counters = self.qos_counters
            # Follower-snapshot scheduling: routed workers place against
            # the LOCAL replica through the shared staleness-bounded
            # source (their dequeue RPC already returns the release
            # floor, so the replica only waits to the floor).
            w.fed_source = self.fed_source
            # Register under the leadership lock: an election landing here
            # must either see the worker (establish pauses it) or have
            # already set _leader (we pause it ourselves).
            with self._leadership_lock:
                w.set_pause(self._leader or self.is_leader())
                self.remote_workers.append(w)
            w.start(name=f"remote-worker-{i}")

    def _leadership_transition(self, is_leader: bool) -> None:
        """(reference: monitorLeadership consuming leaderCh,
        nomad/leader.go:24-56)"""
        with self._leadership_lock:
            if self._shutdown.is_set():
                # A True event racing shutdown must not start fresh worker
                # / plan-applier threads after shutdown's join loop ran.
                return
            if is_leader and not self._leader:
                # Barrier: apply everything from prior terms before
                # rehydrating leader state (reference: leader.go:60-68).
                try:
                    self.raft.barrier()
                except Exception:
                    logger.exception("leadership barrier failed")
                    return
                self.establish_leadership()
            elif not is_leader and self._leader:
                self.revoke_leadership()

    def establish_leadership(self) -> None:
        """(reference: leader.go:107-170)

        WARM failover: everything a leader term needs is re-seeded from
        the replicated store instead of starting cold — broker queue ages
        from the FSM timetable (_restore_evals), node-tensor usage
        resynced against committed allocs, and the device arrays + the
        refresh programs the ChainArbiter's first window would otherwise
        compile mid-serving (README "Failover & streaming snapshots").
        The whole establishment is timed as nomad.server.failover.*."""
        t_establish = time.monotonic()
        self._leader = True
        # The leader's scheduling capacity is its pipelined workers; routed
        # workers stand down first (reference intent: leader.go:110-116).
        for w in self.remote_workers:
            w.set_pause(True)
        self.plan_queue.set_enabled(True)
        self.plan_applier.start()
        self.eval_broker.set_enabled(True)
        self.blocked_evals.set_enabled(True)
        self.periodic.set_enabled(True)

        # FSM hooks only matter on the leader.
        self.fsm.on_eval_update = self._on_eval_update
        self.fsm.on_node_ready = self._on_node_ready
        self.fsm.on_alloc_terminal = self._on_alloc_terminal
        self.fsm.on_job_upsert = self.periodic.add
        self.fsm.on_job_delete = self.periodic.remove

        if self.fed_source is not None:
            # A new term may follow a snapshot restore that swapped the
            # store's tables wholesale; drop the cached snapshot so the
            # first window observes the restored world.
            self.fed_source.invalidate()
        self._restore_evals()
        self._restore_periodic_dispatcher()
        self._warm_failover_state()

        # Workers. Pipelined workers share ONE chain arbiter per
        # leadership term: their windows interleave on a single coherent
        # device usage chain (worker B's kernels see worker A's in-flight
        # placements) instead of each keeping a private chain that the
        # plan applier then bounces. Fresh per term — a prior term's
        # taint/pending state must not leak into the new leader's chain
        # — but WARM: _warm_failover_state resynced the node tensor and
        # pre-uploaded its device arrays, so the arbiter's first acquire
        # chains on committed usage that is already device-resident.
        from nomad_tpu.tensor.node_table import ChainArbiter
        arbiter = ChainArbiter(self.tindex.nt)
        schedulers = list(self.config.enabled_schedulers) + [JobTypeCore]
        for i in range(self.config.num_schedulers):
            # The pipelined fast path IS the TPU engine; a non-default
            # scheduler_impl (cpu-reference denominator) must run every eval
            # through the per-eval scheduler or the knob would silently
            # select the wrong engine.
            if (self.config.pipelined_scheduling
                    and self.config.scheduler_impl == "tpu"):
                from .pipelined_worker import PipelinedWorker
                w = PipelinedWorker(self.raft, self.eval_broker,
                                    self.plan_queue, self.blocked_evals,
                                    self.tindex, schedulers,
                                    window=self.config.scheduler_window,
                                    host_placement=self.config
                                    .host_placement,
                                    chain_arbiter=arbiter,
                                    service_columnar=self.config
                                    .service_columnar)
            else:
                w = Worker(self.raft, self.eval_broker, self.plan_queue,
                           self.blocked_evals, self.tindex, schedulers)
            w.scheduler_impl = self.config.scheduler_impl
            w.core_scheduler = self.core_sched
            w.qos = self.qos
            w.qos_counters = self.qos_counters
            w.fed_source = self.fed_source
            w.start(name=f"worker-{i}")
            self.workers.append(w)

        # Reapers + GC tickers (reference: leader.go:246-332)
        self._start_loop(self._reap_failed_evaluations, 0.5)
        self._start_loop(self._reap_dup_blocked_evaluations, 0.5)
        self._start_loop(lambda: self._schedule_core_gc(CoreJobEvalGC),
                         self.config.eval_gc_interval)
        self._start_loop(lambda: self._schedule_core_gc(CoreJobJobGC),
                         self.config.job_gc_interval)
        self._start_loop(lambda: self._schedule_core_gc(CoreJobNodeGC),
                         self.config.node_gc_interval)
        self._start_loop(self.blocked_evals.unblock_failed,
                         self.config.failed_eval_unblock_interval)
        if federation_enabled(self.fed):
            self._start_loop(self._poll_federation_health,
                             self.fed.health_interval_s)
        self._start_loop(self._emit_stats, 1.0)
        metrics.measure_since(("nomad", "server", "failover",
                               "establish_ms"), t_establish)

    def _poll_federation_health(self) -> None:
        """One leader-loop round of the federation health view: the
        local region's entry straight from its own broker (no RPC), plus
        every other region via the membership plane's Federation.Health
        poll (fed_poll hook, wired by ClusterServer.enable_gossip)."""
        if self.fed_health is None:
            return
        self.fed_health.update(self.config.region, health_payload(self))
        if self.fed_poll is not None:
            self.fed_poll()

    def admit_forward(self, region: str, priority: int) -> None:
        """Edge-shed gate for a cross-region forward (see
        AdmissionController.admit_forward); raises QoSBackpressureError
        before the WAN hop when the home region's cached health says the
        tier would be shed there anyway."""
        self.admission.admit_forward(region, priority)

    def _warm_failover_state(self) -> None:
        """Re-seed device-side leader state from the replicated store.

        A follower's tensor was fed incrementally by FSM applies (and
        rebuilt by TensorIndex.on_restore after a chunked snapshot
        install), but its usage can drift across an election window and
        its device arrays were never uploaded — a cold first window pays
        the full-table transfer plus the dirty-row refresh compiles in
        the middle of the recovery storm. Resync + pre-warm here, while
        the brand-new term has no windows in flight. Dev mode skips the
        device warm-up (every unit-test Server would pay XLA compiles);
        the resync is cheap and always runs."""
        fixed = self.tindex.resync_usage(self.state)
        metrics.incr_counter(("nomad", "server", "failover",
                              "usage_resync_rows"), fixed)
        if fixed:
            logger.warning("warm failover: corrected %d drifted node-tensor "
                           "rows from the replicated store", fixed)
        if hasattr(self.raft, "node"):  # replicated mode only
            t0 = time.monotonic()
            try:
                self.tindex.nt.warm_device()
            except Exception:
                logger.exception("warm failover: device warm-up failed; "
                                 "first window will pay the upload")
            metrics.measure_since(("nomad", "server", "failover",
                                   "warm_ms"), t0)

    def revoke_leadership(self) -> None:
        """(reference: leader.go:390-431)"""
        self._leader = False
        self.eval_broker.set_enabled(False)
        self.blocked_evals.set_enabled(False)
        self.plan_applier.stop()
        self.plan_queue.set_enabled(False)
        self.periodic.set_enabled(False)
        self.heartbeats.clear_all()
        for w in self.workers:
            w.stop()  # non-blocking: may run on the raft notify thread
        self._retired_workers = [w for w in self._retired_workers
                                 if w._thread and w._thread.is_alive()]
        self._retired_workers.extend(self.workers)
        self.workers = []
        self.fsm.on_eval_update = None
        self.fsm.on_node_ready = None
        self.fsm.on_alloc_terminal = None
        self.fsm.on_job_upsert = None
        self.fsm.on_job_delete = None
        for w in self.remote_workers:
            w.set_pause(False)

    def shutdown(self) -> None:
        self._shutdown.set()
        # Close the event broker first: streaming HTTP handlers block in
        # Subscription.next() between heartbeats, and a closed sub wakes
        # them immediately instead of waiting out the heartbeat interval.
        if self.fsm.events is not None:
            self.fsm.events.close()
        # Serialize against in-flight leadership transitions on the raft
        # notify thread: both paths mutate workers/_retired_workers, and an
        # unserialized pair of revoke_leadership runs can drop a worker
        # from the retired list (never joined → XLA-teardown abort). The
        # _shutdown check in _leadership_transition keeps later True
        # events from starting fresh threads once we release the lock.
        with self._leadership_lock:
            remote = self.remote_workers
            for w in remote:
                w.stop()
            self.remote_workers = []
            self.revoke_leadership()
        if hasattr(self.raft, "shutdown"):
            self.raft.shutdown()
        # Wake the alloc-update flusher so it drains any open window (the
        # waiters get NotLeaderError from the dead raft) and exits.
        with self._alloc_update_cond:
            self._alloc_update_cond.notify_all()
        if self._alloc_flush_thread is not None:
            self._alloc_flush_thread.join(timeout=30.0)
        # Join every thread that can touch JAX before returning: a daemon
        # thread still inside an XLA dispatch races CPython/XLA teardown
        # and aborts the interpreter (round-3 regression: BENCH rc=134,
        # MULTICHIP ok:false). Workers were signalled above, so joins
        # overlap their wind-down; the deadline bounds a wedged thread.
        deadline = time.monotonic() + 60.0
        for w in remote + self._retired_workers:
            w.join(timeout=max(0.1, deadline - time.monotonic()))
        self._retired_workers = []
        self.plan_applier.join(timeout=max(0.1, deadline - time.monotonic()))
        for t in self._reapers:
            if t.is_alive() and t is not threading.current_thread():
                t.join(timeout=max(0.1, deadline - time.monotonic()))
        self._reapers = []

    def _emit_stats(self) -> None:
        """Leader-side operational gauges, emitted every second
        (reference: EmitStats loops — eval_broker.go:650-662,
        blocked_evals.go:440-441, plan_queue EmitStats, heartbeat count
        gauge in leader.go)."""
        bs = self.eval_broker.stats
        metrics.set_gauge(("nomad", "broker", "total_ready"), bs.TotalReady)
        metrics.set_gauge(("nomad", "broker", "total_unacked"),
                          bs.TotalUnacked)
        metrics.set_gauge(("nomad", "broker", "total_blocked"),
                          bs.TotalBlocked)
        metrics.set_gauge(("nomad", "broker", "total_waiting"),
                          bs.TotalWaiting)
        for sched, ss in list(bs.ByScheduler.items()):
            metrics.set_gauge(("nomad", "broker", sched, "ready"),
                              ss.get("Ready", 0))
            metrics.set_gauge(("nomad", "broker", sched, "unacked"),
                              ss.get("Unacked", 0))
        blocked = self.blocked_evals.stats
        metrics.set_gauge(("nomad", "blocked_evals", "total_blocked"),
                          blocked.TotalBlocked)
        metrics.set_gauge(("nomad", "blocked_evals", "total_escaped"),
                          blocked.TotalEscaped)
        metrics.set_gauge(("nomad", "plan", "queue_depth"),
                          self.plan_queue.stats["Depth"])
        metrics.set_gauge(("nomad", "heartbeat", "active"),
                          len(self.heartbeats))
        if qos_enabled(self.qos):
            from nomad_tpu.qos import TIER_NAMES

            depths = self.eval_broker.tier_depths()
            burn = self.eval_broker.slo_burn()
            for tier, name in enumerate(TIER_NAMES):
                metrics.set_gauge(("nomad", "qos", "tier", name, "ready"),
                                  depths[tier])
                metrics.set_gauge(("nomad", "qos", "tier", name, "burn"),
                                  burn[tier])
            metrics.set_gauge(("nomad", "qos", "tier", "promoted"),
                              self.eval_broker.tier_promotions())
        if federation_enabled(self.fed):
            metrics.set_gauge(("nomad", "federation", "foreign_parked"),
                              self.eval_broker.foreign_count())

    def _start_loop(self, fn, interval: float) -> None:
        def loop():
            while not self._shutdown.is_set():
                if self._shutdown.wait(interval):
                    return
                if not self._leader:
                    return
                try:
                    fn()
                except Exception:
                    logger.exception("leader loop task failed")

        t = threading.Thread(target=loop, daemon=True,
                             name=f"leader-loop-{fn.__name__}")
        t.start()
        self._reapers.append(t)

    # ------------------------------------------------------------- FSM hooks
    def _on_eval_update(self, ev: Evaluation) -> None:
        """Route evals to broker or blocked tracker (reference: fsm.go:320-344)."""
        if ev.should_enqueue():
            self.eval_broker.enqueue(ev)
        elif ev.should_block():
            token = self.eval_broker.outstanding(ev.ID) or ""
            if token:
                self.blocked_evals.reblock(ev, token)
            else:
                self.blocked_evals.block(ev)

    def _on_node_ready(self, node: Node) -> None:
        self.blocked_evals.unblock(node.ComputedClass, node.ModifyIndex)

    def _on_alloc_terminal(self, alloc: Allocation) -> None:
        node = self.state.node_by_id(alloc.NodeID)
        if node is not None:
            self.blocked_evals.unblock(node.ComputedClass, alloc.ModifyIndex)

    # ------------------------------------------------------- leader restores
    def _restore_evals(self) -> None:
        """Re-hydrate broker + blocked from replicated state
        (reference: leader.go:176-202) — WARM: each eval's first-enqueue
        age re-seeds from the FSM timetable's witness of its CreateIndex
        (the replicated index->wallclock map), so QoS tier aging and SLO
        burn keep measuring from the ORIGINAL enqueue across an election
        instead of resetting every queued eval to age zero. The timetable
        witnesses at a bounded granularity, so the seed errs OLDER —
        conservative for ORDERING (the eval can only promote sooner,
        never lose its place behind fresh arrivals) — and the witness
        spread rides along as SLO-burn slack so the same error cannot
        count as deadline burn the eval may never have suffered (one
        300s-granularity interval would otherwise saturate every tier's
        burn ring after each election and trip admission shedding)."""
        now_wall = time.time()
        now_mono = time.monotonic()

        def age_seed(ev: Evaluation) -> Tuple[float, float]:
            """(monotonic first-enqueue seed, witness slack seconds)."""
            witnessed = self.timetable.nearest_time(ev.CreateIndex)
            if not witnessed:
                return 0.0, 0.0
            upper = self.timetable.nearest_time_after(ev.CreateIndex) \
                or now_wall
            # Map the replicated wall anchor onto this process's
            # monotonic clock (the broker's _ages domain).
            seed = now_mono - max(0.0, now_wall - witnessed)
            slack = max(0.0, min(upper, now_wall) - witnessed)
            return seed, slack

        ready: Dict[str, Tuple[Evaluation, str]] = {}
        ages: Dict[str, float] = {}
        slacks: Dict[str, float] = {}
        blocked = 0
        for ev in self.state.evals():
            if ev.should_enqueue():
                ready[ev.ID] = (ev, "")
                seed, slack = age_seed(ev)
                if seed:
                    ages[ev.ID] = seed
                    slacks[ev.ID] = slack
            elif ev.should_block():
                seed, slack = age_seed(ev)
                self.blocked_evals.block(ev, age=seed)
                if slack:
                    slacks[ev.ID] = slack
                blocked += 1
        if ready:
            self.eval_broker.enqueue_all(ready, ages=ages)
        if slacks:
            self.eval_broker.seed_age_slack(slacks)
        metrics.incr_counter(("nomad", "server", "failover",
                              "evals_restored"), len(ready))
        metrics.incr_counter(("nomad", "server", "failover",
                              "blocked_restored"), blocked)

    def _restore_periodic_dispatcher(self) -> None:
        """(reference: leader.go:204-243)"""
        now = time.time()
        for job in self.state.jobs_by_periodic(True):
            self.periodic.add(job)
            launch = self.state.periodic_launch_by_id(job.ID)
            last = launch.Launch if launch is not None else 0.0
            nxt = job.Periodic.next(last)
            if last and nxt < now:
                # Catch up a missed launch.
                try:
                    self._dispatch_periodic(job, nxt)
                except Exception:
                    logger.exception("periodic: catch-up launch failed")

    # ------------------------------------------------------- periodic launch
    def _dispatch_periodic(self, job: Job, launch_time: float) -> None:
        """Derive and register the child job, deduping by launch table."""
        launch = self.state.periodic_launch_by_id(job.ID)
        if launch is not None and launch.Launch >= launch_time:
            return  # already launched (failover dedupe)
        if job.Periodic is not None and job.Periodic.ProhibitOverlap:
            # Skip if any previous child is still non-terminal.
            children = self.state.jobs_by_id_prefix(job.ID + "/periodic-")
            for child in children:
                if child.Status != "dead":
                    logger.debug("periodic: skipping %s, overlap prohibited",
                                 job.ID)
                    return
        child = derive_job(job, launch_time)
        self.raft.apply(MessageType.PeriodicLaunchType, {
            "Launch": PeriodicLaunch(ID=job.ID, Launch=launch_time)})
        self.job_register(child, trigger=EvalTriggerPeriodicJob)

    # --------------------------------------------------------- reaper loops
    def _reap_failed_evaluations(self) -> None:
        """Mark over-delivered evals failed (reference: leader.go:302-332)."""
        while True:
            try:
                ev, token = self.eval_broker.dequeue([FAILED_QUEUE],
                                                     timeout=0.01)
            except RuntimeError:
                return  # broker disabled: leadership being revoked
            if ev is None:
                return
            updated = ev.copy()
            updated.Status = EvalStatusFailed
            updated.StatusDescription = "evaluation reached delivery limit"
            self.raft.apply(MessageType.EvalUpdate, {"Evals": [updated]})
            self.eval_broker.ack(ev.ID, token)

    def _reap_dup_blocked_evaluations(self) -> None:
        """Cancel duplicate blocked evals (reference: leader.go:334-360)."""
        dups = self.blocked_evals.get_duplicates(0.01)
        if not dups:
            return
        cancelled = []
        for ev in dups:
            updated = ev.copy()
            updated.Status = EvalStatusCancelled
            updated.StatusDescription = (
                f"existing blocked evaluation exists for job {ev.JobID}")
            cancelled.append(updated)
        self.raft.apply(MessageType.EvalUpdate, {"Evals": cancelled})

    def _schedule_core_gc(self, kind: str) -> None:
        """(reference: leader.go:246-271 coreJobEval)"""
        ev = Evaluation(
            ID=generate_uuid(),
            Priority=CoreJobPriority,
            Type=JobTypeCore,
            TriggeredBy="scheduled",
            JobID=f"{kind}:{self.raft.last_index}",
            Region=self._ev_region(None),
            Status=EvalStatusPending,
            ModifyIndex=self.raft.last_index,
        )
        self.eval_broker.enqueue(ev)

    # ========================================================== endpoints ==
    # Job endpoint (reference: nomad/job_endpoint.go)

    def _default_region(self, job: Job) -> None:
        """THE one place a submitted job's empty Region defaults to this
        server's — register and plan ingress both stamp through here, so
        a job forwarded to its home region carries one consistent Region
        on the job, its evals (_ev_region), and its allocs (which embed
        the job) end to end."""
        if not job.Region:
            job.Region = self.config.region

    def _ev_region(self, job: Optional[Job]) -> str:
        """Home region stamped onto evaluations. Federation only — ""
        (the pre-federation value) when disabled, keeping the default
        path bit-identical."""
        if not federation_enabled(self.fed):
            return ""
        if job is not None and job.Region:
            return job.Region
        return self.config.region

    def job_register(self, job: Job, enforce_index: Optional[int] = None,
                     trigger: str = EvalTriggerJobRegister
                     ) -> Tuple[str, int, int]:
        """Returns (eval_id, job_modify_index, index)."""
        job.init_fields()
        self._default_region(job)
        errs = job.validate()
        if errs:
            raise ValueError("; ".join(errs))
        if trigger == EvalTriggerJobRegister:
            # Admission control gates USER submissions only, before any
            # raft write — internal triggers (periodic launches, node
            # evals, requeues) always pass. Raises QoSBackpressureError
            # (typed; RPC remote_type / HTTP 429) to shed.
            self.admission.admit(job.Priority)
        if enforce_index is not None:
            existing = self.state.job_by_id(job.ID)
            cur = existing.JobModifyIndex if existing is not None else 0
            if cur != enforce_index:
                raise ValueError(
                    f"Enforcing job modify index {enforce_index}: "
                    f"job exists with conflicting job modify index: {cur}")
        index = self.raft.apply(MessageType.JobRegister, {"Job": job})

        # Periodic parents are launched by the dispatcher, not evaluated.
        if job.is_periodic():
            return "", index, index

        ev = Evaluation(
            ID=generate_uuid(),
            Priority=job.Priority,
            Type=job.Type,
            TriggeredBy=trigger,
            JobID=job.ID,
            Region=self._ev_region(job),
            JobModifyIndex=index,
            Status=EvalStatusPending,
        )
        self.raft.apply(MessageType.EvalUpdate, {"Evals": [ev]})
        return ev.ID, index, index

    def job_plan(self, job: Job, want_diff: bool = True):
        """Dry-run scheduling: what would registering this job do?
        (reference: job_endpoint.go:422-526 Job.Plan)

        Runs the real scheduler against a scratch copy of current state with
        the submitted job inserted, a Harness planner capturing the plan, and
        returns the annotated structural diff plus per-TG failures. No Raft
        writes happen. The scratch build is O(cluster) per call; a
        copy-on-write store fork would let plan reuse the snapshot directly.
        """
        from nomad_tpu.scheduler.annotate import annotate
        from nomad_tpu.scheduler.testing import Harness
        from nomad_tpu.structs.diff import job_diff

        job.init_fields()
        self._default_region(job)
        errs = job.validate()
        if errs:
            raise ValueError("; ".join(errs))

        snap = self.state.snapshot()
        old_job = snap.job_by_id(job.ID)
        index = old_job.JobModifyIndex if old_job is not None else 0
        updated_index = index + 1 if old_job is not None else 1

        # Periodic parents are never evaluated by register — the dispatcher
        # launches children. Report the diff + next launch only.
        if job.is_periodic():
            diff = None
            if want_diff:
                diff = job_diff(old_job, job, contextual=True)
            next_launch = (job.Periodic.next(time.time())
                           if job.Periodic.Enabled else 0.0)
            return JobPlanResponse(Diff=diff, JobModifyIndex=index,
                                   NextPeriodicLaunch=next_launch)

        # Scratch world: current nodes/allocs/evals + the proposed job.
        harness = Harness()
        scratch = harness.state
        # Copies only: store upserts stamp indexes/status on the objects they
        # are handed, and live snapshot reads return the stored references.
        for node in snap.nodes():
            scratch.upsert_node(harness._next_index(), node.copy())
        for other in snap.jobs():
            if other.ID != job.ID:
                scratch.upsert_job(harness._next_index(), other.copy())
        allocs = [a.copy() for a in snap.allocs()]
        if allocs:
            scratch.upsert_allocs(harness._next_index(), allocs)
        # The upsert stamps JobModifyIndex from the index passed; make the
        # scratch indexes land at updated_index so the eval's
        # JobModifyIndex matches the planned job's.
        harness.next_index = max(harness.next_index, updated_index)
        scratch.upsert_job(harness._next_index(), job.copy())

        ev = Evaluation(
            ID=generate_uuid(),
            Priority=job.Priority,
            Type=job.Type,
            TriggeredBy=EvalTriggerJobRegister,
            JobID=job.ID,
            JobModifyIndex=updated_index,
            Status=EvalStatusPending,
            AnnotatePlan=True,
        )
        harness.process(ev.Type, ev)

        if len(harness.plans) != 1:
            raise RuntimeError(
                f"scheduler resulted in {len(harness.plans)} plans, want 1")
        annotations = harness.plans[0].Annotations

        diff = None
        if want_diff:
            diff = job_diff(old_job, job, contextual=True)
            annotate(diff, annotations)

        updated_eval = harness.evals[0] if harness.evals else ev

        return JobPlanResponse(
            Diff=diff,
            Annotations=annotations,
            FailedTGAllocs=updated_eval.FailedTGAllocs,
            JobModifyIndex=index,
            CreatedEvals=list(harness.creates),
        )

    def job_deregister(self, job_id: str) -> Tuple[str, int]:
        """(reference: job_endpoint.go:155-207)"""
        job = self.state.job_by_id(job_id)
        index = self.raft.apply(MessageType.JobDeregister, {"JobID": job_id})
        priority = job.Priority if job is not None else 50
        jtype = job.Type if job is not None else JobTypeService
        ev = Evaluation(
            ID=generate_uuid(),
            Priority=priority,
            Type=jtype,
            TriggeredBy=EvalTriggerJobDeregister,
            JobID=job_id,
            Region=self._ev_region(job),
            JobModifyIndex=index,
            Status=EvalStatusPending,
        )
        self.raft.apply(MessageType.EvalUpdate, {"Evals": [ev]})
        return ev.ID, index

    def job_evaluate(self, job_id: str) -> Tuple[str, int]:
        """Force a re-evaluation (reference: job_endpoint.go:209-257)."""
        job = self.state.job_by_id(job_id)
        if job is None:
            raise KeyError(f"job not found: {job_id}")
        if job.is_periodic():
            raise ValueError("can't evaluate periodic job")
        # Forced re-evaluation is user ingress like register: gated.
        self.admission.admit(job.Priority)
        ev = Evaluation(
            ID=generate_uuid(),
            Priority=job.Priority,
            Type=job.Type,
            TriggeredBy=EvalTriggerJobRegister,
            JobID=job.ID,
            Region=self._ev_region(job),
            JobModifyIndex=job.JobModifyIndex,
            Status=EvalStatusPending,
        )
        index = self.raft.apply(MessageType.EvalUpdate, {"Evals": [ev]})
        return ev.ID, index

    def periodic_force(self, job_id: str) -> None:
        self.periodic.force_run(job_id)

    # Node endpoint (reference: nomad/node_endpoint.go)

    def node_register(self, node: Node) -> Tuple[float, int]:
        """Returns (heartbeat_ttl, index)."""
        if node.ID == "":
            raise ValueError("missing node ID")
        if node.Datacenter == "":
            raise ValueError("missing datacenter")
        if node.Name == "":
            raise ValueError("missing node name")
        if node.Status == "":
            node.Status = NodeStatusInit
        if not valid_node_status(node.Status):
            raise ValueError(f"invalid status for node: {node.Status}")
        from nomad_tpu.structs import compute_node_class

        compute_node_class(node)
        index = self.raft.apply(MessageType.NodeRegister, {"Node": node})
        ttl = self.heartbeats.reset_heartbeat_timer(node.ID)
        if node.Status == NodeStatusReady:
            self._create_node_evals(node.ID, index)
        return ttl, index

    def node_update_status(self, node_id: str, status: str) -> Tuple[float, int]:
        """(reference: node_endpoint.go:194-235)"""
        if not valid_node_status(status):
            raise ValueError(f"invalid status for node: {status}")
        node = self.state.node_by_id(node_id)
        if node is None:
            raise KeyError(f"node not found: {node_id}")
        index = self.raft.apply(MessageType.NodeUpdateStatus,
                                {"NodeID": node_id, "Status": status})
        if status != node.Status:
            self._create_node_evals(node_id, index)
        if status == NodeStatusDown:
            self.heartbeats.clear_heartbeat_timer(node_id)
            ttl = 0.0
        else:
            ttl = self.heartbeats.reset_heartbeat_timer(node_id)
        return ttl, index

    def node_heartbeat(self, node_id: str) -> float:
        node = self.state.node_by_id(node_id)
        if node is None:
            raise KeyError(f"node not found: {node_id}")
        if node.Status == NodeStatusDown:
            # The TTL already expired and this node was marked down. A
            # bare timer reset would leave it down FOREVER: the client
            # only pushes a ready status during registration. Reject so
            # the client's heartbeat loop falls back to re-registering
            # (reference: the client re-registers on a heartbeat error,
            # client.go registerAndHeartbeat).
            raise KeyError(f"node {node_id} is down; must re-register")
        return self.heartbeats.reset_heartbeat_timer(node_id)

    def node_update_drain(self, node_id: str, drain: bool) -> int:
        node = self.state.node_by_id(node_id)
        if node is None:
            raise KeyError(f"node not found: {node_id}")
        index = self.raft.apply(MessageType.NodeUpdateDrain,
                                {"NodeID": node_id, "Drain": drain})
        if drain:
            self._create_node_evals(node_id, index)
        return index

    def node_deregister(self, node_id: str) -> int:
        index = self.raft.apply(MessageType.NodeDeregister,
                                {"NodeID": node_id})
        self._create_node_evals(node_id, index)
        self.heartbeats.clear_heartbeat_timer(node_id)
        return index

    def node_evaluate(self, node_id: str) -> List[str]:
        node = self.state.node_by_id(node_id)
        if node is None:
            raise KeyError(f"node not found: {node_id}")
        return self._create_node_evals(node_id, self.raft.last_index)

    def _create_node_evals(self, node_id: str, index: int) -> List[str]:
        """One eval per job with allocs on the node + system jobs
        (reference: node_endpoint.go:650-720)."""
        evals: List[Evaluation] = []
        job_ids = set()
        for alloc in self.state.allocs_by_node(node_id):
            if alloc.JobID in job_ids:
                continue
            job_ids.add(alloc.JobID)
            job = self.state.job_by_id(alloc.JobID)
            priority = job.Priority if job is not None else 50
            jtype = job.Type if job is not None else JobTypeService
            evals.append(Evaluation(
                ID=generate_uuid(), Priority=priority, Type=jtype,
                TriggeredBy=EvalTriggerNodeUpdate, JobID=alloc.JobID,
                Region=self._ev_region(job),
                NodeID=node_id, NodeModifyIndex=index,
                Status=EvalStatusPending))
        for job in self.state.jobs_by_scheduler(JobTypeSystem):
            if job.ID in job_ids:
                continue
            evals.append(Evaluation(
                ID=generate_uuid(), Priority=job.Priority, Type=job.Type,
                TriggeredBy=EvalTriggerNodeUpdate, JobID=job.ID,
                Region=self._ev_region(job),
                NodeID=node_id, NodeModifyIndex=index,
                Status=EvalStatusPending))
        if evals:
            self.raft.apply(MessageType.EvalUpdate, {"Evals": evals})
        return [e.ID for e in evals]

    def node_update_allocs(self, allocs: List[Allocation]) -> int:
        """Client alloc status sync, coalesced server-side: all RPCs that
        land within one batch window ride a single raft entry and share a
        future carrying the commit index (reference: batchFuture +
        batchUpdateInterval, node_endpoint.go:530-593). FSM apply order
        within the batch preserves arrival order, so a later update to the
        same alloc wins — same as the reference's appended updates."""
        interval = self.config.alloc_update_batch_interval
        if interval <= 0:
            return self.raft.apply(MessageType.AllocClientUpdate,
                                   {"Alloc": allocs})
        # Leader-only batching, as in the reference: a follower must raise
        # NotLeaderError synchronously so the endpoint layer forwards at
        # once, instead of parking the RPC a full window behind a doomed
        # apply. (Losing leadership after this check is fine — the flush's
        # apply raises into the shared future.)
        if hasattr(self.raft, "is_leader") and not self.raft.is_leader():
            raise NotLeaderError(getattr(self.raft, "leader_id", None))
        with self._alloc_update_cond:
            self._alloc_update_pending.extend(allocs)
            fut = self._alloc_update_future
            if fut is None:
                fut = self._alloc_update_future = _BatchAllocUpdate()
                if (self._alloc_flush_thread is None
                        or not self._alloc_flush_thread.is_alive()):
                    self._alloc_flush_thread = threading.Thread(
                        target=self._alloc_flush_loop, daemon=True,
                        name="alloc-update-flush")
                    self._alloc_flush_thread.start()
                self._alloc_update_cond.notify()
        if not fut.event.wait(timeout=interval + 60.0):
            raise TimeoutError(
                "alloc update batch did not resolve within "
                f"{interval + 60.0:.0f}s (consensus stalled?)")
        if fut.error is not None:
            raise fut.error
        return fut.index

    def _alloc_flush_loop(self) -> None:
        """Dedicated flusher: waits for a window to open, lets it fill for
        one batch interval, commits it as one entry, and wakes every
        waiting RPC with the shared result. A single long-lived thread —
        NOT the shared timer-wheel pool, where a consensus stall's worth of
        heartbeat callbacks could queue a flush behind them for minutes."""
        while True:
            with self._alloc_update_cond:
                while (self._alloc_update_future is None
                       and not self._shutdown.is_set()):
                    self._alloc_update_cond.wait(timeout=0.5)
                if self._shutdown.is_set() and self._alloc_update_future is None:
                    return
            self._shutdown.wait(self.config.alloc_update_batch_interval)
            self._flush_alloc_updates()

    def _flush_alloc_updates(self) -> None:
        with self._alloc_update_cond:
            batch = self._alloc_update_pending
            fut = self._alloc_update_future
            self._alloc_update_pending = []
            self._alloc_update_future = None
        if fut is None:
            return
        metrics.set_gauge(("nomad", "client", "update_alloc_batch"),
                          len(batch))
        try:
            fut.index = self.raft.apply(MessageType.AllocClientUpdate,
                                        {"Alloc": batch})
        # lint: allow(swallow, error is delivered to every batched waiter)
        except Exception as e:  # NotLeaderError et al: every waiter sees it
            fut.error = e
        finally:
            fut.event.set()

    # Service registry (standalone replacement for the reference's Consul
    # delegation, command/agent/consul/syncer.go — see structs.ServiceRegistration)
    def service_sync(self, upserts: List, deletes: List[str]) -> int:
        return self.raft.apply(MessageType.ServiceSync,
                               {"Upserts": upserts, "Deletes": deletes})

    def register_self_service(self, rpc_addr: str = "",
                              http_addr: str = "") -> int:
        """Register this server in the registry so clients can bootstrap
        their server list from any agent's HTTP API (the reference's analogue
        is server self-registration in Consul for client auto-discovery,
        command/agent/agent.go syncAgentServicesWithConsul)."""
        from nomad_tpu.services import build_server_service_regs

        regs = build_server_service_regs(self.config.node_id or "dev",
                                         rpc_addr, http_addr)
        if not regs:
            return 0
        return self.service_sync(regs, [])

    def _invalidate_heartbeat(self, node_id: str) -> None:
        """(reference: heartbeat.go:84-107)"""
        try:
            self.node_update_status(node_id, NodeStatusDown)
        except KeyError:
            pass

    # System endpoint (reference: nomad/system_endpoint.go)

    def force_gc(self) -> None:
        ev = Evaluation(
            ID=generate_uuid(), Priority=CoreJobPriority, Type=JobTypeCore,
            TriggeredBy="scheduled",
            JobID=f"{CoreJobForceGC}:{self.raft.last_index}",
            Region=self._ev_region(None),
            Status=EvalStatusPending)
        self.eval_broker.enqueue(ev)
