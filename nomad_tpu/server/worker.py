"""Worker: the per-server scheduling loop (reference: nomad/worker.go).

Dequeue an evaluation from the broker, wait for the state store to catch up
to the eval's modify index, snapshot, run the scheduler, act as its Planner
(submitting plans to the leader's plan queue and creating/updating evals
through consensus), then ack/nack.

Workers run on EVERY server, not just the leader (reference:
nomad/worker.go:101-130 — all five broker/plan operations resolve through
server.forward to the leader). The seam is a backend object: `LocalBackend`
touches the in-process broker/plan-queue/raft directly (leader), while
`RemoteBackend` performs the same five operations over leader RPC
(Eval.Dequeue / Eval.Ack / Eval.Nack / Plan.Submit / Eval.Update), so
follower CPUs contribute scheduling throughput. The scheduler's state
snapshots always come from the LOCAL raft replica — followers replicate the
FSM, and `_wait_for_index` is exactly the reference's raft-sync barrier
(worker.go:214-244).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import List, Optional, Tuple

from nomad_tpu.resilience import failpoints
from nomad_tpu.resilience.retry import Backoff, RetryPolicy
from nomad_tpu.scheduler import new_scheduler
from nomad_tpu.scheduler.scheduler import SetStatusError
from nomad_tpu.telemetry import metrics, trace
from nomad_tpu.structs import Evaluation, Plan, PlanResult, from_dict, to_dict
from nomad_tpu.structs.structs import EvalStatusBlocked
from nomad_tpu.tensor import TensorIndex

from .blocked_evals import BlockedEvals
from .eval_broker import EvalBroker, NotOutstandingError, TokenMismatchError
from .fsm import DevRaft, MessageType
from .plan_queue import PlanQueue

logger = logging.getLogger("nomad.worker")

# Backoff for failed dequeues (reference: worker.go:32-40)
BACKOFF_BASELINE = 0.02
BACKOFF_LIMIT = 1.0

RAFT_SYNC_LIMIT = 10.0  # max wait for state to catch up (worker.go:214)
DEQUEUE_TIMEOUT = 0.5
PLAN_WAIT = 30.0


def stamp_fed_born(plan: Plan, born: Optional[float]) -> None:
    """Stamp a federation snapshot's birth time onto a plan built from
    it (the applier's staleness reject reads `plan._fed_born`) and
    observe the plan's snapshot age — nomad.federation.staleness_ms, the
    per-plan staleness signal. THE one stamping site for both the
    classic worker and the pipelined window path; no-op when the plan
    came from a direct live snapshot (born None, federation off or the
    exact-path oracle)."""
    if born is None:
        return
    plan._fed_born = born
    metrics.add_sample(("nomad", "federation", "staleness_ms"),
                       (time.monotonic() - born) * 1e3)


class PartialPlanError(Exception):
    """A chunked plan sweep failed mid-sequence. Carries the results of
    every chunk whose wait completed BEFORE the failure, so callers can
    account the committed chunks instead of treating the whole sweep as
    unknown (the committed allocations are real; only the tail is in
    doubt)."""

    def __init__(self, results: List[Optional[PlanResult]],
                 cause: BaseException):
        super().__init__(f"plan sweep failed after {len(results)} "
                         f"chunk(s): {cause}")
        self.results = results


class LocalBackend:
    """Leader-side worker seam: direct access to the in-process broker,
    plan queue and raft apply (the only mode the reference's LEADER needs;
    every operation below has an RPC twin in RemoteBackend)."""

    def __init__(self, raft, eval_broker: EvalBroker, plan_queue: PlanQueue):
        self.raft = raft
        self.eval_broker = eval_broker
        self.plan_queue = plan_queue

    def enabled(self) -> bool:
        return self.eval_broker.enabled()

    def dequeue(self, schedulers: List[str], timeout: float
                ) -> Tuple[Optional[Evaluation], str, int]:
        ev, token = self.eval_broker.dequeue(schedulers, timeout)
        # WaitIndex: everything committed BEFORE this dequeue must be in
        # the scheduling snapshot. ModifyIndex alone is not enough: a
        # duplicate eval created before an earlier eval's plan committed
        # would schedule against pre-plan state and double-place the job
        # (the soak test's 6-of-3 duplication).
        if ev is not None:
            # Federation: the broker's release floor — the store index at
            # which THIS eval became ready — is a sufficient (and much
            # smaller) freshness bound: per-job serialization means no
            # plan for the eval's job commits after its release, so a
            # snapshot at the floor can never double-place. Lets shared
            # follower snapshots serve whole storm bursts instead of
            # chasing the leader's every commit. None when federation is
            # off: the pre-federation global-latest bound below.
            floor = self.eval_broker.release_floor(ev.ID)
            if floor is not None:
                return ev, token, floor
        return ev, token, self.raft.fsm.state.latest_index()

    def ack(self, eval_id: str, token: str) -> None:
        self.eval_broker.ack(eval_id, token)

    def nack(self, eval_id: str, token: str) -> None:
        self.eval_broker.nack(eval_id, token)

    def submit_plan(self, plan: Plan) -> Optional[PlanResult]:
        pending = self.plan_queue.enqueue(plan)
        # Keep the nack timer fresh while we wait on the applier.
        self.eval_broker.outstanding_reset(plan.EvalID, plan.EvalToken)
        return pending.wait(timeout=PLAN_WAIT)

    def submit_plans(self, plans: List[Plan]) -> List[Optional[PlanResult]]:
        """Pipelined multi-plan submit (chunked system sweeps) with a
        bounded in-queue depth of TWO chunks: enough for the applier to
        verify chunk i+1 while chunk i commits (reference model:
        plan_apply.go's verify/apply overlap), but never the whole sweep —
        the queue orders same-priority plans by arrival, so enqueueing all
        chunks up front would recreate exactly the head-of-line blocking
        chunking exists to break. A competing plan arriving mid-sweep now
        waits at most ~2 chunks. If a wait fails mid-sequence, the chunks
        still in the queue are cancelled so they cannot commit behind the
        retrying scheduler's back (a chunk already picked up by the
        applier may still land — the same single-window race the
        monolithic path has). The already-collected results ride the
        raised PartialPlanError so the caller can account committed
        chunks."""
        out: List[Optional[PlanResult]] = []
        in_flight: List = []
        next_i = 0
        try:
            while next_i < len(plans) or in_flight:
                while len(in_flight) < 2 and next_i < len(plans):
                    in_flight.append(
                        self.plan_queue.enqueue(plans[next_i]))
                    next_i += 1
                pending = in_flight.pop(0)
                self.eval_broker.outstanding_reset(
                    pending.plan.EvalID, pending.plan.EvalToken)
                out.append(pending.wait(timeout=PLAN_WAIT))
        except Exception as exc:
            for pending in in_flight:
                pending.cancel()
            raise PartialPlanError(out, exc) from exc
        return out

    def eval_update(self, evals: List[Evaluation], token: str,
                    reset_id: str) -> None:
        if reset_id:
            self.eval_broker.outstanding_reset(reset_id, token)
        self.raft.apply(MessageType.EvalUpdate, {"Evals": evals,
                                                 "EvalToken": token})


class RemoteBackend:
    """Follower-side worker seam: the same five operations over RPC to the
    current raft leader (reference: Eval.Dequeue eval_endpoint.go:68,
    Plan.Submit plan_endpoint.go:16, Eval.Ack/Nack/Update — each forwarded
    by server.forward, rpc.go:177-221). Leader discovery is the local raft
    node's leader hint; while there is no leader (election in flight) every
    operation backs off instead of erroring."""

    def __init__(self, pool, raft, local_addr: str,
                 stop_event: Optional[threading.Event] = None):
        self.pool = pool
        self.raft = raft
        self.local_addr = local_addr
        # The owning Worker shares its stop event at construction (see
        # Worker.__init__) so backoffs below are shutdown-aware.
        self.stop_event = stop_event

    def _backoff(self, delay: float) -> None:
        if self.stop_event is not None:
            self.stop_event.wait(delay)
        else:
            time.sleep(delay)

    def _leader(self) -> Optional[str]:
        leader = getattr(self.raft, "leader_id", None)
        if not leader or leader == self.local_addr:
            return None
        return leader

    def enabled(self) -> bool:
        return self._leader() is not None

    def dequeue(self, schedulers: List[str], timeout: float
                ) -> Tuple[Optional[Evaluation], str, int]:
        leader = self._leader()
        if leader is None:
            self._backoff(0.1)
            return None, "", 0
        try:
            resp = self.pool.call(leader, "Eval.Dequeue",
                                  {"Schedulers": list(schedulers),
                                   "Timeout": timeout},
                                  timeout=timeout + 10.0)
        except Exception as exc:
            # Leader churn / transport failure: treat as an empty dequeue;
            # the run loop retries against the next leader hint.
            logger.debug("remote dequeue failed (leader churn?): %s", exc)
            self._backoff(0.1)
            return None, "", 0
        ev = resp.get("Eval")
        return ((from_dict(Evaluation, ev) if ev else None),
                resp.get("Token", ""), int(resp.get("WaitIndex", 0) or 0))

    @staticmethod
    def _retype(exc) -> None:
        """Surface broker races as their typed exceptions: over the wire
        they arrive as RPCError with the class name in remote_type, and
        callers distinguish normal redelivery races from real failures."""
        remote = getattr(exc, "remote_type", "")
        if remote == "NotOutstandingError":
            raise NotOutstandingError(str(exc)) from exc
        if remote == "TokenMismatchError":
            raise TokenMismatchError(str(exc)) from exc

    def ack(self, eval_id: str, token: str) -> None:
        leader = self._leader()
        if leader is None:
            raise RuntimeError("no leader for eval ack")
        try:
            self.pool.call(leader, "Eval.Ack",
                           {"EvalID": eval_id, "Token": token})
        except Exception as exc:
            self._retype(exc)
            raise

    def nack(self, eval_id: str, token: str) -> None:
        leader = self._leader()
        if leader is None:
            raise RuntimeError("no leader for eval nack")
        try:
            self.pool.call(leader, "Eval.Nack",
                           {"EvalID": eval_id, "Token": token})
        except Exception as exc:
            self._retype(exc)
            raise

    def submit_plan(self, plan: Plan) -> Optional[PlanResult]:
        leader = self._leader()
        if leader is None:
            raise RuntimeError("no leader for plan submit")
        resp = self.pool.call(leader, "Plan.Submit",
                              {"Plan": to_dict(plan)},
                              timeout=PLAN_WAIT + 15.0)
        result = resp.get("Result")
        return from_dict(PlanResult, result) if result else None

    def eval_update(self, evals: List[Evaluation], token: str,
                    reset_id: str) -> None:
        leader = self._leader()
        if leader is None:
            raise RuntimeError("no leader for eval update")
        self.pool.call(leader, "Eval.Update",
                       {"Evals": [to_dict(e) for e in evals],
                        "EvalToken": token, "ResetID": reset_id})


class Worker:
    def __init__(self, raft: DevRaft, eval_broker: Optional[EvalBroker],
                 plan_queue: Optional[PlanQueue],
                 blocked_evals: Optional[BlockedEvals] = None,
                 tindex: Optional[TensorIndex] = None,
                 schedulers: Optional[List[str]] = None,
                 backend=None):
        self.raft = raft
        self.eval_broker = eval_broker
        self.plan_queue = plan_queue
        self.blocked_evals = blocked_evals
        self.tindex = tindex
        self.schedulers = schedulers or ["service", "batch", "system"]
        self.scheduler_impl = "tpu"  # or "cpu-reference" (bench denominator)
        self.backend = backend or LocalBackend(raft, eval_broker, plan_queue)
        # Stable identity for per-worker observability (sched-stats keys
        # its report by this) and stage-thread names; start() overwrites
        # it with the server-assigned name.
        self.name = "worker"
        # QoS wiring (set by the Server like core_scheduler below): the
        # scheduler reads these off its Planner for preemption decisions,
        # and the pipelined worker for deadline-aware window sizing.
        # None = QoS disabled (the default, pre-QoS behavior).
        self.qos = None
        self.qos_counters = None
        self._stop = threading.Event()
        # Share our stop event with a backend that paces on one (the
        # RemoteBackend's leaderless/error backoffs), so stop() wakes a
        # worker parked in a backend-side wait instead of letting it burn
        # the backoff out.
        if getattr(self.backend, "stop_event", False) is None:
            self.backend.stop_event = self._stop
        self._paused = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._token: str = ""
        self._eval: Optional[Evaluation] = None
        self._snapshot = None
        # Set by the server: handles `_core` GC evals (reference:
        # worker.go invokeScheduler -> scheduler.NewScheduler("_core")).
        self.core_scheduler = None
        # Federation (set by the server when ServerConfig.federation is
        # enabled): the shared staleness-bounded SnapshotSource this
        # worker schedules from, and the birth time of the snapshot the
        # CURRENT eval is placing against (stamped onto its plans so the
        # applier can reject over-stale ones). None = federation off:
        # every snapshot below is a direct live-store snapshot, the
        # pre-federation path bit-for-bit.
        self.fed_source = None
        self._fed_born: Optional[float] = None

    # ------------------------------------------------------------- lifecycle
    def start(self, name: str = "worker") -> None:
        self.name = name
        self._stop.clear()
        self._thread = threading.Thread(target=self.run, daemon=True, name=name)
        self._thread.start()

    def stop(self) -> None:
        """Signal the run loop to exit without blocking (the leadership-flap
        path calls this from the raft notify thread). The Server keeps a
        reference and joins retired workers at shutdown — a worker thread
        left inside an XLA dispatch at interpreter exit aborts the whole
        process (round-3 regression: bench rc=134)."""
        self._stop.set()

    def join(self, timeout: float = 30.0) -> None:
        t = self._thread
        if (t is not None and t.is_alive()
                and t is not threading.current_thread()):
            t.join(timeout)

    def set_pause(self, paused: bool) -> None:
        """(reference: worker.go:81-99) Pause during leadership transitions."""
        if paused:
            self._paused.set()
        else:
            self._paused.clear()

    # -------------------------------------------------------------- run loop
    def run(self) -> None:
        """(reference: worker.go:101-130)"""
        while not self._stop.is_set():
            if self._paused.is_set():
                self._stop.wait(0.05)  # shutdown-aware pause spin
                continue
            got = self._dequeue_evaluation()
            if got is None:
                continue
            ev, token, wait_index = got
            self._eval, self._token = ev, token
            try:
                with trace.resume(trace.linked("eval", ev.ID),
                                  "worker.process_eval",
                                  eval=ev.ID, type=ev.Type):
                    min_index = max(ev.ModifyIndex, wait_index)
                    self._wait_for_index(min_index)
                    self._invoke_scheduler(ev, token, min_index=min_index)
            except Exception:
                # Leadership loss tears down the plan queue / broker under a
                # mid-flight eval; drop quietly, redelivery handles the rest
                # (reference: worker pause on leadership, worker.go:88-99).
                if self._stop.is_set() or not self.backend.enabled():
                    logger.debug("worker: dropping eval %s on shutdown", ev.ID)
                    continue
                logger.exception("worker: failed to process eval %s", ev.ID)
                self._send_nack(ev.ID, token)
                continue
            self._send_ack(ev.ID, token)

    def process_one(self, timeout: float = DEQUEUE_TIMEOUT) -> bool:
        """Synchronous single-step variant (dev mode / tests).
        Returns True if an eval was processed."""
        got = self._dequeue_evaluation(timeout)
        if got is None:
            return False
        ev, token, wait_index = got
        # Same Planner-seam state as run(): update_eval/create_eval read
        # self._token — without this, a second process_one call would
        # submit its eval updates under the PREVIOUS eval's token.
        self._eval, self._token = ev, token
        try:
            with trace.resume(trace.linked("eval", ev.ID),
                              "worker.process_eval",
                              eval=ev.ID, type=ev.Type):
                min_index = max(ev.ModifyIndex, wait_index)
                self._wait_for_index(min_index)
                self._invoke_scheduler(ev, token, min_index=min_index)
        except Exception:
            logger.exception("worker: failed to process eval %s", ev.ID)
            self._send_nack(ev.ID, token)
            return True
        self._send_ack(ev.ID, token)
        return True

    def _dequeue_evaluation(self, timeout: float = DEQUEUE_TIMEOUT
                            ) -> Optional[Tuple[Evaluation, str, int]]:
        try:
            if failpoints.fire("worker.dequeue") == "drop":
                # A lost round still consumed its blocking window — an
                # instant None would busy-spin every worker thread
                # through the failpoint lock at full CPU. Shutdown-aware:
                # a stop() mid-window returns immediately.
                self._stop.wait(timeout)
                return None
            ev, token, wait_index = self.backend.dequeue(self.schedulers,
                                                         timeout)
        except (RuntimeError, failpoints.FailpointError):
            self._stop.wait(BACKOFF_BASELINE)
            return None
        if ev is None:
            return None
        return ev, token, wait_index

    def _wait_for_index(self, index: int) -> None:
        """Raft-sync barrier (reference: worker.go:214-244). RetryPolicy
        paces the poll (1-10ms jittered) under the RAFT_SYNC_LIMIT
        deadline; the shutdown-aware sleep aborts the wait the moment
        stop() is called instead of burning out the deadline."""
        start = time.monotonic()

        def check() -> None:
            if self.raft.fsm.state.latest_index() < index:
                raise TimeoutError(f"timed out waiting for index {index}")

        policy = RetryPolicy(max_attempts=None, deadline=RAFT_SYNC_LIMIT,
                             backoff=Backoff(base=0.001, cap=0.01),
                             retry_on=(TimeoutError,),
                             sleep=self._stop.wait,
                             trace_events=False)  # ms-cadence poll
        try:
            policy.call(check)
        finally:
            metrics.measure_since(("nomad", "worker", "wait_for_index"),
                                  start)

    def _invoke_scheduler(self, ev: Evaluation, token: str,
                          min_index: Optional[int] = None) -> None:
        """(reference: worker.go:246-283; timed per scheduler type like
        worker.go's invoke_scheduler MeasureSince). Resumes the eval's
        trace when not already inside it (the pipelined slow/fallback
        path calls this without the run loop's ambient span).

        ``min_index`` (the dequeue-time release floor) opts the eval
        into the federation SnapshotSource: a run-loop eval may place
        against the shared staleness-bounded snapshot, while fallback
        re-runs (pipelined slow path — whose plan just failed against
        possibly-stale state) pass None and always get a direct fresh
        snapshot, preserving the exact-path oracle semantics."""
        start = time.monotonic()
        try:
            with trace.resume(trace.linked("eval", ev.ID),
                              "worker.invoke_scheduler",
                              eval=ev.ID, type=ev.Type):
                if min_index is not None and self.fed_source is not None:
                    self._snapshot, self._fed_born = \
                        self.fed_source.get(min_index)
                else:
                    self._snapshot = self.raft.fsm.state.snapshot()
                    self._fed_born = None
                    if (min_index is not None
                            and self._snapshot.latest_index() < min_index):
                        # The store regressed between the raft-sync
                        # barrier and the snapshot — a replica-digest
                        # quarantine wipes the local store for
                        # snapshot-reinstall. Scheduling from the wiped
                        # view would complete the eval against an empty
                        # world; nack and let redelivery find a replica
                        # that has caught back up.
                        raise TimeoutError(
                            f"snapshot at {self._snapshot.latest_index()} "
                            f"regressed below release floor {min_index}")
                if ev.Type == "_core":
                    if self.core_scheduler is not None:
                        self.core_scheduler.process(ev)
                    return
                sched = new_scheduler(ev.Type, self._snapshot, self,
                                      self.tindex, logger,
                                      impl=self.scheduler_impl)
                sched.process(ev)
        finally:
            metrics.measure_since(
                ("nomad", "worker", "invoke_scheduler", ev.Type), start)

    # ------------------------------------------------------------ ack / nack
    def _send_ack(self, eval_id: str, token: str) -> None:
        try:
            self.backend.ack(eval_id, token)
        except (NotOutstandingError, TokenMismatchError) as e:
            # Normal races: broker teardown on leadership loss, or the eval
            # was redelivered after a nack timeout and someone else owns it.
            logger.debug("worker: ack skipped for %s: %s", eval_id, e)
        except Exception:
            logger.exception("worker: ack failed for %s", eval_id)

    def _send_nack(self, eval_id: str, token: str) -> None:
        try:
            self.backend.nack(eval_id, token)
        except (NotOutstandingError, TokenMismatchError) as e:
            logger.debug("worker: nack skipped for %s: %s", eval_id, e)
        except Exception:
            logger.exception("worker: nack failed for %s", eval_id)

    # --------------------------------------------------------- Planner seam
    def _stamp_fed_born(self, plan: Plan) -> None:
        """The current eval's snapshot birth time onto its plan. getattr:
        harness code builds bare Workers via __new__ for backend-seam
        tests."""
        stamp_fed_born(plan, getattr(self, "_fed_born", None))

    def submit_plan(self, plan: Plan) -> Tuple[Optional[PlanResult], Optional[object]]:
        """(reference: worker.go:285-342)"""
        start = time.monotonic()
        plan.EvalToken = self._token
        self._stamp_fed_born(plan)
        try:
            with trace.span("worker.submit_plan", eval=plan.EvalID):
                result = self.backend.submit_plan(plan)
        finally:
            metrics.measure_since(("nomad", "worker", "submit_plan"), start)

        # If the state is behind the plan result, refresh before retrying.
        # The wait runs against the LOCAL replica: followers see the applied
        # plan through raft replication (reference: worker.go:330-340).
        state = None
        if result is not None and result.RefreshIndex > 0:
            self._wait_for_index(result.RefreshIndex)
            state = self.raft.fsm.state.snapshot()
            # The retry replans from a DIRECT fresh snapshot: its plans
            # are born now, not at the original source handout.
            if getattr(self, "_fed_born", None) is not None:
                self._fed_born = time.monotonic()
        return result, state

    def plan_queue_depth(self) -> int:
        """Pending plans contending for the applier — the system
        scheduler's chunk-or-not signal."""
        try:
            return self.backend.plan_queue.stats["Depth"]
        except AttributeError:
            return 0  # remote backend: no local queue visibility

    def submit_plans(self, plans: List[Plan]
                     ) -> Tuple[List[Optional[PlanResult]], Optional[object]]:
        """Chunked-plan Planner seam: pipelined queue entry, one refresh
        wait for the highest RefreshIndex across chunks.

        A mid-sweep failure degrades instead of erroring — IF a prefix
        committed: those chunks' results (PartialPlanError.results) are
        kept, the unknown tail becomes None results, and the refresh
        wait covers the committed AllocIndexes — so the scheduler's
        retry snapshot SEES the partial commit and re-plans only the
        remainder instead of nacking the whole eval. A total failure
        (zero chunks committed) still raises: there is nothing to
        account, and retrying against the same stale snapshot would
        burn the eval's retry budget to a terminal Failed where a nack
        redelivers it to a healthier worker or the new leader."""
        start = time.monotonic()
        for plan in plans:
            plan.EvalToken = self._token
            self._stamp_fed_born(plan)
        partial = False
        try:
            with trace.span("worker.submit_plans", chunks=len(plans)):
                submit = getattr(self.backend, "submit_plans", None)
                if submit is not None:
                    try:
                        results = submit(plans)
                    except PartialPlanError as exc:
                        if not exc.results:
                            raise  # nothing committed: nack + redeliver
                        logger.warning("worker: %s", exc)
                        results, partial = list(exc.results), True
                else:
                    results = []
                    try:
                        for p in plans:
                            results.append(self.backend.submit_plan(p))
                    except Exception:
                        if not results:
                            raise  # nothing committed: nack + redeliver
                        # Degrade to a partial sweep, but NEVER silently:
                        # the cause may be a real bug, not an injected
                        # fault.
                        logger.exception(
                            "worker: plan sweep failed after %d chunk(s)",
                            len(results))
                        partial = True
                if partial:
                    trace.add_event("fallback", kind="partial_plan_sweep",
                                    committed=len(results))
        finally:
            metrics.measure_since(("nomad", "worker", "submit_plan"), start)
        refresh = max((r.RefreshIndex for r in results if r is not None),
                      default=0)
        if partial:
            logger.warning(
                "worker: plan sweep committed %d/%d chunks before failing;"
                " accounting the committed prefix",
                sum(r is not None for r in results), len(plans))
            results = results + [None] * (len(plans) - len(results))
            # The retry snapshot must include the committed prefix, or
            # the re-plan would double-place the chunks that landed.
            refresh = max([refresh] + [r.AllocIndex for r in results
                                       if r is not None])
        state = None
        if refresh > 0:
            self._wait_for_index(refresh)
            state = self.raft.fsm.state.snapshot()
            if getattr(self, "_fed_born", None) is not None:
                self._fed_born = time.monotonic()
        return results, state

    def update_eval(self, ev: Evaluation) -> None:
        """(reference: worker.go:345-371)"""
        self.backend.eval_update([ev], self._token, ev.ID)

    def create_eval(self, ev: Evaluation) -> None:
        """(reference: worker.go:373-398)"""
        ev.SnapshotIndex = self._snapshot.latest_index() if self._snapshot else 0
        self.backend.eval_update([ev], self._token,
                                 self._eval.ID if self._eval else "")

    def reblock_eval(self, ev: Evaluation) -> None:
        """(reference: worker.go:400-426)"""
        ev.SnapshotIndex = self._snapshot.latest_index() if self._snapshot else 0
        self.backend.eval_update([ev], self._token, ev.ID)
