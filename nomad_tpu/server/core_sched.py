"""CoreScheduler: internal GC scheduler for `_core` evals (reference:
nomad/core_sched.go).

Handles eval-gc, job-gc, node-gc, and force-gc evaluations, translating time
thresholds to Raft indexes through the TimeTable.
"""

from __future__ import annotations

import logging
import time
from typing import List, Optional

from nomad_tpu.structs import Evaluation
from nomad_tpu.structs.structs import (
    CoreJobEvalGC,
    CoreJobForceGC,
    CoreJobJobGC,
    CoreJobNodeGC,
    JobStatusDead,
)

from .fsm import DevRaft, MessageType
from .timetable import TimeTable

logger = logging.getLogger("nomad.core_sched")


class CoreScheduler:
    """(reference: core_sched.go:20-51)"""

    def __init__(self, raft: DevRaft, timetable: TimeTable,
                 eval_gc_threshold: float = 3600.0,
                 job_gc_threshold: float = 4 * 3600.0,
                 node_gc_threshold: float = 24 * 3600.0):
        self.raft = raft
        self.timetable = timetable
        self.eval_gc_threshold = eval_gc_threshold
        self.job_gc_threshold = job_gc_threshold
        self.node_gc_threshold = node_gc_threshold

    def process(self, ev: Evaluation) -> None:
        kind = ev.JobID.split(":")[0]
        if kind == CoreJobEvalGC:
            self._eval_gc()
        elif kind == CoreJobJobGC:
            self._job_gc()
        elif kind == CoreJobNodeGC:
            self._node_gc()
        elif kind == CoreJobForceGC:
            self._eval_gc(force=True)
            self._job_gc(force=True)
            self._node_gc(force=True)
        else:
            raise ValueError(f"core scheduler cannot handle job '{ev.JobID}'")

    def _threshold_index(self, threshold: float, force: bool) -> int:
        if force:
            return self.raft.last_index + 1
        return self.timetable.nearest_index(time.time() - threshold)

    def _eval_gc(self, force: bool = False) -> None:
        """GC terminal evals older than the threshold, plus their allocs
        (reference: core_sched.go:53-117)."""
        state = self.raft.fsm.state
        oldest = self._threshold_index(self.eval_gc_threshold, force)
        gc_evals: List[str] = []
        gc_allocs: List[str] = []
        for ev in state.evals():
            if not ev.terminal_status() or ev.ModifyIndex >= oldest:
                continue
            allocs = state.allocs_by_eval(ev.ID)
            if any(not a.terminal_status() or a.ModifyIndex >= oldest
                   for a in allocs):
                continue
            gc_evals.append(ev.ID)
            gc_allocs.extend(a.ID for a in allocs)
        if gc_evals or gc_allocs:
            logger.info("core: eval GC reaping %d evals, %d allocs",
                        len(gc_evals), len(gc_allocs))
            self.raft.apply(MessageType.EvalDelete,
                            {"Evals": gc_evals, "Allocs": gc_allocs})

    def _job_gc(self, force: bool = False) -> None:
        """GC dead GC-eligible jobs whose evals/allocs are all terminal and
        old (reference: core_sched.go:119-180)."""
        state = self.raft.fsm.state
        oldest = self._threshold_index(self.job_gc_threshold, force)
        for job in state.jobs_by_gc(True):
            if job.Status != JobStatusDead or job.ModifyIndex >= oldest:
                continue
            evals = state.evals_by_job(job.ID)
            if any(not e.terminal_status() or e.ModifyIndex >= oldest
                   for e in evals):
                continue
            allocs = state.allocs_by_job(job.ID)
            if any(not a.terminal_status() or a.ModifyIndex >= oldest
                   for a in allocs):
                continue
            logger.info("core: job GC reaping %s", job.ID)
            if evals or allocs:
                self.raft.apply(MessageType.EvalDelete, {
                    "Evals": [e.ID for e in evals],
                    "Allocs": [a.ID for a in allocs]})
            self.raft.apply(MessageType.JobDeregister, {"JobID": job.ID})

    def _node_gc(self, force: bool = False) -> None:
        """GC down nodes with no non-terminal allocs
        (reference: core_sched.go:182-232)."""
        state = self.raft.fsm.state
        oldest = self._threshold_index(self.node_gc_threshold, force)
        for node in state.nodes():
            if not node.terminal_status() or node.ModifyIndex >= oldest:
                continue
            allocs = state.allocs_by_node(node.ID)
            if any(not a.terminal_status() for a in allocs):
                continue
            logger.info("core: node GC reaping %s", node.ID)
            self.raft.apply(MessageType.NodeDeregister, {"NodeID": node.ID})
