"""Periodic dispatcher: leader-side cron launcher (reference: nomad/periodic.go).

Tracks periodic jobs in a next-launch-time heap; at each fire it derives a
child job `<id>/periodic-<epoch>` and submits it through the job-register
path, deduping via the periodic_launch table so leadership failover doesn't
double-launch.
"""

from __future__ import annotations

import heapq
import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from nomad_tpu.structs import Job, PeriodicLaunch
from nomad_tpu.structs.structs import PeriodicLaunchSuffix

logger = logging.getLogger("nomad.periodic")


class PeriodicDispatch:
    def __init__(self, dispatch_job: Callable[[Job, float], None]):
        """dispatch_job(parent_job, launch_time) performs the derived-job
        registration + launch-table write (the server provides it)."""
        self.dispatch_job = dispatch_job
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._enabled = False
        self._running = False
        self._tracked: Dict[str, Job] = {}
        self._heap: List[Tuple[float, str]] = []
        self._heap_entries: Dict[str, float] = {}
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- lifecycle
    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            self._enabled = enabled
            if enabled and not self._running:
                self._running = True
                self._thread = threading.Thread(target=self._run, daemon=True,
                                                name="periodic")
                self._thread.start()
            self._cond.notify_all()
        if not enabled:
            self.flush()

    def flush(self) -> None:
        with self._lock:
            self._tracked.clear()
            self._heap = []
            self._heap_entries.clear()
            self._running = False
            self._cond.notify_all()

    # -------------------------------------------------------------- tracking
    def add(self, job: Job) -> None:
        """Track or update a periodic job (reference: periodic.go:187-232)."""
        with self._lock:
            if not self._enabled:
                return
            if not job.is_periodic():
                self._remove_locked(job.ID)
                return
            self._tracked[job.ID] = job
            nxt = job.Periodic.next(time.time())
            if nxt > 0:
                self._heap_entries[job.ID] = nxt
                heapq.heappush(self._heap, (nxt, job.ID))
                self._cond.notify_all()

    def remove(self, job_id: str) -> None:
        with self._lock:
            self._remove_locked(job_id)

    def _remove_locked(self, job_id: str) -> None:
        self._tracked.pop(job_id, None)
        self._heap_entries.pop(job_id, None)
        self._cond.notify_all()

    def tracked(self) -> List[Job]:
        with self._lock:
            return list(self._tracked.values())

    # ------------------------------------------------------------------ loop
    def _run(self) -> None:
        """(reference: periodic.go:302-326)"""
        while True:
            with self._lock:
                if not self._enabled:
                    return
                now = time.time()
                fire: List[str] = []
                while self._heap and self._heap[0][0] <= now:
                    launch_time, job_id = heapq.heappop(self._heap)
                    # Skip stale heap entries.
                    if self._heap_entries.get(job_id) != launch_time:
                        continue
                    del self._heap_entries[job_id]
                    fire.append(job_id)
                jobs = [(self._tracked[jid], now) for jid in fire
                        if jid in self._tracked]
                if not fire:
                    wait = (self._heap[0][0] - now) if self._heap else 1.0
                    self._cond.wait(timeout=min(max(wait, 0.01), 1.0))
            for job, launch_time in jobs:
                self._dispatch(job, launch_time)

    def _dispatch(self, job: Job, launch_time: float) -> None:
        """(reference: periodic.go:328-360)"""
        try:
            self.dispatch_job(job, launch_time)
        except Exception:
            logger.exception("periodic: dispatch failed for %s", job.ID)
        # Schedule the next launch.
        with self._lock:
            if job.ID in self._tracked:
                nxt = job.Periodic.next(launch_time)
                if nxt > 0:
                    self._heap_entries[job.ID] = nxt
                    heapq.heappush(self._heap, (nxt, job.ID))
                    self._cond.notify_all()

    def force_run(self, job_id: str) -> None:
        """(reference: periodic.go:274-298)"""
        with self._lock:
            job = self._tracked.get(job_id)
        if job is None:
            raise KeyError(f"periodic job not tracked: {job_id}")
        self._dispatch(job, time.time())


def derived_job_id(parent_id: str, launch_time: float) -> str:
    """(reference: periodic.go:400-410)"""
    return f"{parent_id}{PeriodicLaunchSuffix}{int(launch_time)}"


def derive_job(parent: Job, launch_time: float) -> Job:
    """Build the child job for one launch (reference: periodic.go:412-431)."""
    child = parent.copy()
    child.ID = derived_job_id(parent.ID, launch_time)
    child.Name = child.ID
    child.ParentID = parent.ID
    child.Periodic = None
    child.Status = ""
    child.StatusDescription = ""
    return child
