"""Heartbeats: leader-managed TTL timers per node (reference: nomad/heartbeat.go).

A node that misses its TTL is marked down, which triggers per-job
re-evaluations (node-update evals). The TTL is rate-scaled so heartbeat load
stays under max_heartbeats_per_second across the node count.
"""

from __future__ import annotations

import logging
import random
import threading
from typing import Callable, Dict

from nomad_tpu.analysis import guarded_by
from nomad_tpu.timerwheel import DaemonPool, TimerHandle, wheel

logger = logging.getLogger("nomad.heartbeat")

_EXPIRY_POOL: DaemonPool = None


def _expiry_pool() -> DaemonPool:
    global _EXPIRY_POOL
    if _EXPIRY_POOL is None:
        _EXPIRY_POOL = DaemonPool(8, "hb-expire")
    return _EXPIRY_POOL


class HeartbeatTimers:
    _concurrency = guarded_by("_lock", "_timers")

    def __init__(self, min_ttl: float = 10.0, grace: float = 10.0,
                 max_per_second: float = 50.0,
                 on_expire: Callable[[str], None] = lambda node_id: None):
        self.min_ttl = min_ttl
        self.grace = grace
        self.max_per_second = max_per_second
        self.on_expire = on_expire
        self._lock = threading.Lock()
        self._timers: Dict[str, TimerHandle] = {}

    def reset_heartbeat_timer(self, node_id: str) -> float:
        """Arm (or re-arm) the node's TTL; returns the TTL granted
        (reference: heartbeat.go:47-74)."""
        with self._lock:
            # Rate-scale the TTL by node count (heartbeat.go:52-54).
            n = len(self._timers) + 1
            ttl = max(self.min_ttl, n / self.max_per_second)
            # Jitter so heartbeats spread out.
            ttl += random.random() * ttl / 2
            existing = self._timers.get(node_id)
            if existing is not None:
                existing.cancel()
            self._timers[node_id] = wheel.after(
                ttl + self.grace, self._invalidate, node_id)
            return ttl

    def _invalidate(self, node_id: str) -> None:
        """TTL expired: node is presumed down (reference: heartbeat.go:76-107).
        The handler does a consensus write, so it runs on a dedicated pool —
        a partition expiring thousands of TTLs at once must not starve the
        shared timer wheel's callback workers (the reference runs each
        invalidation in its own goroutine, heartbeat.go:60)."""
        with self._lock:
            self._timers.pop(node_id, None)
        logger.warning("heartbeat: node %s TTL expired", node_id)
        _expiry_pool().submit(self._expire, node_id)

    def _expire(self, node_id: str) -> None:
        try:
            self.on_expire(node_id)
        except Exception:
            logger.exception("heartbeat: expiry handler failed for %s", node_id)

    def clear_heartbeat_timer(self, node_id: str) -> None:
        with self._lock:
            timer = self._timers.pop(node_id, None)
            if timer is not None:
                timer.cancel()

    def clear_all(self) -> None:
        with self._lock:
            for timer in self._timers.values():
                timer.cancel()
            self._timers.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._timers)
