"""ServerMembership: the gossip plane wired into the control plane.

This is the rebuild of nomad/serf.go + the membership halves of
nomad/leader.go and nomad/util.go:

- every server (all regions) joins ONE gossip pool and advertises itself
  through tags (reference: isNomadServer parsing serf.Member tags,
  nomad/util.go:Parts);
- member events maintain a per-region peer table that powers cross-region
  RPC forwarding (reference: s.peers map, nomad/server.go:100-104, consumed
  by forwardRegion nomad/rpc.go:223-242);
- events about same-region servers drive Raft membership: joins add peers,
  failures/leaves remove them (reference: reconcileMember,
  nomad/leader.go:421-459);
- bootstrap-expect: a virgin cluster forms once `expect` servers of the
  region have discovered each other (reference: maybeBootstrap,
  nomad/serf.go:80-139).
"""

from __future__ import annotations

import logging
import random
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional

from nomad_tpu.gossip import (
    EVENT_FAILED,
    EVENT_JOIN,
    EVENT_LEAVE,
    EVENT_UPDATE,
    GossipConfig,
    Member,
    Memberlist,
)
from nomad_tpu.raft import NotLeaderError
from nomad_tpu.rpc.pool import ConnError, ConnPool

LOG = logging.getLogger("nomad.membership")


@dataclass
class ServerParts:
    """Decoded view of one gossiped nomad server (reference:
    nomad/util.go serverParts)."""
    name: str          # gossip name: "<node>.<region>"
    node_name: str
    region: str
    datacenter: str
    rpc_addr: str      # host:port of the RPC/raft listener
    expect: int
    status: str

    @classmethod
    def from_member(cls, m: Member) -> Optional["ServerParts"]:
        if m.tags.get("role") != "nomad":
            return None
        try:
            return cls(
                name=m.name,
                node_name=m.tags.get("node", m.name),
                region=m.tags["region"],
                datacenter=m.tags.get("dc", ""),
                rpc_addr=m.tags["rpc"],
                expect=int(m.tags.get("expect", "0")),
                status=m.state,
            )
        except KeyError:
            return None


class ServerMembership:
    """Owns the Memberlist for one server and keeps its Raft peer set and
    region routing table in sync with the gossip view."""

    def __init__(self, server, rpc_addr: str,
                 node_name: str,
                 bind_addr: str = "127.0.0.1",
                 gossip_port: int = 0,
                 gossip_config: Optional[GossipConfig] = None,
                 reconcile_interval: float = 10.0,
                 tls_context=None):
        self.server = server
        self.rpc_addr = rpc_addr
        self.region = server.config.region
        self.node_name = node_name
        self.expect = server.config.bootstrap_expect
        # name is "<node>.<region>" so one WAN pool can hold every region
        # (reference: serf node naming in nomad/server.go setupSerf)
        self.gossip_name = f"{node_name}.{self.region}"

        self._lock = threading.RLock()
        # region -> gossip_name -> ServerParts (reference: s.peers)
        self.peers: Dict[str, Dict[str, ServerParts]] = {}
        self._bootstrapped = False
        self._pool = ConnPool(tls_context=tls_context)
        self._reconcile_interval = reconcile_interval
        self._wake = threading.Event()
        self._stop = threading.Event()

        tags = {
            "role": "nomad",
            "region": self.region,
            "dc": server.config.datacenter,
            "rpc": rpc_addr,
            "node": node_name,
            "expect": str(self.expect),
        }
        self.memberlist = Memberlist(
            self.gossip_name, bind_addr=bind_addr, port=gossip_port,
            tags=tags, config=gossip_config, on_event=self._on_event)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        self.memberlist.start()
        # Our own entry counts toward bootstrap-expect (a 1-expect server
        # bootstraps immediately, the dev/single-node path).
        self._absorb(self.memberlist.local_member())
        # Bootstrap probes and raft membership changes block (TCP + commit
        # waits), so they run on their own thread — never on the gossip UDP
        # receive path (reference: serf events feed a channel consumed by
        # the leader loop, nomad/leader.go:24-56).
        t = threading.Thread(target=self._reconcile_loop, daemon=True,
                             name=f"membership-{self.gossip_name}")
        t.start()
        self._wake.set()

    def join(self, seeds: List[str]) -> int:
        n = self.memberlist.join(seeds)
        if n:
            self._maybe_bootstrap()
            self.reconcile()
        return n

    def retry_join(self, seeds: List[str], interval: float = 5.0,
                   max_attempts: int = 0) -> None:
        """Keep trying the seed list until one join lands (reference:
        retry_join, command/agent/command.go retryJoin — which retries
        FOREVER by default; max_attempts=0 here does the same, a positive
        value bounds it for tests). Runs on its own daemon thread: joins
        block on TCP dials and on raft work, which must not occupy the
        shared timer wheel's callback workers."""
        def loop() -> None:
            attempt = 0
            while not self._stop.is_set():
                attempt += 1
                try:
                    if self.join(seeds) > 0:
                        return
                except Exception as exc:
                    LOG.debug("%s: join attempt %d raised: %s",
                              self.gossip_name, attempt, exc)
                if max_attempts and attempt >= max_attempts:
                    break
                # Log the first few and then once a minute: a seed that is
                # down for hours must not flood the log.
                if attempt <= 3 or attempt % max(1, int(60 / interval)) == 0:
                    LOG.info("%s: join %s failed (attempt %d); retrying "
                             "every %.0fs", self.gossip_name, seeds, attempt,
                             interval)
                if self._stop.wait(interval):
                    return
            LOG.warning("%s: giving up joining %s", self.gossip_name, seeds)

        threading.Thread(target=loop, daemon=True,
                         name=f"retry-join-{self.gossip_name}").start()

    def leave(self) -> None:
        self.memberlist.leave()
        self._stop.set()
        self._wake.set()

    def shutdown(self) -> None:
        self._stop.set()
        self._wake.set()
        self.memberlist.shutdown()
        self._pool.close()

    def _reconcile_loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(self._reconcile_interval)
            self._wake.clear()
            if self._stop.is_set():
                return
            try:
                self._maybe_bootstrap()
                self.reconcile()
            except Exception:
                LOG.exception("%s: reconcile pass failed", self.gossip_name)

    def force_leave(self, name: str) -> bool:
        return self.memberlist.force_leave(name)

    # -------------------------------------------------------------- queries
    def members(self) -> List[Dict[str, object]]:
        """CLI/API view in the reference's serf.Member shape (reference:
        agent members endpoint feeding `nomad server-members`). Addr/Port
        are the gossip socket; the RPC address rides in Tags["rpc"]."""
        out = []
        for m in self.memberlist.members():
            if m.tags.get("role") != "nomad":
                continue
            out.append({
                "Name": m.name, "Addr": m.addr, "Port": m.port,
                "Status": m.state, "Tags": dict(m.tags),
            })
        return sorted(out, key=lambda d: d["Name"])

    def region_router(self, region: str) -> Optional[str]:
        """Pick one live server of `region` for RPC forwarding (reference:
        forwardRegion's random pick, nomad/rpc.go:223-242)."""
        with self._lock:
            parts = [p for p in self.peers.get(region, {}).values()
                     if p.status in ("alive", "suspect")]
        if not parts:
            return None
        return random.choice(parts).rpc_addr

    def region_servers(self, region: str) -> List[str]:
        """Every live server addr of a region — the hardened region
        forwarder's candidate set (federation/routing.py tries them in
        breaker-admitted order instead of one random pick)."""
        with self._lock:
            addrs = [p.rpc_addr for p in self.peers.get(region, {}).values()
                     if p.status in ("alive", "suspect")]
        random.shuffle(addrs)  # spread forwards across region peers
        return addrs

    def region_lister(self) -> List[str]:
        with self._lock:
            return sorted(r for r, servers in self.peers.items() if servers)

    def poll_federation_health(self, health) -> None:
        """One poll round of every OTHER region's Federation.Health into
        the shared view (federation/qos.py). Called from the leader's
        federation loop; a region that doesn't answer simply ages out of
        the view (stale = assume healthy). The local region's entry is
        filled by the caller from its own broker — no RPC round trip."""
        for region in self.region_lister():
            if region == self.region:
                continue
            for addr in self.region_servers(region):
                try:
                    payload = self._pool.call(addr, "Federation.Health",
                                              {}, timeout=2.0)
                except (OSError, ConnError, TimeoutError) as exc:
                    LOG.debug("%s: federation health poll of %s (%s) "
                              "failed: %s", self.gossip_name, region,
                              addr, exc)
                    continue
                if payload:
                    health.update(region, payload)
                break

    def local_servers(self) -> List[ServerParts]:
        with self._lock:
            return [p for p in self.peers.get(self.region, {}).values()
                    if p.status in ("alive", "suspect")]

    # --------------------------------------------------------------- events
    def _on_event(self, event: str, member: Member) -> None:
        parts = ServerParts.from_member(member)
        if parts is None:
            return
        if event in (EVENT_JOIN, EVENT_UPDATE):
            LOG.info("%s: server %s %s (region %s, rpc %s)", self.gossip_name,
                     parts.name, event, parts.region, parts.rpc_addr)
            self._absorb_parts(parts)
        elif event in (EVENT_FAILED, EVENT_LEAVE):
            LOG.info("%s: server %s %s", self.gossip_name, parts.name, event)
            with self._lock:
                region = self.peers.get(parts.region, {})
                if parts.name in region:
                    region[parts.name].status = "failed"
        # Kick the reconcile thread; membership work must not run on the
        # gossip receive thread that delivered this event.
        self._wake.set()

    def _absorb(self, member: Member) -> None:
        parts = ServerParts.from_member(member)
        if parts is not None:
            self._absorb_parts(parts)

    def _absorb_parts(self, parts: ServerParts) -> None:
        with self._lock:
            self.peers.setdefault(parts.region, {})[parts.name] = parts

    # ------------------------------------------------------------ raft glue
    def _maybe_bootstrap(self) -> None:
        """(reference: maybeBootstrap, nomad/serf.go:80-139)"""
        if self.expect <= 0:
            return
        with self._lock:
            if self._bootstrapped:
                return
        # If our own raft already carries a cluster — a log, a snapshot, or
        # an explicit configuration (a leader's Config entry admitted us
        # while we were still counting expect-peers) — bootstrap is moot:
        # latch and stop probing.
        raft = self.server.raft
        if hasattr(raft, "stats"):
            st = raft.stats()
            if (st.get("last_log_index", 0) > 0
                    or st.get("snapshot_index", 0) > 0
                    or st.get("configured")):
                with self._lock:
                    self._bootstrapped = True
                return
        with self._lock:
            if self._bootstrapped:
                return
            local = [p for p in self.peers.get(self.region, {}).values()
                     if p.status in ("alive", "suspect")]
            # All discovered servers must agree on the expect count
            # (reference: serf.go:104-117 bails on mismatch).
            if any(p.expect != self.expect for p in local):
                LOG.warning("%s: bootstrap_expect mismatch among %s",
                            self.gossip_name,
                            [(p.name, p.expect) for p in local])
                return
            if len(local) < self.expect:
                return
            addrs = sorted(p.rpc_addr for p in local)
            others = [p.rpc_addr for p in local
                      if p.rpc_addr != self.rpc_addr]
        # Before forming a NEW cluster, ask every discovered server whether
        # one already exists — a virgin late-joiner must never re-bootstrap
        # a live cluster (reference: maybeBootstrap probes peers' raft
        # status, nomad/serf.go:104-130). Probe failures abort the attempt;
        # the next reconcile tick retries.
        for addr in others:
            try:
                resp = self._pool.call(addr, "Status.RaftStats", {},
                                       timeout=2.0)
            except (OSError, ConnError, TimeoutError) as exc:
                LOG.info("%s: bootstrap probe of %s failed (%s); deferring",
                         self.gossip_name, addr, exc)
                return
            if resp.get("Bootstrapped"):
                # Do NOT latch _bootstrapped here: that cluster's leader
                # will admit us via reconcile → Config entry, and the
                # own-raft check above latches once it does. Latching on a
                # probe answer wedged round 3 — a wrong "true" (or a
                # cluster that dies before adding us) would leave this
                # node permanently unelectable.
                LOG.info("%s: existing cluster found at %s; waiting to be "
                         "added instead of bootstrapping", self.gossip_name,
                         addr)
                return
        with self._lock:
            if self._bootstrapped:
                return
            self._bootstrapped = True
        raft = self.server.raft
        if hasattr(raft, "bootstrap_cluster"):
            if raft.bootstrap_cluster(addrs):
                LOG.info("%s: bootstrapped raft with %s", self.gossip_name,
                         addrs)

    def reconcile(self) -> None:
        """Leader-only: converge the Raft peer set to the gossip view of the
        local region (reference: reconcileMember, nomad/leader.go:421-459).
        Safe to call from any server/thread; non-leaders no-op."""
        raft = self.server.raft
        if not hasattr(raft, "add_peer") or not raft.is_leader():
            return
        with self._lock:
            local = dict(self.peers.get(self.region, {}))
        want = {p.rpc_addr for p in local.values()
                if p.status in ("alive", "suspect")}
        want.add(self.rpc_addr)
        have = set(raft.peers)
        try:
            for addr in sorted(want - have):
                LOG.info("%s: adding raft peer %s", self.gossip_name, addr)
                raft.add_peer(addr)
            dead = {p.rpc_addr for p in local.values()
                    if p.status not in ("alive", "suspect")}
            for addr in sorted((have - want) & dead):
                LOG.info("%s: removing raft peer %s", self.gossip_name, addr)
                raft.remove_peer(addr)
            self._prune_server_services(dead)
        except NotLeaderError:
            pass  # lost leadership mid-reconcile; next leader redoes it
        except Exception:
            LOG.exception("%s: reconcile failed", self.gossip_name)

    def _prune_server_services(self, dead_addrs: set) -> None:
        """Drop dead servers' "nomad-server" registry entries so clients
        bootstrapping via discovery stop receiving their addresses (crashed
        servers can't deregister themselves the way a graceful shutdown
        does — agent.shutdown)."""
        if not dead_addrs:
            return
        stale = [reg.ID
                 for reg in self.server.state.services_by_name("nomad-server")
                 if reg.NodeID in dead_addrs]
        if stale:
            LOG.info("%s: pruning service registrations of dead servers: %s",
                     self.gossip_name, stale)
            self.server.service_sync([], stale)
