"""Plan applier: THE serialization point (reference: nomad/plan_apply.go).

Dequeues pending plans, verifies every placement against a state snapshot,
computes partial commits + RefreshIndex, applies through the consensus
backend, and responds to the waiting worker. The reference overlaps Raft
apply of plan N with verification of plan N+1 via an optimistic snapshot
(plan_apply.go:24-33); here the apply backend is pluggable. Verification is
host-side: a plan touches only its own nodes, and the check needs exact
port-level network accounting (structs.allocs_fit), so there's nothing hot
to tensorize.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from nomad_tpu.structs import (
    Allocation,
    Plan,
    PlanResult,
    allocs_fit,
    remove_allocs,
)
from nomad_tpu.structs.structs import NodeStatusReady

from .eval_broker import EvalBroker
from .fsm import DevRaft, MessageType
from .plan_queue import PendingPlan, PlanQueue

logger = logging.getLogger("nomad.plan_apply")

def evaluate_plan(snap, plan: Plan) -> PlanResult:
    """Per-node fit re-check of a plan (reference: plan_apply.go:194-316)."""
    result = PlanResult()
    node_ids = list(dict.fromkeys(list(plan.NodeUpdate) + list(plan.NodeAllocation)))

    partial_commit = False
    for node_id in node_ids:
        fit = _evaluate_node_plan(snap, plan, node_id)
        if not fit:
            partial_commit = True
            if plan.AllAtOnce:
                result.NodeUpdate = {}
                result.NodeAllocation = {}
                break
            continue
        if plan.NodeUpdate.get(node_id):
            result.NodeUpdate[node_id] = plan.NodeUpdate[node_id]
        if plan.NodeAllocation.get(node_id):
            result.NodeAllocation[node_id] = plan.NodeAllocation[node_id]

    if partial_commit:
        result.RefreshIndex = max(snap.get_index("nodes"),
                                  snap.get_index("allocs"))
    return result


def _evaluate_node_plan(snap, plan: Plan, node_id: str) -> bool:
    """(reference: plan_apply.go:318-361)"""
    if not plan.NodeAllocation.get(node_id):
        return True  # evict-only always fits
    node = snap.node_by_id(node_id)
    if node is None or node.Status != NodeStatusReady or node.Drain:
        return False
    existing = snap.allocs_by_node_terminal(node_id, False)
    remove: List[Allocation] = list(plan.NodeUpdate.get(node_id, ()))
    remove.extend(plan.NodeAllocation.get(node_id, ()))
    proposed = remove_allocs(list(existing), remove)
    proposed.extend(plan.NodeAllocation.get(node_id, ()))
    try:
        fit, _, _ = allocs_fit(node, proposed)
    except ValueError:
        return False
    return fit


class PlanApplier:
    """The leader's plan-apply loop (reference: plan_apply.go:41-119)."""

    def __init__(self, plan_queue: PlanQueue, raft: DevRaft,
                 eval_broker: Optional[EvalBroker] = None):
        self.plan_queue = plan_queue
        self.raft = raft
        self.eval_broker = eval_broker
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(target=self.run, daemon=True,
                                        name="plan-apply")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def run(self) -> None:
        while not self._stop.is_set():
            try:
                pending = self.plan_queue.dequeue(timeout=0.5)
            except RuntimeError:
                return  # queue disabled
            if pending is None:
                continue
            self.apply_one(pending)

    def apply_one(self, pending: PendingPlan) -> None:
        plan = pending.plan

        # Token check: the eval must still be outstanding to its worker
        # (anti split-brain, reference: plan_apply.go:62-78).
        if self.eval_broker is not None:
            token = self.eval_broker.outstanding(plan.EvalID)
            if token is None or (plan.EvalToken and token != plan.EvalToken):
                pending.respond(None, RuntimeError(
                    f"plan for evaluation {plan.EvalID} has stale token"))
                return

        snap = self.raft.fsm.state.snapshot()
        try:
            result = evaluate_plan(snap, plan)
        except Exception as e:  # verification error: reject the plan
            pending.respond(None, e)
            return

        if result.NodeUpdate or result.NodeAllocation:
            index = self._apply(plan, result)
            result.AllocIndex = index
        pending.respond(result, None)

    def _apply(self, plan: Plan, result: PlanResult) -> int:
        """Commit the verified subset through consensus
        (reference: plan_apply.go:122-164 applyPlan)."""
        allocs: List[Allocation] = []
        for updates in result.NodeUpdate.values():
            allocs.extend(updates)
        for placed in result.NodeAllocation.values():
            allocs.extend(placed)
        return self.raft.apply(MessageType.AllocUpdate, {
            "Job": plan.Job,
            "Alloc": allocs,
        })
