"""Plan applier: THE serialization point (reference: nomad/plan_apply.go).

Dequeues pending plans, verifies every placement against a state snapshot,
computes partial commits + RefreshIndex, applies through the consensus
backend, and responds to the waiting worker.

Two reference optimizations are mirrored here:

- **Overlapped apply** (plan_apply.go:24-33): while plan N's Raft apply is in
  flight, plan N+1 is verified against an OPTIMISTIC snapshot that assumes N
  committed. Productive work happens during consensus latency; the waiter is
  answered asynchronously only after the log really commits.
- **Evaluate pool** (plan_apply_pool.go:38): per-node verification of large
  plans fans out over a thread pool — each node's check is independent.

Verification reads the node tensor: placements without network asks fit-check
as one vector comparison against committed usage (+ the optimistic in-flight
overlay); only nodes needing exact port/bandwidth bitmap accounting
(structs.allocs_fit) take the per-node object path.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from nomad_tpu.resilience import failpoints
from nomad_tpu.tensor.node_table import RES_DIMS, alloc_vec
from nomad_tpu.structs import (
    Allocation,
    Plan,
    PlanResult,
    allocs_fit,
    remove_allocs,
)
from nomad_tpu.structs.structs import NodeStatusReady
from nomad_tpu.telemetry import metrics, trace

from .eval_broker import EvalBroker
from .fsm import DevRaft, MessageType
from .plan_queue import PendingPlan, PlanQueue

logger = logging.getLogger("nomad.plan_apply")

# Below this many touched nodes a plan is verified inline: thread fan-out
# costs more than it saves (reference: pool used unconditionally, but Go
# goroutines are cheaper than pool dispatch here).
_POOL_THRESHOLD = 8

# Max verified plans committed as one consensus entry. Bounds the entry size
# (reference warns at 1MB raft entries, rpc.go:45-47: 16 x 50-alloc plans
# stays well under) and the blast radius of a failed group apply.
_APPLY_BATCH = 16


def _result_allocs(result: "PlanResult") -> List[Allocation]:
    # NodeUpdate (evictions/stops) precede NodeAllocation deliberately:
    # the FSM upserts in list order, so within one commit the state store
    # observes stop-then-place — a preemption's victims are terminal
    # before its placement lands.
    allocs: List[Allocation] = []
    for updates in result.NodeUpdate.values():
        allocs.extend(updates)
    for placed in result.NodeAllocation.values():
        allocs.extend(placed)
    return allocs


def _encode_result(plan: Plan, result: "PlanResult"):
    """One consensus-entry group element for a verified result. A result
    carrying a full-coverage columnar SweepBatch encodes as ONE columnar
    payload (ids + instance names + frozen per-TG templates + per-row
    delta) — not N alloc dicts; its exact-path stops ride the same
    element (`Updates`) so eviction+placement stay one atomic entry.
    Returns (element, is_sweep)."""
    sweep = getattr(result, "_sweep", None)
    if sweep is not None and getattr(sweep, "alloc_ids", None):
        updates: List[Allocation] = []
        for ups in result.NodeUpdate.values():
            updates.extend(ups)
        element = {"Job": plan.Job, "Sweep": sweep.wire()}
        if updates:
            element["Updates"] = updates
        return element, True
    return {"Job": plan.Job, "Alloc": _result_allocs(result)}, False


def _fire_store_commit() -> None:
    """Failure seam: a consensus entry carrying a columnar sweep batch.
    Fires BEFORE raft.apply (like plan.apply.commit), so a killed bulk
    commit never enters the durable log — the waiting workers nack, the
    broker redelivers exactly once, and no replica (or log replay) can
    ever land the killed batch: all rows or none, never torn. Firing
    post-consensus instead would leave the entry in the log and
    duplicate the batch on replay."""
    if failpoints.fire("state.store.commit") == "drop":
        raise failpoints.FailpointError("state.store.commit")


def _fire_preempt_commit(plans) -> None:
    """Failure seam: a consensus commit carrying alloc preemptions. Like
    plan.apply.commit, drop degrades to a failed apply — the waiting
    workers nack, the broker redelivers, and because evictions and their
    placement ride ONE entry, a killed commit loses both or neither."""
    if any(getattr(p, "_preempt", None) for p in plans):
        if failpoints.fire("plan.preempt.commit") == "drop":
            raise failpoints.FailpointError("plan.preempt.commit")


class OptimisticSnapshot:
    """A read view layering not-yet-committed plan results over a state
    snapshot (reference: snap.UpsertAllocs after raft dispatch,
    plan_apply.go:152-158). Supports exactly the reads evaluate_plan needs.

    When built with the node tensor it additionally keeps a per-row usage
    delta of the in-flight result so the vectorized verifier can fit-check
    against (committed usage + in-flight overlay) without re-walking
    allocation objects."""

    def __init__(self, snap, nt=None):
        self.snap = snap
        self.nt = nt
        self._added: Dict[str, List[Allocation]] = {}
        self._removed: Set[str] = set()
        self.row_delta: Dict[int, np.ndarray] = {}
        # Dense in-flight usage overlay, allocated lazily by the first
        # SWEEP result (a system sweep's 10k placements would otherwise
        # become 10k per-row dict entries built one _overlay call at a
        # time). Readers treat it as an additive sibling of row_delta.
        self.row_dense: Optional[np.ndarray] = None

    def apply_result(self, result: PlanResult) -> None:
        for updates in result.NodeUpdate.values():
            for a in updates:
                self._removed.add(a.ID)
        sweep = getattr(result, "_sweep", None)
        if (sweep is not None and self.nt is not None
                and sweep.n_rows == self.nt.n_rows
                and sweep.epoch == self.nt.row_epoch):
            # Columnar sweep result: ONE scatter-add replaces the
            # per-alloc row overlay. The descriptor covers every
            # NodeAllocation key (evaluate_plan only attaches it then),
            # so nothing is missed; _added is still filled per node — the
            # exact verify path of a LATER plan in the group reads it.
            if self.row_dense is None:
                self.row_dense = np.zeros((self.nt.n_rows, RES_DIMS),
                                          dtype=np.float32)
            elif self.row_dense.shape[0] < sweep.n_rows:
                # Table grew since the overlay was allocated; row indices
                # are stable across growth, so zero-extend.
                grown = np.zeros((sweep.n_rows, RES_DIMS), dtype=np.float32)
                grown[:self.row_dense.shape[0]] = self.row_dense
                self.row_dense = grown
            np.add.at(self.row_dense, sweep.rows, sweep.delta)
            for node_id, placed in result.NodeAllocation.items():
                self._added.setdefault(node_id, []).extend(placed)
            return
        for node_id, placed in result.NodeAllocation.items():
            self._added.setdefault(node_id, []).extend(placed)
            for a in placed:
                self._overlay(node_id, a)

    def _overlay(self, node_id: str, alloc: Allocation) -> None:
        """Record an in-flight PLACEMENT in the row overlay. Deliberately
        one-sided: in-flight EVICTIONS are never credited, because the live
        tensor may absorb the in-flight commit mid-verify and crediting the
        eviction twice would understate usage (over-commit). The one-sided
        overlay only ever OVERSTATES usage — worst case a spurious partial
        commit, which the worker resolves through the exact per-eval path."""
        if self.nt is None:
            return
        row = self.nt.row_of.get(node_id)
        if row is None:
            return
        cur = self.row_delta.get(row)
        if cur is None:
            cur = self.row_delta[row] = np.zeros(RES_DIMS, dtype=np.float32)
        cur += alloc_vec(alloc)

    def node_by_id(self, node_id: str):
        return self.snap.node_by_id(node_id)

    def alloc_by_id(self, alloc_id: str):
        return self.snap.alloc_by_id(alloc_id)

    def allocs_by_node_terminal(self, node_id: str, terminal: bool):
        out = [a for a in self.snap.allocs_by_node_terminal(node_id, terminal)
               if a.ID not in self._removed]
        if not terminal:
            out.extend(self._added.get(node_id, ()))
        return out

    def get_index(self, table: str) -> int:
        return self.snap.get_index(table)


def _alloc_asks_network(alloc: Allocation) -> bool:
    if alloc.Resources is not None and alloc.Resources.Networks:
        return True
    for r in alloc.TaskResources.values():
        if r is not None and r.Networks:
            return True
    return False


def _vector_fit(snap, plan: Plan, nt, node_ids: List[str]
                ) -> Tuple[Dict[str, bool], List[str]]:
    """Vectorized fit pre-pass over the node tensor: nodes whose placements
    ask no network resources fit-check as ONE numpy comparison against
    committed usage (+ the optimistic in-flight overlay) instead of per-alloc
    object math. Returns (decided fits, nodes needing the exact path).

    This is the TPU-framework shape of the applier: commit-side verification
    reads the same tensor mirror the placement kernels run on, so a 50-node
    plan verifies in ~one vector op and the applier stops competing with the
    scheduler for interpreter time. Port/bandwidth-device accounting can't
    vectorize (exact bitmap semantics) — those nodes take the exact path."""
    fits: Dict[str, bool] = {}
    exact: List[str] = []
    rows: List[int] = []
    row_ids: List[str] = []
    deltas: List[np.ndarray] = []
    overlay = getattr(snap, "row_delta", None) or {}
    dense = getattr(snap, "row_dense", None)
    # Row indices are STABLE across table growth (_grow only extends), so
    # a dense overlay allocated before a grow stays valid for its rows;
    # rows beyond its bound were grown later and legitimately carry zero
    # in-flight delta. Reads below bound-check instead of assuming the
    # shapes match.
    n_dense = dense.shape[0] if dense is not None else 0

    sweep = getattr(plan, "_sweep", None)
    if (sweep is not None and len(sweep.rows)
            and sweep.epoch == nt.row_epoch and sweep.n_rows == nt.n_rows):
        # Columnar sweep verify: the whole batch is ONE vectorized
        # capacity check — fresh-UUID, no-network placements with their
        # per-row demand precomputed at emit, so the per-node delta
        # assembly loop below has nothing left to derive. Readiness comes
        # from the tensor mirror, which is updated synchronously at state
        # commit and therefore at least as fresh as any snapshot; a row
        # whose identity moved since emit invalidates the descriptor
        # (epoch guard) and falls back to the per-node walk.
        srows = sweep.rows
        d = sweep.delta.astype(np.float32, copy=True)
        if dense is not None:
            in_bound = srows < n_dense
            if in_bound.all():
                d += dense[srows]
            elif in_bound.any():
                d[in_bound] += dense[srows[in_bound]]
        for row, vec in overlay.items():
            i = int(np.searchsorted(srows, row))
            if i < len(srows) and srows[i] == row:
                d[i] += vec
        usage, capacity = nt.snapshot_rows(srows)
        ok = nt.ready[srows] & np.all(usage + d <= capacity, axis=1)
        for nid, fit in zip(sweep.node_ids, ok.tolist()):
            fits[nid] = fit
        metrics.incr_counter(("nomad", "sched", "system", "bulk_verify"))

    for nid in node_ids:
        if nid in fits:
            continue
        placed = plan.NodeAllocation.get(nid)
        if not placed:
            fits[nid] = True  # evict-only always fits
            continue
        node = snap.node_by_id(nid)
        if node is None or node.Status != NodeStatusReady or node.Drain:
            fits[nid] = False
            continue
        row = nt.row_of.get(nid)
        if row is None:
            exact.append(nid)
            continue
        delta = np.zeros(RES_DIMS, dtype=np.float32)
        simple = True
        for a in placed:
            # Port asks need bitmap accounting; an alloc replacing a live
            # version of itself (in-place update) needs remove-then-add.
            if _alloc_asks_network(a):
                simple = False
                break
            prev = snap.alloc_by_id(a.ID)
            if prev is not None and not prev.terminal_status():
                simple = False
                break
            delta += alloc_vec(a)
        if not simple:
            exact.append(nid)
            continue
        for a in plan.NodeUpdate.get(nid, ()):
            full = snap.alloc_by_id(a.ID) or a
            if not full.terminal_status():
                delta -= alloc_vec(full)
        ov = overlay.get(row)
        if ov is not None:
            delta += ov
        if dense is not None and row < n_dense:
            delta += dense[row]
        rows.append(row)
        row_ids.append(nid)
        deltas.append(delta)
    if rows:
        r = np.asarray(rows, dtype=np.int64)
        d = np.stack(deltas)
        # Row copies under the tensor lock: alloc commits mutate usage rows
        # in place, and a torn row read mid-`+=` could mis-admit a placement.
        usage, capacity = nt.snapshot_rows(r)
        ok = np.all(usage + d <= capacity, axis=1)
        for nid, fit in zip(row_ids, ok):
            fits[nid] = bool(fit)
    return fits, exact


def evaluate_plan(snap, plan: Plan,
                  pool: Optional[ThreadPoolExecutor] = None,
                  nt=None) -> PlanResult:
    """Per-node fit re-check of a plan (reference: plan_apply.go:194-316).
    With the node tensor, no-port placements verify as one vector op; with a
    pool, remaining exact node checks run in parallel (plan_apply_pool.go)."""
    result = PlanResult()
    node_ids = list(dict.fromkeys(list(plan.NodeUpdate) + list(plan.NodeAllocation)))

    decided: Dict[str, bool] = {}
    exact_ids = node_ids
    if nt is not None:
        decided, exact_ids = _vector_fit(snap, plan, nt, node_ids)

    if pool is not None and len(exact_ids) >= _POOL_THRESHOLD:
        # Chunked fan-out: one pool task per worker, not per node — pool
        # dispatch overhead is comparable to a single node check, so per-node
        # submission would spend more time queueing than verifying.
        workers = getattr(pool, "_max_workers", 4)
        step = max(1, -(-len(exact_ids) // workers))
        chunks = [exact_ids[i:i + step] for i in range(0, len(exact_ids), step)]
        fits_chunks = pool.map(
            lambda chunk: [_evaluate_node_plan(snap, plan, nid)
                           for nid in chunk], chunks)
        for chunk, chunk_fits in zip(chunks, fits_chunks):
            decided.update(zip(chunk, chunk_fits))
    else:
        for nid in exact_ids:
            decided[nid] = _evaluate_node_plan(snap, plan, nid)

    preempt = getattr(plan, "_preempt", None)
    if preempt:
        # Preemption atomicity, belt-and-braces: a preempting node's
        # evictions must NEVER commit without their placement. The
        # per-node verify already drops both sides of a node together;
        # this guards a malformed plan (evictions recorded, placement
        # stripped) from riding the evict-only-always-fits rule — on
        # BOTH the wholesale-admit and the partial paths below.
        for nid in preempt:
            if decided.get(nid) and not plan.NodeAllocation.get(nid):
                decided[nid] = False

    if decided and len(decided) == len(node_ids) \
            and all(decided.values()):
        # Everything fits (the healthy-sweep common case): admit the plan
        # wholesale instead of re-walking 10k node ids to copy dict
        # entries one at a time. A full-coverage sweep descriptor rides
        # the result so the optimistic overlay applies it as one scatter.
        result.NodeUpdate = dict(plan.NodeUpdate)
        result.NodeAllocation = dict(plan.NodeAllocation)
        sweep = getattr(plan, "_sweep", None)
        if sweep is not None \
                and len(sweep.node_ids) == len(plan.NodeAllocation):
            result._sweep = sweep
        return result

    partial_commit = False
    for node_id in node_ids:
        fit = decided[node_id]
        if not fit:
            partial_commit = True
            if plan.AllAtOnce:
                result.NodeUpdate = {}
                result.NodeAllocation = {}
                break
            continue
        if plan.NodeUpdate.get(node_id):
            result.NodeUpdate[node_id] = plan.NodeUpdate[node_id]
        if plan.NodeAllocation.get(node_id):
            result.NodeAllocation[node_id] = plan.NodeAllocation[node_id]

    if partial_commit:
        result.RefreshIndex = max(snap.get_index("nodes"),
                                  snap.get_index("allocs"))
    return result


def _evaluate_node_plan(snap, plan: Plan, node_id: str) -> bool:
    """(reference: plan_apply.go:318-361)"""
    if not plan.NodeAllocation.get(node_id):
        return True  # evict-only always fits
    node = snap.node_by_id(node_id)
    if node is None or node.Status != NodeStatusReady or node.Drain:
        return False
    existing = snap.allocs_by_node_terminal(node_id, False)
    remove: List[Allocation] = list(plan.NodeUpdate.get(node_id, ()))
    remove.extend(plan.NodeAllocation.get(node_id, ()))
    proposed = remove_allocs(list(existing), remove)
    proposed.extend(plan.NodeAllocation.get(node_id, ()))
    try:
        fit, _, _ = allocs_fit(node, proposed)
    except ValueError:
        return False
    return fit


class PlanApplier:
    """The leader's plan-apply loop with verify/apply overlap
    (reference: planApply, plan_apply.go:41-119).

    Concurrency note (why no guarded_by registry here): the applier's
    mutable state is confined by protocol, not by a lock. The run loop
    owns verify-side stats keys; the single in-flight apply thread owns
    apply-side keys (`applied`/`apply_failed`/`t_apply_ms`); the run
    loop only reads apply-side keys after `wait.join()`, which is the
    happens-before edge. At most one apply thread exists at a time."""

    def __init__(self, plan_queue: PlanQueue, raft: DevRaft,
                 eval_broker: Optional[EvalBroker] = None,
                 pool_size: Optional[int] = None, tindex=None,
                 qos_counters=None, fed=None):
        self.plan_queue = plan_queue
        self.raft = raft
        self.eval_broker = eval_broker
        self.tindex = tindex
        # FederationConfig (None = federation off): plans stamped with a
        # snapshot birth time (`_fed_born`, worker-side) older than
        # fed.reject_after_s at verify time are rejected outright — the
        # Omega staleness backstop (see federation/snapshots.py).
        self.fed = fed
        # QoS flow counters (qos/tiers.py QoSCounters): preempt_placed /
        # preempt_evictions are counted HERE, at commit, so rejected
        # preemption plans never inflate the "landed" numbers.
        self.qos_counters = qos_counters
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._retired: List[threading.Thread] = []
        self._pool_size = pool_size or max(1, (os.cpu_count() or 2) // 2)
        self._pool: Optional[ThreadPoolExecutor] = None
        # Counters for telemetry/tests (t_* wall-clock; under GIL
        # contention these overcount serialized python, like the worker's).
        self.stats = {"applied": 0, "rejected": 0, "overlapped": 0,
                      "apply_failed": 0, "t_verify_ms": 0.0,
                      "t_apply_ms": 0.0}

    def _nt(self):
        return self.tindex.nt if self.tindex is not None else None

    def _count_preempt(self, plan: Plan, result: PlanResult) -> None:
        """Count preemption outcomes that actually COMMITTED: placements
        on preempting nodes that survived verification, and the victim
        evictions that rode them."""
        descriptor = getattr(plan, "_preempt", None)
        if not descriptor:
            return
        counts = getattr(plan, "_preempt_counts", None) or {}
        placed = evicted = 0
        for node_id, victim_ids in descriptor.items():
            landed = result.NodeAllocation.get(node_id)
            if landed:
                # Only the instances placed VIA preemption count — the
                # node may also carry the plan's normal placements.
                placed += min(counts.get(node_id, len(landed)),
                              len(landed))
                committed = {a.ID for a in result.NodeUpdate.get(node_id,
                                                                 ())}
                evicted += sum(1 for v in victim_ids if v in committed)
        if not placed:
            return
        if self.qos_counters is not None:
            self.qos_counters.incr("preempt_placed", placed)
            self.qos_counters.incr("preempt_evictions", evicted)
        metrics.incr_counter(("nomad", "qos", "preempt", "placed"), placed)
        metrics.incr_counter(("nomad", "qos", "preempt", "evictions"),
                             evicted)

    def start(self) -> None:
        """Each run gets its OWN stop event, handed to the thread — a
        leadership flap that calls stop();start() must not revive the old
        run by clearing a shared flag (two live appliers would break the
        one-apply-in-flight invariant and could over-commit). The new run
        serializes behind the old thread before consuming the queue, and
        the old thread is retired for join() so shutdown still reaps it."""
        prev = self._thread
        if prev is not None and prev.is_alive():
            self._retired.append(prev)
        self._retired = [t for t in self._retired if t.is_alive()]
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self.run, args=(self._stop, prev), daemon=True,
            name="plan-apply")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def join(self, timeout: float = 30.0) -> None:
        """The apply path commits plan results into the tensor index (JAX
        device arrays); an unjoined thread there at interpreter exit
        aborts XLA teardown. Joins retired (flap-era) runs too."""
        deadline = time.monotonic() + timeout
        for t in [*self._retired, self._thread]:
            if (t is not None and t.is_alive()
                    and t is not threading.current_thread()):
                t.join(max(0.1, deadline - time.monotonic()))

    def run(self, stop: Optional[threading.Event] = None,
            prev: Optional[threading.Thread] = None) -> None:
        stop = stop if stop is not None else self._stop
        if prev is not None and prev.is_alive():
            # One applier at a time: wait out the previous run's last
            # iteration (bounded by its 0.5s dequeue poll + in-flight
            # apply) before touching the queue.
            prev.join(timeout=60.0)
        self._pool = ThreadPoolExecutor(max_workers=self._pool_size,
                                        thread_name_prefix="plan-eval")
        # One in-flight raft apply at a time; while it commits, the NEXT
        # GROUP of plans verifies against `opt`, an optimistic view that
        # assumes it landed. Plans queued back-to-back (a worker window
        # submitting its plans) verify against the chained overlay and
        # commit as ONE log entry / state transaction (fsm Batch shape) —
        # the reference overlaps verify with apply latency
        # (plan_apply.go:24-33); here apply is also CPU on this core, so
        # grouping cuts the work itself, not just the wait.
        wait: Optional[threading.Thread] = None
        opt: Optional[OptimisticSnapshot] = None
        try:
            while not stop.is_set():
                try:
                    pending = self.plan_queue.dequeue(timeout=0.5)
                    batch = [pending] if pending is not None else []
                    if pending is not None and len(batch) < _APPLY_BATCH:
                        # ONE lock hold drains the rest of the group:
                        # workers enqueue whole windows atomically
                        # (PlanQueue.enqueue_all), so the group is either
                        # already there or not coming this iteration —
                        # per-plan timed dequeues only convoyed the lock
                        # against concurrently submitting workers.
                        batch.extend(self.plan_queue.dequeue_ready(
                            _APPLY_BATCH - len(batch)))
                except RuntimeError:
                    return  # queue disabled
                live = []
                for p in batch:
                    if p.cancelled:
                        # Abandoned chunk (its submitter's earlier chunk
                        # failed): answer the future, commit nothing.
                        p.respond(None, RuntimeError("plan cancelled"))
                    else:
                        live.append(p)
                batch = live
                if not batch:
                    continue

                # Last apply already done? Fall back to a fresh snapshot.
                if wait is not None and not wait.is_alive():
                    wait.join()
                    wait = None
                    opt = None
                # The optimistic view is only valid WHILE an apply is in
                # flight; with nothing outstanding, always verify against
                # fresh state (matches plan_apply.go:71-79's `waitCh == nil`
                # refresh — an old view could miss a node going down).
                if wait is None or opt is None:
                    opt = OptimisticSnapshot(self.raft.fsm.state.snapshot(),
                         nt=self._nt())

                def resync():
                    # Spurious-partial guard: the one-sided overlay can
                    # double-count the in-flight group once its commit
                    # lands in the live tensor mid-verify. A plan that
                    # verifies PARTIAL while an apply is outstanding gets
                    # one re-verify against settled state — a genuine
                    # overcommit still fails, a double-count victim passes
                    # instead of bouncing its whole eval through the
                    # worker's exact-path fallback (and the chain rebase
                    # stall that follows it). Also reports whether the
                    # joined apply FAILED: verdicts that assumed it landed
                    # (e.g. its evictions) are then stale, and the caller
                    # must re-verify them — setting wait=None here skips
                    # the run loop's own apply_failed re-check.
                    nonlocal wait
                    failed_before = self.stats["apply_failed"]
                    if wait is not None:
                        wait.join()
                        wait = None
                    return (OptimisticSnapshot(
                                self.raft.fsm.state.snapshot(),
                                nt=self._nt()),
                            self.stats["apply_failed"] != failed_before)

                group, opt = self._verify_group(
                    batch, opt, overlapped=wait is not None, resync=resync)
                if not group:
                    continue

                # One apply in flight at a time: wait for the previous one,
                # then re-snapshot so the optimistic view can't drift more
                # than one group from the log (plan_apply.go:96-103).
                if wait is not None:
                    prev_failed_before = self.stats["apply_failed"]
                    wait.join()
                    opt = OptimisticSnapshot(self.raft.fsm.state.snapshot(),
                         nt=self._nt())
                    if self.stats["apply_failed"] != prev_failed_before:
                        # The apply this group's verification assumed never
                        # landed (e.g. its evictions); re-verify against the
                        # real state before committing.
                        group, opt = self._verify_group(
                            [p for p, _ in group], opt, overlapped=False)
                        if not group:
                            wait = None
                            continue
                    else:
                        # Fresh snapshot lacks this group's own results:
                        # restore them to the overlay. (When no apply was in
                        # flight, _verify_group already layered them.)
                        for _, result in group:
                            opt.apply_result(result)

                wait = threading.Thread(
                    target=self._apply_group, args=(group,),
                    daemon=True, name="plan-apply-async")
                wait.start()
        finally:
            if wait is not None:
                wait.join()
            # Pool work is synchronous within _verify, so the pool is idle
            # here; wait=True is immediate and leaves no worker for the
            # interpreter-exit join to trip over.
            self._pool.shutdown(wait=True)
            self._pool = None

    def _verify_group(self, batch: List[PendingPlan],
                      opt: OptimisticSnapshot, overlapped: bool,
                      resync=None
                      ) -> Tuple[List[Tuple[PendingPlan, PlanResult]],
                                 OptimisticSnapshot]:
        """Verify plans in queue order against the shared overlay; each
        admitted plan's result is layered into `opt` so the next plan of the
        group sees it (the group analogue of the single-plan chain). No-op
        results respond immediately; rejected plans were answered by
        _verify. A PARTIAL verdict reached while an apply was in flight is
        suspect (the one-sided overlay may have double-counted that commit
        as it landed): `resync` waits the apply out and returns a settled
        snapshot, and the plan gets exactly one clean re-verify. Returns
        (group, opt) — opt is replaced when a resync happened."""
        group: List[Tuple[PendingPlan, PlanResult]] = []
        tv0 = time.perf_counter()
        queue = list(batch)
        i = 0
        while i < len(queue):
            pending = queue[i]
            result = self._verify(pending, opt,
                                  overlapped=overlapped or bool(group))
            if (result is not None and result.RefreshIndex
                    and overlapped and resync is not None):
                # PARTIAL while an apply was in flight: the one-sided
                # overlay may have double-counted — annotate the eval's
                # trace so the re-verify shows up in its timeline.
                trace.add_trace_event(
                    trace.linked("eval", pending.plan.EvalID),
                    "plan.partial_reverify", eval=pending.plan.EvalID)
                opt, in_flight_failed = resync()
                overlapped = False
                if in_flight_failed:
                    # The apply this group's earlier verdicts assumed
                    # never landed (e.g. its evictions): every admitted
                    # plan is stale. Re-verify them all against the
                    # settled state, in order — the run loop's own
                    # apply_failed re-check won't run (wait is None now).
                    queue = [p for p, _ in group] + queue[i:]
                    group = []
                    i = 0
                    continue
                # The settled snapshot lacks this group's own admitted
                # results; restore them so plan ordering is preserved.
                for _, r in group:
                    opt.apply_result(r)
                result = self._verify(pending, opt,
                                      overlapped=bool(group))
            i += 1
            if result is None:
                continue
            if not result.NodeUpdate and not result.NodeAllocation:
                pending.respond(result, None)
                continue
            opt.apply_result(result)
            group.append((pending, result))
        self.stats["t_verify_ms"] += (time.perf_counter() - tv0) * 1e3
        return group, opt

    def _verify(self, pending: PendingPlan, opt: OptimisticSnapshot,
                overlapped: bool) -> Optional[PlanResult]:
        plan = pending.plan
        # Token check: the eval must still be outstanding to its worker
        # (anti split-brain, reference: plan_apply.go:62-78).
        if self.eval_broker is not None:
            token = self.eval_broker.outstanding(plan.EvalID)
            if token is None or (plan.EvalToken and token != plan.EvalToken):
                pending.respond(None, RuntimeError(
                    f"plan for evaluation {plan.EvalID} has stale token"))
                self.stats["rejected"] += 1
                return None
        born = getattr(plan, "_fed_born", None)
        if (born is not None and self.fed is not None
                and self.fed.reject_after_s > 0):
            # Follower-snapshot staleness backstop: a plan built against
            # a snapshot far past the dequeue-side bound (a wedged or
            # deliberately-pinned source) is rejected BEFORE verification
            # — the worker nacks, the broker redelivers the eval exactly
            # once, and the re-run places against a fresh snapshot.
            age = time.monotonic() - born
            if age > self.fed.reject_after_s:
                from nomad_tpu.federation import StaleSnapshotError

                metrics.incr_counter(("nomad", "federation",
                                      "stale_plans"))
                pending.respond(None, StaleSnapshotError(
                    f"plan for evaluation {plan.EvalID} built against a "
                    f"{age * 1e3:.0f}ms-old snapshot (bound "
                    f"{self.fed.reject_after_s * 1e3:.0f}ms)"))
                self.stats["rejected"] += 1
                return None
        try:
            with trace.resume(trace.linked("eval", plan.EvalID),
                              "plan.evaluate", eval=plan.EvalID,
                              overlapped=overlapped):
                with metrics.measure(("nomad", "plan", "evaluate")):
                    result = evaluate_plan(opt, plan, self._pool,
                                           nt=self._nt())
        # lint: allow(swallow, error is delivered to the plan's waiter)
        except Exception as e:  # verification error: reject the plan
            pending.respond(None, e)
            self.stats["rejected"] += 1
            return None
        if overlapped:
            self.stats["overlapped"] += 1
        return result

    def _apply_group(self, group: List[Tuple[PendingPlan, PlanResult]]
                     ) -> None:
        """Commit a verified group as ONE consensus entry, then answer every
        waiting worker. All plans of the group share the entry's index."""
        # Every plan's trace gets a plan.apply span covering the shared
        # commit (explicit spans: each belongs to its OWN trace); the first
        # live span doubles as the ambient context, so fsm/raft child
        # spans AND failpoint/retry events of the commit land on it.
        spans = [trace.start_from(trace.linked("eval", pending.plan.EvalID),
                                  "plan.apply", eval=pending.plan.EvalID,
                                  batch=len(group))
                 for pending, _ in group]
        primary = next((s for s in spans if s is not None), None)
        try:
            ta0 = time.perf_counter()
            with (primary if primary is not None else trace.attach(None)):
                with metrics.measure(("nomad", "plan", "apply")):
                    if len(group) == 1:
                        pending, result = group[0]
                        index = self._apply(pending.plan, result)
                    else:
                        if failpoints.fire("plan.apply.commit") == "drop":
                            raise failpoints.FailpointError(
                                "plan.apply.commit")
                        _fire_preempt_commit(
                            p.plan for p, _ in group)
                        encoded = [_encode_result(pending.plan, result)
                                   for pending, result in group]
                        # Any columnar member upgrades the whole entry to
                        # the sweep-batch op (its Batch shape is a strict
                        # superset of AllocUpdate's); all-object entries
                        # keep the reference AllocUpdate type.
                        msg = (MessageType.ApplySweepBatch
                               if any(f for _, f in encoded)
                               else MessageType.AllocUpdate)
                        if msg is MessageType.ApplySweepBatch:
                            _fire_store_commit()
                        index = self.raft.apply(msg, {
                            "Batch": [e for e, _ in encoded],
                        })
            self.stats["t_apply_ms"] += (time.perf_counter() - ta0) * 1e3
            for span in spans:
                if span is not None:
                    span.finish()
            for pending, result in group:
                result.AllocIndex = index
                self.stats["applied"] += 1
                self._count_preempt(pending.plan, result)
                pending.respond(result, None)
        # lint: allow(swallow, error is delivered to every plan's waiter)
        except Exception as e:
            self.stats["apply_failed"] += 1
            for span in spans:
                if span is not None:
                    span.finish(error=str(e))
            for pending, _ in group:
                pending.respond(None, e)

    def apply_one(self, pending: PendingPlan) -> None:
        """Synchronous single-plan path (tests / dev tools)."""
        opt = OptimisticSnapshot(self.raft.fsm.state.snapshot(),
                         nt=self._nt())
        result = self._verify(pending, opt, overlapped=False)
        if result is None:
            return
        if result.NodeUpdate or result.NodeAllocation:
            with trace.resume(trace.linked("eval", pending.plan.EvalID),
                              "plan.apply", eval=pending.plan.EvalID,
                              batch=1):
                result.AllocIndex = self._apply(pending.plan, result)
            self._count_preempt(pending.plan, result)
        pending.respond(result, None)

    def _apply(self, plan: Plan, result: PlanResult) -> int:
        """Commit the verified subset through consensus
        (reference: plan_apply.go:122-164 applyPlan)."""
        # No drop semantics at a consensus commit: a triggered failpoint
        # always surfaces as a failed apply (workers nack + re-evaluate).
        if failpoints.fire("plan.apply.commit") == "drop":
            raise failpoints.FailpointError("plan.apply.commit")
        _fire_preempt_commit((plan,))
        element, is_sweep = _encode_result(plan, result)
        if is_sweep:
            _fire_store_commit()
            return self.raft.apply(MessageType.ApplySweepBatch,
                                   {"Batch": [element]})
        return self.raft.apply(MessageType.AllocUpdate, {
            "Job": plan.Job,
            "Alloc": _result_allocs(result),
        })
