"""Plan applier: THE serialization point (reference: nomad/plan_apply.go).

Dequeues pending plans, verifies every placement against a state snapshot,
computes partial commits + RefreshIndex, applies through the consensus
backend, and responds to the waiting worker.

Two reference optimizations are mirrored here:

- **Overlapped apply** (plan_apply.go:24-33): while plan N's Raft apply is in
  flight, plan N+1 is verified against an OPTIMISTIC snapshot that assumes N
  committed. Productive work happens during consensus latency; the waiter is
  answered asynchronously only after the log really commits.
- **Evaluate pool** (plan_apply_pool.go:38): per-node verification of large
  plans fans out over a thread pool — each node's check is independent.

Verification itself is host-side: a plan touches only its own nodes, and the
check needs exact port-level network accounting (structs.allocs_fit), so
there's nothing hot to tensorize.
"""

from __future__ import annotations

import logging
import os
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Set, Tuple

from nomad_tpu.structs import (
    Allocation,
    Plan,
    PlanResult,
    allocs_fit,
    remove_allocs,
)
from nomad_tpu.structs.structs import NodeStatusReady
from nomad_tpu.telemetry import metrics

from .eval_broker import EvalBroker
from .fsm import DevRaft, MessageType
from .plan_queue import PendingPlan, PlanQueue

logger = logging.getLogger("nomad.plan_apply")

# Below this many touched nodes a plan is verified inline: thread fan-out
# costs more than it saves (reference: pool used unconditionally, but Go
# goroutines are cheaper than pool dispatch here).
_POOL_THRESHOLD = 8


class OptimisticSnapshot:
    """A read view layering not-yet-committed plan results over a state
    snapshot (reference: snap.UpsertAllocs after raft dispatch,
    plan_apply.go:152-158). Supports exactly the reads evaluate_plan needs."""

    def __init__(self, snap):
        self.snap = snap
        self._added: Dict[str, List[Allocation]] = {}
        self._removed: Set[str] = set()

    def apply_result(self, result: PlanResult) -> None:
        for updates in result.NodeUpdate.values():
            for a in updates:
                self._removed.add(a.ID)
        for node_id, placed in result.NodeAllocation.items():
            self._added.setdefault(node_id, []).extend(placed)

    def node_by_id(self, node_id: str):
        return self.snap.node_by_id(node_id)

    def allocs_by_node_terminal(self, node_id: str, terminal: bool):
        out = [a for a in self.snap.allocs_by_node_terminal(node_id, terminal)
               if a.ID not in self._removed]
        if not terminal:
            out.extend(self._added.get(node_id, ()))
        return out

    def get_index(self, table: str) -> int:
        return self.snap.get_index(table)


def evaluate_plan(snap, plan: Plan,
                  pool: Optional[ThreadPoolExecutor] = None) -> PlanResult:
    """Per-node fit re-check of a plan (reference: plan_apply.go:194-316).
    With a pool, node checks run in parallel (plan_apply_pool.go)."""
    result = PlanResult()
    node_ids = list(dict.fromkeys(list(plan.NodeUpdate) + list(plan.NodeAllocation)))

    if pool is not None and len(node_ids) >= _POOL_THRESHOLD:
        fits = list(pool.map(
            lambda nid: _evaluate_node_plan(snap, plan, nid), node_ids))
    else:
        fits = [_evaluate_node_plan(snap, plan, nid) for nid in node_ids]

    partial_commit = False
    for node_id, fit in zip(node_ids, fits):
        if not fit:
            partial_commit = True
            if plan.AllAtOnce:
                result.NodeUpdate = {}
                result.NodeAllocation = {}
                break
            continue
        if plan.NodeUpdate.get(node_id):
            result.NodeUpdate[node_id] = plan.NodeUpdate[node_id]
        if plan.NodeAllocation.get(node_id):
            result.NodeAllocation[node_id] = plan.NodeAllocation[node_id]

    if partial_commit:
        result.RefreshIndex = max(snap.get_index("nodes"),
                                  snap.get_index("allocs"))
    return result


def _evaluate_node_plan(snap, plan: Plan, node_id: str) -> bool:
    """(reference: plan_apply.go:318-361)"""
    if not plan.NodeAllocation.get(node_id):
        return True  # evict-only always fits
    node = snap.node_by_id(node_id)
    if node is None or node.Status != NodeStatusReady or node.Drain:
        return False
    existing = snap.allocs_by_node_terminal(node_id, False)
    remove: List[Allocation] = list(plan.NodeUpdate.get(node_id, ()))
    remove.extend(plan.NodeAllocation.get(node_id, ()))
    proposed = remove_allocs(list(existing), remove)
    proposed.extend(plan.NodeAllocation.get(node_id, ()))
    try:
        fit, _, _ = allocs_fit(node, proposed)
    except ValueError:
        return False
    return fit


class PlanApplier:
    """The leader's plan-apply loop with verify/apply overlap
    (reference: planApply, plan_apply.go:41-119)."""

    def __init__(self, plan_queue: PlanQueue, raft: DevRaft,
                 eval_broker: Optional[EvalBroker] = None,
                 pool_size: Optional[int] = None):
        self.plan_queue = plan_queue
        self.raft = raft
        self.eval_broker = eval_broker
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._pool_size = pool_size or max(1, (os.cpu_count() or 2) // 2)
        self._pool: Optional[ThreadPoolExecutor] = None
        # Counters for telemetry/tests.
        self.stats = {"applied": 0, "rejected": 0, "overlapped": 0,
                      "apply_failed": 0}

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(target=self.run, daemon=True,
                                        name="plan-apply")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def run(self) -> None:
        self._pool = ThreadPoolExecutor(max_workers=self._pool_size,
                                        thread_name_prefix="plan-eval")
        # One in-flight raft apply at a time; while it commits, the NEXT plan
        # verifies against `opt`, an optimistic view that assumes it landed.
        wait: Optional[threading.Thread] = None
        opt: Optional[OptimisticSnapshot] = None
        try:
            while not self._stop.is_set():
                try:
                    pending = self.plan_queue.dequeue(timeout=0.5)
                except RuntimeError:
                    return  # queue disabled
                if pending is None:
                    continue

                # Last apply already done? Fall back to a fresh snapshot.
                if wait is not None and not wait.is_alive():
                    wait.join()
                    wait = None
                    opt = None
                # The optimistic view is only valid WHILE an apply is in
                # flight; with nothing outstanding, always verify against
                # fresh state (matches plan_apply.go:71-79's `waitCh == nil`
                # refresh — an old view could miss a node going down).
                if wait is None or opt is None:
                    opt = OptimisticSnapshot(self.raft.fsm.state.snapshot())

                result = self._verify(pending, opt, overlapped=wait is not None)
                if result is None:
                    continue  # rejected; already responded
                if not result.NodeUpdate and not result.NodeAllocation:
                    pending.respond(result, None)
                    continue

                # One apply in flight at a time: wait for the previous one,
                # then re-snapshot so the optimistic view can't drift more
                # than one plan from the log (plan_apply.go:96-103).
                if wait is not None:
                    prev_failed_before = self.stats["apply_failed"]
                    wait.join()
                    opt = OptimisticSnapshot(self.raft.fsm.state.snapshot())
                    if self.stats["apply_failed"] != prev_failed_before:
                        # The apply this result's verification assumed never
                        # landed (e.g. its evictions); re-verify against the
                        # real state before committing.
                        result = self._verify(pending, opt, overlapped=False)
                        if result is None:
                            continue
                        if not result.NodeUpdate and not result.NodeAllocation:
                            pending.respond(result, None)
                            continue

                opt.apply_result(result)
                wait = threading.Thread(
                    target=self._apply_and_respond,
                    args=(pending, pending.plan, result),
                    daemon=True, name="plan-apply-async")
                wait.start()
        finally:
            if wait is not None:
                wait.join()
            self._pool.shutdown(wait=False)
            self._pool = None

    def _verify(self, pending: PendingPlan, opt: OptimisticSnapshot,
                overlapped: bool) -> Optional[PlanResult]:
        plan = pending.plan
        # Token check: the eval must still be outstanding to its worker
        # (anti split-brain, reference: plan_apply.go:62-78).
        if self.eval_broker is not None:
            token = self.eval_broker.outstanding(plan.EvalID)
            if token is None or (plan.EvalToken and token != plan.EvalToken):
                pending.respond(None, RuntimeError(
                    f"plan for evaluation {plan.EvalID} has stale token"))
                self.stats["rejected"] += 1
                return None
        try:
            with metrics.measure(("nomad", "plan", "evaluate")):
                result = evaluate_plan(opt, plan, self._pool)
        except Exception as e:  # verification error: reject the plan
            pending.respond(None, e)
            self.stats["rejected"] += 1
            return None
        if overlapped:
            self.stats["overlapped"] += 1
        return result

    def _apply_and_respond(self, pending: PendingPlan, plan: Plan,
                           result: PlanResult) -> None:
        """Commit through consensus, then answer the waiting worker
        (reference: applyPlan + asyncPlanWait, plan_apply.go:122-190)."""
        try:
            with metrics.measure(("nomad", "plan", "apply")):
                index = self._apply(plan, result)
            result.AllocIndex = index
            self.stats["applied"] += 1
            pending.respond(result, None)
        except Exception as e:
            self.stats["apply_failed"] += 1
            pending.respond(None, e)

    def apply_one(self, pending: PendingPlan) -> None:
        """Synchronous single-plan path (tests / dev tools)."""
        opt = OptimisticSnapshot(self.raft.fsm.state.snapshot())
        result = self._verify(pending, opt, overlapped=False)
        if result is None:
            return
        if result.NodeUpdate or result.NodeAllocation:
            result.AllocIndex = self._apply(pending.plan, result)
        pending.respond(result, None)

    def _apply(self, plan: Plan, result: PlanResult) -> int:
        """Commit the verified subset through consensus
        (reference: plan_apply.go:122-164 applyPlan)."""
        allocs: List[Allocation] = []
        for updates in result.NodeUpdate.values():
            allocs.extend(updates)
        for placed in result.NodeAllocation.values():
            allocs.extend(placed)
        return self.raft.apply(MessageType.AllocUpdate, {
            "Job": plan.Job,
            "Alloc": allocs,
        })
