"""PlanQueue: leader-side priority queue of pending plans (reference:
nomad/plan_queue.go).

Each enqueued plan carries a future the scheduling worker blocks on; the plan
applier dequeues in priority order and resolves the futures.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from nomad_tpu.analysis import guarded_by
from nomad_tpu.structs import Plan, PlanResult


class PendingPlan:
    """A plan + its response future (reference: plan_queue.go:52-93)."""

    def __init__(self, plan: Plan):
        self.plan = plan
        self._event = threading.Event()
        self._result: Optional[PlanResult] = None
        self._error: Optional[Exception] = None
        self.cancelled = False

    def wait(self, timeout: Optional[float] = None) -> PlanResult:
        if not self._event.wait(timeout):
            raise TimeoutError("plan response timeout")
        if self._error is not None:
            raise self._error
        return self._result

    def respond(self, result: Optional[PlanResult],
                error: Optional[Exception]) -> None:
        self._result = result
        self._error = error
        self._event.set()

    def cancel(self) -> None:
        """Mark a still-queued plan abandoned (a chunked submit whose
        earlier chunk failed): the applier skips it at dequeue instead of
        committing work nobody is waiting on. Best-effort — a plan the
        applier already picked up still lands."""
        self.cancelled = True


class PlanQueue:
    _concurrency = guarded_by("_lock", "_enabled", "_heap", "stats")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._enabled = False
        self._heap: List[Tuple[int, int, PendingPlan]] = []
        self._seq = itertools.count()
        self.stats = {"Depth": 0}

    def enabled(self) -> bool:
        with self._lock:
            return self._enabled

    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            self._enabled = enabled
        if not enabled:
            self.flush()

    def enqueue(self, plan: Plan) -> PendingPlan:
        """(reference: plan_queue.go:95-124)"""
        with self._lock:
            if not self._enabled:
                raise RuntimeError("plan queue is disabled")
            pending = PendingPlan(plan)
            heapq.heappush(self._heap,
                           (-plan.Priority, next(self._seq), pending))
            self.stats["Depth"] += 1
            self._cond.notify_all()
            return pending

    def enqueue_all(self, plans: List[Plan]) -> List[PendingPlan]:
        """Enqueue a window's plans under ONE lock hold / ONE wakeup.
        A pipelined worker submits its window back-to-back; per-plan lock
        rounds convoy with a second submitting worker and interleave the
        two windows' plans arbitrarily. One critical section keeps each
        window contiguous in arrival order (same-priority plans pop FIFO),
        which is the order the chain dispatched them in."""
        with self._lock:
            if not self._enabled:
                raise RuntimeError("plan queue is disabled")
            out: List[PendingPlan] = []
            for plan in plans:
                pending = PendingPlan(plan)
                heapq.heappush(self._heap,
                               (-plan.Priority, next(self._seq), pending))
                out.append(pending)
            self.stats["Depth"] += len(out)
            self._cond.notify_all()
            return out

    def dequeue_ready(self, max_count: int) -> List[PendingPlan]:
        """Pop up to max_count queued plans under ONE lock hold, without
        waiting (the applier's group drain: per-plan dequeue rounds on
        the serialization point convoy with concurrently submitting
        workers)."""
        out: List[PendingPlan] = []
        with self._lock:
            if not self._enabled:
                raise RuntimeError("plan queue is disabled")
            while self._heap and len(out) < max_count:
                _, _, pending = heapq.heappop(self._heap)
                out.append(pending)
            self.stats["Depth"] -= len(out)
        return out

    def dequeue(self, timeout: Optional[float] = None) -> Optional[PendingPlan]:
        """(reference: plan_queue.go:126-152)"""
        end = None if not timeout else time.monotonic() + timeout
        with self._lock:
            while True:
                if not self._enabled:
                    raise RuntimeError("plan queue is disabled")
                if self._heap:
                    _, _, pending = heapq.heappop(self._heap)
                    self.stats["Depth"] -= 1
                    return pending
                if end is None:
                    self._cond.wait(timeout=0.2)
                else:
                    remaining = end - time.monotonic()
                    if remaining <= 0 or not self._cond.wait(remaining):
                        return None

    def flush(self) -> None:
        with self._lock:
            for _, _, pending in self._heap:
                pending.respond(None, RuntimeError("plan queue flushed"))
            self._heap = []
            self.stats["Depth"] = 0
            self._cond.notify_all()
