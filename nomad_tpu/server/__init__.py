"""Server coordination services (reference: nomad/*.go).

Leader-side singletons — eval broker, blocked-evals tracker, plan queue,
plan applier — plus the FSM, scheduling workers, heartbeats, the periodic
dispatcher, and the core GC scheduler: the host-side control plane around
the TPU placement path.
"""

from .fsm import FSM, MessageType, DevRaft  # noqa: F401
from .eval_broker import EvalBroker  # noqa: F401
from .blocked_evals import BlockedEvals  # noqa: F401
from .plan_queue import PlanQueue, PendingPlan  # noqa: F401
from .plan_apply import PlanApplier, evaluate_plan  # noqa: F401
from .worker import Worker  # noqa: F401
from .heartbeat import HeartbeatTimers  # noqa: F401
from .periodic import PeriodicDispatch, derive_job, derived_job_id  # noqa: F401
from .timetable import TimeTable  # noqa: F401
from .core_sched import CoreScheduler  # noqa: F401
from .server import Server, ServerConfig  # noqa: F401
