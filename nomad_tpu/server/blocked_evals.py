"""BlockedEvals: capacity-gated evaluation parking (reference:
nomad/blocked_evals.go).

Evals that failed placement wait here until node capacity changes. Keyed by
computed node class: an unblock on class C wakes evals that were eligible for
C or never saw C; escaped evals (constraints outside class memoization) wake
on any capacity change. missed-unblock indexes close the race between a
scheduler running on an old snapshot and capacity arriving meanwhile.
"""

from __future__ import annotations

import queue as _queue
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from nomad_tpu.analysis import guarded_by, requires_lock
from nomad_tpu.resilience import failpoints
from nomad_tpu.structs import Evaluation
from nomad_tpu.structs.structs import EvalTriggerMaxPlans

from .eval_broker import EvalBroker


@dataclass
class _Wrapped:
    eval: Evaluation
    token: str
    # Original FIRST-enqueue monotonic timestamp, captured from the broker
    # at block time (falling back to the parent eval's for a fresh blocked
    # eval) and handed back on requeue — a capacity-unblocked eval must
    # keep its queue age instead of resetting behind fresh arrivals.
    age: float = 0.0


@dataclass
class BlockedStats:
    TotalEscaped: int = 0
    TotalBlocked: int = 0


class BlockedEvals:
    _concurrency = guarded_by(
        "_lock", "_enabled", "_captured", "_escaped", "_jobs",
        "_unblock_indexes", "_duplicates", "stats")

    def __init__(self, eval_broker: EvalBroker):
        self.eval_broker = eval_broker
        self._enabled = False
        self._lock = threading.Lock()
        self.stats = BlockedStats()

        self._captured: Dict[str, _Wrapped] = {}
        self._escaped: Dict[str, _Wrapped] = {}
        self._jobs: set = set()
        self._unblock_indexes: Dict[str, int] = {}
        self._duplicates: List[Evaluation] = []
        self._dup_cond = threading.Condition(self._lock)
        self._capacity_ch: _queue.Queue = _queue.Queue(maxsize=8096)
        self._watcher: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # ------------------------------------------------------------- lifecycle
    def enabled(self) -> bool:
        with self._lock:
            return self._enabled

    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            if self._enabled == enabled:
                return
            self._enabled = enabled
            if enabled:
                self._stop = threading.Event()
                self._watcher = threading.Thread(target=self._watch_capacity,
                                                 daemon=True,
                                                 name="blocked-evals-watch")
                self._watcher.start()
            else:
                self._stop.set()
        if not enabled:
            self.flush()

    def flush(self) -> None:
        with self._lock:
            self.stats = BlockedStats()
            self._captured.clear()
            self._escaped.clear()
            self._jobs.clear()
            self._duplicates = []
            self._capacity_ch = _queue.Queue(maxsize=8096)

    # ----------------------------------------------------------------- block
    def block(self, ev: Evaluation, age: float = 0.0) -> None:
        """``age`` seeds the first-enqueue timestamp (monotonic) for an
        eval entering from OUTSIDE the broker — the warm-failover restore
        passes the timetable-derived original enqueue time so a blocked
        eval that rode out an election keeps its true queue age."""
        self._process_block(ev, "", age=age)

    def reblock(self, ev: Evaluation, token: str) -> None:
        """Block by an outstanding evaluation; carries its broker token."""
        self._process_block(ev, token)

    def _process_block(self, ev: Evaluation, token: str,
                       age: float = 0.0) -> None:
        # Queue-age carry: read BEFORE taking our lock (consistent
        # blocked->broker lock order everywhere else in this file). A
        # fresh blocked eval (new ID) inherits its parent's first-enqueue
        # time; a reblocked eval still owns its own entry. An explicit
        # seed (warm-failover restore) wins only when the broker has no
        # memory of the eval at all.
        age = (self.eval_broker.queue_age(ev.ID)
               or (self.eval_broker.queue_age(ev.PreviousEval)
                   if ev.PreviousEval else None) or age or 0.0)
        with self._lock:
            if not self._enabled:
                return
            # One blocked eval per job; extras become duplicates for the
            # leader's reaper to cancel.
            if ev.JobID in self._jobs:
                self._duplicates.append(ev)
                self._dup_cond.notify_all()
                return
            if self._missed_unblock(ev):
                self.eval_broker.enqueue_all(
                    {ev.ID: (ev, token)},
                    ages={ev.ID: age} if age else None)
                return
            self.stats.TotalBlocked += 1
            self._jobs.add(ev.JobID)
            wrapped = _Wrapped(ev, token, age=age)
            if ev.EscapedComputedClass:
                self._escaped[ev.ID] = wrapped
                self.stats.TotalEscaped += 1
            else:
                self._captured[ev.ID] = wrapped

    @requires_lock("_lock")
    def _missed_unblock(self, ev: Evaluation) -> bool:
        """(reference: blocked_evals.go:208-245)"""
        max_index = 0
        for cls, index in self._unblock_indexes.items():
            max_index = max(max_index, index)
            elig = ev.ClassEligibility.get(cls)
            if elig is None and ev.SnapshotIndex < index:
                # Class appeared after the eval was processed: unblock.
                return True
            if elig and ev.SnapshotIndex < index:
                return True
        if ev.EscapedComputedClass and ev.SnapshotIndex < max_index:
            return True
        return False

    # --------------------------------------------------------------- unblock
    def unblock(self, computed_class: str, index: int) -> None:
        with self._lock:
            if not self._enabled:
                return
            self._unblock_indexes[computed_class] = index
        # Failure seam: the wakeup EVENT can be lost (a crashed watcher, a
        # full channel, an injected fault) — the classic missed wakeup.
        # The unblock index above is already recorded, which is exactly
        # the recovery net: evals blocked AFTER the loss re-enqueue via
        # _missed_unblock, and already-parked ones wake on the next real
        # capacity change. Raising here would take down the raft apply
        # thread that runs the FSM hooks, so every armed mode degrades to
        # a dropped event.
        try:
            if failpoints.fire("server.blocked.unblock") == "drop":
                return
        except failpoints.FailpointError:
            return
        self._capacity_ch.put((computed_class, index))

    def _watch_capacity(self) -> None:
        while not self._stop.is_set():
            try:
                computed_class, index = self._capacity_ch.get(timeout=0.2)
            except _queue.Empty:
                continue
            self._unblock(computed_class, index)

    def _unblock(self, computed_class: str, index: int) -> None:
        with self._lock:
            if not self._enabled:
                return
            unblocked: Dict[str, Tuple[Evaluation, str]] = {}
            ages: Dict[str, float] = {}
            for eid, wrapped in list(self._escaped.items()):
                unblocked[eid] = (wrapped.eval, wrapped.token)
                if wrapped.age:
                    ages[eid] = wrapped.age
                del self._escaped[eid]
                self._jobs.discard(wrapped.eval.JobID)
            for eid, wrapped in list(self._captured.items()):
                elig = wrapped.eval.ClassEligibility.get(computed_class)
                if elig is False:
                    continue  # explicitly ineligible for this class
                unblocked[eid] = (wrapped.eval, wrapped.token)
                if wrapped.age:
                    ages[eid] = wrapped.age
                self._jobs.discard(wrapped.eval.JobID)
                del self._captured[eid]
            if unblocked:
                self.stats.TotalEscaped = 0
                self.stats.TotalBlocked -= len(unblocked)
                self.eval_broker.enqueue_all(unblocked, ages=ages)

    def unblock_failed(self) -> None:
        """Periodic retry of evals blocked by plan failures
        (reference: blocked_evals.go:335-366)."""
        with self._lock:
            if not self._enabled:
                return
            unblocked: Dict[str, Tuple[Evaluation, str]] = {}
            ages: Dict[str, float] = {}
            for source in (self._captured, self._escaped):
                for eid, wrapped in list(source.items()):
                    if wrapped.eval.TriggeredBy == EvalTriggerMaxPlans:
                        unblocked[eid] = (wrapped.eval, wrapped.token)
                        if wrapped.age:
                            ages[eid] = wrapped.age
                        del source[eid]
                        self._jobs.discard(wrapped.eval.JobID)
                        if source is self._escaped:
                            self.stats.TotalEscaped -= 1
            if unblocked:
                self.stats.TotalBlocked -= len(unblocked)
                self.eval_broker.enqueue_all(unblocked, ages=ages)

    def get_duplicates(self, timeout: float) -> List[Evaluation]:
        """Blocking fetch of duplicate blocked evals for cancellation
        (reference: blocked_evals.go:370-398)."""
        end = time.monotonic() + timeout
        with self._lock:
            while True:
                if self._duplicates:
                    dups = self._duplicates
                    self._duplicates = []
                    return dups
                remaining = end - time.monotonic()
                if remaining <= 0 or not self._dup_cond.wait(remaining):
                    return []
