"""PipelinedWorker: the TPU-native served scheduling path.

The base Worker processes one evaluation at a time: dispatch the placement
kernel, BLOCK on the device->host readback, submit the plan, wait, ack. On a
remote-attached TPU every readback pays a fixed RTT, so throughput is
RTT-bound, not compute-bound.

This worker batch-dequeues a WINDOW of evaluations and runs the pure-placement
ones (the common case in registration storms — no evictions, no in-place
updates) through a device-resident pipeline:

  1. dispatch: each eval's placement kernel is launched with the PREVIOUS
     eval's usage_after array as its usage input — the chain never leaves the
     device (reference analogue: optimistic concurrency of N workers against
     snapshots, nomad/worker.go:45-49; here the "snapshot" is the live chain)
  2. one readback drains the whole window's packed results
  3. plans are built host-side (network/port assignment for winners only) and
     enqueued to the plan applier back-to-back; the applier re-verifies every
     placement against committed state before commit (plan_apply.py), which
     makes the optimistic chain safe
  4. eval status updates for the window are applied through consensus as ONE
     EvalUpdate batch, then everything acks

Windows OVERLAP: a finisher thread owns steps 2-4 while the run loop
dispatches the next window, chaining its kernels on the previous window's
device-side usage tail. On a remote-attached TPU both the window's readback
and the dirty-row table refresh are full network round trips; overlap hides
the readback behind the next window's host work, and chaining makes the
usage refresh skippable entirely mid-storm (node_table.device_arrays
skip_usage). The chain rebases to committed state whenever the pipeline
drains (and on node-table resize), so drift is bounded by the storm length;
oversubscription is impossible regardless — the plan applier re-verifies
every placement against committed state.

Anything not pure-placement — updates, migrations, stops, system jobs, core
GC, deregisters, annotate requests — falls back to the exact per-eval
GenericScheduler path (scheduler/generic_sched.py), as does any eval whose
plan partially commits (stale chain) or whose winner fails host-side port
assignment. Fallbacks preserve reference semantics bit-for-bit; the fast path
only accelerates evals whose outcome is provably the same.

N workers share ONE logical usage chain through the ChainArbiter
(tensor/node_table.py): a window lease serializes the dispatch handoff so
worker B's kernels chain on worker A's in-flight tail (each placement sees
every placement dispatched before it, whoever dispatched it), while the
drain fetches (GIL released) and build stages of different workers
interleave. Broker windows batch-dequeue under one lock (disjoint eval
sets, no interleave-stealing), per-stage deadline re-arms and window acks
are one lock round each, and a window's plans enqueue contiguously — the
contention seams that made a second worker SLOWER than one.
"""

from __future__ import annotations

import logging
import queue
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from nomad_tpu.federation import StaleSnapshotError
from nomad_tpu.resilience import failpoints
from nomad_tpu.scheduler.context import EvalContext
from nomad_tpu.scheduler.generic_sched import (
    _HANDLED_TRIGGERS,
    class_eligibility,
    filter_complete_allocs,
    has_escaped,
)
from nomad_tpu.scheduler import kernels
from nomad_tpu.scheduler.stack import (
    GenericStack,
    PreparedBatch,
    WindowAccumulator,
    device_input,
)
from nomad_tpu.scheduler.util import (
    BLOCKED_EVAL_FAILED_PLACEMENTS,
    diff_allocs,
    materialize_task_groups,
    ready_nodes_in_dcs,
    tainted_nodes,
)
from nomad_tpu.structs import AllocMetric, Evaluation, Plan
from nomad_tpu.telemetry import metrics, trace
from nomad_tpu.tensor.node_table import ChainArbiter
from nomad_tpu.structs.structs import (
    EvalStatusBlocked,
    EvalStatusComplete,
    JobTypeBatch,
    JobTypeService,
)

from .fsm import MessageType
from .worker import DEQUEUE_TIMEOUT, Worker, stamp_fed_born

logger = logging.getLogger("nomad.worker.pipelined")

# How long to wait for additional evals once one is in hand. Near-zero: the
# window exists to drain bursts, not to add latency to a lone eval.
FILL_TIMEOUT = 0.002

# THE declared stats schema: every counter and stage timer the worker
# maintains, pre-seeded at construction so the debug endpoint
# (/v1/agent/debug/sched-stats), bench.py's reset/aggregate loops, and
# tests can rely on key presence instead of .get() defaults that drift.
# README's "Serving pipeline observability" section documents each key.
STATS_COUNTERS = (
    "fast",       # evals committed via the device-chained fast path
    "slow",       # evals routed to the per-eval GenericScheduler
    "fallback",   # fast dispatches re-run slow (partial commit/ports)
    "stale",      # evals redelivered mid-window and abandoned
    "host",       # fast evals placed host-side (shallow windows)
    "multi",      # fused place_batch_multi launches
    "windows",    # dispatched windows
    "rebases",    # chain rebases onto committed usage
    "qos_cut",    # windows cut short by a tier's deadline budget (QoS)
    "mesh_windows",    # keyed windows run on the sharded mesh pipeline
    "mesh_warm",       # of those, warm (pool-resident, zero-exchange)
    "mesh_bytes",      # winner-candidate bytes crossing the interconnect
    "mesh_shards",     # device count of the serving mesh (gauge)
    "mesh_cert_miss",  # warm windows whose exactness certificate failed
    #                    (window nacked + chain tainted -> cold redispatch)
    "fed_stale",       # windows nacked for a stale federation snapshot
    #                    (applier StaleSnapshotError -> exactly-once
    #                    redelivery onto a fresh snapshot)
)
STATS_TIMERS_MS = (
    "t_lease_ms",        # waiting for the shared chain-lease (ChainArbiter)
    "t_refresh_ms",      # node-table device refresh at dispatch
    "t_diff_ms",         # job diff/alloc filtering per eval
    "t_prep_ms",         # PreparedBatch assembly (device inputs)
    "t_launch_ms",       # kernel launches (host or device, async)
    "t_drain_stack_ms",  # drain-plan build: stack + compaction dispatch
    #                      (runs in the DISPATCH stage since round 6)
    "t_dispatch_ms",     # whole dispatch stage (includes the five above)
    "t_drain_ms",        # whole drain stage
    "t_drain_fetch_ms",  # blocking device->host readback
    "t_collect_ms",      # packed output -> plan allocations
    "t_build_ms",        # whole plan build/submit pass
    "t_planwait_ms",     # waiting on the plan applier
    "t_evalupd_ms",      # consensus EvalUpdate batch
    "t_slow_ms",         # slow-path evals of the window
    "t_mesh_exchange_ms",  # mesh pipeline: cold rebuild + winner exchange
)


def new_stats() -> dict:
    """A fresh zeroed stats dict with every schema key present."""
    stats: dict = {k: 0 for k in STATS_COUNTERS}
    stats.update({k: 0.0 for k in STATS_TIMERS_MS})
    return stats


@dataclass(eq=False)  # identity semantics: recs are tracked by object
class _FastEval:
    ev: Evaluation
    token: str
    plan: Plan
    ctx: EvalContext
    stack: GenericStack
    prep: PreparedBatch
    place: list                   # diff.place AllocTuples
    res: object                   # device-side PlacementResult
    failed_tg_allocs: Dict[str, AllocMetric] = field(default_factory=dict)
    pending: object = None        # PendingPlan once enqueued
    fallback: bool = False
    stale: bool = False           # redelivered mid-window: abandoned
    shareable: bool = False       # prep eligible for place_batch_multi
    span: object = None           # trace span covering dispatch -> ack


class _MultiSlice:
    """View of one eval's rows inside a place_batch_multi result. The
    drain stage fetches the PARENT's packed array once for the whole
    window and slices host-side."""

    __slots__ = ("parent", "index", "p_pad")

    def __init__(self, parent, index: int, p_pad: int):
        self.parent = parent
        self.index = index
        self.p_pad = p_pad

    @property
    def packed(self):  # device-side; drain special-cases the fetch
        return self.parent.packed

    @property
    def usage_after(self):
        return self.parent.usage_after


@dataclass
class _DrainPlan:
    """Dispatch-time plan of a window's device->host drain: the compaction
    programs are dispatched (async) and their outputs' host copies started
    while the window is still in the dispatch stage, so the bytes ride the
    tunnel under the PREVIOUS window's build instead of serializing behind
    the drain stage's blocking fetch (double-buffered readback)."""

    fetches: dict                  # key -> (chosen, scores, nf_last, ok)
    layout: list                   # per-rec ("host", CompactResult) |
    #                                ("dev", key, row-in-fetched-arrays)


@dataclass
class _WindowWork:
    """One dispatched window flowing through the drain -> build stages."""

    fast: List[_FastEval]
    slow: List[Tuple[Evaluation, str]]
    drain: Optional[_DrainPlan] = None         # set by the dispatch stage
    packed: Optional[list] = None              # CompactResults, set by drain
    failed: bool = False                       # drain blew up: nack window
    chained: bool = False       # dispatched on a previous window's tail
    taint_seq: int = 0          # arbiter taint seq observed at chain read
    published: bool = False     # tail published: arbiter counts us in flight
    chain_seq: int = 0          # chain position (arbiter finish barrier)
    mesh_flags: Optional[list] = None  # warm-window exactness certificates
    #                            (device scalars; drain fetches + enforces)
    fed_born: Optional[float] = None   # federation snapshot birth time
    #                            (stamped onto the window's plans; None
    #                             when federation is off)


def _prep_sig(job, place, batch: bool) -> Optional[tuple]:
    """Value signature of a prepared batch: two jobs with equal constraints,
    task shapes, and placement sequence produce byte-identical device inputs,
    so their PreparedBatch can be shared within a window. Returns None when
    sharing is unsafe (network asks need per-node port bookkeeping)."""
    from nomad_tpu.tensor.constraints import constraint_sig

    tg_sigs = {}
    names = []
    for t in place:
        tg = t.TaskGroup
        names.append(tg.Name)
        if tg.Name in tg_sigs:
            continue
        tasks = []
        for task in tg.Tasks:
            r = task.Resources
            if r is not None and r.Networks:
                return None
            tasks.append((task.Name, task.Driver,
                          (r.CPU, r.MemoryMB, r.DiskMB, r.IOPS)
                          if r is not None else None,
                          constraint_sig(task.Constraints)))
        tg_sigs[tg.Name] = (tuple(tasks), constraint_sig(tg.Constraints))
    return (batch, constraint_sig(job.Constraints), tuple(names),
            tuple(sorted(tg_sigs.items())))


class PipelinedWorker(Worker):
    """Drop-in Worker with windowed device-chained placement."""

    def __init__(self, *args, window: int = 32, host_placement: bool = True,
                 chain_arbiter: Optional[ChainArbiter] = None,
                 service_columnar: bool = True, **kwargs):
        super().__init__(*args, **kwargs)
        self.window = max(1, window)
        self.host_placement = host_placement
        # Columnar service commits (ServerConfig.service_columnar): the
        # all-placed window build attaches a SweepBatch descriptor so the
        # plan commits as ONE ApplySweepBatch entry + SweepSegment scatter.
        # False keeps the per-object commit path (bench A/B oracle side).
        self.service_columnar = service_columnar
        self._noise: Optional[np.ndarray] = None
        # Observability: how evals flowed (fast = device-chained window,
        # slow = per-eval GenericScheduler, fallback = fast dispatch that
        # re-ran slow after partial commit / port collision) and where the
        # wall-clock went (t_*_ms phase totals across both threads). One
        # declared schema (STATS_COUNTERS/STATS_TIMERS_MS) — every key is
        # pre-seeded and mutated with +=, never lazily .get()-defaulted.
        self.stats = new_stats()
        # Cross-window (and cross-WORKER) usage chain: the server hands
        # every pipelined worker the SAME arbiter so their windows
        # interleave on one coherent chain. A standalone worker (tests)
        # gets a private one — identical single-worker semantics.
        self._arbiter = chain_arbiter or ChainArbiter(self.tindex.nt)
        # Stage handoffs: dispatch -> drain -> build, one window queued per
        # seam. The drain stage spends its time in a device readback (GIL
        # released) while the build stage runs host Python — splitting them
        # lets window N+1's readback ride under window N's plan building,
        # and (with N workers) lets worker B build while worker A's fetch
        # has the interpreter released.
        self._drain_q: "queue.Queue[Optional[_WindowWork]]" = queue.Queue(
            maxsize=1)
        self._build_q: "queue.Queue[Optional[_WindowWork]]" = queue.Queue(
            maxsize=1)

    # -------------------------------------------------------------- run loop
    def run(self) -> None:
        name = getattr(self, "name", "pipelined")
        drainer = threading.Thread(target=self._drain_loop, daemon=True,
                                   name=f"{name}-drain")
        builder = threading.Thread(target=self._build_loop, daemon=True,
                                   name=f"{name}-build")
        drainer.start()
        builder.start()
        try:
            while not self._stop.is_set():
                if self._paused.is_set():
                    self._stop.wait(0.05)  # shutdown-aware pause spin
                    continue
                # Wait for the lease to be FREE (without taking it), then
                # dequeue ONE eval lease-free, take the lease, and batch-
                # fill the window under it. Ordering matters at every
                # step: parking on the arbiter first means a worker never
                # dequeues evals it could not launch anyway (hostage
                # evals burning their deadlines while the storm splinters
                # into one-eval windows); dequeuing one eval before
                # acquiring means an idle worker holds neither lease nor
                # evals; filling under the lease captures everything that
                # accumulated while another worker's dispatch held it —
                # so windows stay full.
                tw0 = time.perf_counter()
                idle = self._arbiter.wait_dispatch_idle(DEQUEUE_TIMEOUT)
                # The park above IS the convoy time (it only blocks while
                # another worker's dispatch holds the lease), so it counts
                # toward t_lease_ms — the later acquire is near-instant by
                # construction and would report ~0 under real convoying.
                self.stats["t_lease_ms"] += (time.perf_counter() - tw0) * 1e3
                if not idle:
                    continue
                got = self._dequeue_first()
                if got is None:
                    continue
                work = None
                batch: List[Tuple[Evaluation, str]] = [got]
                tl0 = time.perf_counter()
                try:
                    lease = self._arbiter.acquire(self._stop, holder=self.name)
                except RuntimeError:
                    continue  # stopping; the eval redelivers via its timer
                self.stats["t_lease_ms"] += (time.perf_counter() - tl0) * 1e3
                if lease.rebased:
                    self.stats["rebases"] += 1
                try:
                    batch.extend(self._fill_window(got[0]))
                    work = self._dispatch_window(batch, lease)
                except Exception:
                    # Broker/plan-queue teardown on leadership loss: drop
                    # quietly, redelivery handles the rest (worker.go:88-99).
                    if self._stop.is_set() or not self.eval_broker.enabled():
                        continue
                    logger.exception("pipelined worker: dispatch failed")
                    for ev, token in batch:
                        self._send_nack(ev.ID, token)
                finally:
                    # No-op when the dispatch published the tail; frees the
                    # lease on empty windows, all-slow windows, and every
                    # failure path.
                    self._arbiter.abort(lease)
                if work is not None:
                    self._drain_q.put(work)
        finally:
            self._drain_q.put(None)
            drainer.join(timeout=60.0)
            builder.join(timeout=60.0)

    def _reset_window_deadlines(self, work: _WindowWork) -> None:
        """Push the broker nack deadline out for every live eval of the
        window — ONE lock round for the whole window. A window can wait
        behind two others' drain+build stages (cold compiles take tens of
        seconds), so each stage entry re-arms the deadline the way the
        pre-split loop's single pass did. An eval already redelivered is
        marked stale here — its device work is abandoned rather than
        racing another worker's."""
        pairs = [(rec.ev.ID, rec.token) for rec in work.fast if not rec.stale]
        if not pairs:
            return
        try:
            stale = self.eval_broker.outstanding_reset_batch(pairs)
        except Exception as exc:
            # Broker teardown: downstream handling owns it.
            logger.debug("outstanding-reset sweep aborted: %s", exc)
            return
        if stale:
            for rec in work.fast:
                if rec.ev.ID in stale and not rec.stale:
                    logger.debug("eval %s redelivered between stages",
                                 rec.ev.ID)
                    rec.stale = True

    def _drain_loop(self) -> None:
        """Stage 2: block on each window's device readback (a full network
        round trip on remote-attached TPUs), then hand off host-side."""
        while True:
            work = self._drain_q.get()
            if work is None:
                self._build_q.put(None)
                return
            self._reset_window_deadlines(work)
            try:
                if work.fast and not work.failed:
                    t0 = time.perf_counter()
                    work.packed = self._drain_window(work)
                    self.stats["t_drain_ms"] += \
                        (time.perf_counter() - t0) * 1e3
                    for rec in work.fast:
                        if rec.span is not None:
                            rec.span.event("drained")
            except Exception:
                work.failed = True
                if not (self._stop.is_set()
                        or not self.eval_broker.enabled()):
                    logger.exception("pipelined worker: window drain failed")
            self._build_q.put(work)

    def _build_loop(self) -> None:
        """Stage 3: plan build/submit -> status batch -> acks, plus the
        slow-path evals of the window."""
        while True:
            work = self._build_q.get()
            if work is None:
                return
            self._reset_window_deadlines(work)
            try:
                if work.failed:
                    raise RuntimeError("window drain failed")
                if work.fast:
                    self._finish_fast(work)
                t0 = time.perf_counter()
                for ev, token in work.slow:
                    self._process_slow(ev, token)
                self.stats["t_slow_ms"] += (time.perf_counter() - t0) * 1e3
            except Exception:
                if work.published:
                    # None of this window's kernel placements will commit,
                    # but they are baked into the usage chain: raise the
                    # taint so in-flight windows quarantine their squeezed
                    # evals and the next dispatch rebases — the same
                    # phantom-usage hole as a stale record, via the
                    # whole-window-failure source.
                    self._arbiter.taint()
                if not (self._stop.is_set()
                        or not self.eval_broker.enabled()):
                    logger.exception("pipelined worker: window finish failed")
                    # Nack everything; already-acked/stale evals surface as
                    # NotOutstanding races that _send_nack logs at debug.
                    for rec in work.fast:
                        if rec.span is not None:
                            rec.span.finish(error="window finish failed")
                        self._send_nack(rec.ev.ID, rec.token)
                    for ev, token in work.slow:
                        self._send_nack(ev.ID, token)
            finally:
                if work.published:
                    # Failure paths raise the taint above without reaching
                    # _finish_fast's settle point; successors must not
                    # wait out the barrier timeout for a dead window.
                    self._arbiter.mark_settled(work.chain_seq)
                if work.published and self._arbiter.finish_window():
                    # Pipeline drained across ALL workers: the NEXT window
                    # will rebase onto committed usage and pay the
                    # dirty-row refresh (one blocking host->device RTT
                    # after a storm). This thread is idle until then —
                    # prefetch the refresh now so dispatch finds clean
                    # device state. Serialized with dispatch by the tensor
                    # lock; a no-op when nothing is dirty.
                    try:
                        self.tindex.nt.device_arrays()
                    # lint: allow(swallow, next dispatch retries synchronously)
                    except Exception:
                        pass

    def _dequeue_first(self) -> Optional[Tuple[Evaluation, str]]:
        """Blocking dequeue of a window's FIRST eval — the shared
        Worker._dequeue_evaluation seam (failpoint + backoff handling
        lives there, once), taken BEFORE the chain lease so an idle
        worker parks holding neither lease nor evals."""
        got = self._dequeue_evaluation()
        if got is None:
            return None
        ev, token, wait_index = got
        # Snapshot freshness barrier for the window (see worker.py
        # dequeue WaitIndex); trivially satisfied on the leader, where
        # the pipelined worker runs against its own committed state.
        self._window_wait_index = wait_index
        return ev, token

    def _fill_window(self, first: Optional[Evaluation] = None
                     ) -> List[Tuple[Evaluation, str]]:
        """Fill the rest of the window in ONE broker lock round
        (EvalBroker.dequeue_window), AFTER the chain lease is in hand:
        with N workers, per-eval fill loops interleave-steal each other's
        windows and convoy on the broker lock — the batch hands this
        worker a disjoint, contiguous set, including everything that
        arrived while another worker's dispatch held the lease.

        With QoS enabled the window carries a LATENCY BUDGET derived from
        the first (oldest) eval's tier deadline and its true queue age
        (preserved across redeliveries): a budget-tight window takes fewer
        evals and lingers less for stragglers — it dispatches short rather
        than blowing the tier's deadline on batch efficiency."""
        count = self.window - 1
        if count <= 0:
            return []  # window=1 never batch-fills, QoS or not
        fill = FILL_TIMEOUT
        qos = self.qos
        if qos is not None and qos.enabled and first is not None:
            enq_ts = self.eval_broker.queue_age(first.ID)
            if enq_ts is not None:
                count, fill = qos.window_fill(
                    time.monotonic() - enq_ts, first.Priority,
                    count, FILL_TIMEOUT)
                if count < self.window - 1:
                    self.stats["qos_cut"] += 1
                    if self.qos_counters is not None:
                        self.qos_counters.incr("window_cuts")
        try:
            return self.eval_broker.dequeue_window(
                self.schedulers, count, FILL_TIMEOUT,
                fill_timeout=fill)
        except RuntimeError:
            return []

    def _dequeue_window(self) -> List[Tuple[Evaluation, str]]:
        """First eval + batch fill, lease-free (tests and callers that
        dispatch synchronously)."""
        got = self._dequeue_first()
        if got is None:
            return []
        return [got] + self._fill_window()

    # ------------------------------------------------------------ the window
    def _dispatch_window(self, batch: List[Tuple[Evaluation, str]],
                         lease=None) -> Optional[_WindowWork]:
        """Dispatch one window's kernels chained on the leased usage tail;
        publishes the new tail (ending the lease) once the window's
        launches are all in flight. run() passes the lease it acquired
        BEFORE dequeuing and aborts it if we return unpublished; tests
        calling without one get the same acquire/abort wrapper here."""
        if lease is None:
            lease = self._arbiter.acquire(self._stop, holder=self.name)
            if lease.rebased:
                self.stats["rebases"] += 1
            try:
                return self._dispatch_window(batch, lease)
            finally:
                self._arbiter.abort(lease)  # no-op after a publish
        # The window is in hand: push every eval's nack deadline out NOW
        # (one broker lock round for the whole window). Filling +
        # dispatching + draining a cold window (first compiles) can exceed
        # the redelivery timeout (reference: worker.go heartbeats the
        # broker via OutstandingReset during long scheduling). An eval
        # already redelivered belongs to another worker — drop it here
        # rather than paying a device dispatch that the token check will
        # reject anyway.
        stale_ids = self.eval_broker.outstanding_reset_batch(
            [(ev.ID, token) for ev, token in batch])
        if stale_ids:
            for ev, _ in batch:
                if ev.ID in stale_ids:
                    logger.debug("window drop: eval %s redelivered", ev.ID)
            batch = [(ev, t) for ev, t in batch if ev.ID not in stale_ids]
        if not batch:
            return None
        min_index = max([ev.ModifyIndex for ev, _ in batch]
                        + [getattr(self, "_window_wait_index", 0)])
        self._wait_for_index(min_index)
        if self.fed_source is not None:
            # Follower-snapshot scheduling: the window places against the
            # shared staleness-bounded snapshot instead of pinning a
            # fresh watermark on the live store per window per worker.
            # The applier re-verifies (and staleness-rejects) so a stale
            # snapshot costs a redelivery, never a bad commit.
            snap, fed_born = self.fed_source.get(min_index)
        else:
            snap = self.raft.fsm.state.snapshot()
            fed_born = None
        t0 = time.perf_counter()

        nt = self.tindex.nt
        # The lease captured the taint sequence BEFORE handing out the
        # chain: a taint raised in between must surface as external at
        # finish time (the false-positive direction — quarantining an
        # untainted window's failed evals into exact-path re-runs — is
        # safe).
        usage_chain = lease.chain
        chained_at_dispatch = usage_chain is not None
        # Shallow windows place HOST-SIDE (kernels.place_batch_host): on a
        # remote-attached TPU every host sync is a fixed ~100ms round trip,
        # so a near-idle broker's evals finish in single-digit ms as numpy
        # while storms keep the device chain. Host mode needs a host-
        # compatible chain (None = committed table, or a previous host
        # window's numpy tail); once an eval upgrades to device mid-window
        # the rest of the window follows (never read a device chain back).
        from nomad_tpu.scheduler.stack import HOST_ROW_STEP_BUDGET

        host_mode = (
            self.host_placement
            and (usage_chain is None or isinstance(usage_chain, np.ndarray))
            and len(batch) * nt.n_rows * 64 <= HOST_ROW_STEP_BUDGET)
        # The entry gate above is an ESTIMATE (64 placements/eval); the
        # actual spend is debited per eval from this running budget as
        # each diff's true placement count becomes known, so a window of
        # larger-than-estimated evals upgrades to the device mid-window
        # instead of overshooting the documented budget ~4x.
        self._host_rows_left = HOST_ROW_STEP_BUDGET if host_mode else 0
        # With a live chain the device usage array is dead weight: skip its
        # dirty-row flush (one blocking host->device RTT mid-storm) and
        # refresh only capacity/readiness changes. A host-mode window skips
        # the device refresh entirely — it never reads the device tables;
        # an eval that upgrades to device mid-window fetches them lazily
        # inside stack.dispatch.
        tables = None if host_mode else nt.device_arrays(
            skip_usage=usage_chain is not None)
        self.stats["t_refresh_ms"] += (time.perf_counter() - t0) * 1e3

        fast: List[_FastEval] = []
        slow: List[Tuple[Evaluation, str]] = []
        # Shared per-window: every eval sees the same snapshot, so the ready
        # node list, candidate mask, class-eligibility cache, AND the node
        # table's device arrays (whose dirty-row refresh is a blocking
        # host->device transfer) are built once per window, not once per
        # eval. The tie-break noise is refreshed every 64 windows — enough
        # to spread load across ties without paying an upload per window.
        node_cache: Dict[tuple, tuple] = {}
        if self._noise is None or self._noise.shape[0] != nt.n_rows \
                or self.stats["windows"] % 64 == 0:
            from nomad_tpu.scheduler.stack import make_noise_vec

            self._noise = make_noise_vec(nt.n_rows, random.Random())
        noise_vec = self._noise
        for ev, token in batch:
            rec = None
            try:
                rec = self._try_dispatch_fast(ev, token, snap, usage_chain,
                                              node_cache, noise_vec, tables,
                                              host=host_mode)
            except Exception:
                logger.exception("fast dispatch failed for eval %s", ev.ID)
            if rec is None:
                slow.append((ev, token))
            else:
                # Explicit (cross-thread) span: this eval's window ride is
                # dispatch (this thread) -> drain -> build/ack (the stage
                # threads); finished wherever the rec leaves the pipeline.
                rec.span = trace.start_from(
                    trace.linked("eval", ev.ID), "worker.window",
                    eval=ev.ID, type=ev.Type)
                if rec.res is not None:  # host path launched inline
                    usage_chain = rec.res.usage_after
                fast.append(rec)

        # Launch the deferred device recs in window order, fusing each
        # consecutive run of SHARED-prep evals into one place_batch_multi
        # call: a storm window then costs ONE kernel dispatch and (at
        # drain) ONE readback, instead of per-eval launches plus an eager
        # window-wide stack — both of which scale with window size on the
        # dispatch-RTT-bound tunnel. Deferred recs are stably grouped by
        # prep identity first — an interleaved A,B,A,B window fuses into
        # two runs. Reordering within a window is safe: any sequential
        # order of optimistic placements is valid (each eval sees every
        # placement dispatched before its own, and the plan applier
        # re-verifies all of them against committed state).
        tl0 = time.perf_counter()
        i = 0
        # Warm mesh windows carry an exactness-certificate flag (device
        # scalar) per dispatch; the drain stage fetches and enforces them
        # (a failed certificate nacks the window like a failed drain).
        mesh_flags: list = []
        pend = [r for r in fast if r.res is None]
        group_ids: Dict[int, int] = {}
        pend.sort(key=lambda r: group_ids.setdefault(
            id(r.prep) if r.shareable else id(r), len(group_ids)))
        while i < len(pend):
            rec = pend[i]
            j = i + 1
            if rec.shareable:
                while (j < len(pend) and pend[j].shareable
                       and pend[j].prep is rec.prep):
                    j += 1
            run = pend[i:j]
            try:
                if len(run) >= 2:
                    if tables is None:
                        tables = nt.device_arrays(
                            skip_usage=usage_chain is not None)
                    res, _ = rec.stack.dispatch_multi(
                        rec.prep, len(run), usage_override=usage_chain,
                        tables=tables)
                    for k, r in enumerate(run):
                        r.res = _MultiSlice(res, k, rec.prep.p_pad)
                    usage_chain = res.usage_after
                    self.stats["multi"] += 1
                else:
                    rec.res = rec.stack.dispatch(
                        rec.prep, usage_override=usage_chain, tables=tables)
                    usage_chain = rec.res.usage_after
                fl = getattr(usage_chain, "flag", None)
                if fl is not None:
                    mesh_flags.append(fl)
            except Exception:
                logger.exception("window launch failed; routing %d evals "
                                 "to the exact path", len(run))
                for r in run:
                    r.fallback = True
                    fast.remove(r)
                    slow.append((r.ev, r.token))
            i = j
        # Reorder `fast` to CHAIN order (host-placed recs, then deferred
        # device recs in their sorted launch order): the phantom-usage
        # quarantine in _finish_fast reasons about "evals placed behind a
        # stale record" by list position, and the shared window_usage
        # accumulator replays the chain — both must see the order the
        # kernels actually chained in, not dequeue order.
        pend_ids = {id(r) for r in pend}
        launched = [r for r in fast if id(r) not in pend_ids]
        fast = launched + [r for r in pend if not r.fallback]
        self.stats["t_launch_ms"] += (time.perf_counter() - tl0) * 1e3

        if fast:
            # Publish the window's device-side usage tail as the shared
            # chain even though its plans haven't committed yet: the next
            # window — ANY worker's — chains on it. The lease carried the
            # row epoch captured at chain validation, BEFORE this window
            # dispatched: a row freed mid-dispatch still rebases the next
            # window. Publishing also ends the lease, so another worker
            # can start its dispatch while we assemble the drain plan.
            self._arbiter.publish(lease, usage_chain)
        self.stats["windows"] += 1
        self.stats["slow"] += len(slow)
        work = _WindowWork(fast=fast, slow=slow, published=bool(fast),
                           chain_seq=lease.seq,
                           mesh_flags=mesh_flags or None,
                           fed_born=fed_born)
        # Build the drain plan NOW: the compaction kernels dispatch async
        # behind the window's placement kernels and their (much smaller)
        # outputs start copying to the host immediately, so the drain
        # stage's blocking fetch finds the bytes en route — window k+1's
        # transfer overlaps window k's build instead of serializing.
        # A runtime failure here (device OOM, tunnel drop mid-dispatch)
        # must flow through the NORMAL window-failure path: the chain tail
        # above is already published, so the build stage's failure handler
        # — which raises the phantom-usage taint and nacks — owns it, not
        # the dispatch handler (which would nack WITHOUT tainting and
        # leave later windows chained on usage that never commits).
        try:
            work.drain = self._plan_drain(fast)
        except Exception:
            work.failed = True
            if not (self._stop.is_set() or not self.eval_broker.enabled()):
                logger.exception("pipelined worker: drain plan failed")
        self.stats["t_dispatch_ms"] += (time.perf_counter() - t0) * 1e3
        # Mesh pipeline roll-up: module counters drain into the declared
        # schema here (workers sharing a mesh may attribute a window to
        # whichever worker drains first; totals are preserved).
        ms = kernels.mesh_stats_drain()
        if ms["windows"]:
            self.stats["mesh_windows"] += ms["windows"]
            self.stats["mesh_warm"] += ms["warm_windows"]
            self.stats["mesh_bytes"] += ms["candidate_bytes"]
            self.stats["t_mesh_exchange_ms"] += ms["exchange_ms"]
            self.stats["mesh_shards"] = (
                int(nt.mesh.devices.size) if nt.mesh is not None else 1)
            metrics.incr_counter(("nomad", "mesh", "windows"),
                                 ms["windows"])
            metrics.incr_counter(("nomad", "mesh", "warm"),
                                 ms["warm_windows"])
            metrics.incr_counter(("nomad", "mesh", "candidate_bytes"),
                                 ms["candidate_bytes"])
            metrics.add_sample(("nomad", "mesh", "exchange_ms"),
                               ms["exchange_ms"])
        # Taint bookkeeping: a window dispatched on a previous window's
        # tail inherits any phantom usage that tail turns out to carry;
        # record the taint sequence the lease saw so _finish_fast can
        # detect a taint raised while this window was in flight.
        work.chained = chained_at_dispatch
        work.taint_seq = lease.taint_seq
        return work

    def reset_stats(self) -> None:
        """Zero every schema key IN PLACE (readers like the debug endpoint
        and bench.py hold a reference to the dict, not a copy). Call
        quiesce() first when the zeros must not race in-flight windows."""
        self.stats.update(new_stats())

    def quiesce(self, timeout: float = 30.0) -> bool:
        """Wait until every dispatched window — across ALL workers sharing
        the chain arbiter — has fully finished (drained, built, acked).
        For tests/benchmarks that read or reset `stats`: eval completion
        becomes visible at the EvalUpdate apply, which is BEFORE the build
        stage's final stats writes for that window."""
        return self._arbiter.wait_drained(timeout)

    def _try_dispatch_fast(self, ev: Evaluation, token: str, snap,
                           usage_chain,
                           node_cache: Dict[tuple, tuple],
                           noise_vec: Optional[np.ndarray] = None,
                           tables: Optional[dict] = None,
                           host: bool = False
                           ) -> Optional[_FastEval]:
        """Launch the eval's placement kernel chained on the window's usage,
        or return None to route it through the per-eval GenericScheduler."""
        if ev.Type not in (JobTypeService, JobTypeBatch):
            return None
        if ev.TriggeredBy not in _HANDLED_TRIGGERS or ev.AnnotatePlan:
            return None
        td0 = time.perf_counter()
        job = snap.job_by_id(ev.JobID)
        if job is None:
            return None
        batch = ev.Type == JobTypeBatch
        groups = materialize_task_groups(job)
        allocs = filter_complete_allocs(
            list(snap.allocs_by_job(ev.JobID)), batch)
        tainted = tainted_nodes(snap, allocs)
        diff = diff_allocs(job, tainted, groups, allocs)
        # Pure placement only: stops/updates/migrations carry eviction and
        # rolling-limit semantics the per-eval path owns.
        if diff.update or diff.migrate or diff.stop or not diff.place:
            return None
        td1 = time.perf_counter()
        self.stats["t_diff_ms"] += (td1 - td0) * 1e3

        # Alias the snapshot's job into the plan (no deep copy): committed
        # jobs are value-frozen in the state store and the plan only reads.
        plan = ev.make_plan(job, copy_job=False)
        ctx = EvalContext(snap, plan, logger)
        stack = GenericStack(ctx, self.tindex, batch,
                             columnar=self.service_columnar)
        dc_key = tuple(sorted(job.Datacenters))
        cached = node_cache.get(dc_key)
        if cached is None:
            from nomad_tpu.tensor.constraints import ClassEligibility

            nodes, by_dc = ready_nodes_in_dcs(snap, job.Datacenters)
            nt = self.tindex.nt
            nodes_by_id = {n.ID: n for n in nodes}
            cand_mask = np.zeros(nt.n_rows, dtype=bool)
            for n in nodes:
                row = nt.row_of.get(n.ID)
                if row is not None:
                    cand_mask[row] = True
            elig = ClassEligibility(nt, nodes)
            cached = (nodes_by_id, cand_mask, elig, by_dc, {})
            node_cache[dc_key] = cached
        nodes_by_id, cand_mask, elig, by_dc, prep_cache = cached
        if not nodes_by_id:
            return None
        stack.job = job
        stack.adopt_nodes(nodes_by_id, cand_mask, elig)
        ctx.metrics.NodesAvailable = by_dc

        td2 = time.perf_counter()
        # A storm re-submits value-identical jobs: share the whole prepared
        # batch (and its resolved device inputs) across them. Only sound
        # when the job has no prior allocs (zero anti-affinity/banned base).
        sig = None if allocs else _prep_sig(job, diff.place, batch)
        prep = prep_cache.get(sig) if sig is not None else None
        if prep is None:
            prep = stack.prepare_batch([t.TaskGroup for t in diff.place],
                                       noise_vec=noise_vec)
            if sig is not None:
                prep_cache[sig] = prep
        td3 = time.perf_counter()
        self.stats["t_prep_ms"] += (td3 - td2) * 1e3
        # A huge eval blows the host budget even alone; it goes to the
        # device instead. Its launch is deferred like any device rec, so
        # within a host-mode window it chains AFTER the host-placed evals
        # (a pure reorder — every eval still sees a usage state containing
        # all placements committed before its own). The shared window
        # budget debits each eval's TRUE row-step cost.
        host_cost = self.tindex.nt.n_rows * prep.p_pad
        if host and len(diff.place) <= 256 \
                and host_cost <= self._host_rows_left:
            self._host_rows_left -= host_cost
            res = stack.dispatch_host(prep, usage_override=usage_chain)
            self.stats["host"] += 1
        else:
            # Device launch is DEFERRED: the window loop groups
            # consecutive shared-prep recs into one place_batch_multi
            # dispatch (a storm window = one kernel, not one per eval).
            res = None
        self.stats["t_launch_ms"] += (time.perf_counter() - td3) * 1e3
        # shareable: prep came from (or went into) the window prep cache,
        # which only holds value-identical jobs with NO prior allocs —
        # exactly the precondition for the multi kernel's per-eval resets.
        return _FastEval(ev=ev, token=token, plan=plan, ctx=ctx, stack=stack,
                         prep=prep, place=diff.place, res=res,
                         shareable=sig is not None)

    def _finish_fast(self, work: _WindowWork) -> None:
        """Build + submit plans, wait, batch status updates (packed results
        already drained by stage 2)."""
        fast, packed = work.fast, work.packed
        t1 = time.perf_counter()

        # Build and enqueue plans back-to-back: the applier verifies plan i
        # while we materialize plan i+1's ports host-side.
        nt = self.tindex.nt
        # The kernels ran chained: eval k saw evals 1..k-1's placements.
        # The shared accumulator can reproduce that chain host-side so
        # exhaustion diagnostics diff against the usage the kernel actually
        # saw — but it stays DEFERRED (queued batches, no scatter) until an
        # exhaustion actually reads it, which an all-placed storm window
        # never does.
        acc = WindowAccumulator(nt.n_rows)
        submit: List[_FastEval] = []
        for rec, cr in zip(fast, packed):
            if rec.stale:
                continue  # redelivered between stages: abandoned
            tc0 = time.perf_counter()
            try:
                ok = rec.stack.collect_build(
                    rec.prep, cr, rec.ev.ID, rec.plan.Job, rec.place,
                    rec.plan, rec.failed_tg_allocs, acc)
            except Exception:
                logger.exception("collect failed for eval %s", rec.ev.ID)
                rec.fallback = True
                continue
            if not ok:
                # Port collision against the cached index (or a node that
                # vanished mid-window): rare; the sync path's banned-row
                # retry loop owns it.
                rec.fallback = True
                continue
            self.stats["t_collect_ms"] += (time.perf_counter() - tc0) * 1e3
            if rec.plan.is_no_op() and not rec.failed_tg_allocs:
                rec.fallback = True  # nothing placeable; let sync path decide
                continue
            rec.plan.EvalToken = rec.token
            stamp_fed_born(rec.plan, work.fed_born)
            submit.append(rec)
        # ONE broker lock round re-arms every submitting eval's deadline
        # and surfaces redeliveries; ONE queue lock round enqueues the
        # window's plans contiguously in chain order (a second worker's
        # window cannot interleave into ours mid-submit).
        if submit:
            try:
                stale_ids = self.eval_broker.outstanding_reset_batch(
                    [(r.ev.ID, r.token) for r in submit])
                live = []
                for rec in submit:
                    if rec.ev.ID in stale_ids:
                        # Redelivered mid-window: another worker owns this
                        # eval now — abandon it entirely (no fallback
                        # re-run, no ack).
                        logger.debug("eval %s redelivered mid-window",
                                     rec.ev.ID)
                        rec.stale = True
                    elif not rec.plan.is_no_op():
                        live.append(rec)
                for rec, pending in zip(live, self.plan_queue.enqueue_all(
                        [r.plan for r in live])):
                    rec.pending = pending
            except Exception:
                logger.exception("plan enqueue failed for window")
                for rec in submit:
                    if not rec.stale and rec.pending is None:
                        rec.fallback = True

        t2 = time.perf_counter()
        self.stats["t_build_ms"] += (t2 - t1) * 1e3

        # Wait for the applier; anything not fully committed re-runs sync.
        for rec in fast:
            if rec.fallback or rec.stale or rec.pending is None:
                continue
            try:
                # Raises on timeout or applier rejection (stale token):
                # only THIS eval falls back, not the whole window.
                result = rec.pending.wait(timeout=30.0)
            except StaleSnapshotError:
                # The applier rejected the window's snapshot as over the
                # federation staleness bound — every plan of the window
                # shares it, so the WHOLE window fails: the build-loop
                # handler nacks every eval and taints the chain, and the
                # broker's exactly-once redelivery re-runs them against a
                # fresh snapshot (the same machinery as a killed window).
                self.stats["fed_stale"] += 1
                raise
            except Exception:
                logger.debug("plan for eval %s not committed; re-running"
                             " per-eval", rec.ev.ID)
                rec.fallback = True
                continue
            full_commit, _, _ = result.full_commit(rec.plan)
            if not full_commit:
                rec.fallback = True

        # Phantom-usage quarantine: a stale/fallback record's kernel
        # placements were baked into the window's device chain but never
        # commit as dispatched. Any eval placed BEHIND that phantom usage
        # that could not fully place must re-run on the exact path instead
        # of emitting a spurious blocked eval (no capacity-change event
        # would ever unblock it — the capacity was never really taken).
        # Two taint sources: a stale/fallback record EARLIER in this
        # window, and a taint raised by a previously-dispatched window
        # while this one (chained on its tail) was in flight.
        tainted_from = next((i for i, rec in enumerate(fast)
                             if rec.stale or rec.fallback), None)
        # Chain-order barrier: every window published BEFORE ours must
        # have made its taint decision first. One worker's build thread
        # settles its own windows in order, but a window chained on
        # ANOTHER worker's tail could otherwise beat that worker's build
        # here and read the taint sequence before the phantom it rode on
        # is announced.
        if not self._arbiter.wait_turn(work.chain_seq, self._stop):
            logger.debug("window %d: predecessors unsettled after barrier "
                         "timeout; taint check may be early", work.chain_seq)
        external_taint = (work.chained
                          and self._arbiter.taint_changed(work.taint_seq))
        if tainted_from is not None:
            # Windows in flight on OUR tail — any worker's — inherit the
            # phantom too.
            self._arbiter.taint()
        # Our taint decision is made: successors may now make theirs
        # (they need our taint, not our acks — settle BEFORE the status
        # batch and ack round below).
        self._arbiter.mark_settled(work.chain_seq)
        if tainted_from is not None or external_taint:
            start = 0 if external_taint else tainted_from + 1
            for rec in fast[start:]:
                if (not rec.stale and not rec.fallback
                        and rec.failed_tg_allocs):
                    logger.debug(
                        "eval %s failed placements behind phantom window "
                        "usage; re-running per-eval", rec.ev.ID)
                    rec.fallback = True

        # QoS preemption routing: a HIGH-tier eval that could not fully
        # place must not quietly park as a blocked eval — it re-runs on
        # the exact per-eval path, where the scheduler may evict
        # lower-tier allocs to make room (qos/preemption.py). Lower tiers
        # keep the normal blocked-eval flow.
        qos = self.qos
        if qos is not None and qos.enabled and qos.preemption:
            from nomad_tpu.qos.tiers import TIER_HIGH

            for rec in fast:
                if (not rec.fallback and not rec.stale
                        and rec.failed_tg_allocs
                        and qos.tier_of(rec.ev.Priority) == TIER_HIGH):
                    rec.fallback = True

        eval_updates: List[Evaluation] = []
        done: List[_FastEval] = []
        for rec in fast:
            if rec.fallback or rec.stale:
                continue
            eval_updates.extend(self._status_evals(rec))
            done.append(rec)

        t3 = time.perf_counter()
        self.stats["t_planwait_ms"] += (t3 - t2) * 1e3
        if eval_updates:
            self.raft.apply(MessageType.EvalUpdate, {"Evals": eval_updates})
        self.stats["t_evalupd_ms"] += (time.perf_counter() - t3) * 1e3
        self.stats["fast"] += len(done)
        if done:
            # ONE broker lock round acks the whole window; per-eval races
            # (redelivered / token rotated) come back as failures instead
            # of aborting the rest of the window's acks.
            try:
                for eval_id, e in self.eval_broker.ack_batch(
                        [(rec.ev.ID, rec.token) for rec in done]):
                    logger.debug("worker: ack skipped for %s: %s", eval_id, e)
            except Exception:
                logger.exception("worker: window ack failed")
        for rec in done:
            if rec.span is not None:
                rec.span.set_attr("path", "fast")
                rec.span.finish()
        for rec in fast:
            if rec.fallback:
                self.stats["fallback"] += 1
                if rec.span is not None:
                    # Tail-retention rule: a fallback marks the trace.
                    rec.span.event("fallback", eval=rec.ev.ID)
                    rec.span.finish()
                self._process_slow(rec.ev, rec.token)
            elif rec.stale:
                self.stats["stale"] += 1
                if rec.span is not None:
                    rec.span.event("stale", eval=rec.ev.ID)
                    rec.span.finish()

    def _status_evals(self, rec: _FastEval) -> List[Evaluation]:
        """Terminal status (+ blocked follow-up) for one fast eval, matching
        GenericScheduler.process/set_status exactly."""
        out: List[Evaluation] = []
        blocked = None
        if rec.failed_tg_allocs and rec.ev.Status != EvalStatusBlocked:
            escaped = has_escaped(rec.stack, rec.plan.Job)
            elig = {} if escaped else class_eligibility(
                rec.stack, rec.plan.Job, self.tindex)
            blocked = rec.ev.create_blocked_eval(elig, escaped)
            blocked.StatusDescription = BLOCKED_EVAL_FAILED_PLACEMENTS
            blocked.SnapshotIndex = rec.ctx.state.latest_index()
            out.append(blocked)
        if rec.ev.Status == EvalStatusBlocked and rec.failed_tg_allocs:
            # A blocked eval that still couldn't fully place is re-blocked.
            new_eval = rec.ev.copy()
            new_eval.EscapedComputedClass = has_escaped(rec.stack,
                                                        rec.plan.Job)
            new_eval.ClassEligibility = class_eligibility(
                rec.stack, rec.plan.Job, self.tindex)
            new_eval.SnapshotIndex = rec.ctx.state.latest_index()
            out.append(new_eval)
            return out
        new_eval = rec.ev.copy()
        new_eval.Status = EvalStatusComplete
        new_eval.StatusDescription = ""
        new_eval.FailedTGAllocs = rec.failed_tg_allocs or {}
        if blocked is not None:
            new_eval.BlockedEval = blocked.ID
        out.append(new_eval)
        return out

    def _plan_drain(self, fast: List[_FastEval]) -> _DrainPlan:
        """Dispatch-time drain assembly: reduce every device-side result to
        the minimal host arrays (kernels.compact_window — int32 chosen
        rows, winner scores, per-eval nf_last + success mask) and START the
        device->host copies, all async. The drain stage then only waits on
        transfers already in flight. Host-placed results compact inline
        (numpy, no device round trip). Singleton device results still
        stack on device first — arity padded to the configured window size
        so XLA compiles ONE program per packed shape, never one per
        distinct window fill level."""
        t0 = time.perf_counter()
        layout: list = [None] * len(fast)
        fetches: dict = {}
        # parent id -> (parent, [(pos-in-fast, slice-index)], prep)
        multi: Dict[int, tuple] = {}
        singles: Dict[int, list] = {}  # p_pad -> [(pos-in-fast, rec)]
        for i, rec in enumerate(fast):
            res = rec.res
            if isinstance(res, _MultiSlice):
                multi.setdefault(id(res.parent),
                                 (res.parent, [], rec.prep))[1].append(
                    (i, res.index))
            elif isinstance(res.packed, np.ndarray):
                layout[i] = ("host",
                             kernels.compact_host(res.packed,
                                                  rec.prep.n_valid))
            else:
                singles.setdefault(rec.prep.p_pad, []).append((i, rec))
        if not multi and not singles:
            return _DrainPlan(fetches=fetches, layout=layout)
        try:
            import jax.numpy as jnp

            for pid, (parent, slices, prep) in multi.items():
                p = prep.p_pad
                e_pad = parent.packed.shape[0] // p
                valid = np.zeros((e_pad, p), dtype=bool)
                for _, sl_idx in slices:
                    valid[sl_idx] = prep.valid
                last = np.full(e_pad, prep.n_valid - 1, dtype=np.int32)
                key = ("multi", pid)
                # valid/last are byte-identical across a storm's windows:
                # the content-addressed cache uploads them once.
                fetches[key] = kernels.compact_window(
                    parent.packed.reshape(e_pad, p, 3),
                    device_input(valid), device_input(last))
                for i, sl_idx in slices:
                    layout[i] = ("dev", key, sl_idx)
            for p_pad, group in singles.items():
                arrs = [rec.res.packed for _, rec in group]
                if len(arrs) < self.window:
                    arrs = arrs + [arrs[-1]] * (self.window - len(arrs))
                valid = np.zeros((len(arrs), p_pad), dtype=bool)
                last = np.zeros(len(arrs), dtype=np.int32)
                for k, (_, rec) in enumerate(group):
                    valid[k] = rec.prep.valid
                    last[k] = rec.prep.n_valid - 1
                key = ("stack", p_pad)
                fetches[key] = kernels.compact_window(
                    jnp.stack(arrs), device_input(valid),
                    device_input(last))
                for k, (i, _) in enumerate(group):
                    layout[i] = ("dev", key, k)
            # Start the host copies NOW: the bytes ride the tunnel under
            # the next window's dispatch / the previous window's build.
            for out in fetches.values():
                for arr in out:
                    try:
                        arr.copy_to_host_async()
                    # lint: allow(swallow, fetch still works without the head start)
                    except Exception:
                        pass
        except (ImportError, TypeError, AttributeError):
            # Non-jax device results (host-side arrays in tests): resolve
            # everything inline, no fetch needed.
            fetches = {}
            for pid, (parent, slices, prep) in multi.items():
                arr = np.asarray(parent.packed)
                p = prep.p_pad
                for i, sl_idx in slices:
                    layout[i] = ("host", kernels.compact_host(
                        arr[sl_idx * p:(sl_idx + 1) * p], prep.n_valid))
            for p_pad, group in singles.items():
                for i, rec in group:
                    layout[i] = ("host", kernels.compact_host(
                        np.asarray(rec.res.packed), rec.prep.n_valid))
        self.stats["t_drain_stack_ms"] += (time.perf_counter() - t0) * 1e3
        return _DrainPlan(fetches=fetches, layout=layout)

    def _drain_window(self, work: _WindowWork) -> list:
        """ONE blocking device->host call for the whole window, however it
        mixes fused parents and stacked per-eval results: the compaction
        outputs were dispatched (and their copies started) at dispatch
        time, so this jax.device_get waits on transfers already in flight
        instead of initiating them. Every separate host sync costs a ~95ms
        round trip on the axon tunnel, so the drain never pays more than
        one. Returns one CompactResult per fast rec, in chain order."""
        # Failure seam: a worker dying mid-window (process kill, tunnel
        # drop during the fetch) must nack the window for exactly-once
        # redelivery and taint the chain for a coherent rebase — the
        # chaos schedule in tests/test_chaos_schedules.py drives it.
        if failpoints.fire("worker.window.drain") == "drop":
            raise failpoints.FailpointError("worker.window.drain")
        plan = work.drain
        out: list = [None] * len(plan.layout)
        fetched = {}
        flags = work.mesh_flags or []
        if plan.fetches or flags:
            import jax

            t0 = time.perf_counter()
            # The warm-mesh exactness certificates (tiny device scalars)
            # ride the SAME blocking call as the compaction outputs, so
            # the one-host-sync invariant above survives the mesh path.
            flags_h, fetched = jax.device_get((flags, plan.fetches))
            self.stats["t_drain_fetch_ms"] += \
                (time.perf_counter() - t0) * 1e3
            if any(float(f) > 0 for f in flags_h):
                # Warm mesh windows are exact only when the certificate
                # held (kernels.py 'shard-local mesh pipeline'): a failed
                # certificate means a winner may have come from outside
                # the resident pool, so the window's placements are
                # suspect. Fail the drain — the build stage's failure
                # handler nacks every eval and taints the chain, and the
                # broker's exactly-once redelivery re-runs them on a
                # COLD (unconditionally exact) window after the rebase.
                self.stats["mesh_cert_miss"] += 1
                metrics.incr_counter(("nomad", "mesh", "cert_miss"))
                raise RuntimeError(
                    "mesh warm-window exactness certificate failed; "
                    "nacking window for cold redispatch")
        for i, ent in enumerate(plan.layout):
            if ent[0] == "host":
                out[i] = ent[1]
            else:
                _, key, idx = ent
                chosen, scores, nf_last, ok = fetched[key]
                out[i] = kernels.CompactResult(
                    chosen=chosen[idx], scores=scores[idx],
                    nf_last=int(nf_last[idx]), ok=bool(ok[idx]))
        return out

    # ------------------------------------------------------------- slow path
    def _process_slow(self, ev: Evaluation, token: str) -> None:
        """Exact per-eval Worker behavior for everything off the fast path."""
        self._eval, self._token = ev, token
        try:
            self._invoke_scheduler(ev, token)
        except Exception:
            if self._stop.is_set() or not self.eval_broker.enabled():
                logger.debug("worker: dropping eval %s on shutdown", ev.ID)
                return
            logger.exception("worker: failed to process eval %s", ev.ID)
            self._send_nack(ev.ID, token)
            return
        self._send_ack(ev.ID, token)
