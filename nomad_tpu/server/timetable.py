"""TimeTable: sparse Raft-index <-> wallclock mapping (reference:
nomad/timetable.go).

GC thresholds are expressed in time but state is indexed by Raft index; the
timetable witnesses (index, time) pairs at a bounded granularity so
NearestIndex(time) can translate.
"""

from __future__ import annotations

import threading
from typing import List, Tuple


class TimeTable:
    def __init__(self, granularity: float = 300.0, limit: float = 72 * 3600.0):
        self.granularity = granularity
        self.limit = limit
        # Fixed capacity of limit/granularity entries, oldest dropped on
        # overflow (reference: timetable.go:28-31 — a ring sized by the
        # retention window, NOT a timestamp prune; the boundary entry at
        # exactly `limit` age falls off when capacity is reached).
        self._max = max(1, int(limit / granularity))
        self._lock = threading.Lock()
        self._table: List[Tuple[int, float]] = []  # newest first

    def witness(self, index: int, when: float) -> None:
        with self._lock:
            # Monotonic indexes only (reference: timetable.go:73-75).
            if self._table and index < self._table[0][0]:
                return
            if self._table and when - self._table[0][1] < self.granularity:
                return
            self._table.insert(0, (index, when))
            del self._table[self._max:]

    def nearest_index(self, when: float) -> int:
        """Largest index witnessed at or before `when`."""
        with self._lock:
            for index, t in self._table:
                if t <= when:
                    return index
            return 0

    def nearest_time(self, index: int) -> float:
        with self._lock:
            for idx, t in self._table:
                if idx <= index:
                    return t
            return 0.0

    def nearest_time_after(self, index: int) -> float:
        """Earliest witness at or after `index` — an UPPER bound on when
        the index was applied (0.0 if nothing that new was witnessed).
        Paired with nearest_time this brackets an index's wall time to
        one witness interval; the failover age re-seed uses the spread
        as burn slack."""
        with self._lock:
            for idx, t in reversed(self._table):  # oldest first
                if idx >= index:
                    return t
            return 0.0

    def serialize(self) -> List[Tuple[int, float]]:
        with self._lock:
            return list(self._table)

    def deserialize(self, data) -> None:
        with self._lock:
            self._table = [(int(i), float(t)) for i, t in data]
