"""TimeTable: sparse Raft-index <-> wallclock mapping (reference:
nomad/timetable.go).

GC thresholds are expressed in time but state is indexed by Raft index; the
timetable witnesses (index, time) pairs at a bounded granularity so
NearestIndex(time) can translate.
"""

from __future__ import annotations

import threading
from typing import List, Tuple


class TimeTable:
    def __init__(self, granularity: float = 300.0, limit: float = 72 * 3600.0):
        self.granularity = granularity
        self.limit = limit
        self._lock = threading.Lock()
        self._table: List[Tuple[int, float]] = []  # newest first

    def witness(self, index: int, when: float) -> None:
        with self._lock:
            if self._table and when - self._table[0][1] < self.granularity:
                return
            self._table.insert(0, (index, when))
            # Prune entries beyond the limit.
            cutoff = when - self.limit
            while self._table and self._table[-1][1] < cutoff:
                self._table.pop()

    def nearest_index(self, when: float) -> int:
        """Largest index witnessed at or before `when`."""
        with self._lock:
            for index, t in self._table:
                if t <= when:
                    return index
            return 0

    def nearest_time(self, index: int) -> float:
        with self._lock:
            for idx, t in self._table:
                if idx <= index:
                    return t
            return 0.0

    def serialize(self) -> List[Tuple[int, float]]:
        with self._lock:
            return list(self._table)

    def deserialize(self, data) -> None:
        with self._lock:
            self._table = [(int(i), float(t)) for i, t in data]
