"""nomad_tpu — a TPU-native cluster scheduler framework.

A brand-new implementation of the capabilities of HashiCorp Nomad v0.4.0
(declarative jobs -> evaluations -> plans -> allocations over a replicated
server cluster, with pluggable task drivers on client nodes), re-architected
for TPU hardware: the scheduling hot path — feasibility masking, bin-pack
scoring, and plan verification over the node table — runs as vectorized,
`jit`/`pjit`-sharded XLA programs with the node axis laid out over the device
mesh, while the control plane (state store, eval broker, plan applier, RPC,
client runtime) runs host-side.

Package layout:
  structs/    data model + wire structs      (reference: nomad/structs/)
  state/      MVCC state store + watches     (reference: nomad/state/)
  tensor/     node-table tensorization       (new: TPU-first design)
  scheduler/  schedulers + XLA kernels       (reference: scheduler/)
  server/     broker, plan applier, worker   (reference: nomad/*.go)
  client/     node agent + drivers           (reference: client/)
  agent/      HTTP API + composite agent     (reference: command/agent/)
  api/        client library                 (reference: api/)
  cli/        command line                   (reference: command/)
  jobspec/    HCL job spec parser            (reference: jobspec/)
"""

__version__ = "0.1.0"

API_MAJOR_VERSION = 1
API_MINOR_VERSION = 0
