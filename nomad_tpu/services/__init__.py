"""Service discovery & health checking.

The standalone replacement for the reference's external-Consul delegation
(reference: command/agent/consul/syncer.go, client/driver/executor/checks.go):

- registrations are first-class replicated objects in the state store
  (structs.ServiceRegistration), written through the FSM and queryable
  cluster-wide with blocking queries (`Service.List` / `Service.GetService`)
- each client agent runs http/tcp/script check runners node-locally on the
  shared timer wheel and syncs status changes up in batches
  (services/manager.py)
- servers self-register under the name "nomad-server" so clients can
  bootstrap their server list from any agent's HTTP API
"""

from typing import List

from .checks import run_check
from .manager import ServiceManager

__all__ = ["ServiceManager", "build_server_service_regs",
           "server_service_reg_ids", "run_check"]


def build_server_service_regs(node_id: str, rpc_addr: str = "",
                              http_addr: str = "") -> List:
    """Registrations advertising one server under "nomad-server" (used by
    agent self-registration; clients bootstrap their server list from
    these — client/rpc.py discover_servers)."""
    from nomad_tpu.structs import ServiceRegistration
    from nomad_tpu.structs.structs import CheckStatusPassing

    regs = []
    for tag, addr in (("rpc", rpc_addr), ("http", http_addr)):
        if not addr:
            continue
        host, _, port = addr.rpartition(":")
        regs.append(ServiceRegistration(
            ID=f"_nomad-server-{node_id}-{tag}",
            ServiceName="nomad-server", Tags=[tag], NodeID=node_id,
            Address=host, Port=int(port or 0), Status=CheckStatusPassing))
    return regs


def server_service_reg_ids(node_id: str) -> List[str]:
    return [f"_nomad-server-{node_id}-{tag}" for tag in ("rpc", "http")]
