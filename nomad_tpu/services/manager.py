"""ServiceManager: node-local service registration + check execution.

Owned by the client agent. Task runners report task starts/stops; the
manager materializes ServiceRegistrations (resolving each service's
PortLabel against the task's scheduler-assigned networks), runs the
services' checks on the shared timer wheel, and syncs registrations up to
the servers in debounced batches over Service.Sync.

Reference behavior being replaced: the Consul syncer's periodic reconcile
(consul/syncer.go:772-836) and the executor's script-check runner
(client/driver/executor/checks.go). Status here additionally drives task
restarts: a check that stays critical for `critical_threshold` consecutive
runs restarts the task through its restart policy — the capability the
reference defers to operators watching Consul.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from nomad_tpu.resilience import failpoints
from nomad_tpu.telemetry import trace
from nomad_tpu.structs import (
    Allocation,
    CheckState,
    ServiceRegistration,
    Task,
)
from nomad_tpu.structs.structs import (
    CheckStatusCritical,
    CheckStatusUnknown,
    ns_to_seconds,
)
from nomad_tpu.timerwheel import DaemonPool, wheel

from .checks import run_check

logger = logging.getLogger("nomad.services")

SYNC_INTERVAL = 0.5  # debounced push cadence (reference syncs each 5s +jitter)
# Anti-entropy: periodically re-push EVERYTHING, dirty or not. Heals
# server-side drift the client can't observe — e.g. the registry marking a
# down node's services critical; when the node recovers, the next full sync
# restores true statuses (reference: the syncer's periodic full
# reconciliation, syncer.go:772-836).
FULL_SYNC_INTERVAL = 30.0


class _Check:
    __slots__ = ("spec", "state", "critical_count", "timer", "seq")

    def __init__(self, spec):
        self.spec = spec
        self.state = CheckState(Name=spec.Name, Type=spec.Type.lower(),
                                Status=CheckStatusUnknown)
        self.critical_count = 0
        self.timer = None
        self.seq = 0  # invalidates in-flight timers after deregistration


class _Instance:
    __slots__ = ("reg", "checks", "alloc_id", "task_name", "cwd", "env",
                 "exec_fn")

    def __init__(self, reg: ServiceRegistration, checks: List[_Check],
                 alloc_id: str, task_name: str,
                 cwd: Optional[str], env: Optional[dict], exec_fn=None):
        self.reg = reg
        self.checks = checks
        self.alloc_id = alloc_id
        self.task_name = task_name
        self.cwd = cwd
        self.env = env
        # In-task script exec (DriverHandle.exec_in_task), preferred over
        # host cwd/env execution for script checks.
        self.exec_fn = exec_fn


def _same_registration(prev: _Instance, reg: ServiceRegistration,
                       svc) -> bool:
    """True when the new definition matches the live instance: tags,
    address/port, and every check SPEC (not check state). Unchanged
    definitions keep their check state, counters, and timers."""
    p = prev.reg
    if (p.Tags, p.Address, p.Port) != (reg.Tags, reg.Address, reg.Port):
        return False
    spec = [(c.Name, c.Type, c.Command, tuple(c.Args), c.Path, c.Protocol,
             c.Interval, c.Timeout) for c in svc.Checks]
    have = [(c.spec.Name, c.spec.Type, c.spec.Command, tuple(c.spec.Args),
             c.spec.Path, c.spec.Protocol, c.spec.Interval, c.spec.Timeout)
            for c in prev.checks]
    return spec == have


class ServiceManager:
    def __init__(self, node,
                 sync_fn: Callable[[List[ServiceRegistration], List[str]],
                                   None],
                 restart_fn: Optional[Callable[[str, str, str], None]] = None,
                 critical_threshold: int = 3):
        self.node = node
        self.sync_fn = sync_fn
        self.restart_fn = restart_fn
        self.critical_threshold = critical_threshold
        self._lock = threading.Lock()
        self._instances: Dict[str, _Instance] = {}
        self._dirty: set = set()
        self._deletes: set = set()
        self._stop = threading.Event()
        # Checks block (connect timeouts, scripts): they run on a dedicated
        # pool so the shared timer wheel's workers stay responsive.
        self._pool = DaemonPool(4, "svc-check")
        self._thread = threading.Thread(target=self._sync_loop, daemon=True,
                                        name="service-sync")
        self._thread.start()

    # ------------------------------------------------------------- lifecycle
    def register_task(self, alloc: Allocation, task: Task,
                      cwd: Optional[str] = None,
                      env: Optional[dict] = None,
                      exec_fn=None) -> None:
        """Register the task's services — idempotent, and RECONCILING: a
        service dropped from the task definition (in-place update) is
        deregistered (reference: the Consul syncer diffs desired vs
        registered, syncer.go:574-674)."""
        with self._lock:
            wanted = {f"_nomad-task-{alloc.ID}-{task.Name}-{svc.Name}"
                      for svc in task.Services}
            for rid, inst in list(self._instances.items()):
                if (inst.alloc_id == alloc.ID
                        and inst.task_name == task.Name
                        and rid not in wanted):
                    self._drop(rid)
            for svc in task.Services:
                address, port = self._resolve(task, svc.PortLabel)
                reg = ServiceRegistration(
                    ID=f"_nomad-task-{alloc.ID}-{task.Name}-{svc.Name}",
                    ServiceName=svc.Name, Tags=list(svc.Tags),
                    JobID=alloc.JobID, AllocID=alloc.ID, TaskName=task.Name,
                    NodeID=self.node.ID, Address=address, Port=port)
                prev = self._instances.get(reg.ID)
                inst_cwd, inst_env, inst_exec = cwd, env, exec_fn
                if prev is not None:
                    if _same_registration(prev, reg, svc):
                        continue  # unchanged: keep check state and timers
                    # Definition changed (in-place update): keep the script
                    # check context unless the caller re-supplied it, and
                    # retire the old instance's check timers. Locals only —
                    # one service's preserved context must not leak into
                    # its siblings.
                    if inst_cwd is None:
                        inst_cwd = prev.cwd
                    if inst_env is None:
                        inst_env = prev.env
                    if inst_exec is None:
                        inst_exec = prev.exec_fn
                    self._drop(reg.ID)
                checks = [_Check(c) for c in svc.Checks]
                reg.Checks = [c.state for c in checks]
                reg.Status = reg.derive_status()
                inst = _Instance(reg, checks, alloc.ID, task.Name,
                                 inst_cwd, inst_env, inst_exec)
                self._instances[reg.ID] = inst
                self._deletes.discard(reg.ID)
                self._dirty.add(reg.ID)
                for check in checks:
                    self._schedule(reg.ID, check, first=True)

    def deregister_task(self, alloc_id: str, task_name: str) -> None:
        with self._lock:
            for rid, inst in list(self._instances.items()):
                if inst.alloc_id == alloc_id and inst.task_name == task_name:
                    self._drop(rid)

    def deregister_alloc(self, alloc_id: str) -> None:
        with self._lock:
            for rid, inst in list(self._instances.items()):
                if inst.alloc_id == alloc_id:
                    self._drop(rid)

    def shutdown(self) -> None:
        with self._lock:
            for rid in list(self._instances):
                self._drop(rid)
        self._flush()  # best-effort final dereg push
        self._stop.set()

    def _drop(self, rid: str) -> None:
        inst = self._instances.pop(rid, None)
        if inst is None:
            return
        for check in inst.checks:
            check.seq += 1  # kills rescheduling of in-flight runs
            if check.timer is not None:
                check.timer.cancel()
        self._dirty.discard(rid)
        self._deletes.add(rid)

    # ----------------------------------------------------------- port resolve
    def _resolve(self, task: Task, port_label: str) -> Tuple[str, int]:
        node_ip = (self.node.Attributes or {}).get(
            "unique.network.ip-address", "127.0.0.1")
        if task.Resources is None or not port_label:
            return node_ip, 0
        for net in task.Resources.Networks:
            for p in list(net.ReservedPorts) + list(net.DynamicPorts):
                if p.Label == port_label:
                    return net.IP or node_ip, p.Value
        return node_ip, 0

    # ----------------------------------------------------------------- checks
    def _schedule(self, rid: str, check: _Check, first: bool = False) -> None:
        interval = max(ns_to_seconds(check.spec.Interval), 1.0)
        seq = check.seq
        delay = min(1.0, interval) if first else interval
        check.timer = wheel.after(
            delay, lambda: self._pool.submit(self._run, rid, check, seq))

    def _run(self, rid: str, check: _Check, seq: int) -> None:
        with self._lock:
            inst = self._instances.get(rid)
            if inst is None or check.seq != seq:
                return
            reg = inst.reg
            cwd, env, exec_fn = inst.cwd, inst.env, inst.exec_fn
        status, output = run_check(check.spec, reg.Address, reg.Port,
                                   cwd=cwd, env=env, exec_fn=exec_fn)
        restart: Optional[str] = None
        with self._lock:
            if check.seq != seq or rid not in self._instances:
                return
            changed = (status != check.state.Status
                       or output != check.state.Output)
            check.state.Status = status
            check.state.Output = output
            check.state.Timestamp = time.time()
            if status == CheckStatusCritical:
                check.critical_count += 1
                if (self.restart_fn is not None
                        and check.critical_count >= self.critical_threshold):
                    check.critical_count = 0
                    restart = (f"check {check.spec.Name!r} critical "
                               f"{self.critical_threshold}x: {output}")
            else:
                check.critical_count = 0
            new_status = reg.derive_status()
            if changed or new_status != reg.Status:
                reg.Status = new_status
                self._dirty.add(rid)
            self._schedule(rid, check)
        if restart is not None:
            try:
                self.restart_fn(inst.alloc_id, inst.task_name, restart)
            except Exception:
                logger.exception("health restart failed for %s/%s",
                                 inst.alloc_id, inst.task_name)

    # ------------------------------------------------------------------- sync
    def _sync_loop(self) -> None:
        last_full = time.monotonic()
        while not self._stop.wait(SYNC_INTERVAL):
            if time.monotonic() - last_full >= FULL_SYNC_INTERVAL:
                last_full = time.monotonic()
                with self._lock:
                    self._dirty.update(self._instances)
            self._flush()

    def _flush(self) -> None:
        with self._lock:
            if not self._dirty and not self._deletes:
                return
            upserts = [self._instances[rid].reg.copy()
                       for rid in self._dirty if rid in self._instances]
            deletes = list(self._deletes)
            self._dirty.clear()
            self._deletes.clear()
        # Traced as its own root (only when a batch actually pushes): the
        # sync seam is the ROADMAP-named failpoint site, and a triggered
        # fault must land as an event on this span.
        with trace.root_span("client.services.sync",
                             upserts=len(upserts), deletes=len(deletes)):
            try:
                if failpoints.fire("services.sync") == "drop":
                    # A lost batch, the way a partitioned wire would lose
                    # it: the except path re-queues everything for the
                    # next flush / anti-entropy pass.
                    raise failpoints.FailpointError(
                        "services.sync", "service sync batch dropped")
                self.sync_fn(upserts, deletes)
            except Exception:
                logger.exception("service sync failed; will retry")
                with self._lock:
                    for reg in upserts:
                        if reg.ID in self._instances:
                            self._dirty.add(reg.ID)
                    # Only re-queue deletes still absent from _instances: a
                    # registration re-registered between the failed sync and
                    # the retry must not get a delete racing its upsert (the
                    # FSM applies upserts then deletes, which would
                    # deregister the live service until the next
                    # anti-entropy full sync).
                    self._deletes.update(
                        rid for rid in deletes
                        if rid not in self._instances)
