"""Health check runners: http / tcp / script.

One stateless entry point, `run_check`, executed on the service manager's
worker pool per (check, interval) tick. http/tcp checks run from the client
agent; script checks run INSIDE the task's execution context via the
driver handle's exec (docker exec for containers, chroot-side execution
for exec tasks — reference: client/driver/executor/checks.go:31-65),
falling back to host execution with the task's cwd/env only when the
driver has no in-task exec (raw_exec semantics).
"""

from __future__ import annotations

import socket
import subprocess
import time
import urllib.error
import urllib.request
from typing import Optional, Tuple

from nomad_tpu.structs import ServiceCheck
from nomad_tpu.structs.structs import (
    CheckStatusCritical,
    CheckStatusPassing,
    CheckStatusWarning,
    ServiceCheckHTTP,
    ServiceCheckScript,
    ServiceCheckTCP,
    ns_to_seconds,
)


def run_check(check: ServiceCheck, address: str, port: int,
              cwd: Optional[str] = None,
              env: Optional[dict] = None,
              exec_fn=None) -> Tuple[str, str]:
    """Execute one check; returns (status, output). Never raises.

    exec_fn: optional `(command, args, timeout) -> (exit_code, output) |
    None` running inside the task's isolation (DriverHandle.exec_in_task);
    script checks prefer it over host execution."""
    timeout = max(ns_to_seconds(check.Timeout), 1.0)
    kind = check.Type.lower()
    try:
        if kind == ServiceCheckHTTP:
            return _http_check(check, address, port, timeout)
        if kind == ServiceCheckTCP:
            return _tcp_check(address, port, timeout)
        if kind == ServiceCheckScript:
            return _script_check(check, timeout, cwd, env, exec_fn)
        return CheckStatusCritical, f"unknown check type {check.Type!r}"
    # lint: allow(swallow, failure IS the critical check result)
    except Exception as e:  # a check must never take down the manager
        return CheckStatusCritical, str(e)


def _http_check(check: ServiceCheck, address: str, port: int,
                timeout: float) -> Tuple[str, str]:
    proto = (check.Protocol or "http").lower()
    path = check.Path if check.Path.startswith("/") else "/" + check.Path
    url = f"{proto}://{address}:{port}{path}"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            code = resp.status
    except urllib.error.HTTPError as e:
        code = e.code
    # lint: allow(swallow, failure IS the critical check result)
    except Exception as e:
        return CheckStatusCritical, f"GET {url}: {e}"
    # Consul semantics: 2xx passing, 429 warning, else critical.
    if 200 <= code < 300:
        return CheckStatusPassing, f"HTTP {code}"
    if code == 429:
        return CheckStatusWarning, f"HTTP {code}"
    return CheckStatusCritical, f"HTTP {code}"


def _tcp_check(address: str, port: int, timeout: float) -> Tuple[str, str]:
    try:
        with socket.create_connection((address, port), timeout=timeout):
            return CheckStatusPassing, "connect ok"
    except OSError as e:
        return CheckStatusCritical, f"connect {address}:{port}: {e}"


def _script_check(check: ServiceCheck, timeout: float,
                  cwd: Optional[str], env: Optional[dict],
                  exec_fn=None) -> Tuple[str, str]:
    """Exit 0 passing, 1 warning, else critical (Consul script semantics).
    Runs in the task's isolation when the driver provides an exec."""
    if exec_fn is not None:
        try:
            result = exec_fn(check.Command, list(check.Args), timeout)
        # lint: allow(swallow, failure IS the critical check result)
        except Exception as e:
            result = (2, f"in-task exec failed: {e}")
        if result is not None:
            code, output = result
            if code == 0:
                return CheckStatusPassing, output
            if code == 1:
                return CheckStatusWarning, output
            return CheckStatusCritical, output
        # Driver has no in-task exec: host fallback below.
    try:
        proc = subprocess.run(
            [check.Command] + list(check.Args), capture_output=True,
            timeout=timeout, cwd=cwd or None, env=env, text=True)
    except subprocess.TimeoutExpired:
        return CheckStatusCritical, f"script timed out after {timeout:.0f}s"
    except OSError as e:
        return CheckStatusCritical, str(e)
    output = (proc.stdout + proc.stderr)[-4096:]
    if proc.returncode == 0:
        return CheckStatusPassing, output
    if proc.returncode == 1:
        return CheckStatusWarning, output
    return CheckStatusCritical, output
