"""Cluster event stream: raft-index-ordered lifecycle events with
bounded catch-up and streaming subscriptions (README "Event stream")."""

from .broker import (
    DEFAULT_QUEUE_SIZE,
    DEFAULT_RING_SIZE,
    EventBroker,
    EventGapError,
    Subscription,
    expand_batch,
)
from .builders import build_events
from .schema import EVENT_TYPES, TOPICS, new_event

__all__ = [
    "DEFAULT_QUEUE_SIZE", "DEFAULT_RING_SIZE", "EventBroker",
    "EventGapError", "Subscription", "expand_batch", "build_events",
    "EVENT_TYPES", "TOPICS", "new_event",
]
