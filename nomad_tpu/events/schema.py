"""Declared event schema: topics, event types, and the constructor every
publish seam goes through (reference: nomad/structs/structs.go Topic*
constants + the per-type event payloads in nomad/state/events.go).

The schema is DECLARED, not emergent: `nomad-tpu lint` checks every
string-literal topic/type passed to :func:`new_event` (and every literal
in `EVENT_TYPES` itself) against this module, the same way metric and
trace key literals are schema-checked — a typo'd topic is a lint
finding, not a silently unmatchable subscription. The `nomad.events.*`
metric keys the broker emits are listed in the README stats-key table
next to the rest of the telemetry schema.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

__all__ = ["TOPICS", "EVENT_TYPES", "new_event"]

# Topics mirror the state surfaces an event describes (reference:
# TopicNode/TopicJob/TopicEvaluation/TopicAllocation/TopicService).
# "AllocationBatch" is this repo's columnar addition: one event per
# committed sweep batch carrying the row/count descriptor instead of
# per-alloc fan-out (expansion is opt-in at read time).
TOPICS = frozenset((
    "Node",
    "Job",
    "Eval",
    "Alloc",
    "AllocationBatch",
    "Service",
))

# Event type -> owning topic. One entry per lifecycle transition the FSM
# publishes; the lint checker enforces that new_event() literals agree
# with this table.
EVENT_TYPES: Dict[str, str] = {
    "NodeRegistered": "Node",
    "NodeDeregistered": "Node",
    "NodeStatusUpdated": "Node",
    "NodeDrainUpdated": "Node",
    "JobRegistered": "Job",
    "JobDeregistered": "Job",
    "PeriodicLaunchUpserted": "Job",
    "PeriodicLaunchDeleted": "Job",
    "EvalUpdated": "Eval",
    "EvalDeleted": "Eval",
    "AllocUpdated": "Alloc",
    "AllocClientUpdated": "Alloc",
    "AllocDeleted": "Alloc",
    # Derived at read time by the opt-in per-alloc fan-out of an
    # AllocationBatch event — never published by the FSM itself.
    "AllocPlaced": "Alloc",
    "AllocationBatchCommitted": "AllocationBatch",
    "ServiceRegistered": "Service",
    "ServiceDeregistered": "Service",
}


def new_event(topic: str, etype: str, key: str,
              payload: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Construct one event dict. Index/Region/TraceID/SpanID are stamped
    by the broker at publish (the builders that call this run inside the
    FSM apply and only know the state transition). Validates against the
    declared schema so a drifted literal fails the first test that
    exercises it, not just the lint run."""
    if topic not in TOPICS:
        raise ValueError(f"unknown event topic {topic!r}")
    if EVENT_TYPES.get(etype) != topic:
        raise ValueError(
            f"event type {etype!r} is not declared under topic {topic!r}")
    return {
        "Topic": topic,
        "Type": etype,
        "Key": key,
        "Index": 0,
        "Payload": payload if payload is not None else {},
    }
