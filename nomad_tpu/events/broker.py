"""EventBroker: raft-index-ordered lifecycle event fan-out (reference:
nomad/stream/event_broker.go + event_buffer.go, reshaped for this
codebase's replicated-FSM feed).

Every replica's FSM publishes one batch per applied raft entry, so every
server — follower or leader — holds an identical index-ordered ring.
That symmetry is the failover story: a subscriber that reconnects to the
NEW leader (or any server in the region) with ``from_index=<last seen>``
replays the retained window from that server's own ring and continues
gapless and duplicate-free, because both rings were fed by the same log.

Ordering: the ring is ordered by raft index, full stop. Dev-mode applies
can reach the FSM out of index order (DevRaft assigns the index under
its lock but applies outside it), so the broker exposes a two-phase
``reserve(index)`` / ``publish(index, events)`` sequencer: reservations
are taken in index order under the DevRaft lock, and a published batch
is held back until every lower reserved index has published. The
replicated backend applies strictly in order and never reserves.

Slow consumers: per-subscriber bounded queues, drop-oldest. A full
subscriber loses its oldest frames — counted under ``nomad.events.
dropped`` and annotated on the next delivered frame — and NEVER blocks
the publisher: the apply loop's cost per entry is one lock hold and a
few deque appends regardless of consumer health.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Tuple

from nomad_tpu.analysis import guarded_by
from nomad_tpu.resilience import failpoints
from nomad_tpu.telemetry import metrics, trace

__all__ = ["EventBroker", "EventGapError", "Subscription", "expand_batch"]

DEFAULT_RING_SIZE = 4096
DEFAULT_QUEUE_SIZE = 1024


class EventGapError(Exception):
    """``from_index`` precedes the retained window: events in
    ``(requested, floor]`` existed but have been evicted (or predate this
    server's snapshot install). The consumer must re-snapshot state and
    resubscribe from the current index."""

    def __init__(self, requested: int, floor: int):
        super().__init__(
            f"event stream gap: requested index {requested} precedes the "
            f"retained window (floor {floor}); re-snapshot and resubscribe")
        self.requested = requested
        self.floor = floor


def expand_batch(event: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Opt-in per-alloc fan-out of one ``AllocationBatch`` event, AT READ
    TIME: derive per-alloc ``AllocPlaced`` summaries from the columnar
    row/count descriptor the sweep committed. The publish path never
    materializes these — a 10k-alloc sweep stays one event until a
    subscriber explicitly asks for rows."""
    from .schema import new_event

    p = event["Payload"]
    out: List[Dict[str, Any]] = []
    node_ids = p["RowNodeIDs"]
    counts = p["Counts"]
    pos = 0
    for node_id, count in zip(node_ids, counts):
        for _ in range(int(count)):
            ev = new_event("Alloc", "AllocPlaced", p["AllocIDs"][pos], {
                "ID": p["AllocIDs"][pos],
                "Name": p["Names"][pos],
                "NodeID": node_id,
                "JobID": p["JobID"],
                "EvalID": p["EvalID"],
                "Kind": p["Kind"],
            })
            ev["Index"] = event["Index"]
            ev["Region"] = event.get("Region", "")
            if "TraceID" in event:
                ev["TraceID"] = event["TraceID"]
                ev["SpanID"] = event["SpanID"]
            out.append(ev)
            pos += 1
    return out


class Subscription:
    """One consumer's bounded view of the stream. Frames are
    ``{"Index": N, "Events": [...]}`` dicts (plus a ``"Dropped"``
    annotation on the first frame after an overflow). ``next()`` blocks
    up to ``timeout`` and returns ``None`` on expiry — the HTTP layer
    turns that into a heartbeat."""

    _concurrency = guarded_by("_cond", "_frames", "_dropped_pending",
                              "closed", "close_reason")

    def __init__(self, topics: Optional[Iterable[str]] = None,
                 filters: Optional[Dict[str, Iterable[str]]] = None,
                 fanout: bool = False,
                 queue_size: int = DEFAULT_QUEUE_SIZE):
        self.topics = frozenset(topics) if topics else None
        self.filters = {t: frozenset(keys)
                        for t, keys in (filters or {}).items() if keys}
        self.fanout = bool(fanout)
        self.queue_size = max(1, int(queue_size))
        self._cond = threading.Condition()
        self._frames: deque = deque()
        self._dropped_pending = 0
        self.closed = False
        self.close_reason = ""
        # Monotone cursor of the last frame handed out; read-only telemetry
        # for the owner thread (no cross-thread contract).
        self.last_index = 0
        self.dropped_total = 0

    # ------------------------------------------------------------ filtering
    def _match(self, event: Dict[str, Any]) -> bool:
        topic = event["Topic"]
        if self.topics is not None and topic not in self.topics:
            return False
        keys = self.filters.get(topic)
        if keys and event["Key"] not in keys:
            return False
        return True

    # ------------------------------------------------------ publisher side
    def push(self, index: int, events: Tuple[Dict[str, Any], ...]) -> None:
        """Called by the broker with its lock held; takes only this
        subscription's condition (broker lock -> sub cond, never the
        reverse). Non-blocking: overflow drops the OLDEST frame."""
        matched = [ev for ev in events if self._match(ev)]
        if not matched:
            return
        with self._cond:
            if self.closed:
                return
            if len(self._frames) >= self.queue_size:
                self._frames.popleft()
                self._dropped_pending += 1
                self.dropped_total += 1
                metrics.incr_counter(("nomad", "events", "dropped"))
            self._frames.append({"Index": index, "Events": matched})
            self._cond.notify_all()

    # ------------------------------------------------------- consumer side
    def next(self, timeout: Optional[float] = None) -> Optional[Dict[str, Any]]:
        """Pop the next frame, blocking up to ``timeout``. Returns None on
        timeout; returns None immediately (forever) once closed and
        drained. With ``fanout``, AllocationBatch events expand into
        per-alloc rows here — at read time, per subscriber."""
        with self._cond:
            while not self._frames and not self.closed:
                if not self._cond.wait(timeout):
                    return None
            if not self._frames:
                return None  # closed and drained
            frame = self._frames.popleft()
            if self._dropped_pending:
                frame = dict(frame)
                frame["Dropped"] = self._dropped_pending
                self._dropped_pending = 0
        self.last_index = frame["Index"]
        if self.fanout:
            events: List[Dict[str, Any]] = []
            for ev in frame["Events"]:
                if ev["Topic"] == "AllocationBatch":
                    events.extend(expand_batch(ev))
                else:
                    events.append(ev)
            frame = dict(frame, Events=events)
        return frame

    def status(self) -> Tuple[bool, str]:
        """(closed, reason) snapshot for the transport layer — it must
        distinguish a ``next()`` timeout (send a heartbeat) from a closed
        stream (tell the consumer why, then end)."""
        with self._cond:
            return self.closed, self.close_reason

    def close(self, reason: str = "") -> None:
        with self._cond:
            self.closed = True
            self.close_reason = reason
            self._cond.notify_all()


class EventBroker:
    """The per-server event ring + subscriber registry. One instance per
    FSM, attached as ``fsm.events``; ``None`` (events disabled) keeps the
    apply path's cost at a single attribute check."""

    _concurrency = guarded_by(
        "_lock", "_ring", "_tail", "_floor", "_reserved", "_staged",
        "_subs", "_published", "_closed")

    def __init__(self, size: int = DEFAULT_RING_SIZE, region: str = ""):
        self.size = max(1, int(size))
        # Region tag stamped onto every event; "" outside federation
        # (matching the evaluations' home-region contract). Set once at
        # server boot, before any publish.
        self.region = region
        self._lock = threading.Lock()
        # Retained (index, events-tuple) batches, index-ascending; only
        # non-empty batches occupy ring slots.
        self._ring: deque = deque()
        # Highest index COVERED by the stream (advances on every publish,
        # empty or not) and highest index NOT retained (advances on ring
        # eviction / snapshot reset). Gap check: from_index < floor.
        self._tail = 0
        self._floor = 0
        # Dev-mode sequencer state: reserved-but-unpublished indexes plus
        # batches published out of order, held for their predecessors.
        self._reserved: set = set()
        self._staged: Dict[int, Tuple[Dict[str, Any], ...]] = {}
        self._subs: List[Subscription] = []
        self._published = 0
        self._closed = False

    # ------------------------------------------------------------ sequencer
    def reserve(self, index: int) -> None:
        """Claim ``index`` for a future publish. Callers invoke this in
        index order (DevRaft: under its own assignment lock) so the
        reservation set encodes exactly which lower indexes are still in
        flight when a publish arrives early."""
        with self._lock:
            if not self._closed:
                self._reserved.add(index)

    def publish(self, index: int,
                events: Iterable[Dict[str, Any]]) -> None:
        """Publish one applied entry's events. Never raises into the FSM:
        the ``events.publish`` failpoint's error/drop modes surface as
        subscriber-visible loss (coverage still advances — the oracle
        fold, not a gap error, is what catches it), and delay mode is
        injected latency on the apply path, by design."""
        batch = tuple(events)
        if batch:
            # Fire OUTSIDE the lock: delay mode must not serialize every
            # other publisher, and error mode must stay FSM-invisible.
            try:
                if failpoints.fire("events.publish") == "drop":
                    batch = ()
            except failpoints.FailpointError:
                batch = ()
        if batch:
            sp = trace.current() if trace.is_enabled() else None
            region = self.region
            for ev in batch:
                ev["Index"] = index
                ev["Region"] = region
                if sp is not None:
                    ev["TraceID"] = sp.trace_id
                    ev["SpanID"] = sp.span_id
        depth = 0
        with self._lock:
            if self._closed or index <= self._tail:
                return  # shutdown, or a replayed/duplicate entry
            if index in self._reserved:
                self._staged[index] = batch
                # Drain every staged batch whose predecessors have all
                # published: the lowest outstanding reservation gates.
                while self._reserved:
                    lo = min(self._reserved)
                    if lo not in self._staged:
                        break
                    self._reserved.discard(lo)
                    self._emit_locked(lo, self._staged.pop(lo))
            else:
                self._emit_locked(index, batch)
            depth = len(self._ring)
        metrics.set_gauge(("nomad", "events", "ring_depth"), depth)

    def _emit_locked(self, index: int,
                     batch: Tuple[Dict[str, Any], ...]) -> None:
        self._tail = index
        if not batch:
            return
        self._ring.append((index, batch))
        while len(self._ring) > self.size:
            evicted_index, _ = self._ring.popleft()
            self._floor = evicted_index
        self._published += len(batch)
        metrics.incr_counter(("nomad", "events", "published"), len(batch))
        for sub in self._subs:
            sub.push(index, batch)

    # --------------------------------------------------------- subscribers
    def subscribe(self, topics: Optional[Iterable[str]] = None,
                  filters: Optional[Dict[str, Iterable[str]]] = None,
                  from_index: int = 0, fanout: bool = False,
                  queue_size: int = DEFAULT_QUEUE_SIZE) -> Subscription:
        """Replay the retained window after ``from_index`` (exclusive —
        pass the last index you saw), then go live. Registration and
        replay happen under one lock hold, so no event falls between the
        replayed window and the live feed. Raises :class:`EventGapError`
        when ``from_index`` precedes the retained window."""
        sub = Subscription(topics=topics, filters=filters, fanout=fanout,
                           queue_size=queue_size)
        with self._lock:
            if self._closed:
                raise EventGapError(from_index, self._tail)
            if from_index < self._floor:
                raise EventGapError(from_index, self._floor)
            for index, batch in self._ring:
                if index > from_index:
                    sub.push(index, batch)
            self._subs.append(sub)
        metrics.set_gauge(("nomad", "events", "subscribers"),
                          self._sub_count())
        return sub

    def unsubscribe(self, sub: Subscription) -> None:
        with self._lock:
            try:
                self._subs.remove(sub)
            except ValueError:
                return  # already removed (reset/close raced us)
        sub.close("unsubscribed")
        metrics.set_gauge(("nomad", "events", "subscribers"),
                          self._sub_count())

    def _sub_count(self) -> int:
        with self._lock:
            return len(self._subs)

    # ------------------------------------------------------------ lifecycle
    def reset(self, floor: int) -> None:
        """Snapshot install: this replica's state jumped to ``floor``
        without applying the intervening entries, so nothing below it is
        servable. Drop the ring, and close live subscribers — their
        stream no longer continues from what they saw; they reconnect,
        hit the gap check, and re-snapshot."""
        with self._lock:
            self._ring.clear()
            self._staged.clear()
            self._reserved.clear()
            self._tail = max(self._tail, floor)
            self._floor = max(self._floor, floor)
            subs, self._subs = self._subs, []
        for sub in subs:
            sub.close("reset: state restored from snapshot")
        metrics.set_gauge(("nomad", "events", "subscribers"), 0)

    def close(self) -> None:
        with self._lock:
            self._closed = True
            subs, self._subs = self._subs, []
        for sub in subs:
            sub.close("broker closed")

    # ------------------------------------------------------------ telemetry
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "Tail": self._tail,
                "Floor": self._floor,
                "Depth": len(self._ring),
                "Size": self.size,
                "Subscribers": len(self._subs),
                "Published": self._published,
                "Dropped": sum(s.dropped_total for s in self._subs),
            }
