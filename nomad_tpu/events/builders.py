"""Event builders: one per FSM MessageType (reference: the per-type
event constructors in nomad/state/events.go, keyed off the raft message
the entry carried).

Builders run inside ``FSM.apply`` AFTER the handler committed, on every
replica, so they are deterministic functions of (payload, post-apply
state) — identical event streams on leader and followers, which is what
makes failover resume gapless. They derive from the raft PAYLOAD (the
same dict-or-object shapes the handlers accept) rather than re-reading
whole objects back, and they publish SUMMARIES, not full object dumps:
an event identifies the transition and the ids/statuses a consumer folds
into shadow state; full objects stay one API read away.

The columnar rule (the reason this module exists at all): an
``ApplySweepBatch`` entry — one raft entry for a 10k-alloc sweep —
publishes ONE ``AllocationBatch`` event carrying the row/count
descriptor. No per-alloc materialization happens here; per-alloc
fan-out is opt-in at read time (broker.expand_batch).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List

from .schema import new_event

__all__ = ["build_events"]


def _f(obj: Any, name: str, default: Any = "") -> Any:
    """Field access across the two payload shapes (wire dicts / dev-mode
    objects), mirroring the handlers' own tolerance."""
    if isinstance(obj, dict):
        return obj.get(name, default)
    return getattr(obj, name, default)


def _aslist(value: Any) -> List[Any]:
    if isinstance(value, list):
        return value
    return list(value)


def _alloc_event(etype: str, alloc: Any, job: Any = None) -> Dict[str, Any]:
    job_id = _f(alloc, "JobID") or (_f(job, "ID") if job is not None else "")
    return new_event("Alloc", etype, _f(alloc, "ID"), {
        "ID": _f(alloc, "ID"),
        "Name": _f(alloc, "Name"),
        "JobID": job_id,
        "EvalID": _f(alloc, "EvalID"),
        "NodeID": _f(alloc, "NodeID"),
        "DesiredStatus": _f(alloc, "DesiredStatus"),
        "ClientStatus": _f(alloc, "ClientStatus"),
    })


def _node_register(fsm, req):
    node = req["Node"]
    return [new_event("Node", "NodeRegistered", _f(node, "ID"), {
        "ID": _f(node, "ID"),
        "Name": _f(node, "Name"),
        "Status": _f(node, "Status"),
        "Datacenter": _f(node, "Datacenter"),
        "NodeClass": _f(node, "NodeClass"),
    })]


def _node_deregister(fsm, req):
    return [new_event("Node", "NodeDeregistered", req["NodeID"],
                      {"ID": req["NodeID"]})]


def _node_status(fsm, req):
    return [new_event("Node", "NodeStatusUpdated", req["NodeID"],
                      {"ID": req["NodeID"], "Status": req["Status"]})]


def _node_drain(fsm, req):
    return [new_event("Node", "NodeDrainUpdated", req["NodeID"],
                      {"ID": req["NodeID"], "Drain": bool(req["Drain"])})]


def _job_register(fsm, req):
    job = req["Job"]
    return [new_event("Job", "JobRegistered", _f(job, "ID"), {
        "ID": _f(job, "ID"),
        "Name": _f(job, "Name"),
        "Type": _f(job, "Type"),
        "Priority": _f(job, "Priority", 0),
    })]


def _job_deregister(fsm, req):
    return [new_event("Job", "JobDeregistered", req["JobID"],
                      {"ID": req["JobID"]})]


def _eval_update(fsm, req):
    return [new_event("Eval", "EvalUpdated", _f(ev, "ID"), {
        "ID": _f(ev, "ID"),
        "JobID": _f(ev, "JobID"),
        "Status": _f(ev, "Status"),
        "Type": _f(ev, "Type"),
        "TriggeredBy": _f(ev, "TriggeredBy"),
    }) for ev in req["Evals"]]


def _eval_delete(fsm, req):
    events = [new_event("Eval", "EvalDeleted", eval_id, {"ID": eval_id})
              for eval_id in req.get("Evals", ())]
    events.extend(new_event("Alloc", "AllocDeleted", alloc_id,
                            {"ID": alloc_id})
                  for alloc_id in req.get("Allocs", ()))
    return events


def _alloc_update(fsm, req):
    groups = req.get("Batch")
    if groups is None:
        groups = [req]
    events = []
    for group in groups:
        job = group.get("Job")
        events.extend(_alloc_event("AllocUpdated", a, job)
                      for a in group["Alloc"])
    return events


def _alloc_client_update(fsm, req):
    events = []
    for a in req["Alloc"]:
        # Mirror the handler: updates for already-GC'd allocs were
        # dropped before the write, so they publish nothing. The status
        # comes from the STORE read-back — the handler merges client
        # fields, and the event must carry what committed.
        updated = fsm.state.alloc_by_id(_f(a, "ID"))
        if updated is None:
            continue
        events.append(new_event("Alloc", "AllocClientUpdated", updated.ID, {
            "ID": updated.ID,
            "ClientStatus": updated.ClientStatus,
            "DesiredStatus": updated.DesiredStatus,
            "Terminal": updated.terminal_status(),
        }))
    return events


def _sweep_batch(fsm, req):
    groups = req.get("Batch")
    if groups is None:
        groups = [req]
    events = []
    for group in groups:
        job = group.get("Job")
        sweep = group.get("Sweep")
        if sweep is None:
            events.extend(_alloc_event("AllocUpdated", a, job)
                          for a in group.get("Alloc", ()))
            continue
        # Exact-path evictions ride the sweep group ahead of its
        # placements; they are per-object updates and publish as such.
        events.extend(_alloc_event("AllocUpdated", a, job)
                      for a in group.get("Updates", ()))
        templates = sweep["Templates"]
        alloc_ids = _aslist(sweep["AllocIDs"])
        events.append(new_event(
            "AllocationBatch", "AllocationBatchCommitted",
            _f(templates[0], "JobID"), {
                "JobID": _f(templates[0], "JobID"),
                "EvalID": _f(templates[0], "EvalID"),
                "Kind": sweep.get("Kind", "system"),
                "Count": len(alloc_ids),
                "AllocIDs": alloc_ids,
                "Names": _aslist(sweep["Names"]),
                "RowNodeIDs": _aslist(sweep["RowNodeIDs"]),
                "Counts": [int(c) for c in sweep["Counts"]],
            }))
    return events


def _periodic_launch(fsm, req):
    job_id = _f(req["Launch"], "ID")
    return [new_event("Job", "PeriodicLaunchUpserted", job_id,
                      {"JobID": job_id})]


def _periodic_launch_delete(fsm, req):
    return [new_event("Job", "PeriodicLaunchDeleted", req["JobID"],
                      {"JobID": req["JobID"]})]


def _service_sync(fsm, req):
    events = [new_event("Service", "ServiceRegistered", _f(reg, "ID"), {
        "ID": _f(reg, "ID"),
        "ServiceName": _f(reg, "ServiceName"),
        "JobID": _f(reg, "JobID"),
        "AllocID": _f(reg, "AllocID"),
        "NodeID": _f(reg, "NodeID"),
    }) for reg in req.get("Upserts", ())]
    events.extend(new_event("Service", "ServiceDeregistered", reg_id,
                            {"ID": reg_id})
                  for reg_id in req.get("Deletes", ()))
    return events


# MessageType.value -> builder. Keyed by int so this module never imports
# server.fsm (which imports the broker through the events package — the
# dependency points one way only).
_BUILDERS: Dict[int, Callable[[Any, Dict[str, Any]],
                              List[Dict[str, Any]]]] = {
    0: _node_register,
    1: _node_deregister,
    2: _node_status,
    3: _node_drain,
    4: _job_register,
    5: _job_deregister,
    6: _eval_update,
    7: _eval_delete,
    8: _alloc_update,
    9: _alloc_client_update,
    10: _periodic_launch,
    11: _periodic_launch_delete,
    12: _service_sync,
    13: _sweep_batch,
}


def build_events(fsm, msg_type: int,
                 payload: Dict[str, Any]) -> List[Dict[str, Any]]:
    """The FSM's one publish hook per MessageType: dispatch to the
    builder for this entry's type. Unknown types publish nothing (a
    newer leader's entry replaying on an older replica must not wedge
    the sequencer)."""
    builder = _BUILDERS.get(int(msg_type))
    if builder is None:
        return []
    return builder(fsm, payload)
