"""Gossip membership plane (reference: hashicorp/serf + memberlist,
consumed by nomad/serf.go)."""

from .memberlist import (
    ALIVE,
    DEAD,
    EVENT_FAILED,
    EVENT_JOIN,
    EVENT_LEAVE,
    EVENT_UPDATE,
    LEFT,
    SUSPECT,
    GossipConfig,
    Member,
    Memberlist,
)

__all__ = [
    "Memberlist", "Member", "GossipConfig",
    "ALIVE", "SUSPECT", "DEAD", "LEFT",
    "EVENT_JOIN", "EVENT_LEAVE", "EVENT_FAILED", "EVENT_UPDATE",
]
