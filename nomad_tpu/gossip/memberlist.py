"""SWIM-style gossip membership: the rebuild's third communication plane.

The reference delegates membership to hashicorp/serf over memberlist
(reference: nomad/serf.go:16-180 consumes the events; vendored
hashicorp/memberlist implements the protocol). This is a from-scratch
implementation of the same capability — scalable weakly-consistent
membership with failure detection — built on the SWIM algorithm:

- **Probe loop**: each probe interval, one member is pinged over UDP;
  no ack within the timeout triggers indirect pings through k random
  peers; total failure marks the member *suspect*.
- **Suspicion**: a suspect member has `suspicion_mult * log(n)` probe
  intervals to refute (any node that still hears from it, or the node
  itself bumping its incarnation) before it is declared *dead*.
- **Dissemination**: state changes (alive / suspect / dead) ride
  piggybacked on ping/ack traffic and a periodic fanout gossip tick,
  each broadcast retransmitted O(log n) times.
- **Anti-entropy**: periodic full state push-pull over TCP against one
  random member, also used for `join()`.

Incarnation numbers order statements about a member; only the member
itself may increment its own (that is the refutation mechanism).

Wire format: msgpack compound packets (a list of messages) over UDP,
length-prefixed msgpack frames over TCP (shared with rpc/wire.py).
"""

from __future__ import annotations

import logging
import math
import random
import socket
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import msgpack

from nomad_tpu.analysis import guarded_by
from nomad_tpu.resilience import failpoints
from nomad_tpu.rpc.wire import recv_frame, send_frame

LOG = logging.getLogger("nomad.gossip")

# Member states
ALIVE = "alive"
SUSPECT = "suspect"
DEAD = "dead"
LEFT = "left"

# Events delivered to the listener callback
EVENT_JOIN = "member-join"
EVENT_LEAVE = "member-leave"
EVENT_FAILED = "member-failed"
EVENT_UPDATE = "member-update"

# UDP message kinds (tuples keep packets small)
_PING = 0        # (_PING, seq, target_name, from_name)
_ACK = 1         # (_ACK, seq)
_PING_REQ = 2    # (_PING_REQ, seq, target, taddr, tport, from, faddr, fport)
_ALIVE = 3       # (_ALIVE, name, addr, port, incarnation, tags)
_SUSPECT = 4     # (_SUSPECT, name, incarnation, from_name)
_DEAD = 5        # (_DEAD, name, incarnation, from_name, left)


@dataclass
class GossipConfig:
    probe_interval: float = 1.0
    probe_timeout: float = 0.5
    indirect_checks: int = 3
    gossip_interval: float = 0.2
    gossip_fanout: int = 3
    retransmit_mult: int = 4
    suspicion_mult: int = 4
    push_pull_interval: float = 30.0
    packet_limit: int = 1400

    @classmethod
    def fast(cls) -> "GossipConfig":
        """Test-friendly timings (reference analogue: the tightened Serf
        timeouts in nomad/server_test.go testServer)."""
        return cls(probe_interval=0.06, probe_timeout=0.03,
                   gossip_interval=0.02, push_pull_interval=0.5)


@dataclass
class Member:
    name: str
    addr: str
    port: int
    tags: Dict[str, str]
    incarnation: int = 0
    state: str = ALIVE
    state_change: float = field(default_factory=time.monotonic)
    # suspicion deadline (monotonic) when state == SUSPECT
    suspect_deadline: float = 0.0

    def snapshot(self) -> "Member":
        return Member(self.name, self.addr, self.port, dict(self.tags),
                      self.incarnation, self.state, self.state_change)


class Memberlist:
    """One gossip participant. Thread-safe; all background work runs on
    daemon threads started by `start()`."""

    _concurrency = guarded_by(
        "_lock", "_members", "_incarnation", "_probe_ring", "_probe_pos",
        "_seq", "_broadcasts", "_left")

    def __init__(self, name: str, bind_addr: str = "127.0.0.1",
                 port: int = 0, tags: Optional[Dict[str, str]] = None,
                 config: Optional[GossipConfig] = None,
                 on_event: Optional[Callable[[str, Member], None]] = None):
        self.name = name
        self.config = config or GossipConfig()
        self.on_event = on_event

        # UDP and TCP share one port number. With port=0 the kernel picks the
        # UDP port freely, and the matching TCP port may be taken by an
        # unrelated process — retry the pair until both bind.
        for attempt in range(16):
            self._udp = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            self._udp.bind((bind_addr, port))
            self.addr, self.port = self._udp.getsockname()
            self._tcp = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._tcp.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            try:
                self._tcp.bind((bind_addr, self.port))
                break
            except OSError:
                self._udp.close()
                self._tcp.close()
                if port != 0 or attempt == 15:
                    raise
        self._tcp.listen(16)

        self._lock = threading.RLock()
        self._members: Dict[str, Member] = {}
        self._incarnation = 0
        self._members[name] = Member(name, self.addr, self.port,
                                     dict(tags or {}), incarnation=0)
        self._probe_ring: List[str] = []
        self._probe_pos = 0

        self._seq = 0
        self._acks: Dict[int, threading.Event] = {}
        # broadcast queue: [remaining_transmits, packed_message]
        self._broadcasts: List[List[Any]] = []

        self._shutdown = threading.Event()
        self._left = False
        self._threads: List[threading.Thread] = []
        # Fault-injection seam (tests only): called with (dest, msgs) before
        # every UDP send; return False to drop the packet. Models lossy
        # links and asymmetric partitions — the conditions SWIM's
        # suspicion/refutation pipeline exists to survive. Never set in
        # production paths.
        self.transport_filter: Optional[
            Callable[[Tuple[str, int], List[Any]], bool]] = None

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        for target, nm in ((self._udp_loop, "udp"), (self._tcp_loop, "tcp"),
                           (self._probe_loop, "probe"),
                           (self._gossip_loop, "gossip"),
                           (self._push_pull_loop, "pushpull")):
            t = threading.Thread(target=target, daemon=True,
                                 name=f"gossip-{nm}-{self.name}")
            t.start()
            self._threads.append(t)

    def shutdown(self) -> None:
        self._shutdown.set()
        try:
            self._udp.close()
        except OSError:
            pass
        try:
            # shutdown() wakes the blocked accept(); close() alone leaves
            # the kernel socket LISTENING under the accept thread on Linux,
            # so a restarted agent could never rebind its serf port.
            self._tcp.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._tcp.close()
        except OSError:
            pass

    def leave(self) -> None:
        """Graceful departure: broadcast our own death with the `left` flag
        so peers emit a leave (not a failure) event, give the gossip a few
        ticks to spread it, then stop."""
        with self._lock:
            self._left = True
            me = self._members[self.name]
            me.state = LEFT
            msg = (_DEAD, self.name, me.incarnation, self.name, True)
            self._queue_broadcast_locked(msg)
        # push the leave out directly too — don't rely on gossip ticks
        for m in self._random_members(self.config.gossip_fanout * 2):
            self._send_udp((m.addr, m.port), [msg])
        # Give the gossip ticks a window to spread the leave; a concurrent
        # shutdown() cuts the grace period short instead of blocking it.
        self._shutdown.wait(4 * self.config.gossip_interval)
        self.shutdown()

    def force_leave(self, name: str) -> bool:
        """Operator override: declare a (usually already unreachable) member
        dead without waiting for the suspicion pipeline (reference: serf
        ForceLeave behind the force-leave CLI)."""
        with self._lock:
            m = self._members.get(name)
            if m is None:
                return False
            inc = m.incarnation
        self._on_dead(name, inc, self.name, True)
        return True

    # ------------------------------------------------------------- queries
    def members(self) -> List[Member]:
        """All known members in any state (snapshot copies)."""
        with self._lock:
            return [m.snapshot() for m in self._members.values()]

    def alive_members(self) -> List[Member]:
        with self._lock:
            return [m.snapshot() for m in self._members.values()
                    if m.state in (ALIVE, SUSPECT)]

    def local_member(self) -> Member:
        with self._lock:
            return self._members[self.name].snapshot()

    def num_alive(self) -> int:
        with self._lock:
            return sum(1 for m in self._members.values()
                       if m.state in (ALIVE, SUSPECT))

    def set_tags(self, tags: Dict[str, str]) -> None:
        """Update our metadata and re-broadcast (reference: serf SetTags,
        used for e.g. advertising leadership/ports)."""
        with self._lock:
            me = self._members[self.name]
            me.tags = dict(tags)
            self._incarnation += 1
            me.incarnation = self._incarnation
            self._queue_broadcast_locked(self._alive_msg_locked(me))

    # ---------------------------------------------------------------- join
    def join(self, seeds: List[Any]) -> int:
        """Sync state with each seed ("host:port" or (host, port)); returns
        the number of seeds successfully contacted."""
        ok = 0
        for seed in seeds:
            if isinstance(seed, str):
                host, _, p = seed.rpartition(":")
                seed = (host, int(p))
            try:
                self._push_pull(tuple(seed))
                ok += 1
            except OSError as exc:
                LOG.warning("%s: join %s failed: %s", self.name, seed, exc)
        return ok

    # ------------------------------------------------------------ transport
    def _send_udp(self, dest: Tuple[str, int], msgs: List[Any]) -> None:
        if failpoints.fire("gossip.send") == "drop":
            return  # datagram lost in transit
        f = self.transport_filter
        if f is not None and not f(dest, msgs):
            return
        try:
            self._udp.sendto(msgpack.packb(msgs, use_bin_type=True), dest)
        except OSError:
            pass

    def _udp_loop(self) -> None:
        while not self._shutdown.is_set():
            try:
                raw, src = self._udp.recvfrom(65535)
            except OSError:
                return
            try:
                msgs = msgpack.unpackb(raw, raw=False)
            except Exception:
                LOG.debug("%s: undecodable datagram from %s dropped",
                          self.name, src)
                continue
            for msg in msgs:
                try:
                    self._handle_udp(msg, src)
                except Exception:
                    LOG.exception("%s: bad gossip message", self.name)

    def _handle_udp(self, msg: List[Any], src: Tuple[str, int]) -> None:
        kind = msg[0]
        if kind == _PING:
            _, seq, target, frm = msg
            if target != self.name:
                return  # misdirected (stale addr)
            out: List[Any] = [(_ACK, seq)]
            out.extend(self._drain_piggyback())
            self._send_udp(src, out)
        elif kind == _ACK:
            ev = self._acks.pop(msg[1], None)
            if ev is not None:
                ev.set()
        elif kind == _PING_REQ:
            _, seq, target, taddr, tport, frm, faddr, fport = msg
            self._indirect_probe(seq, target, (taddr, tport), (faddr, fport))
        elif kind == _ALIVE:
            self._on_alive(msg[1], msg[2], msg[3], msg[4], msg[5])
        elif kind == _SUSPECT:
            self._on_suspect(msg[1], msg[2], msg[3])
        elif kind == _DEAD:
            self._on_dead(msg[1], msg[2], msg[3], msg[4])

    def _indirect_probe(self, orig_seq: int, target: str,
                        taddr: Tuple[str, int],
                        reply_to: Tuple[str, int]) -> None:
        """Probe `target` on behalf of `reply_to`; relay the ack."""
        def run() -> None:
            if self._ping(target, taddr):
                self._send_udp(reply_to, [(_ACK, orig_seq)])
        threading.Thread(target=run, daemon=True,
                         name=f"gossip-relay-{self.name}").start()

    def _ping(self, target: str, dest: Tuple[str, int]) -> bool:
        if failpoints.fire("gossip.probe") == "drop":
            return False  # probe lost: caller escalates to indirect pings
        with self._lock:
            self._seq += 1
            seq = self._seq
        ev = threading.Event()
        self._acks[seq] = ev
        out: List[Any] = [(_PING, seq, target, self.name)]
        out.extend(self._drain_piggyback())
        self._send_udp(dest, out)
        ok = ev.wait(self.config.probe_timeout)
        self._acks.pop(seq, None)
        return ok

    # ----------------------------------------------------------- probe loop
    def _probe_loop(self) -> None:
        while not self._shutdown.wait(self.config.probe_interval):
            try:
                self._expire_suspects()
                member = self._next_probe_target()
                if member is not None:
                    self._probe(member)
            except Exception:
                # The failure detector must outlive any single bad probe
                # round (injected or real): a dead probe loop would stop
                # ALL failure detection on this member, silently.
                LOG.exception("%s: probe round failed", self.name)

    def _next_probe_target(self) -> Optional[Member]:
        with self._lock:
            candidates = [n for n, m in self._members.items()
                          if n != self.name and m.state in (ALIVE, SUSPECT)]
            if not candidates:
                return None
            if self._probe_pos >= len(self._probe_ring):
                self._probe_ring = candidates
                random.shuffle(self._probe_ring)
                self._probe_pos = 0
            while self._probe_pos < len(self._probe_ring):
                name = self._probe_ring[self._probe_pos]
                self._probe_pos += 1
                m = self._members.get(name)
                if m is not None and m.state in (ALIVE, SUSPECT):
                    return m.snapshot()
            return None

    def _probe(self, member: Member) -> None:
        if self._ping(member.name, (member.addr, member.port)):
            return
        # Indirect probes through k random other members
        ev = threading.Event()
        with self._lock:
            self._seq += 1
            seq = self._seq
        self._acks[seq] = ev
        req = (_PING_REQ, seq, member.name, member.addr, member.port,
               self.name, self.addr, self.port)
        relays = [m for m in self._random_members(self.config.indirect_checks)
                  if m.name != member.name]
        for r in relays:
            self._send_udp((r.addr, r.port), [req])
        ok = ev.wait(self.config.probe_interval)
        self._acks.pop(seq, None)
        if not ok:
            with self._lock:
                cur = self._members.get(member.name)
                inc = cur.incarnation if cur else member.incarnation
            self._on_suspect(member.name, inc, self.name)

    def _expire_suspects(self) -> None:
        now = time.monotonic()
        expired: List[Tuple[str, int]] = []
        with self._lock:
            for m in self._members.values():
                if m.state == SUSPECT and now >= m.suspect_deadline:
                    expired.append((m.name, m.incarnation))
        for name, inc in expired:
            self._on_dead(name, inc, self.name, False)

    def _suspicion_timeout(self) -> float:
        n = max(1, self.num_alive())
        return (self.config.suspicion_mult
                * max(1.0, math.log10(n) + 1.0)
                * self.config.probe_interval)

    # --------------------------------------------------------- dissemination
    def _retransmit_limit(self) -> int:
        n = max(1, self.num_alive())
        return self.config.retransmit_mult * int(math.ceil(math.log10(n) + 1))

    def _queue_broadcast_locked(self, msg: Tuple) -> None:
        # A newer statement about a node invalidates queued older ones.
        name = msg[1]
        self._broadcasts = [b for b in self._broadcasts
                            if b[1][1] != name]
        self._broadcasts.append([self._retransmit_limit(), msg])

    def _drain_piggyback(self, budget: int = 6) -> List[Tuple]:
        out: List[Tuple] = []
        with self._lock:
            for b in list(self._broadcasts):
                if len(out) >= budget:
                    break
                out.append(b[1])
                b[0] -= 1
                if b[0] <= 0:
                    self._broadcasts.remove(b)
        return out

    def _random_members(self, k: int) -> List[Member]:
        with self._lock:
            pool = [m.snapshot() for n, m in self._members.items()
                    if n != self.name and m.state in (ALIVE, SUSPECT)]
        random.shuffle(pool)
        return pool[:k]

    def _gossip_loop(self) -> None:
        while not self._shutdown.wait(self.config.gossip_interval):
            msgs = self._drain_piggyback()
            if not msgs:
                continue
            for m in self._random_members(self.config.gossip_fanout):
                self._send_udp((m.addr, m.port), msgs)

    # ------------------------------------------------------------ state FSM
    def _alive_msg_locked(self, m: Member) -> Tuple:
        return (_ALIVE, m.name, m.addr, m.port, m.incarnation, m.tags)

    def _notify(self, event: str, member: Member) -> None:
        if self.on_event is not None:
            try:
                self.on_event(event, member)
            except Exception:
                LOG.exception("%s: member event handler failed", self.name)

    def _on_alive(self, name: str, addr: str, port: int, inc: int,
                  tags: Dict[str, str]) -> None:
        notify: Optional[Tuple[str, Member]] = None
        with self._lock:
            if name == self.name:
                # A statement about us we didn't make: refute if it's old
                # news (e.g. a stale address) by out-incarnating it.
                me = self._members[self.name]
                if (addr, port) != (me.addr, me.port) \
                        and inc > me.incarnation and me.incarnation > 0:
                    # Post-restart echoes of our stale record arrive while
                    # our incarnation is still 0 and are refuted silently;
                    # only a claim that OUT-INCARNATES a refutation we
                    # already issued means a live node is fighting us for
                    # the name.
                    LOG.warning(
                        "%s: ANOTHER member is gossiping under our name "
                        "from %s:%s — member names must be unique per "
                        "region (set a distinct `name` in each agent "
                        "config)", self.name, addr, port)
                if inc > me.incarnation and not self._left:
                    self._incarnation = inc + 1
                    me.incarnation = self._incarnation
                    self._queue_broadcast_locked(self._alive_msg_locked(me))
                return
            m = self._members.get(name)
            if m is None:
                m = Member(name, addr, port, dict(tags), inc)
                self._members[name] = m
                self._queue_broadcast_locked(self._alive_msg_locked(m))
                notify = (EVENT_JOIN, m.snapshot())
            elif inc > m.incarnation:
                rejoined = m.state in (DEAD, LEFT)
                updated = (tags != m.tags or addr != m.addr
                           or port != m.port)
                m.addr, m.port, m.tags = addr, port, dict(tags)
                m.incarnation = inc
                if m.state != ALIVE:
                    m.state = ALIVE
                    m.state_change = time.monotonic()
                self._queue_broadcast_locked(self._alive_msg_locked(m))
                if rejoined:
                    notify = (EVENT_JOIN, m.snapshot())
                elif updated:
                    notify = (EVENT_UPDATE, m.snapshot())
        if notify is not None:
            self._notify(*notify)

    def _on_suspect(self, name: str, inc: int, from_name: str) -> None:
        with self._lock:
            if name == self.name:
                if self._left:
                    return
                # Refute: only we may raise our incarnation (SWIM's
                # mechanism against false positives).
                me = self._members[self.name]
                self._incarnation = max(self._incarnation, inc) + 1
                me.incarnation = self._incarnation
                self._queue_broadcast_locked(self._alive_msg_locked(me))
                return
            m = self._members.get(name)
            if m is None or inc < m.incarnation:
                return
            if m.state == ALIVE:
                m.state = SUSPECT
                m.state_change = time.monotonic()
                m.suspect_deadline = (time.monotonic()
                                      + self._suspicion_timeout())
                m.incarnation = inc
                self._queue_broadcast_locked((_SUSPECT, name, inc, from_name))

    def _on_dead(self, name: str, inc: int, from_name: str,
                 left: bool) -> None:
        notify: Optional[Tuple[str, Member]] = None
        with self._lock:
            if name == self.name:
                if self._left:
                    return
                me = self._members[self.name]
                self._incarnation = max(self._incarnation, inc) + 1
                me.incarnation = self._incarnation
                self._queue_broadcast_locked(self._alive_msg_locked(me))
                return
            m = self._members.get(name)
            if m is None or inc < m.incarnation:
                return
            if m.state in (DEAD, LEFT):
                return
            m.state = LEFT if left else DEAD
            m.state_change = time.monotonic()
            m.incarnation = inc
            self._queue_broadcast_locked((_DEAD, name, inc, from_name, left))
            notify = (EVENT_LEAVE if left else EVENT_FAILED, m.snapshot())
        if notify is not None:
            self._notify(*notify)

    # ----------------------------------------------------------- push-pull
    def _local_state(self) -> List[List[Any]]:
        with self._lock:
            return [[m.name, m.addr, m.port, m.incarnation, m.tags, m.state]
                    for m in self._members.values()]

    def _merge_state(self, remote: List[List[Any]]) -> None:
        for name, addr, port, inc, tags, state in remote:
            if state in (ALIVE, SUSPECT):
                self._on_alive(name, addr, port, inc, tags)
                if state == SUSPECT:
                    self._on_suspect(name, inc, name)
            elif state in (DEAD, LEFT):
                self._on_dead(name, inc, name, state == LEFT)

    def _push_pull(self, dest: Tuple[str, int]) -> None:
        sock = socket.create_connection(dest, timeout=2.0)
        try:
            send_frame(sock, {"PushPull": self._local_state(),
                              "From": self.name})
            resp = recv_frame(sock)
            if resp is not None:
                self._merge_state(resp.get("PushPull", []))
        finally:
            sock.close()

    def _tcp_loop(self) -> None:
        while not self._shutdown.is_set():
            try:
                conn, _ = self._tcp.accept()
            except OSError:
                return
            threading.Thread(target=self._handle_tcp, args=(conn,),
                             daemon=True,
                             name=f"gossip-tcp-conn-{self.name}").start()

    def _handle_tcp(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(2.0)
            req = recv_frame(conn)
            if req is None:
                return
            send_frame(conn, {"PushPull": self._local_state(),
                              "From": self.name})
            self._merge_state(req.get("PushPull", []))
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _push_pull_loop(self) -> None:
        while not self._shutdown.wait(self.config.push_pull_interval):
            targets = self._random_members(1)
            if targets:
                m = targets[0]
                # The fault-injection seam gates anti-entropy too: a
                # "partitioned" link must not heal through the TCP side.
                f = self.transport_filter
                if f is not None and not f((m.addr, m.port),
                                           [("push-pull",)]):
                    continue
                try:
                    self._push_pull((m.addr, m.port))
                except OSError:
                    pass
