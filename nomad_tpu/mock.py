"""Canonical test fixtures (reference: nomad/mock/mock.go).

Same shapes and resource numbers as the reference fixtures so scenario tests
and benchmarks are comparable.
"""

from __future__ import annotations

from nomad_tpu.structs import (
    Allocation,
    Constraint,
    Evaluation,
    Job,
    LogConfig,
    NetworkResource,
    Node,
    PeriodicConfig,
    Plan,
    PlanResult,
    Port,
    Resources,
    RestartPolicy,
    Service,
    ServiceCheck,
    Task,
    TaskGroup,
    compute_node_class,
    generate_uuid,
)
from nomad_tpu.structs.structs import (
    MINUTE,
    SECOND,
    AllocClientStatusPending,
    AllocDesiredStatusRun,
    EvalStatusPending,
    JobStatusPending,
    JobTypeBatch,
    JobTypeService,
    JobTypeSystem,
    NodeStatusReady,
    PeriodicSpecCron,
    RestartPolicyModeDelay,
    ServiceCheckScript,
)


def node() -> Node:
    n = Node(
        ID=generate_uuid(),
        Datacenter="dc1",
        Name="foobar",
        Attributes={
            "kernel.name": "linux",
            "arch": "x86",
            "version": "0.1.0",
            "driver.exec": "1",
        },
        Resources=Resources(
            CPU=4000, MemoryMB=8192, DiskMB=100 * 1024, IOPS=150,
            Networks=[NetworkResource(Device="eth0", CIDR="192.168.0.100/32", MBits=1000)],
        ),
        Reserved=Resources(
            CPU=100, MemoryMB=256, DiskMB=4 * 1024,
            Networks=[NetworkResource(Device="eth0", IP="192.168.0.100",
                                      ReservedPorts=[Port("main", 22)], MBits=1)],
        ),
        Links={"consul": "foobar.dc1"},
        Meta={"pci-dss": "true", "database": "mysql", "version": "5.6"},
        NodeClass="linux-medium-pci",
        Status=NodeStatusReady,
    )
    compute_node_class(n)
    return n


def job() -> Job:
    j = Job(
        Region="global",
        ID=generate_uuid(),
        Name="my-job",
        Type=JobTypeService,
        Priority=50,
        AllAtOnce=False,
        Datacenters=["dc1"],
        Constraints=[Constraint(LTarget="${attr.kernel.name}", RTarget="linux", Operand="=")],
        TaskGroups=[
            TaskGroup(
                Name="web",
                Count=10,
                RestartPolicy=RestartPolicy(Attempts=3, Interval=10 * MINUTE,
                                            Delay=1 * MINUTE, Mode=RestartPolicyModeDelay),
                Tasks=[
                    Task(
                        Name="web",
                        Driver="exec",
                        Config={"command": "/bin/date"},
                        Env={"FOO": "bar"},
                        Services=[
                            Service(
                                Name="${TASK}-frontend",
                                PortLabel="http",
                                Tags=["pci:${meta.pci-dss}", "datacenter:${node.datacenter}"],
                                Checks=[ServiceCheck(
                                    Name="check-table",
                                    Type=ServiceCheckScript,
                                    Command="/usr/local/check-table-${meta.database}",
                                    Args=["${meta.version}"],
                                    Interval=30 * SECOND,
                                    Timeout=5 * SECOND,
                                )],
                            ),
                            Service(Name="${TASK}-admin", PortLabel="admin"),
                        ],
                        LogConfig=LogConfig(),
                        Resources=Resources(
                            CPU=500, MemoryMB=256, DiskMB=150,
                            Networks=[NetworkResource(
                                MBits=50,
                                DynamicPorts=[Port("http", 0), Port("admin", 0)],
                            )],
                        ),
                        Meta={"foo": "bar"},
                    )
                ],
                Meta={"elb_check_type": "http", "elb_check_interval": "30s",
                      "elb_check_min": "3"},
            )
        ],
        Meta={"owner": "armon"},
        Status=JobStatusPending,
        CreateIndex=42,
        ModifyIndex=99,
        JobModifyIndex=99,
    )
    j.init_fields()
    return j


def system_job() -> Job:
    return Job(
        Region="global",
        ID=generate_uuid(),
        Name="my-job",
        Type=JobTypeSystem,
        Priority=100,
        AllAtOnce=False,
        Datacenters=["dc1"],
        Constraints=[Constraint(LTarget="${attr.kernel.name}", RTarget="linux", Operand="=")],
        TaskGroups=[
            TaskGroup(
                Name="web",
                Count=1,
                RestartPolicy=RestartPolicy(Attempts=3, Interval=10 * MINUTE,
                                            Delay=1 * MINUTE, Mode=RestartPolicyModeDelay),
                Tasks=[
                    Task(
                        Name="web",
                        Driver="exec",
                        Config={"command": "/bin/date"},
                        Resources=Resources(
                            CPU=500, MemoryMB=256,
                            Networks=[NetworkResource(MBits=50,
                                                      DynamicPorts=[Port("http", 0)])],
                        ),
                        LogConfig=LogConfig(),
                    )
                ],
            )
        ],
        Meta={"owner": "armon"},
        Status=JobStatusPending,
        CreateIndex=42,
        ModifyIndex=99,
    )


def periodic_job() -> Job:
    j = job()
    j.Type = JobTypeBatch
    j.Periodic = PeriodicConfig(Enabled=True, SpecType=PeriodicSpecCron,
                                Spec="*/30 * * * *")
    return j


def eval() -> Evaluation:  # noqa: A001 - mirrors the reference fixture name
    return Evaluation(
        ID=generate_uuid(),
        Priority=50,
        Type=JobTypeService,
        JobID=generate_uuid(),
        Status=EvalStatusPending,
    )


def alloc() -> Allocation:
    j = job()
    res = Resources(
        CPU=500, MemoryMB=256, DiskMB=10,
        Networks=[NetworkResource(
            Device="eth0", IP="192.168.0.100",
            ReservedPorts=[Port("main", 5000)], MBits=50,
            DynamicPorts=[Port("http", 0)],
        )],
    )
    a = Allocation(
        ID=generate_uuid(),
        EvalID=generate_uuid(),
        NodeID="12345678-abcd-efab-cdef-123456789abc",
        TaskGroup="web",
        Resources=res,
        TaskResources={"web": res.copy()},
        Job=j,
        JobID=j.ID,
        DesiredStatus=AllocDesiredStatusRun,
        ClientStatus=AllocClientStatusPending,
    )
    return a


def plan() -> Plan:
    return Plan(Priority=50)


def plan_result() -> PlanResult:
    return PlanResult()
