"""HCL job spec -> structs.Job (reference: jobspec/parse.go).

Schema and defaults mirror the reference: one `job` block with nested
`group`/`task`/`resources`/`network`/`port` blocks, constraint sugar
(`version`, `regexp`, `distinct_hosts`), duration strings ("30s", "10m"),
default count 1, bare tasks wrapped into a group of the same name, strict
unknown-key validation.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional

from nomad_tpu.structs import (
    Constraint,
    Job,
    LogConfig,
    NetworkResource,
    PeriodicConfig,
    Port,
    Resources,
    RestartPolicy,
    Service,
    ServiceCheck,
    Task,
    TaskArtifact,
    TaskGroup,
    UpdateStrategy,
)
from nomad_tpu.structs.structs import (
    JobDefaultPriority,
    PeriodicSpecCron,
)

from .hcl import parse as parse_hcl

_DURATION_RE = re.compile(r"(\d+(?:\.\d+)?)(ns|us|µs|ms|s|m|h)")
_DURATION_UNITS = {"ns": 1, "us": 1_000, "µs": 1_000, "ms": 1_000_000,
                   "s": 1_000_000_000, "m": 60_000_000_000,
                   "h": 3_600_000_000_000}


def parse_duration(value: Any) -> int:
    """Go-style duration string -> integer nanoseconds."""
    if isinstance(value, (int, float)):
        return int(value)
    if not isinstance(value, str):
        raise ValueError(f"invalid duration: {value!r}")
    total = 0
    pos = 0
    for m in _DURATION_RE.finditer(value):
        if m.start() != pos:
            raise ValueError(f"invalid duration: {value!r}")
        total += int(float(m.group(1)) * _DURATION_UNITS[m.group(2)])
        pos = m.end()
    if pos != len(value) or pos == 0:
        raise ValueError(f"invalid duration: {value!r}")
    return total


class JobSpecError(ValueError):
    pass


def _check_keys(body: Dict[str, Any], valid: set, context: str) -> None:
    for key in body:
        if key not in valid:
            raise JobSpecError(f"invalid key '{key}' in {context}")


def _as_list(value: Any) -> List[Any]:
    if value is None:
        return []
    if isinstance(value, list):
        return value
    return [value]


def parse_job_file(path: str) -> Job:
    with open(path) as f:
        return parse_job(f.read())


def parse_job(text: str) -> Job:
    """(reference: jobspec/parse.go:24 Parse)"""
    root = parse_hcl(text)
    jobs = root.get("job")
    if not jobs:
        raise JobSpecError("'job' block not found")
    if isinstance(jobs, list) or len(jobs) != 1:
        raise JobSpecError("only one 'job' block allowed per file")
    (job_id, body), = jobs.items()
    return _parse_job(job_id, body)


_JOB_KEYS = {"id", "name", "region", "all_at_once", "type", "priority",
             "datacenters", "constraint", "update", "periodic", "meta",
             "task", "group"}


def _parse_job(job_id: str, body: Dict[str, Any]) -> Job:
    _check_keys(body, _JOB_KEYS, f"job {job_id!r}")
    job = Job(
        ID=body.get("id", job_id),
        Name=body.get("name", job_id),
        Region=body.get("region", "global"),
        Type=body.get("type", "service"),
        Priority=int(body.get("priority", JobDefaultPriority)),
        AllAtOnce=bool(body.get("all_at_once", False)),
        Datacenters=[str(d) for d in _as_list(body.get("datacenters"))],
        Meta={k: str(v) for k, v in (body.get("meta") or {}).items()},
    )
    job.Constraints = _parse_constraints(body.get("constraint"))

    if "update" in body:
        ub = body["update"]
        _check_keys(ub, {"stagger", "max_parallel"}, "update block")
        job.Update = UpdateStrategy(
            Stagger=parse_duration(ub.get("stagger", 0)),
            MaxParallel=int(ub.get("max_parallel", 0)))

    if "periodic" in body:
        pb = body["periodic"]
        _check_keys(pb, {"enabled", "cron", "prohibit_overlap"}, "periodic block")
        job.Periodic = PeriodicConfig(
            Enabled=bool(pb.get("enabled", True)),
            Spec=str(pb.get("cron", "")),
            SpecType=PeriodicSpecCron,
            ProhibitOverlap=bool(pb.get("prohibit_overlap", False)))

    # Groups; a bare task at job level becomes a group of the same name
    # (reference: parse.go parseJob).
    for name, gbody in _labeled(body.get("group")):
        job.TaskGroups.append(_parse_group(name, gbody))
    for name, tbody in _labeled(body.get("task")):
        job.TaskGroups.append(TaskGroup(
            Name=name, Count=1, Tasks=[_parse_task(name, tbody)]))
    return job


def _labeled(node: Any):
    """Yield (label, body) pairs from a label-keyed block tree."""
    if node is None:
        return
    if isinstance(node, dict):
        for label, body in node.items():
            if isinstance(body, list):
                for item in body:
                    yield label, item
            else:
                yield label, body
    elif isinstance(node, list):
        for item in node:
            yield from _labeled(item)


_GROUP_KEYS = {"count", "constraint", "restart", "meta", "task"}


def _parse_group(name: str, body: Dict[str, Any]) -> TaskGroup:
    _check_keys(body, _GROUP_KEYS, f"group {name!r}")
    tg = TaskGroup(
        Name=name,
        Count=int(body.get("count", 1)),
        Meta={k: str(v) for k, v in (body.get("meta") or {}).items()},
    )
    tg.Constraints = _parse_constraints(body.get("constraint"))
    if "restart" in body:
        rb = body["restart"]
        _check_keys(rb, {"attempts", "interval", "delay", "mode"}, "restart block")
        tg.RestartPolicy = RestartPolicy(
            Attempts=int(rb.get("attempts", 0)),
            Interval=parse_duration(rb.get("interval", 0)),
            Delay=parse_duration(rb.get("delay", 0)),
            Mode=str(rb.get("mode", "delay")))
    for tname, tbody in _labeled(body.get("task")):
        tg.Tasks.append(_parse_task(tname, tbody))
    return tg


_TASK_KEYS = {"driver", "user", "config", "env", "service", "constraint",
              "resources", "meta", "kill_timeout", "logs", "artifact"}


def _parse_task(name: str, body: Dict[str, Any]) -> Task:
    _check_keys(body, _TASK_KEYS, f"task {name!r}")
    task = Task(
        Name=name,
        Driver=str(body.get("driver", "")),
        User=str(body.get("user", "")),
        Config=dict(body.get("config") or {}),
        Env={k: str(v) for k, v in (body.get("env") or {}).items()},
        Meta={k: str(v) for k, v in (body.get("meta") or {}).items()},
    )
    task.Constraints = _parse_constraints(body.get("constraint"))
    if "kill_timeout" in body:
        task.KillTimeout = parse_duration(body["kill_timeout"])
    if "resources" in body:
        task.Resources = _parse_resources(body["resources"])
    else:
        task.Resources = Resources.default()
    if "logs" in body:
        lb = body["logs"]
        _check_keys(lb, {"max_files", "max_file_size"}, "logs block")
        task.LogConfig = LogConfig(
            MaxFiles=int(lb.get("max_files", 10)),
            MaxFileSizeMB=int(lb.get("max_file_size", 10)))
    else:
        # Every task gets a log budget (reference: parse.go assigns
        # DefaultLogConfig so disk validation can account for it).
        task.LogConfig = LogConfig()
    for ab in _as_list(body.get("artifact")):
        _check_keys(ab, {"source", "destination", "options"}, "artifact block")
        task.Artifacts.append(TaskArtifact(
            GetterSource=str(ab.get("source", "")),
            RelativeDest=str(ab.get("destination", "local/")),
            GetterOptions={k: str(v)
                           for k, v in (ab.get("options") or {}).items()}))
    for sname, sbody in _service_blocks(body.get("service")):
        task.Services.append(_parse_service(sname, sbody))
    return task


def _service_blocks(node: Any):
    if node is None:
        return
    for item in _as_list(node):
        yield item.get("name", ""), item


_SERVICE_KEYS = {"name", "tags", "port", "check"}


def _parse_service(name: str, body: Dict[str, Any]) -> Service:
    _check_keys(body, _SERVICE_KEYS, f"service {name!r}")
    svc = Service(
        Name=str(body.get("name", "")),
        Tags=[str(t) for t in _as_list(body.get("tags"))],
        PortLabel=str(body.get("port", "")),
    )
    for cb in _as_list(body.get("check")):
        _check_keys(cb, {"name", "type", "interval", "timeout", "path",
                         "protocol", "command", "args"}, "check block")
        svc.Checks.append(ServiceCheck(
            Name=str(cb.get("name", "")),
            Type=str(cb.get("type", "")),
            Interval=parse_duration(cb.get("interval", 0)),
            Timeout=parse_duration(cb.get("timeout", 0)),
            Path=str(cb.get("path", "")),
            Protocol=str(cb.get("protocol", "")),
            Command=str(cb.get("command", "")),
            Args=[str(a) for a in _as_list(cb.get("args"))]))
    return svc


_RESOURCE_KEYS = {"cpu", "memory", "disk", "iops", "network"}


def _parse_resources(body: Dict[str, Any]) -> Resources:
    _check_keys(body, _RESOURCE_KEYS, "resources block")
    res = Resources(
        CPU=int(body.get("cpu", 100)),
        MemoryMB=int(body.get("memory", 10)),
        DiskMB=int(body.get("disk", 300)),
        IOPS=int(body.get("iops", 0)),
    )
    for nb in _as_list(body.get("network")):
        _check_keys(nb, {"mbits", "port"}, "network block")
        net = NetworkResource(MBits=int(nb.get("mbits", 10)))
        for label, pbody in _labeled(nb.get("port")):
            if pbody and "static" in pbody:
                net.ReservedPorts.append(Port(label, int(pbody["static"])))
            else:
                net.DynamicPorts.append(Port(label, 0))
        res.Networks.append(net)
    return res


def _parse_constraints(node: Any) -> List[Constraint]:
    """Constraint blocks incl. sugar keys (reference: parse.go parseConstraints)."""
    out: List[Constraint] = []
    for cb in _as_list(node):
        lt = str(cb.get("attribute", ""))
        rt = str(cb.get("value", ""))
        op = str(cb.get("operator", "="))
        if "version" in cb:
            op = "version"
            rt = str(cb["version"])
        elif "regexp" in cb:
            op = "regexp"
            rt = str(cb["regexp"])
        if cb.get("distinct_hosts"):
            out.append(Constraint(Operand="distinct_hosts"))
            continue
        out.append(Constraint(LTarget=lt, RTarget=rt, Operand=op))
    return out
