"""Minimal HCL (HashiCorp Configuration Language v1) parser.

Standalone tokenizer + recursive-descent parser covering the subset the job
spec and agent config files use (reference grammar: hashicorp/hcl as consumed
by jobspec/parse.go and command/agent/config_parse.go): blocks with string
labels, assignments, strings with escapes, heredocs, numbers, booleans,
lists, objects, and `#`, `//`, `/* */` comments.

Parses to plain Python dicts; repeated blocks accumulate into lists.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Tuple

_TOKEN_RE = re.compile(r"""
    (?P<ws>\s+)
  | (?P<comment>\#[^\n]*|//[^\n]*|/\*.*?\*/)
  | (?P<heredoc><<-?(?P<tag>[A-Za-z0-9_]+)\n(?P<body>.*?)\n\s*(?P=tag))
  | (?P<string>"(?:\\.|[^"\\])*")
  | (?P<number>-?\d+(?:\.\d+)?(?:[eE][+-]?\d+)?)
  | (?P<bool>\btrue\b|\bfalse\b)
  | (?P<ident>[A-Za-z_][A-Za-z0-9_\-.]*)
  | (?P<punct>[{}\[\],=:])
""", re.VERBOSE | re.DOTALL)


class HCLParseError(ValueError):
    def __init__(self, msg: str, pos: int, text: str):
        line = text.count("\n", 0, pos) + 1
        col = pos - (text.rfind("\n", 0, pos) + 1) + 1
        super().__init__(f"{msg} at line {line}, column {col}")
        self.line = line
        self.column = col


def _tokenize(text: str) -> List[Tuple[str, Any, int]]:
    tokens: List[Tuple[str, Any, int]] = []
    pos = 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if m is None:
            raise HCLParseError(f"unexpected character {text[pos]!r}", pos, text)
        kind = m.lastgroup
        if kind in ("ws", "comment"):
            pass
        elif kind == "heredoc":
            tokens.append(("string", m.group("body"), pos))
        elif kind == "string":
            raw = m.group("string")[1:-1]
            tokens.append(("string", _unescape(raw), pos))
        elif kind == "number":
            raw = m.group("number")
            val = float(raw) if ("." in raw or "e" in raw or "E" in raw) else int(raw)
            tokens.append(("number", val, pos))
        elif kind == "bool":
            tokens.append(("bool", m.group("bool") == "true", pos))
        elif kind == "ident":
            tokens.append(("ident", m.group("ident"), pos))
        elif kind == "punct":
            # The heredoc regex consumes its own match; `tag` group overlap is
            # impossible here.
            tokens.append((m.group("punct"), m.group("punct"), pos))
        pos = m.end()
    tokens.append(("eof", None, len(text)))
    return tokens


def _unescape(raw: str) -> str:
    out = []
    i = 0
    while i < len(raw):
        c = raw[i]
        if c == "\\" and i + 1 < len(raw):
            nxt = raw[i + 1]
            out.append({"n": "\n", "t": "\t", "r": "\r", '"': '"',
                        "\\": "\\"}.get(nxt, "\\" + nxt))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


class _Parser:
    def __init__(self, text: str):
        self.text = text
        self.tokens = _tokenize(text)
        self.i = 0

    def peek(self) -> Tuple[str, Any, int]:
        return self.tokens[self.i]

    def next(self) -> Tuple[str, Any, int]:
        tok = self.tokens[self.i]
        self.i += 1
        return tok

    def expect(self, kind: str) -> Any:
        tok = self.next()
        if tok[0] != kind:
            raise HCLParseError(f"expected {kind}, got {tok[0]} ({tok[1]!r})",
                                tok[2], self.text)
        return tok[1]

    # ------------------------------------------------------------- grammar
    def parse_body(self, terminator: Optional[str]) -> Dict[str, Any]:
        """A sequence of attributes and blocks until terminator/eof."""
        out: Dict[str, Any] = {}
        while True:
            kind, value, pos = self.peek()
            if kind == "eof" or (terminator is not None and kind == terminator):
                return out
            if kind not in ("ident", "string"):
                raise HCLParseError(
                    f"expected identifier, got {kind} ({value!r})", pos, self.text)
            key = self.next()[1]

            kind, value, pos = self.peek()
            if kind == "=":
                self.next()
                _merge(out, key, self.parse_value())
            elif kind in ("string", "ident", "{"):
                # Block, possibly with labels: key "label" ["label2"] { ... }
                labels = []
                while self.peek()[0] in ("string", "ident"):
                    labels.append(self.next()[1])
                self.expect("{")
                body = self.parse_body("}")
                self.expect("}")
                # Nest under the labels so repeated blocks group naturally.
                node: Any = body
                for label in reversed(labels):
                    node = {label: node}
                _merge_block(out, key, node, labeled=bool(labels))
            else:
                raise HCLParseError(
                    f"expected '=' or block after {key!r}", pos, self.text)
            # Optional comma separators between items (objects).
            if self.peek()[0] == ",":
                self.next()

    def parse_value(self) -> Any:
        kind, value, pos = self.next()
        if kind in ("string", "number", "bool"):
            return value
        if kind == "ident":  # bare word treated as string
            return value
        if kind == "[":
            items = []
            while True:
                if self.peek()[0] == "]":
                    self.next()
                    return items
                items.append(self.parse_value())
                if self.peek()[0] == ",":
                    self.next()
        if kind == "{":
            body = self.parse_body("}")
            self.expect("}")
            return body
        raise HCLParseError(f"unexpected {kind} in value", pos, self.text)


def _merge(out: Dict[str, Any], key: str, value: Any) -> None:
    if key in out:
        existing = out[key]
        if isinstance(existing, list):
            existing.append(value)
        else:
            out[key] = [existing, value]
    else:
        out[key] = value


def _merge_block(out: Dict[str, Any], key: str, node: Any, labeled: bool) -> None:
    if key not in out:
        out[key] = node
        return
    existing = out[key]
    if labeled and isinstance(existing, dict) and isinstance(node, dict):
        # Merge label trees: job "a" {...} job "b" {...}
        for label, body in node.items():
            if label in existing:
                _merge_block(existing, label, body, labeled=False)
            else:
                existing[label] = body
        return
    if isinstance(existing, list):
        existing.append(node)
    else:
        out[key] = [existing, node]


def parse(text: str) -> Dict[str, Any]:
    """Parse HCL text into nested dicts/lists."""
    p = _Parser(text)
    return p.parse_body(None)
