"""Job spec frontend: HCL -> structs.Job (reference: jobspec/parse.go)."""

from .parse import parse_job, parse_job_file, parse_duration  # noqa: F401
from .hcl import parse as parse_hcl, HCLParseError  # noqa: F401
