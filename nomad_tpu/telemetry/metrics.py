"""Operational metrics: counters, gauges, and latency samples with pluggable
sinks (reference: the armon/go-metrics surface the reference instruments
through — MeasureSince/IncrCounter/SetGauge calls like nomad/fsm.go:147,
nomad/eval_broker.go:650-662 — with its InmemSink interval aggregation and
statsd push sink, configured from command/agent/command.go:556-580).

Design notes (TPU-first framework, Python runtime): one process-global
registry with a plain lock — every op is a couple of dict writes, far below
the cost of the raft/RPC/scheduler work being measured. Timings are
milliseconds (go-metrics convention). Keys are tuples of path segments,
rendered dotted ("nomad.fsm.apply") for sinks and the HTTP endpoint.
"""

from __future__ import annotations

import logging
import socket
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, Iterable, List, Optional, Tuple

from nomad_tpu.analysis import guarded_by, requires_lock

logger = logging.getLogger("nomad.telemetry")

Key = Tuple[str, ...]


def _name(key: Iterable[str]) -> str:
    return ".".join(str(p) for p in key)


class _Aggregate:
    """Streaming count/sum/min/max for one metric within one interval
    (reference: go-metrics AggregateSample)."""

    __slots__ = ("count", "sum", "min", "max")

    def __init__(self) -> None:
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")

    def ingest(self, v: float) -> None:
        self.count += 1
        self.sum += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v

    def to_dict(self, name: str) -> Dict[str, Any]:
        mean = self.sum / self.count if self.count else 0.0
        return {"Name": name, "Count": self.count, "Sum": self.sum,
                "Min": self.min if self.count else 0.0,
                "Max": self.max if self.count else 0.0, "Mean": mean}


class InMemSink:
    """Fixed-interval aggregating sink backing /v1/agent/metrics and the
    SIGUSR1-style dump (reference: go-metrics inmem.go — gauges keep last
    value, counters and samples aggregate per interval, a bounded ring of
    past intervals is retained)."""

    _concurrency = guarded_by("_lock", "_intervals")

    def __init__(self, interval: float = 10.0, retain: int = 60):
        # Sub-second intervals make every sample its own interval (and 0
        # would divide by zero inside the swallow-all sink fan-out, silently
        # blanking telemetry) — floor to 1s.
        self.interval = max(float(interval), 1.0)
        self.retain = retain
        self._lock = threading.Lock()
        self._intervals: List[Dict[str, Any]] = []

    @requires_lock("_lock")
    def _current(self, now: float) -> Dict[str, Any]:
        start = now - (now % self.interval)
        cur = self._intervals[-1] if self._intervals else None
        if cur is None or cur["start"] != start:
            cur = {"start": start, "gauges": {}, "counters": {},
                   "samples": {}}
            self._intervals.append(cur)
            if len(self._intervals) > self.retain:
                self._intervals = self._intervals[-self.retain:]
        return cur

    def set_gauge(self, key: Key, value: float) -> None:
        with self._lock:
            self._current(time.time())["gauges"][_name(key)] = value

    def incr_counter(self, key: Key, value: float) -> None:
        with self._lock:
            cur = self._current(time.time())["counters"]
            agg = cur.get(_name(key))
            if agg is None:
                agg = cur[_name(key)] = _Aggregate()
            agg.ingest(value)

    def add_sample(self, key: Key, value: float) -> None:
        with self._lock:
            cur = self._current(time.time())["samples"]
            agg = cur.get(_name(key))
            if agg is None:
                agg = cur[_name(key)] = _Aggregate()
            agg.ingest(value)

    def snapshot(self) -> Dict[str, Any]:
        """Most recent complete-or-current interval, display-formatted
        (reference: go-metrics DisplayMetrics shape behind the agent
        metrics endpoint)."""
        with self._lock:
            if not self._intervals:
                return {"Timestamp": "", "Gauges": [], "Counters": [],
                        "Samples": []}
            cur = self._intervals[-1]
            return {
                "Timestamp": time.strftime(
                    "%Y-%m-%d %H:%M:%S +0000",
                    time.gmtime(cur["start"])),
                "Gauges": [{"Name": n, "Value": v}
                           for n, v in sorted(cur["gauges"].items())],
                "Counters": [agg.to_dict(n) for n, agg in
                             sorted(cur["counters"].items())],
                "Samples": [agg.to_dict(n) for n, agg in
                            sorted(cur["samples"].items())],
            }


class StatsdSink:
    """Push sink emitting statsd datagrams over UDP, best-effort
    (reference: go-metrics statsd.go — gauges as |g, counters as |c,
    timers as |ms). Never raises into the instrumented path."""

    def __init__(self, addr: str, host_label: str = ""):
        host, port = addr.rsplit(":", 1)
        # Resolve once: an unresolved hostname target would pay a DNS
        # lookup on every sendto from instrumented hot paths.
        info = socket.getaddrinfo(host, int(port), socket.AF_INET,
                                  socket.SOCK_DGRAM)
        self._target = info[0][4]
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.setblocking(False)
        # Shared-aggregator sinks need per-node series (reference: go-metrics
        # hostname key prefix); the in-memory sink is per-agent and stays
        # unprefixed.
        self._prefix = f"{host_label}." if host_label else ""

    def _send(self, payload: str) -> None:
        try:
            self._sock.sendto(payload.encode(), self._target)
        except OSError:
            pass

    def set_gauge(self, key: Key, value: float) -> None:
        self._send(f"{self._prefix}{_name(key)}:{value:g}|g")

    def incr_counter(self, key: Key, value: float) -> None:
        self._send(f"{self._prefix}{_name(key)}:{value:g}|c")

    def add_sample(self, key: Key, value: float) -> None:
        self._send(f"{self._prefix}{_name(key)}:{value:g}|ms")

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


class MetricsRegistry:
    """Fan-out front for all sinks. Always carries one InMemSink so the
    agent metrics endpoint works without configuration."""

    _concurrency = guarded_by("_lock", "_sinks")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.inmem = InMemSink()
        self._sinks: List[Any] = [self.inmem]
        self.host_label: str = ""

    def configure(self, statsd_addr: str = "",
                  collection_interval: float = 10.0,
                  host_label: str = "") -> None:
        """(reference: command/agent/command.go:556-580 setupTelemetry)

        Reload-safe: a SIGHUP reconfigure swaps the sink list atomically
        (``_fan`` snapshots the reference under the lock and the list is
        never mutated in place) and CLOSES any replaced StatsdSink — the
        old UDP socket would otherwise leak once per reload. A statsd
        sink that cannot be constructed (unresolvable address) degrades
        to a logged warning instead of aborting agent boot/reload; the
        in-memory sink always survives."""
        sinks: List[Any] = [InMemSink(interval=collection_interval)]
        if statsd_addr:
            try:
                sinks.append(StatsdSink(statsd_addr, host_label=host_label))
            except (OSError, ValueError) as exc:
                logger.warning(
                    "telemetry: statsd sink %s unavailable (%s); "
                    "keeping in-memory sink only", statsd_addr, exc)
        with self._lock:
            old = self._sinks
            self.inmem = sinks[0]
            self._sinks = sinks
            self.host_label = host_label
        for sink in old:
            if sink in sinks:
                continue
            close = getattr(sink, "close", None)
            if close is not None:
                try:
                    close()
                # lint: allow(swallow, best-effort close of a replaced sink)
                except Exception:
                    pass

    def add_sink(self, sink: Any) -> None:
        with self._lock:
            # Replace, never mutate: _fan iterates its snapshot lock-free.
            self._sinks = self._sinks + [sink]

    def _fan(self, op: str, key: Key, value: float) -> None:
        # Snapshot the list REFERENCE under the lock: configure() swaps
        # whole lists, so a concurrent reload can never tear this walk.
        with self._lock:
            sinks = self._sinks
        for sink in sinks:
            try:
                getattr(sink, op)(key, value)
            # lint: allow(swallow, a broken sink must never break the measured path)
            except Exception:
                pass

    # ------------------------------------------------------------- surface
    def set_gauge(self, key: Key, value: float) -> None:
        self._fan("set_gauge", tuple(key), float(value))

    def incr_counter(self, key: Key, value: float = 1.0) -> None:
        self._fan("incr_counter", tuple(key), float(value))

    def add_sample(self, key: Key, value: float) -> None:
        self._fan("add_sample", tuple(key), float(value))

    def measure_since(self, key: Key, start: float) -> None:
        """`start` is a time.monotonic() stamp; records milliseconds."""
        self.add_sample(tuple(key), (time.monotonic() - start) * 1000.0)

    @contextmanager
    def measure(self, key: Key):
        start = time.monotonic()
        try:
            yield
        finally:
            self.measure_since(key, start)

    def snapshot(self) -> Dict[str, Any]:
        return self.inmem.snapshot()


# Process-global registry: instrumentation sites call these directly, the
# agent configures sinks at boot (reference: go-metrics global metrics
# singleton initialised by setupTelemetry).
registry = MetricsRegistry()

set_gauge = registry.set_gauge
incr_counter = registry.incr_counter
add_sample = registry.add_sample
measure_since = registry.measure_since
measure = registry.measure
snapshot = registry.snapshot
configure = registry.configure
