"""Causal tracing for the evaluation lifecycle (reference technique:
Dapper-style trace/span propagation — Sigelman et al. 2010 — as deployed
in systems like the reference's opentelemetry hooks; here a dependency-free
core sized for the scheduler's needs).

A *trace* is one logical operation (a job register riding through broker,
worker, plan apply, raft, and the client agent); a *span* is one timed
stage of it. Spans carry monotonic durations anchored to a wall-clock
start, free-form attributes, and timestamped events (failpoint triggers,
retry attempts, fallbacks).

Propagation has three legs:

* **Ambient context** — a ``threading.local`` span stack. ``span()``
  opens a child of the current span; synchronous call chains (RPC handler
  -> raft apply -> FSM) need no plumbing.
* **Wire carrier** — ``inject()`` produces a small dict that rides the
  msgpack RPC envelope (rpc/wire.py ``Trace`` field); the receiving
  dispatcher ``attach()``-es it so one trace spans processes.
* **Async links** — queue hops (eval broker, plan queue, client alloc
  pickup) break the thread chain. The enqueueing side calls
  ``link("eval", ev.ID)``; the dequeueing side ``resume()``-s from
  ``linked("eval", ev.ID)``.

Sampling: a head decision at trace creation (``sample_ratio``) plus a
tail rule — a trace that records an error/failpoint/fallback is retained
even when the head coin said no. The tail rule is why sampling bounds
RETENTION and visibility, not recording cost: while tracing is enabled
every trace records its spans (you cannot retroactively keep an
error trace you never recorded), so ``sample_ratio`` is a memory/noise
knob, not a CPU one — enabling tracing is itself the opt-in to the
recording overhead. Disarmed (``enabled=False``, the default) every
entry point is one module-attribute truthiness check and a shared no-op
context manager: ``bench.py --smoke`` parity is the gate.
"""

from __future__ import annotations

import threading
import time
import uuid
from collections import OrderedDict
from typing import Any, Dict, List, Optional

from . import metrics

__all__ = [
    "Span", "configure", "is_enabled", "root_span", "span", "resume",
    "start_from", "attach", "current", "add_event", "inject", "link",
    "linked", "linked_entry", "record_span", "traces", "get_trace",
    "export_chrome", "clear", "status",
]

# Events whose presence retains an otherwise-unsampled trace (tail rule).
_PROMOTE_EVENTS = frozenset({"failpoint", "error", "fallback"})

_LINK_CAP = 4096          # async-hop carrier registry bound
_DEFAULT_RING = 128       # completed/live traces retained


class _NoopSpan:
    """Shared disarmed span: every operation is a no-op."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def event(self, name: str, **attrs) -> None:
        pass

    def set_attr(self, key: str, value) -> None:
        pass

    def finish(self, error: Optional[str] = None) -> None:
        pass


_NOOP = _NoopSpan()


class _Trace:
    __slots__ = ("trace_id", "sampled", "spans", "events", "root_name",
                 "start_wall", "error", "complete")

    def __init__(self, trace_id: str, sampled: bool):
        self.trace_id = trace_id
        self.sampled = sampled
        self.spans: List[Span] = []
        # Trace-level annotations (e.g. a PARTIAL re-verify noticed after
        # the owning span closed): (wall_ts, name, attrs).
        self.events: List[tuple] = []
        self.root_name = ""
        self.start_wall = time.time()
        self.error = False
        self.complete = False

    @property
    def retained(self) -> bool:
        return self.sampled or self.error


class Span:
    """One timed stage. Use as a context manager (ambient) or hold the
    object and call ``finish()`` explicitly (cross-thread stages)."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "start_wall",
                 "start_mono", "duration_ms", "attrs", "events", "thread",
                 "error", "_trace", "_is_root", "_ambient", "_finished")

    def __init__(self, trace: _Trace, name: str, parent_id: Optional[str],
                 attrs: Dict[str, Any], is_root: bool):
        self.trace_id = trace.trace_id
        self.span_id = uuid.uuid4().hex[:16]
        self.parent_id = parent_id
        self.name = name
        self.start_wall = time.time()
        self.start_mono = time.monotonic()
        self.duration_ms: Optional[float] = None
        self.attrs = dict(attrs)
        self.events: List[tuple] = []  # (offset_ms, name, attrs)
        self.thread = threading.current_thread().name
        self.error = False
        self._trace = trace
        self._is_root = is_root
        self._ambient = False
        self._finished = False

    # ------------------------------------------------------------- recording
    def event(self, name: str, **attrs) -> None:
        off = (time.monotonic() - self.start_mono) * 1000.0
        self.events.append((off, name, attrs))
        if name in _PROMOTE_EVENTS:
            self.error = True
            self._trace.error = True

    def set_attr(self, key: str, value) -> None:
        self.attrs[key] = value

    def finish(self, error: Optional[str] = None) -> None:
        if self._finished:
            return
        self._finished = True
        if error:
            self.error = True
            self.attrs.setdefault("error", error)
        self.duration_ms = (time.monotonic() - self.start_mono) * 1000.0
        with _lock:
            self._trace.spans.append(self)
            if self.error:
                self._trace.error = True
            if self._is_root:
                self._trace.complete = True
        # Span durations bridge into the metrics registry under
        # nomad.trace.<span name> so sinks/statsd see trace latencies too.
        metrics.add_sample(("nomad", "trace") + tuple(self.name.split(".")),
                           self.duration_ms)

    # ------------------------------------------------------- context manager
    def __enter__(self) -> "Span":
        stack = _stack()
        stack.append(self)
        self._ambient = True
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._ambient:
            stack = _stack()
            if stack and stack[-1] is self:
                stack.pop()
            self._ambient = False
        if exc_type is not None:
            self.event("error", type=exc_type.__name__)
        self.finish(error=exc_type.__name__ if exc_type else None)
        return False

    def carrier(self) -> Dict[str, Any]:
        return {"TraceID": self.trace_id, "SpanID": self.span_id,
                "Sampled": self._trace.sampled}

    def to_dict(self) -> Dict[str, Any]:
        return {
            "TraceID": self.trace_id,
            "SpanID": self.span_id,
            "ParentID": self.parent_id,
            "Name": self.name,
            "Start": self.start_wall,
            "DurationMs": self.duration_ms,
            "Thread": self.thread,
            "Error": self.error,
            "Attrs": self.attrs,
            "Events": [{"OffsetMs": round(off, 3), "Name": name,
                        "Attrs": attrs}
                       for off, name, attrs in self.events],
        }


class _RemoteCtx:
    """Ambient stack entry for an extracted wire carrier: parents the next
    span under the remote caller's span without opening a local one. Holds
    only the carrier fields — the local _Trace is created LAZILY when a
    span is actually opened, so carrier-bearing frames whose handlers
    never span (raft replication on followers) cannot fill the ring with
    empty traces."""

    __slots__ = ("trace_id", "span_id", "sampled")

    def __init__(self, trace_id: str, span_id: str, sampled: bool):
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = sampled


# ------------------------------------------------------------------ state
_lock = threading.Lock()
_enabled = False
_sample_ratio = 1.0
_ring_max = _DEFAULT_RING
_traces: "OrderedDict[str, _Trace]" = OrderedDict()
_links: "OrderedDict[tuple, tuple]" = OrderedDict()  # (kind,key)->(carrier,t)
_tls = threading.local()


def _stack() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def configure(enabled: Optional[bool] = None,
              sample_ratio: Optional[float] = None,
              ring: Optional[int] = None) -> None:
    global _enabled, _sample_ratio, _ring_max
    with _lock:
        if enabled is not None:
            _enabled = bool(enabled)
        if sample_ratio is not None:
            _sample_ratio = min(1.0, max(0.0, float(sample_ratio)))
        if ring is not None:
            _ring_max = max(1, int(ring))


def is_enabled() -> bool:
    return _enabled


def status() -> Dict[str, Any]:
    with _lock:
        return {"Enabled": _enabled, "SampleRatio": _sample_ratio,
                "Ring": _ring_max,
                "Traces": sum(1 for t in _traces.values() if t.retained)}


def clear() -> None:
    with _lock:
        _traces.clear()
        _links.clear()


# ------------------------------------------------------------ trace store
def _new_trace_locked(trace_id: Optional[str] = None,
                      sampled: Optional[bool] = None) -> _Trace:
    if sampled is None:
        import random

        sampled = random.random() < _sample_ratio
    t = _Trace(trace_id or uuid.uuid4().hex, sampled)
    _traces[t.trace_id] = t
    # Bounded at exactly the configured ring: evict unsampled-and-clean
    # traces first (they only exist in case a late error promotes them),
    # then the oldest outright.
    while len(_traces) > _ring_max:
        victim = next((tid for tid, tr in _traces.items()
                       if not tr.retained), None)
        _traces.pop(victim if victim is not None
                    else next(iter(_traces)), None)
    return t


def _trace_for_carrier_locked(carrier: Dict[str, Any]) -> Optional[_Trace]:
    tid = carrier.get("TraceID")
    if not tid:
        return None
    t = _traces.get(tid)
    if t is None:
        t = _new_trace_locked(tid, bool(carrier.get("Sampled", True)))
    return t


# ----------------------------------------------------------- span entries
def root_span(name: str, **attrs):
    """Open a span, creating a NEW trace when no ambient context exists
    (the trace-ingress points: RPC dispatch, service sync). Joins the
    current trace as a child when one is active."""
    if not _enabled:
        return _NOOP
    top = _stack()[-1] if _stack() else None
    if top is not None:
        return _child_of(top, name, attrs)
    with _lock:
        trace = _new_trace_locked()
        trace.root_name = name
    return Span(trace, name, None, attrs, is_root=True)


def span(name: str, **attrs):
    """Open a child span of the ambient context; no-op when there is no
    active trace (background work must not spawn trace spam)."""
    if not _enabled:
        return _NOOP
    top = _stack()[-1] if _stack() else None
    if top is None:
        return _NOOP
    return _child_of(top, name, attrs)


def resume(carrier: Optional[Dict[str, Any]], name: str, **attrs):
    """Open a span continuing from an async-hop/wire carrier. Prefers the
    ambient context when one is active; no-op without either."""
    if not _enabled:
        return _NOOP
    top = _stack()[-1] if _stack() else None
    if top is not None:
        return _child_of(top, name, attrs)
    if not carrier or not isinstance(carrier, dict):
        return _NOOP
    with _lock:
        trace = _trace_for_carrier_locked(carrier)
    if trace is None:
        return _NOOP
    return Span(trace, name, carrier.get("SpanID"), attrs, is_root=False)


def start_from(carrier: Optional[Dict[str, Any]], name: str,
               **attrs) -> Optional[Span]:
    """Explicit (non-ambient) span from a carrier, for stages that cross
    threads: hold the Span and call ``finish()`` when the stage ends.
    Returns None when tracing is off or the carrier is empty."""
    if not _enabled or not carrier or not isinstance(carrier, dict):
        return None
    with _lock:
        trace = _trace_for_carrier_locked(carrier)
    if trace is None:
        return None
    return Span(trace, name, carrier.get("SpanID"), attrs, is_root=False)


def _child_of(top, name: str, attrs: Dict[str, Any]) -> Span:
    if isinstance(top, _RemoteCtx):
        with _lock:
            trace = _trace_for_carrier_locked(
                {"TraceID": top.trace_id, "Sampled": top.sampled})
        return Span(trace, name, top.span_id, attrs, is_root=False)
    return Span(top._trace, name, top.span_id, attrs, is_root=False)


class _Attach:
    """Context manager establishing a remote parent from a wire carrier
    (no local span): the dispatcher's handler spans become its children."""

    __slots__ = ("_ctx",)

    def __init__(self, ctx: Optional[_RemoteCtx]):
        self._ctx = ctx

    def __enter__(self):
        if self._ctx is not None:
            _stack().append(self._ctx)
        return self

    def __exit__(self, *exc) -> bool:
        if self._ctx is not None:
            stack = _stack()
            if stack and stack[-1] is self._ctx:
                stack.pop()
        return False


def attach(carrier: Optional[Dict[str, Any]]) -> _Attach:
    if not _enabled or not carrier or not isinstance(carrier, dict) \
            or not carrier.get("TraceID"):
        return _Attach(None)
    return _Attach(_RemoteCtx(carrier["TraceID"],
                              carrier.get("SpanID", ""),
                              bool(carrier.get("Sampled", True))))


def current() -> Optional[Span]:
    stack = _stack()
    for entry in reversed(stack):
        if isinstance(entry, Span):
            return entry
    return None


def add_event(name: str, **attrs) -> None:
    """Record an event on the active ambient span (failpoint triggers,
    retry attempts). One truthiness check when tracing is disarmed."""
    if not _enabled:
        return
    s = current()
    if s is not None:
        s.event(name, **attrs)


def add_trace_event(carrier: Optional[Dict[str, Any]], name: str,
                    **attrs) -> None:
    """Trace-level annotation via a carrier, for after the owning span
    closed (e.g. the plan applier's PARTIAL re-verify)."""
    if not _enabled or not carrier or not isinstance(carrier, dict):
        return
    with _lock:
        trace = _traces.get(carrier.get("TraceID", ""))
        if trace is None:
            return
        trace.events.append((time.time(), name, attrs))
        if name in _PROMOTE_EVENTS:
            trace.error = True


def inject() -> Optional[Dict[str, Any]]:
    """Carrier for the active context, for the RPC envelope."""
    if not _enabled:
        return None
    stack = _stack()
    if not stack:
        return None
    top = stack[-1]
    if isinstance(top, _RemoteCtx):
        return {"TraceID": top.trace_id, "SpanID": top.span_id,
                "Sampled": top.sampled}
    return top.carrier()


# ------------------------------------------------------------ async links
def link(kind: str, key: str) -> None:
    """Register the active context's carrier under (kind, key) so an
    async consumer (worker, applier, client) can ``resume`` the trace."""
    if not _enabled:
        return
    carrier = inject()
    if carrier is None:
        return
    with _lock:
        _links[(kind, key)] = (carrier, time.monotonic())
        while len(_links) > _LINK_CAP:
            _links.popitem(last=False)


def linked(kind: str, key: str) -> Optional[Dict[str, Any]]:
    if not _enabled:
        return None
    with _lock:
        entry = _links.get((kind, key))
    return entry[0] if entry is not None else None


def linked_entry(kind: str, key: str) -> Optional[tuple]:
    """(carrier, monotonic-link-time) — queue-wait reconstruction."""
    if not _enabled:
        return None
    with _lock:
        return _links.get((kind, key))


def record_span(carrier: Optional[Dict[str, Any]], name: str,
                start_mono: float, **attrs) -> None:
    """Synthesize an already-finished span from a measured interval (e.g.
    broker queue wait: enqueue-link time -> dequeue time)."""
    if not _enabled or not carrier or not isinstance(carrier, dict):
        return
    with _lock:
        trace = _trace_for_carrier_locked(carrier)
    if trace is None:
        return
    s = Span(trace, name, carrier.get("SpanID"), attrs, is_root=False)
    now_mono = time.monotonic()
    s.start_mono = start_mono
    s.start_wall = s.start_wall - (now_mono - start_mono)
    s.finish()


# ------------------------------------------------------------- inspection
def traces() -> List[Dict[str, Any]]:
    """Summaries of retained traces, newest last."""
    with _lock:
        kept = [t for t in _traces.values() if t.retained]
        out = []
        for t in kept:
            root = next((s for s in t.spans if s._is_root), None)
            out.append({
                "TraceID": t.trace_id,
                "Root": t.root_name or (root.name if root else ""),
                "Start": t.start_wall,
                "DurationMs": (root.duration_ms if root is not None
                               else None),
                "Spans": len(t.spans),
                "Complete": t.complete,
                "Error": t.error,
            })
        return out


def get_trace(trace_id: str) -> Optional[Dict[str, Any]]:
    with _lock:
        t = _traces.get(trace_id)
        if t is None:
            return None
        return {
            "TraceID": t.trace_id,
            "Root": t.root_name,
            "Start": t.start_wall,
            "Sampled": t.sampled,
            "Error": t.error,
            "Complete": t.complete,
            "Spans": [s.to_dict() for s in t.spans],
            "Events": [{"Time": ts, "Name": name, "Attrs": attrs}
                       for ts, name, attrs in t.events],
        }


def export_chrome(trace_id: Optional[str] = None) -> Dict[str, Any]:
    """Chrome trace-event JSON (the ``chrome://tracing`` / Perfetto
    format): complete ``X`` events per span, instant ``i`` events per span
    event, with process/thread-name metadata. Loadable in Perfetto."""
    with _lock:
        if trace_id is not None:
            picked = [t for t in (_traces.get(trace_id),) if t is not None]
        else:
            picked = [t for t in _traces.values() if t.retained]
        events: List[Dict[str, Any]] = []
        for pid, t in enumerate(picked, start=1):
            tids: Dict[str, int] = {}
            events.append({"name": "process_name", "ph": "M", "ts": 0,
                           "pid": pid, "tid": 0,
                           "args": {"name": f"{t.root_name or 'trace'} "
                                            f"{t.trace_id[:8]}"}})
            for s in t.spans:
                tid = tids.setdefault(s.thread, len(tids) + 1)
                ts_us = s.start_wall * 1e6
                events.append({
                    "name": s.name, "cat": "nomad", "ph": "X",
                    "ts": ts_us,
                    "dur": (s.duration_ms or 0.0) * 1000.0,
                    "pid": pid, "tid": tid,
                    "args": {"span_id": s.span_id,
                             "parent_id": s.parent_id,
                             "error": s.error, **s.attrs},
                })
                for off, name, attrs in s.events:
                    events.append({
                        "name": f"{s.name}:{name}", "cat": "nomad",
                        "ph": "i", "s": "t",
                        "ts": ts_us + off * 1000.0,
                        "pid": pid, "tid": tid, "args": dict(attrs),
                    })
            for ts, name, attrs in t.events:
                events.append({"name": name, "cat": "nomad", "ph": "i",
                               "s": "p", "ts": ts * 1e6, "pid": pid,
                               "tid": 0, "args": dict(attrs)})
            for tname, tid in tids.items():
                events.append({"name": "thread_name", "ph": "M", "ts": 0,
                               "pid": pid, "tid": tid,
                               "args": {"name": tname}})
    return {"displayTimeUnit": "ms", "traceEvents": events}
