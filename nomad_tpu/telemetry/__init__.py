"""Telemetry: operational metrics with in-memory aggregation and push sinks
(reference: the go-metrics instrumentation threaded through nomad/*.go and
configured by command/agent/command.go setupTelemetry), plus Dapper-style
evaluation-lifecycle tracing (trace.py)."""

from . import trace  # noqa: F401
from .metrics import (
    InMemSink,
    MetricsRegistry,
    StatsdSink,
    add_sample,
    configure,
    incr_counter,
    measure,
    measure_since,
    registry,
    set_gauge,
    snapshot,
)

__all__ = [
    "trace",
    "InMemSink",
    "MetricsRegistry",
    "StatsdSink",
    "add_sample",
    "configure",
    "incr_counter",
    "measure",
    "measure_since",
    "registry",
    "set_gauge",
    "snapshot",
]
