"""Artifact download (reference: client/getter/getter.go).

http/https fetch with optional sha256 checksum verification and
escape-prevention on the destination, plus env interpolation of the source.
"""

from __future__ import annotations

import hashlib
import os
import urllib.parse
import urllib.request

from nomad_tpu.structs import TaskArtifact

from .env import TaskEnv


class ArtifactError(Exception):
    pass


def get_artifact(artifact: TaskArtifact, task_dir: str,
                 task_env: TaskEnv) -> str:
    """Fetch into the task dir; returns the destination path."""
    source = task_env.replace(artifact.GetterSource)
    parsed = urllib.parse.urlparse(source)
    if parsed.scheme not in ("http", "https", "file"):
        raise ArtifactError(f"unsupported artifact scheme: {parsed.scheme!r}")

    root = os.path.normpath(task_dir)
    dest_dir = os.path.normpath(os.path.join(root, artifact.RelativeDest))
    if dest_dir != root and not dest_dir.startswith(root + os.sep):
        raise ArtifactError("artifact destination escapes task directory")
    os.makedirs(dest_dir, exist_ok=True)
    filename = os.path.basename(parsed.path) or "artifact"
    dest = os.path.join(dest_dir, filename)

    try:
        with urllib.request.urlopen(source, timeout=300) as resp, \
                open(dest, "wb") as out:
            while True:
                chunk = resp.read(65536)
                if not chunk:
                    break
                out.write(chunk)
    except Exception as e:
        raise ArtifactError(f"failed to fetch {source!r}: {e}") from e

    checksum = artifact.GetterOptions.get("checksum", "")
    if checksum:
        algo, _, want = checksum.partition(":")
        h = hashlib.new(algo or "sha256")
        with open(dest, "rb") as f:
            for chunk in iter(lambda: f.read(65536), b""):
                h.update(chunk)
        if h.hexdigest() != want:
            raise ArtifactError(
                f"checksum mismatch for {source!r}: got {h.hexdigest()}")
    if os.name == "posix":
        os.chmod(dest, 0o755)
    return dest
