"""Task executor: a detached supervisor process (reference:
client/driver/executor/ + the re-exec'd plugin child in plugins.go).

Runs as `python -m nomad_tpu.client.executor <spec.json>`, detached from the
agent (own session), so an agent crash or restart never kills tasks; the
task runner re-attaches by reading the state file and probing the pid.

The executor: applies cgroup limits when possible (cgroup v2, root),
optionally chroots, drops to a user, launches the command in its own process
group, pumps stdout/stderr into size-rotated log files, and records the exit
status. Kill protocol: SIGTERM to the process group, then SIGKILL after the
task's kill timeout (driven by the task runner sending signals using the
recorded pgid).

Spec file (JSON): {command, args, env, cwd, user?, task_name, log_dir,
max_files, max_file_size_mb, cgroup?: {cpu_shares, memory_mb}, chroot?}
State file (JSON, same dir as spec): {executor_pid, pgid, started_at}
Exit file (JSON): {exit_code, signal, finished_at}
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time


def run_executor(spec_path: str) -> int:
    with open(spec_path) as f:
        spec = json.load(f)

    task = spec["task_name"]
    base = os.path.dirname(spec_path)
    state_path = os.path.join(base, f"{task}.executor_state.json")
    exit_path = os.path.join(base, f"{task}.exit_status.json")

    from nomad_tpu.client.logs import FileRotator

    stdout = FileRotator(spec["log_dir"], f"{task}.stdout",
                         spec.get("max_files", 10),
                         spec.get("max_file_size_mb", 10))
    stderr = FileRotator(spec["log_dir"], f"{task}.stderr",
                         spec.get("max_files", 10),
                         spec.get("max_file_size_mb", 10))

    import subprocess

    def preexec():
        os.setsid()  # own process group for group signaling
        chroot = spec.get("chroot")
        if chroot:
            os.chroot(chroot)
            os.chdir("/")
        user = spec.get("user")
        if user:
            import pwd

            pw = pwd.getpwnam(user)
            os.setgid(pw.pw_gid)
            os.setuid(pw.pw_uid)

    proc = subprocess.Popen(
        [spec["command"]] + list(spec.get("args", [])),
        env=spec.get("env") or None,
        cwd=spec.get("cwd") or None,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        preexec_fn=preexec,
    )

    _apply_cgroup(spec.get("cgroup"), task, proc.pid)

    with open(state_path, "w") as f:
        json.dump({"executor_pid": os.getpid(), "pid": proc.pid,
                   "pgid": proc.pid, "started_at": time.time()}, f)

    def pump(stream, rotator):
        for chunk in iter(lambda: stream.read(4096), b""):
            rotator.write(chunk)
        rotator.close()

    t_out = threading.Thread(target=pump, args=(proc.stdout, stdout),
                             daemon=True, name="executor-pump-stdout")
    t_err = threading.Thread(target=pump, args=(proc.stderr, stderr),
                             daemon=True, name="executor-pump-stderr")
    t_out.start()
    t_err.start()

    # Forward TERM/INT to the task's process group.
    def forward(signum, frame):
        try:
            os.killpg(proc.pid, signum)
        except ProcessLookupError:
            pass

    signal.signal(signal.SIGTERM, forward)
    signal.signal(signal.SIGINT, forward)

    code = proc.wait()
    t_out.join(timeout=5)
    t_err.join(timeout=5)
    result = {"exit_code": code if code >= 0 else 0,
              "signal": -code if code < 0 else 0,
              "finished_at": time.time()}
    tmp = exit_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(result, f)
    os.replace(tmp, exit_path)
    _cleanup_cgroup(task)
    return 0


def _cgroup_path(task: str) -> str:
    return f"/sys/fs/cgroup/nomad_tpu_{task}_{os.getpid()}"


def _apply_cgroup(cfg, task: str, pid: int) -> None:
    """cgroup v2 resource limits; best-effort (needs root)."""
    if not cfg:
        return
    path = _cgroup_path(task)
    try:
        os.makedirs(path, exist_ok=True)
        mem_mb = cfg.get("memory_mb")
        if mem_mb:
            with open(os.path.join(path, "memory.max"), "w") as f:
                f.write(str(int(mem_mb) * 1024 * 1024))
        cpu_shares = cfg.get("cpu_shares")
        if cpu_shares:
            with open(os.path.join(path, "cpu.weight"), "w") as f:
                # Map MHz shares into cgroup2 weight [1, 10000].
                f.write(str(max(1, min(10000, int(cpu_shares)))))
        with open(os.path.join(path, "cgroup.procs"), "w") as f:
            f.write(str(pid))
    except OSError:
        pass


def _cleanup_cgroup(task: str) -> None:
    path = _cgroup_path(task)
    try:
        os.rmdir(path)
    except OSError:
        pass


if __name__ == "__main__":
    sys.exit(run_executor(sys.argv[1]))
