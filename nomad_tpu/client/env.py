"""Task environment construction + interpolation (reference:
client/driver/env/env.go, helper/args/).

Builds the NOMAD_* environment for a task and interpolates ${...} references
(node attributes, metadata, env vars) in task configs, service names/tags,
and artifact sources.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional

from nomad_tpu.structs import Allocation, Node, Resources, Task

_VAR_RE = re.compile(r"\$\{([^}]+)\}")

# Env keys (reference: env/env.go:14-60)
ALLOC_DIR = "NOMAD_ALLOC_DIR"
TASK_LOCAL_DIR = "NOMAD_TASK_DIR"
MEMORY_LIMIT = "NOMAD_MEMORY_LIMIT"
CPU_LIMIT = "NOMAD_CPU_LIMIT"
ALLOC_ID = "NOMAD_ALLOC_ID"
ALLOC_NAME = "NOMAD_ALLOC_NAME"
ALLOC_INDEX = "NOMAD_ALLOC_INDEX"
TASK_NAME = "NOMAD_TASK_NAME"
ADDR_PREFIX = "NOMAD_ADDR_"
PORT_PREFIX = "NOMAD_PORT_"
IP_PREFIX = "NOMAD_IP_"
META_PREFIX = "NOMAD_META_"


class TaskEnv:
    def __init__(self, node: Optional[Node] = None,
                 task: Optional[Task] = None,
                 alloc: Optional[Allocation] = None,
                 alloc_dir: str = "", task_dir: str = ""):
        self.env: Dict[str, str] = {}
        self.node_values: Dict[str, str] = {}
        if node is not None:
            self._load_node(node)
        if task is not None:
            self._load_task(task, alloc)
        if alloc is not None:
            self.env[ALLOC_ID] = alloc.ID
            self.env[ALLOC_NAME] = alloc.Name
            if "[" in alloc.Name:
                self.env[ALLOC_INDEX] = alloc.Name.rsplit("[", 1)[1].rstrip("]")
        if alloc_dir:
            self.env[ALLOC_DIR] = alloc_dir
        if task_dir:
            self.env[TASK_LOCAL_DIR] = task_dir

    def _load_node(self, node: Node) -> None:
        nv = self.node_values
        nv["node.unique.id"] = node.ID
        nv["node.datacenter"] = node.Datacenter
        nv["node.unique.name"] = node.Name
        nv["node.class"] = node.NodeClass
        for k, v in node.Attributes.items():
            nv[f"attr.{k}"] = v
        for k, v in node.Meta.items():
            nv[f"meta.{k}"] = v

    def _load_task(self, task: Task, alloc: Optional[Allocation]) -> None:
        self.env[TASK_NAME] = task.Name
        res = None
        if alloc is not None:
            res = alloc.TaskResources.get(task.Name)
        if res is None:
            res = task.Resources
        if res is not None:
            self.env[MEMORY_LIMIT] = str(res.MemoryMB)
            self.env[CPU_LIMIT] = str(res.CPU)
            for net in res.Networks:
                for label, value in net.port_labels().items():
                    # Label case is preserved (reference: env.go:140 uses the
                    # label verbatim — jobs reference ${NOMAD_PORT_http}).
                    key = label.replace("-", "_")
                    self.env[f"{IP_PREFIX}{key}"] = net.IP
                    self.env[f"{PORT_PREFIX}{key}"] = str(value)
                    self.env[f"{ADDR_PREFIX}{key}"] = f"{net.IP}:{value}"
        for k, v in task.Meta.items():
            self.env[f"{META_PREFIX}{k.upper().replace('-', '_')}"] = v
        for k, v in task.Env.items():
            self.env[k] = v

    # ---------------------------------------------------------- interpolate
    def replace(self, value: str) -> str:
        """Interpolate ${...} against node values then env."""
        def sub(m: re.Match) -> str:
            key = m.group(1).strip()
            if key in self.node_values:
                return self.node_values[key]
            if key.startswith("env."):
                return self.env.get(key[4:], "")
            return self.env.get(key, m.group(0))

        return _VAR_RE.sub(sub, value)

    def replace_any(self, value: Any) -> Any:
        if isinstance(value, str):
            return self.replace(value)
        if isinstance(value, list):
            return [self.replace_any(v) for v in value]
        if isinstance(value, dict):
            return {k: self.replace_any(v) for k, v in value.items()}
        return value

    def build_env(self) -> Dict[str, str]:
        """Final environment map with values interpolated."""
        return {k: self.replace(v) for k, v in self.env.items()}
