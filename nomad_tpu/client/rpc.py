"""Client <-> server channel (reference: the msgpack RPC surface the client
uses — Node.Register, Node.UpdateStatus, Node.GetClientAllocs with blocking,
Alloc.GetAllocs, Node.UpdateAlloc; nomad/rpc.go + client/rpcproxy/).

The dev-mode/in-process implementation calls the Server directly and uses
state-store watches for blocking queries; a wire implementation (msgpack over
TCP) plugs in behind the same interface.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Dict, List, Optional, Protocol, Tuple

from nomad_tpu.resilience.retry import Backoff, CircuitBreaker, RetryPolicy
from nomad_tpu.state.watch import Item
from nomad_tpu.telemetry import trace
from nomad_tpu.structs import Allocation, Node, from_dict, to_dict

logger = logging.getLogger("nomad.client.rpc")


class ServerChannel(Protocol):
    def register_node(self, node: Node) -> float: ...
    def heartbeat(self, node_id: str) -> float: ...
    def update_node_status(self, node_id: str, status: str) -> float: ...
    def get_client_allocs(self, node_id: str, min_index: int,
                          max_wait: float) -> Tuple[Dict[str, int], int]: ...
    def get_allocs(self, alloc_ids: List[str]) -> List[Allocation]: ...
    def update_allocs(self, allocs: List[Allocation]) -> None: ...
    def sync_services(self, upserts: List, deletes: List[str]) -> None: ...


class InProcServerChannel:
    """Direct in-process channel to a Server (dev mode, reference: the
    agent's server-embedded RPC shortcut, command/agent/agent.go:597)."""

    def __init__(self, server):
        self.server = server

    def register_node(self, node: Node) -> float:
        ttl, _ = self.server.node_register(node)
        return ttl

    def heartbeat(self, node_id: str) -> float:
        return self.server.node_heartbeat(node_id)

    def update_node_status(self, node_id: str, status: str) -> float:
        ttl, _ = self.server.node_update_status(node_id, status)
        return ttl

    def get_client_allocs(self, node_id: str, min_index: int,
                          max_wait: float) -> Tuple[Dict[str, int], int]:
        """Blocking query: alloc_id -> AllocModifyIndex for the node
        (reference: node_endpoint.go:474-528 GetClientAllocs). Reads the
        store's columnar-aware index map: a sweep-placed alloc's id and
        commit index come straight off the segment columns, so the pull
        signal never materializes Allocation objects the node hasn't
        actually fetched yet (state_store.client_alloc_map)."""
        state = self.server.state
        event = threading.Event()
        items = [Item(alloc_node=node_id)]
        state.watch(items, event)
        try:
            while True:
                alloc_map, index = state.client_alloc_map(node_id)
                if index > min_index or max_wait <= 0:
                    return alloc_map, index
                event.clear()
                if not event.wait(max_wait):
                    return alloc_map, index
        finally:
            state.stop_watch(items, event)

    def get_allocs(self, alloc_ids: List[str]) -> List[Allocation]:
        out = []
        for aid in alloc_ids:
            alloc = self.server.state.alloc_by_id(aid)
            if alloc is not None:
                out.append(alloc)
        return out

    def update_allocs(self, allocs: List[Allocation]) -> None:
        self.server.node_update_allocs(allocs)

    def sync_services(self, upserts: List, deletes: List[str]) -> None:
        self.server.service_sync(upserts, deletes)


def discover_servers(http_addr: str, timeout: float = 5.0) -> List[str]:
    """Bootstrap a server list from any agent's HTTP API via the service
    registry (the reference's analogue: clients discovering "nomad-server"
    rpc services from the local Consul agent, client/client.go:1240-1278).
    Returns rpc addresses for every registered server."""
    import json
    import urllib.request

    if not http_addr.startswith("http"):
        http_addr = "http://" + http_addr
    url = f"{http_addr.rstrip('/')}/v1/service/nomad-server"
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        regs = json.load(resp)
    return sorted({f"{r['Address']}:{r['Port']}" for r in regs
                   if "rpc" in (r.get("Tags") or ())})


class RpcProxy:
    """Client-side server list manager: primary servers learned from
    heartbeats, round-robin failover on error, manual backup seeds
    (reference: client/rpcproxy/rpcproxy.go:88-135 FindServer /
    NotifyFailedServer / RebalanceServers).

    Each server carries a circuit breaker: repeated failures quarantine
    the address, so a dead server costs one probe per reset window
    instead of one connect timeout per call in rotation. When EVERY
    server is quarantined the proxy degrades gracefully and serves the
    head of the list anyway — refusing outright would turn a transient
    full outage into a permanent client-side one."""

    BREAKER_FAILURES = 3
    BREAKER_RESET = 10.0

    def __init__(self, servers: Optional[List[str]] = None):
        self._lock = threading.Lock()
        self._servers: List[str] = list(servers or [])
        self._breakers: Dict[str, CircuitBreaker] = {}

    def _breaker(self, addr: str) -> CircuitBreaker:
        """Caller holds the lock."""
        b = self._breakers.get(addr)
        if b is None:
            b = self._breakers[addr] = CircuitBreaker(
                failure_threshold=self.BREAKER_FAILURES,
                reset_timeout=self.BREAKER_RESET)
        return b

    def servers(self) -> List[str]:
        with self._lock:
            return list(self._servers)

    def find_server(self) -> Optional[str]:
        with self._lock:
            for addr in self._servers:
                if self._breaker(addr).allow():
                    return addr
            # All quarantined: degrade to round-robin rather than failing.
            return self._servers[0] if self._servers else None

    def quarantined(self) -> List[str]:
        """Servers currently held out by an open breaker (introspection)."""
        with self._lock:
            return [a for a in self._servers
                    if self._breakers.get(a) is not None
                    and self._breakers[a].state == CircuitBreaker.OPEN]

    def notify_failed(self, addr: str) -> None:
        """Rotate the failed server to the back and feed its breaker
        (reference: rpcproxy.go:355-377)."""
        with self._lock:
            if addr in self._servers:
                self._servers.remove(addr)
                self._servers.append(addr)
            self._breaker(addr).record_failure()

    def notify_success(self, addr: str) -> None:
        with self._lock:
            self._breaker(addr).record_success()

    def update(self, servers: List[str]) -> None:
        """Replace the primary list (from heartbeat NodeServerInfo,
        reference: client.go:720+ / rpcproxy.go RefreshServerLists)."""
        with self._lock:
            keep = [s for s in self._servers if s in servers]
            new = [s for s in servers if s not in keep]
            self._servers = keep + new
            for gone in set(self._breakers) - set(self._servers):
                del self._breakers[gone]

    def rebalance(self, ping: "Callable[[str], bool]") -> Optional[str]:
        """Shuffle the list and promote the first server that answers a
        ping — spreads client load across servers and skips dead ones
        (reference: rpcproxy.go:317-449 RebalanceServers: shuffle, then
        ping-test the selected server before committing the new order)."""
        import random as _random

        with self._lock:
            shuffled = list(self._servers)
        if len(shuffled) <= 1:
            return shuffled[0] if shuffled else None
        _random.shuffle(shuffled)
        for i, addr in enumerate(shuffled):
            if not ping(addr):
                # A failed rebalance ping is breaker evidence like any
                # other failed call.
                with self._lock:
                    self._breaker(addr).record_failure()
                continue
            order = shuffled[i:] + shuffled[:i]
            with self._lock:
                # A ping IS a health probe: close the breaker so
                # find_server doesn't keep skipping the server we just
                # proved alive.
                self._breaker(addr).record_success()
                # Re-intersect with the live list: update() may have
                # added/removed servers during the unlocked ping window,
                # and a removed server must stay removed.
                order = [s for s in order if s in self._servers]
                if not order or order[0] != addr:
                    # The pinged server itself was removed: don't promote
                    # a server whose health was never tested.
                    return None
                extra = [s for s in self._servers if s not in order]
                self._servers = order + extra
                return addr
        return None


class _TerminalRemoteError(Exception):
    """Internal wrapper: a remote handler error that must NOT be retried
    or failed over, carried out of the retry policy and unwrapped."""

    def __init__(self, inner: Exception):
        super().__init__(str(inner))
        self.inner = inner


class NetServerChannel:
    """ServerChannel over the wire: msgpack-RPC through a ConnPool with
    rpcproxy failover (reference: the client's RPC path, client.go:332 +
    rpcproxy). Works against any server — followers forward writes to the
    leader server-side. Server membership is learned from register/heartbeat
    responses (reference: NodeServerInfo, node_endpoint.go:194+)."""

    # Ride out a leader election before surfacing NotLeaderError
    # (reference: rpc.go ErrNoLeader retry with jitter).
    NO_LEADER_RETRIES = 10
    NO_LEADER_BACKOFF = 0.25

    # Periodic ping-based rebalance cadence (reference: rpcproxy.go
    # clusterInfo-scaled rebalance timer; a small fixed default here).
    REBALANCE_INTERVAL = 120.0

    def __init__(self, servers: List[str],
                 rebalance_interval: Optional[float] = None,
                 tls_context=None):  # noqa: D401
        from nomad_tpu.rpc import ConnPool

        self.pool = ConnPool(tls_context=tls_context)
        self.proxy = RpcProxy(servers)
        self._stop_rebalance = threading.Event()
        interval = (self.REBALANCE_INTERVAL if rebalance_interval is None
                    else rebalance_interval)
        if interval > 0:
            threading.Thread(target=self._rebalance_loop, args=(interval,),
                             daemon=True, name="rpcproxy-rebalance").start()

    def close(self) -> None:
        self._stop_rebalance.set()
        try:
            self.pool.close()
        # lint: allow(swallow, best-effort socket close on teardown)
        except Exception:
            pass

    def _ping(self, addr: str) -> bool:
        try:
            return bool(self.pool.call(addr, "Status.Ping", {}, timeout=3.0))
        # lint: allow(swallow, a failed ping IS the False result)
        except Exception:
            return False

    def _rebalance_loop(self, interval: float) -> None:
        while not self._stop_rebalance.wait(interval):
            try:
                self.proxy.rebalance(self._ping)
            except Exception as exc:
                logger.debug("server rebalance pass failed: %s", exc)

    def _call(self, method: str, body: dict, timeout: Optional[float] = None):
        # Child-only span: a traced client operation (e.g. the service
        # sync root) sees its wire call — with failovers and NotLeader
        # retries as events — and the pool injects the carrier into the
        # envelope so the server side joins the same trace.
        with trace.span("client.rpc." + method):
            return self._call_traced(method, body, timeout)

    def _call_traced(self, method: str, body: dict,
                     timeout: Optional[float] = None):
        from nomad_tpu.rpc.pool import RPCError

        def one_round():
            """Walk the server list once: transport failures fail over to
            the next server (feeding its breaker); a NotLeaderError
            raises out for the policy to back off on."""
            last_exc: Optional[Exception] = None
            for _ in range(max(1, len(self.proxy.servers()))):
                addr = self.proxy.find_server()
                if addr is None:
                    raise ConnectionError("no known servers")
                try:
                    out = self.pool.call(addr, method, body, timeout=timeout)
                except RPCError as exc:
                    # The server ANSWERED: transport-wise it is healthy,
                    # and a half-open probe must not leak _probing=True
                    # (which would quarantine a live server forever).
                    self.proxy.notify_success(addr)
                    if exc.remote_type == "NotLeaderError":
                        raise  # election window: policy backs off + retries
                    raise _TerminalRemoteError(exc)  # failover won't help
                # lint: allow(swallow, failure marks the server and fails over)
                except Exception as exc:  # transport: try the next server
                    last_exc = exc
                    self.proxy.notify_failed(addr)
                    continue
                self.proxy.notify_success(addr)
                return out
            raise last_exc  # type: ignore[misc]  # all servers down

        # Ride out a leader election (reference: rpc.go ErrNoLeader retry
        # with jitter); everything else surfaces after one round.
        policy = RetryPolicy(
            max_attempts=self.NO_LEADER_RETRIES,
            backoff=Backoff(base=self.NO_LEADER_BACKOFF,
                            cap=4 * self.NO_LEADER_BACKOFF),
            retry_on=(RPCError,),
            should_retry=lambda e: getattr(e, "remote_type", "")
            == "NotLeaderError")
        try:
            return policy.call(one_round)
        except _TerminalRemoteError as wrapped:
            raise wrapped.inner

    def _absorb_server_info(self, resp: Dict) -> None:
        servers = resp.get("Servers") or []
        if servers:
            self.proxy.update(servers)

    # ----------------------------------------------------- ServerChannel
    def register_node(self, node: Node) -> float:
        resp = self._call("Node.Register", {"Node": to_dict(node)})
        self._absorb_server_info(resp)
        return resp["HeartbeatTTL"]

    def heartbeat(self, node_id: str) -> float:
        resp = self._call("Node.Heartbeat", {"NodeID": node_id})
        self._absorb_server_info(resp)
        return resp["HeartbeatTTL"]

    def update_node_status(self, node_id: str, status: str) -> float:
        resp = self._call("Node.UpdateStatus",
                          {"NodeID": node_id, "Status": status})
        self._absorb_server_info(resp)
        return resp["HeartbeatTTL"]

    def get_client_allocs(self, node_id: str, min_index: int,
                          max_wait: float) -> Tuple[Dict[str, int], int]:
        # AllowStale: the min-index protocol already tolerates replica
        # lag, and stale watches let any server carry the long-poll load
        # instead of funnelling every client onto the leader (reference:
        # watchAllocations sets AllowStale, client.go:1010).
        resp = self._call("Node.GetClientAllocs",
                          {"NodeID": node_id, "MinQueryIndex": min_index,
                           "MaxQueryTime": max_wait, "AllowStale": True},
                          # Margin covers the server's wait/16 herd jitter
                          # on top of the grace (rpc/endpoints.py).
                          timeout=max_wait * 17.0 / 16.0 + 10.0)
        return resp["Allocs"], resp["Index"]

    def get_allocs(self, alloc_ids: List[str]) -> List[Allocation]:
        resp = self._call("Alloc.GetAllocs", {"AllocIDs": alloc_ids,
                                              "AllowStale": True})
        return [from_dict(Allocation, a) for a in resp["Allocs"]]

    def update_allocs(self, allocs: List[Allocation]) -> None:
        self._call("Node.UpdateAlloc",
                   {"Allocs": [to_dict(a) for a in allocs]})

    def sync_services(self, upserts: List, deletes: List[str]) -> None:
        self._call("Service.Sync",
                   {"Upserts": [to_dict(r) for r in upserts],
                    "Deletes": list(deletes)})
