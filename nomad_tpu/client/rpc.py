"""Client <-> server channel (reference: the msgpack RPC surface the client
uses — Node.Register, Node.UpdateStatus, Node.GetClientAllocs with blocking,
Alloc.GetAllocs, Node.UpdateAlloc; nomad/rpc.go + client/rpcproxy/).

The dev-mode/in-process implementation calls the Server directly and uses
state-store watches for blocking queries; a wire implementation (msgpack over
TCP) plugs in behind the same interface.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Protocol, Tuple

from nomad_tpu.state.watch import Item
from nomad_tpu.structs import Allocation, Node


class ServerChannel(Protocol):
    def register_node(self, node: Node) -> float: ...
    def heartbeat(self, node_id: str) -> float: ...
    def update_node_status(self, node_id: str, status: str) -> float: ...
    def get_client_allocs(self, node_id: str, min_index: int,
                          max_wait: float) -> Tuple[Dict[str, int], int]: ...
    def get_allocs(self, alloc_ids: List[str]) -> List[Allocation]: ...
    def update_allocs(self, allocs: List[Allocation]) -> None: ...


class InProcServerChannel:
    """Direct in-process channel to a Server (dev mode, reference: the
    agent's server-embedded RPC shortcut, command/agent/agent.go:597)."""

    def __init__(self, server):
        self.server = server

    def register_node(self, node: Node) -> float:
        ttl, _ = self.server.node_register(node)
        return ttl

    def heartbeat(self, node_id: str) -> float:
        return self.server.node_heartbeat(node_id)

    def update_node_status(self, node_id: str, status: str) -> float:
        ttl, _ = self.server.node_update_status(node_id, status)
        return ttl

    def get_client_allocs(self, node_id: str, min_index: int,
                          max_wait: float) -> Tuple[Dict[str, int], int]:
        """Blocking query: alloc_id -> AllocModifyIndex for the node
        (reference: node_endpoint.go:474-528 GetClientAllocs)."""
        state = self.server.state
        event = threading.Event()
        items = [Item(alloc_node=node_id)]
        state.watch(items, event)
        try:
            while True:
                allocs = state.allocs_by_node(node_id)
                index = max((a.AllocModifyIndex for a in allocs),
                            default=state.get_index("allocs"))
                if index > min_index or max_wait <= 0:
                    return ({a.ID: a.AllocModifyIndex for a in allocs}, index)
                event.clear()
                if not event.wait(max_wait):
                    return ({a.ID: a.AllocModifyIndex for a in allocs}, index)
        finally:
            state.stop_watch(items, event)

    def get_allocs(self, alloc_ids: List[str]) -> List[Allocation]:
        out = []
        for aid in alloc_ids:
            alloc = self.server.state.alloc_by_id(aid)
            if alloc is not None:
                out.append(alloc)
        return out

    def update_allocs(self, allocs: List[Allocation]) -> None:
        self.server.node_update_allocs(allocs)
