"""Allocation directory layout (reference: client/allocdir/alloc_dir.go).

  <alloc_dir>/<alloc_id>/
    alloc/            shared between the alloc's tasks
      logs/ tmp/ data/
    <task>/
      local/          task-private scratch

Also provides the list/stat/read primitives behind the fs API
(reference: AllocDirFS, client/allocdir/alloc_dir.go:303-360) and, when
running as root on Linux, chroot population for the exec driver via
read-only bind mounts of the host system dirs (reference:
alloc_dir_linux.go Embed/MountSpecialDirs).
"""

from __future__ import annotations

import logging
import os
import shutil
import stat as statmod
import subprocess
from dataclasses import dataclass
from typing import Dict, List

logger = logging.getLogger("nomad.allocdir")

SHARED_ALLOC_NAME = "alloc"
SHARED_DIRS = ("logs", "tmp", "data")
TASK_LOCAL = "local"

# Host dirs bind-mounted into an exec task's chroot (reference default:
# chrootEnv in client/config, applied by alloc_dir_linux.go Embed). Missing
# sources are skipped.
DEFAULT_CHROOT_ENV = ("/bin", "/etc", "/lib", "/lib32", "/lib64",
                      "/run/resolvconf", "/sbin", "/usr")


@dataclass
class FileInfo:
    Name: str = ""
    IsDir: bool = False
    Size: int = 0
    FileMode: str = ""
    ModTime: float = 0.0


class AllocDir:
    def __init__(self, root: str):
        self.alloc_dir = root
        self.shared_dir = os.path.join(root, SHARED_ALLOC_NAME)
        self.task_dirs: Dict[str, str] = {}
        # Active bind mounts inside task chroots, in mount order. MUST be
        # unmounted before any rmtree: deleting through a live bind mount
        # of /bin would destroy the host's.
        self._mounts: List[str] = []
        self._chroots: set = set()  # tasks whose chroot is already built

    def build(self, tasks: List[str]) -> None:
        os.makedirs(self.alloc_dir, exist_ok=True)
        os.makedirs(self.shared_dir, exist_ok=True)
        for sub in SHARED_DIRS:
            os.makedirs(os.path.join(self.shared_dir, sub), exist_ok=True)
        for task in tasks:
            tdir = os.path.join(self.alloc_dir, task)
            os.makedirs(os.path.join(tdir, TASK_LOCAL), exist_ok=True)
            self.task_dirs[task] = tdir

    def log_dir(self) -> str:
        return os.path.join(self.shared_dir, "logs")

    # ------------------------------------------------------------- chroot
    def build_chroot(self, task: str, chroot_env=DEFAULT_CHROOT_ENV) -> str:
        """Populate the task dir as a chroot: read-only bind mounts of the
        host system dirs plus /dev and /proc (reference:
        alloc_dir_linux.go Embed + MountSpecialDirs). Requires root; the
        task dir itself becomes the chroot root, so the task sees its
        `local/` and the shared `alloc/` at /local and /alloc. Returns the
        chroot path. Raises on mount failure (half-built mounts are torn
        down). Idempotent per task: a restarting task reuses its existing
        chroot instead of stacking a second set of mounts."""
        root = self.task_dirs[task]
        if task in self._chroots:
            return root
        # Roll back only THIS task's mounts on failure: a sibling task of
        # the same alloc may be running out of its own chroot.
        before = len(self._mounts)
        try:
            for src in chroot_env:
                if not os.path.isdir(src):
                    continue
                dest = os.path.join(root, src.lstrip("/"))
                os.makedirs(dest, exist_ok=True)
                self._bind(src, dest, readonly=True)
            # Special dirs: devices and /proc (MountSpecialDirs).
            dev = os.path.join(root, "dev")
            os.makedirs(dev, exist_ok=True)
            self._bind("/dev", dev, readonly=False)
            proc = os.path.join(root, "proc")
            os.makedirs(proc, exist_ok=True)
            subprocess.run(["mount", "-t", "proc", "proc", proc],
                           check=True, capture_output=True)
            self._mounts.append(proc)
            # The shared alloc dir appears at /alloc inside the chroot.
            shared = os.path.join(root, SHARED_ALLOC_NAME)
            os.makedirs(shared, exist_ok=True)
            self._bind(self.shared_dir, shared, readonly=False)
        except Exception:
            mine, self._mounts = self._mounts[before:], self._mounts[:before]
            self._unmount(mine)
            raise
        self._chroots.add(task)
        return root

    def _bind(self, src: str, dest: str, readonly: bool) -> None:
        subprocess.run(["mount", "--bind", src, dest],
                       check=True, capture_output=True)
        self._mounts.append(dest)
        if readonly:
            # A silent failure here would leave host /bin//etc//usr
            # WRITABLE inside the chroot — fail the task start instead.
            r = subprocess.run(
                ["mount", "-o", "remount,ro,bind", dest],
                capture_output=True, text=True)
            if r.returncode != 0:
                raise RuntimeError(
                    f"read-only remount of {dest} failed: {r.stderr}")

    @staticmethod
    def _live_mounts() -> set:
        """Mount points from /proc/self/mountinfo. os.path.ismount is blind
        to bind mounts on the same filesystem (equal st_dev), which is
        exactly what /bin-into-allocdir binds are — the rmtree safety check
        must use the kernel's own table."""
        points = set()
        try:
            with open("/proc/self/mountinfo") as f:
                for line in f:
                    fields = line.split()
                    if len(fields) >= 5:
                        # Field 5 is the mount point, octal-escaped.
                        points.add(
                            fields[4].encode().decode("unicode_escape"))
        except OSError:
            pass
        return points

    @staticmethod
    def _unmount(dests) -> None:
        for dest in reversed(list(dests)):
            r = subprocess.run(["umount", dest], capture_output=True)
            if r.returncode != 0:
                # Busy mount: detach lazily; callers re-verify via
                # /proc/self/mountinfo.
                subprocess.run(["umount", "-l", dest], capture_output=True)

    def unmount_all(self) -> bool:
        """Tear down chroot mounts in reverse order — the tracked list PLUS
        anything /proc/self/mountinfo shows under the alloc dir. The kernel
        table is authoritative: after an agent restart the in-memory list
        is empty but the previous process's chroot mounts are still live,
        and destroy()'s rmtree through a live /dev or /bin bind would
        delete host files. Returns True when nothing remains mounted under
        the alloc dir."""
        root = os.path.realpath(self.alloc_dir)

        def under_alloc(points) -> List[str]:
            # Deepest-first so nested mounts unwind in order.
            return sorted(
                (p for p in points
                 if p == root or p.startswith(root + os.sep)),
                key=len, reverse=True)

        self._unmount(self._mounts)
        untracked = under_alloc(self._live_mounts())
        if untracked:
            logger.info("unmounting %d untracked chroot mounts under %s "
                        "(previous agent run)", len(untracked), root)
            self._unmount(untracked)
        remaining = under_alloc(self._live_mounts())
        for dest in remaining:
            logger.error("chroot mount still active: %s", dest)
        self._mounts = remaining
        if not remaining:
            self._chroots.clear()
        return not remaining

    def destroy(self) -> None:
        # Refuse to delete while any bind mount is live: an rmtree through
        # a mounted /bin or /usr would delete the HOST's files.
        if not self.unmount_all():
            logger.error("alloc dir %s NOT removed: chroot mounts could "
                         "not be unmounted", self.alloc_dir)
            return
        shutil.rmtree(self.alloc_dir, ignore_errors=True)

    # ------------------------------------------------------------ fs API
    def _resolve(self, path: str) -> str:
        """Resolve a relative path, refusing escapes from the alloc dir."""
        root = os.path.normpath(self.alloc_dir)
        full = os.path.normpath(os.path.join(root, path.lstrip("/")))
        # Separator-anchored check: a sibling like <root>-evil must not pass.
        if full != root and not full.startswith(root + os.sep):
            raise PermissionError(f"path escapes alloc dir: {path}")
        return full

    def list_dir(self, path: str) -> List[FileInfo]:
        full = self._resolve(path)
        out = []
        for name in sorted(os.listdir(full)):
            st = os.stat(os.path.join(full, name))
            out.append(FileInfo(
                Name=name, IsDir=statmod.S_ISDIR(st.st_mode),
                Size=st.st_size, FileMode=statmod.filemode(st.st_mode),
                ModTime=st.st_mtime))
        return out

    def stat(self, path: str) -> FileInfo:
        full = self._resolve(path)
        st = os.stat(full)
        return FileInfo(
            Name=os.path.basename(full), IsDir=statmod.S_ISDIR(st.st_mode),
            Size=st.st_size, FileMode=statmod.filemode(st.st_mode),
            ModTime=st.st_mtime)

    def read_at(self, path: str, offset: int = 0, limit: int = -1) -> bytes:
        full = self._resolve(path)
        with open(full, "rb") as f:
            f.seek(offset)
            return f.read(limit if limit >= 0 else -1)
