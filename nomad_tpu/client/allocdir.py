"""Allocation directory layout (reference: client/allocdir/alloc_dir.go).

  <alloc_dir>/<alloc_id>/
    alloc/            shared between the alloc's tasks
      logs/ tmp/ data/
    <task>/
      local/          task-private scratch

Also provides the list/stat/read primitives behind the fs API
(reference: AllocDirFS, client/allocdir/alloc_dir.go:303-360).
"""

from __future__ import annotations

import os
import shutil
import stat as statmod
from dataclasses import dataclass
from typing import Dict, List

SHARED_ALLOC_NAME = "alloc"
SHARED_DIRS = ("logs", "tmp", "data")
TASK_LOCAL = "local"


@dataclass
class FileInfo:
    Name: str = ""
    IsDir: bool = False
    Size: int = 0
    FileMode: str = ""
    ModTime: float = 0.0


class AllocDir:
    def __init__(self, root: str):
        self.alloc_dir = root
        self.shared_dir = os.path.join(root, SHARED_ALLOC_NAME)
        self.task_dirs: Dict[str, str] = {}

    def build(self, tasks: List[str]) -> None:
        os.makedirs(self.alloc_dir, exist_ok=True)
        os.makedirs(self.shared_dir, exist_ok=True)
        for sub in SHARED_DIRS:
            os.makedirs(os.path.join(self.shared_dir, sub), exist_ok=True)
        for task in tasks:
            tdir = os.path.join(self.alloc_dir, task)
            os.makedirs(os.path.join(tdir, TASK_LOCAL), exist_ok=True)
            self.task_dirs[task] = tdir

    def log_dir(self) -> str:
        return os.path.join(self.shared_dir, "logs")

    def destroy(self) -> None:
        shutil.rmtree(self.alloc_dir, ignore_errors=True)

    # ------------------------------------------------------------ fs API
    def _resolve(self, path: str) -> str:
        """Resolve a relative path, refusing escapes from the alloc dir."""
        root = os.path.normpath(self.alloc_dir)
        full = os.path.normpath(os.path.join(root, path.lstrip("/")))
        # Separator-anchored check: a sibling like <root>-evil must not pass.
        if full != root and not full.startswith(root + os.sep):
            raise PermissionError(f"path escapes alloc dir: {path}")
        return full

    def list_dir(self, path: str) -> List[FileInfo]:
        full = self._resolve(path)
        out = []
        for name in sorted(os.listdir(full)):
            st = os.stat(os.path.join(full, name))
            out.append(FileInfo(
                Name=name, IsDir=statmod.S_ISDIR(st.st_mode),
                Size=st.st_size, FileMode=statmod.filemode(st.st_mode),
                ModTime=st.st_mtime))
        return out

    def stat(self, path: str) -> FileInfo:
        full = self._resolve(path)
        st = os.stat(full)
        return FileInfo(
            Name=os.path.basename(full), IsDir=statmod.S_ISDIR(st.st_mode),
            Size=st.st_size, FileMode=statmod.filemode(st.st_mode),
            ModTime=st.st_mtime)

    def read_at(self, path: str, offset: int = 0, limit: int = -1) -> bytes:
        full = self._resolve(path)
        with open(full, "rb") as f:
            f.seek(offset)
            return f.read(limit if limit >= 0 else -1)
