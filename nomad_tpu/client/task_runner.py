"""TaskRunner: per-task lifecycle FSM (reference: client/task_runner.go).

validate -> download artifacts -> driver start -> wait loop (exit / update /
destroy) -> restart policy with backoff. Persists the driver handle ID so an
agent restart re-attaches to the live executor process.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

from nomad_tpu.structs import Allocation, Task, TaskEvent, TaskState
from nomad_tpu.telemetry import trace
from nomad_tpu.structs.structs import (
    TaskArtifactDownloadFailed,
    TaskDriverFailure,
    TaskKilled,
    TaskNotRestarting,
    TaskReceived,
    TaskRestarting,
    TaskStarted,
    TaskStateDead,
    TaskStatePending,
    TaskStateRunning,
    TaskTerminated,
    ns_to_seconds,
)

from .driver import DriverContext, ExecContext, new_driver
from .driver.base import WaitResult
from .env import TaskEnv
from .getter import get_artifact
from .restarts import NO_RESTART, RestartTracker

logger = logging.getLogger("nomad.task_runner")


class TaskRunner:
    def __init__(self, client_config, alloc: Allocation, task: Task,
                 exec_ctx: ExecContext, node,
                 on_state_change: Callable[[str, str, Optional[TaskEvent]], None],
                 restart_tracker: RestartTracker):
        self.config = client_config
        self.alloc = alloc
        self.task = task
        self.exec_ctx = exec_ctx
        self.node = node
        self.on_state_change = on_state_change
        self.restart_tracker = restart_tracker

        self.handle = None
        self.handle_id: str = ""
        self._launch_span = None
        self._destroy = threading.Event()
        self._restart = threading.Event()
        self._restart_reason = ""
        self._update_lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- lifecycle
    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.run, daemon=True,
            name=f"task-{self.alloc.ID[:8]}-{self.task.Name}")
        self._thread.start()

    def destroy(self) -> None:
        self._destroy.set()

    def trigger_restart(self, reason: str) -> None:
        """Kill the task and let the restart policy decide what happens next
        (driven by failing health checks, services/manager.py)."""
        self._restart_reason = reason
        self._restart.set()

    def restore(self, handle_id: str) -> bool:
        """Re-attach to a live executor (reference: task_runner.go:141-191)."""
        try:
            driver = new_driver(self.task.Driver, self._driver_ctx())
            self.handle = driver.open(self.exec_ctx, handle_id)
            self.handle_id = handle_id
            return True
        except Exception:
            logger.exception("task %s: failed to restore handle", self.task.Name)
            return False

    def _finish_launch_span(self, error: Optional[str] = None,
                            reattached: bool = False) -> None:
        span = self._launch_span
        if span is None:
            return
        self._launch_span = None
        if reattached:
            span.set_attr("reattached", True)
        span.finish(error=error)

    def _driver_ctx(self) -> DriverContext:
        return DriverContext(task_name=self.task.Name, config=self.config,
                             node=self.node)

    def _set_state(self, state: str, event: Optional[TaskEvent]) -> None:
        self.on_state_change(self.task.Name, state, event)

    # --------------------------------------------------------------- run loop
    def run(self) -> None:
        """(reference: task_runner.go:252-457)"""
        self._set_state(TaskStatePending, TaskEvent.new(TaskReceived))

        # Trace the LAUNCH leg only (receive -> first running/dead), not
        # the task's whole lifetime: the span joins the placing eval's
        # trace through the alloc link the AllocRunner registered.
        self._launch_span = trace.start_from(
            trace.linked("alloc", self.alloc.ID), "client.task_start",
            alloc=self.alloc.ID, task=self.task.Name)

        if self.handle is None:
            if not self._prepare():
                self._finish_launch_span(error="validation/artifacts")
                return
        else:
            # Reattached to a live executor after agent restart: report
            # running so downstream consumers (service registration, alloc
            # status) see the task alive again.
            event = TaskEvent.new(TaskStarted)
            event.Message = "reattached to running task"
            self._set_state(TaskStateRunning, event)
            self._finish_launch_span(reattached=True)

        while not self._destroy.is_set():
            if self.handle is None:
                started = self._start_task()
                self._finish_launch_span(
                    error=None if started else "driver start failed")
                if not started:
                    return

            result = self._wait_for_exit()
            if result is None:  # destroyed
                self._kill_task()
                return

            event = TaskEvent.new(TaskTerminated)
            event.ExitCode = result.exit_code
            event.Signal = result.signal
            event.Message = result.error
            self.handle = None

            decision, wait = self.restart_tracker.next_restart(result.exit_code)
            if decision == NO_RESTART:
                self._set_state(TaskStateDead, event)
                return
            self._set_state(TaskStatePending, event)
            restart_event = TaskEvent.new(TaskRestarting)
            restart_event.StartDelay = int(wait * 1e9)
            self._set_state(TaskStatePending, restart_event)
            if self._destroy.wait(wait):
                self._set_state(TaskStateDead, TaskEvent.new(TaskKilled))
                return

    def _prepare(self) -> bool:
        """Validate + fetch artifacts."""
        errs = self.task.validate()
        # Driver config schema: reject typo'd/unknown keys BEFORE any
        # artifact download or driver start (reference: TaskRunner
        # validateTask -> driver.Validate, client/task_runner.go:143-169).
        try:
            new_driver(self.task.Driver,
                       self._driver_ctx()).validate(self.task.Config or {})
        except ValueError as e:
            errs = list(errs) + [str(e)]
        if errs:
            event = TaskEvent.new("Failed Validation")
            event.ValidationError = "; ".join(errs)
            self._set_state(TaskStateDead, event)
            return False
        if self.task.Artifacts:
            self._set_state(TaskStatePending,
                            TaskEvent.new("Downloading Artifacts"))
            task_dir = self.exec_ctx.alloc_dir.task_dirs[self.task.Name]
            for artifact in self.task.Artifacts:
                try:
                    get_artifact(artifact, task_dir, self.exec_ctx.task_env)
                # lint: allow(swallow, error is recorded on the task event)
                except Exception as e:
                    event = TaskEvent.new(TaskArtifactDownloadFailed)
                    event.DownloadError = str(e)
                    self._set_state(TaskStateDead, event)
                    return False
        return True

    def _start_task(self) -> bool:
        while True:
            try:
                driver = new_driver(self.task.Driver, self._driver_ctx())
                self.handle = driver.start(self.exec_ctx, self.task)
                self.handle_id = self.handle.id()
            # lint: allow(swallow, error is recorded on the task event)
            except Exception as e:
                event = TaskEvent.new(TaskDriverFailure)
                event.DriverError = str(e)
                decision, wait = self.restart_tracker.next_restart(-1)
                if decision == NO_RESTART:
                    self._set_state(TaskStateDead, event)
                    return False
                self._set_state(TaskStatePending, event)
                if self._destroy.wait(wait):
                    return False
                continue
            # A restart signaled against the PREVIOUS incarnation (e.g. its
            # health check went critical as it exited) must not kill the
            # fresh process.
            self._restart.clear()
            self._set_state(TaskStateRunning, TaskEvent.new(TaskStarted))
            return True

    def _wait_for_exit(self) -> Optional[WaitResult]:
        while not self._destroy.is_set():
            if self._restart.is_set():
                self._restart.clear()
                reason = self._restart_reason or "restart signaled"
                timeout = ns_to_seconds(self.task.KillTimeout)
                self.handle.kill(kill_timeout=timeout)
                result = self.handle.wait(timeout=timeout + 5.0)
                if result is None:
                    result = WaitResult(exit_code=-1, error=reason)
                else:
                    result.error = result.error or reason
                    if result.successful():
                        # Restart-by-check is a failure for policy purposes.
                        result.exit_code = 1
                return result
            result = self.handle.wait(timeout=0.2)
            if result is not None:
                return result
        return None

    def _kill_task(self) -> None:
        if self.handle is not None:
            timeout = ns_to_seconds(self.task.KillTimeout)
            self.handle.kill(kill_timeout=timeout)
            self.handle = None
        self._set_state(TaskStateDead, TaskEvent.new(TaskKilled))
