"""Task drivers (reference: client/driver/).

A Driver validates config, fingerprints its availability onto the node
(`driver.<name>` attribute), starts tasks, and re-opens handles after agent
restart. Built-ins: raw_exec, exec (cgroup/chroot isolation), java, qemu,
docker, and mock_driver for tests.

Deliberate exclusion: the reference's rkt driver (client/driver/rkt.go) is
not reproduced. The rkt project was archived in 2020 and its container
images/CLI are unavailable on modern systems; its use cases are covered by
the docker and exec drivers. The Driver interface is the extension seam if
an equivalent is ever needed.
"""

from .base import Driver, DriverContext, DriverHandle, ExecContext, WaitResult  # noqa: F401
from .raw_exec import RawExecDriver
from .exec_driver import ExecDriver
from .java import JavaDriver
from .qemu import QemuDriver
from .docker import DockerDriver
from .mock_driver import MockDriver

BUILTIN_DRIVERS = {
    "raw_exec": RawExecDriver,
    "exec": ExecDriver,
    "java": JavaDriver,
    "qemu": QemuDriver,
    "docker": DockerDriver,
    "mock_driver": MockDriver,
}


def new_driver(name: str, ctx: DriverContext) -> Driver:
    cls = BUILTIN_DRIVERS.get(name)
    if cls is None:
        raise ValueError(f"unknown driver '{name}'")
    return cls(ctx)


def job_config_warnings(job) -> list:
    """Submitter-visible warnings for a job spec: driver config keys that
    validate (reference compatibility) but are ignored at runtime —
    e.g. docker's `privileged`/`pid_mode`/`dns_servers`. Returned from
    Job.Register / surfaced by `nomad-tpu run` and `validate`, because a
    once-per-process client log line never reaches whoever wrote the job
    and the container would silently run with materially different
    isolation than the reference."""
    warnings = []
    for tg in job.TaskGroups or ():
        for task in tg.Tasks or ():
            schema = getattr(BUILTIN_DRIVERS.get(task.Driver), "schema",
                             None)
            if schema is None:
                continue
            for key in schema.ignored_keys(task.Config or {}):
                warnings.append(
                    f"task {task.Name!r} ({task.Driver}): config key "
                    f"{key!r} is accepted for reference compatibility "
                    f"but not implemented; it will be ignored at runtime")
    return warnings
