"""raw_exec driver: no-isolation process runner (reference:
client/driver/raw_exec.go).

Gated behind the client option `driver.raw_exec.enable` exactly like the
reference (raw_exec.go:40-56) because it runs tasks with the agent's own
privileges.
"""

from __future__ import annotations

import os
from typing import Any, Dict

from nomad_tpu.structs import Node, Task

from .base import (ConfigField, ConfigSchema, Driver, DriverHandle,
                   ExecContext, ExecutorHandle, build_executor_spec,
                   launch_executor)


class RawExecDriver(Driver):
    name = "raw_exec"

    def fingerprint(self, config, node: Node) -> bool:
        enabled = False
        if config is not None:
            enabled = str(config.read_option(
                "driver.raw_exec.enable", "false")).lower() in ("1", "true")
        if enabled:
            node.Attributes["driver.raw_exec"] = "1"
            return True
        node.Attributes.pop("driver.raw_exec", None)
        return False

    # (reference: client/driver/raw_exec.go Validate's fields map)
    schema = ConfigSchema(
        command=ConfigField("string", required=True),
        args=ConfigField("list"),
    )

    def start(self, ctx: ExecContext, task: Task) -> DriverHandle:
        self.validate(task.Config)
        spec = build_executor_spec(ctx, task, task.Config["command"],
                                   task.Config.get("args", []))
        return launch_executor(ctx.alloc_dir.task_dirs[task.Name],
                               task.Name, spec)

    def open(self, ctx: ExecContext, handle_id: str) -> DriverHandle:
        return ExecutorHandle.from_id(handle_id)
