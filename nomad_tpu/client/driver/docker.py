"""docker driver: container lifecycle via the docker CLI (reference:
client/driver/docker.go speaks the daemon API; the CLI carries the same
operations without a vendored daemon client).
"""

from __future__ import annotations

import json
import shutil
import subprocess
import threading
import time
from typing import Any, Dict, Optional

from nomad_tpu.structs import Node, Task

from .base import Driver, DriverHandle, ExecContext, WaitResult


class DockerHandle(DriverHandle):
    def __init__(self, container_id: str):
        self.container_id = container_id
        self._result: Optional[WaitResult] = None
        self._done = threading.Event()
        self._watcher = threading.Thread(target=self._watch, daemon=True)
        self._watcher.start()

    def id(self) -> str:
        return json.dumps({"container_id": self.container_id})

    @staticmethod
    def from_id(handle_id: str) -> "DockerHandle":
        return DockerHandle(json.loads(handle_id)["container_id"])

    def _watch(self) -> None:
        try:
            out = subprocess.run(["docker", "wait", self.container_id],
                                 capture_output=True, text=True)
            code = int(out.stdout.strip() or 0)
            self._result = WaitResult(exit_code=code)
        except Exception as e:
            self._result = WaitResult(error=str(e))
        self._done.set()

    def wait(self, timeout: Optional[float] = None) -> Optional[WaitResult]:
        if not self._done.wait(timeout):
            return None
        return self._result

    def kill(self, kill_timeout: float = 5.0) -> None:
        subprocess.run(["docker", "stop", "-t", str(int(kill_timeout)),
                        self.container_id], capture_output=True)


class DockerDriver(Driver):
    name = "docker"

    def fingerprint(self, config, node: Node) -> bool:
        if shutil.which("docker") is None:
            node.Attributes.pop("driver.docker", None)
            return False
        try:
            out = subprocess.run(["docker", "version", "--format",
                                  "{{.Server.Version}}"],
                                 capture_output=True, text=True, timeout=10)
            if out.returncode != 0:
                node.Attributes.pop("driver.docker", None)
                return False
            node.Attributes["driver.docker"] = "1"
            node.Attributes["driver.docker.version"] = out.stdout.strip()
            return True
        except Exception:
            return False

    def validate(self, config: Dict[str, Any]) -> None:
        if not config.get("image"):
            raise ValueError("missing image for docker driver")

    def start(self, ctx: ExecContext, task: Task) -> DriverHandle:
        self.validate(task.Config)
        env = ctx.task_env
        image = env.replace(str(task.Config["image"]))
        task_dir = ctx.alloc_dir.task_dirs[task.Name]
        cmd = ["docker", "run", "-d",
               "-v", f"{ctx.alloc_dir.shared_dir}:/alloc",
               "-v", f"{task_dir}/local:/local"]
        if task.Resources is not None:
            cmd.extend(["--memory", f"{task.Resources.MemoryMB}m",
                        "--cpu-shares", str(task.Resources.CPU)])
            for net in task.Resources.Networks:
                for label, value in net.port_labels().items():
                    guest = task.Config.get("port_map", {}).get(label, value)
                    cmd.extend(["-p", f"{value}:{guest}"])
        for k, v in env.build_env().items():
            cmd.extend(["-e", f"{k}={v}"])
        cmd.append(image)
        if task.Config.get("command"):
            cmd.append(env.replace(str(task.Config["command"])))
            cmd.extend(env.replace(str(a))
                       for a in task.Config.get("args", []))
        out = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
        if out.returncode != 0:
            raise RuntimeError(f"docker run failed: {out.stderr.strip()}")
        return DockerHandle(out.stdout.strip())

    def open(self, ctx: ExecContext, handle_id: str) -> DriverHandle:
        return DockerHandle.from_id(handle_id)
