"""docker driver: container lifecycle via the docker CLI (reference:
client/driver/docker.go speaks the daemon API; the CLI carries the same
operations without a vendored daemon client).
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import subprocess
import threading
import time
from typing import Any, Dict, Optional

from nomad_tpu.structs import Node, Task

from .base import (ConfigField, ConfigSchema, Driver, DriverHandle,
                   ExecContext, WaitResult, config_map)

logger = logging.getLogger("nomad.driver.docker")


def docker_conn_env(config) -> dict:
    """Daemon connection settings from client options (reference:
    docker.go:66-120 config structs — docker.endpoint, docker.cert.path,
    docker.tls.verify): environment for every docker CLI invocation, so a
    remote or TLS-protected dockerd works exactly like the local socket."""
    env = dict(os.environ)
    if config is None:
        return env
    endpoint = str(config.read_option("docker.endpoint", ""))
    cert_path = str(config.read_option("docker.cert.path", ""))
    tls_verify = str(config.read_option("docker.tls.verify", ""))
    if endpoint:
        env["DOCKER_HOST"] = endpoint
    if cert_path:
        env["DOCKER_CERT_PATH"] = cert_path
        env.setdefault("DOCKER_TLS_VERIFY", "1")
    if tls_verify:
        env["DOCKER_TLS_VERIFY"] = \
            "1" if tls_verify.lower() in ("1", "true") else ""
    return env


class DockerHandle(DriverHandle):
    def __init__(self, container_id: str, log_dir: str = "",
                 task_name: str = "", max_files: int = 10,
                 max_file_size_mb: int = 10,
                 docker_env: dict = None,
                 cleanup_container: bool = True,
                 cleanup_image: bool = False,
                 image: str = ""):
        self.container_id = container_id
        self.log_dir = log_dir
        self.task_name = task_name
        self.max_files = max_files
        self.max_file_size_mb = max_file_size_mb
        # Daemon connection env + cleanup policy (reference:
        # docker.cleanup.container / docker.cleanup.image options).
        self.docker_env = docker_env or dict(os.environ)
        self.cleanup_container = cleanup_container
        self.cleanup_image = cleanup_image
        self.image = image
        self._result: Optional[WaitResult] = None
        self._done = threading.Event()
        self._log_proc: Optional[subprocess.Popen] = None
        self._watcher = threading.Thread(target=self._watch, daemon=True,
                                         name=f"docker-watch-{task_name}")
        self._watcher.start()
        if log_dir and task_name:
            self._start_log_pump()

    def id(self) -> str:
        return json.dumps({"container_id": self.container_id,
                           "log_dir": self.log_dir,
                           "task_name": self.task_name,
                           "max_files": self.max_files,
                           "max_file_size_mb": self.max_file_size_mb,
                           "cleanup_container": self.cleanup_container,
                           "cleanup_image": self.cleanup_image,
                           "image": self.image})

    @staticmethod
    def from_id(handle_id: str, docker_env: dict = None) -> "DockerHandle":
        data = json.loads(handle_id)
        return DockerHandle(
            data["container_id"],
            log_dir=data.get("log_dir", ""),
            task_name=data.get("task_name", ""),
            max_files=data.get("max_files", 10),
            max_file_size_mb=data.get("max_file_size_mb", 10),
            docker_env=docker_env,
            cleanup_container=data.get("cleanup_container", True),
            cleanup_image=data.get("cleanup_image", False),
            image=data.get("image", ""))

    def exec_in_task(self, command: str, args: list, timeout: float):
        """`docker exec` into the container (reference: DockerScriptCheck,
        executor/checks.go:31-53): a script check observes the container's
        filesystem/network, not the host's.

        The deadline is enforced IN-CONTAINER via timeout(1) when the image
        has it: killing only the local docker CLI on timeout leaves the
        exec'd process running inside the container, leaking one stuck
        check process per tick. The host-side timeout stays as the backstop
        for images without coreutils/busybox."""
        from .base import run_exec_argv

        wrapped = ["docker", "exec", self.container_id, "timeout",
                   str(int(timeout)), command] + list(args)
        code, output = run_exec_argv(wrapped, timeout + 5,
                                     env=self.docker_env)
        if code in (126, 127) and "timeout" in output and (
                "not found" in output or "executable" in output):
            # Image lacks timeout(1): run unwrapped with the host deadline.
            plain = ["docker", "exec", self.container_id, command] \
                + list(args)
            code, output = run_exec_argv(plain, timeout,
                                         env=self.docker_env)
        elif code == 124:  # timeout(1)'s timed-out exit code
            return 2, f"in-task exec timed out after {timeout:.0f}s"
        return code, output

    def _since_path(self) -> str:
        return os.path.join(self.log_dir,
                            f".{self.task_name}.docker_log_since")

    def _start_log_pump(self) -> None:
        """Pump container stdout/stderr into the alloc's rotated log files
        so `nomad fs` serves docker task logs like any executor driver's
        (reference routes docker logs through a syslog server,
        client/driver/logging/; a follow-pump is the same capability without
        the daemon hop). Progress is checkpointed to a since-file so an
        agent restart resumes from where the pump left off (bounded
        duplication, no loss); the first start pumps from the beginning."""
        from nomad_tpu.client.logs import FileRotator

        stdout = FileRotator(self.log_dir, f"{self.task_name}.stdout",
                             self.max_files, self.max_file_size_mb)
        stderr = FileRotator(self.log_dir, f"{self.task_name}.stderr",
                             self.max_files, self.max_file_size_mb)
        since = ""
        try:
            with open(self._since_path()) as f:
                since = f.read().strip()
        except OSError:
            pass
        cmd = ["docker", "logs", "-f"]
        if since:
            cmd.extend(["--since", since])
        cmd.append(self.container_id)
        try:
            self._log_proc = subprocess.Popen(
                cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                env=self.docker_env)
        except OSError:
            return

        def pump(stream, rotator):
            for chunk in iter(lambda: stream.read(4096), b""):
                rotator.write(chunk)
            rotator.close()

        def checkpoint():
            # Lag the checkpoint behind wall time: output can sit in the
            # docker-logs pipe or a blocked rotator write, so "now" isn't
            # proof of durability. A 30s lag bounds restart duplication at
            # ~35s and loses data only if the pump stalls longer than that.
            while self._log_proc is not None \
                    and self._log_proc.poll() is None:
                try:
                    tmp = self._since_path() + ".tmp"
                    with open(tmp, "w") as f:
                        f.write(str(int(time.time()) - 30))
                    os.replace(tmp, self._since_path())
                except OSError:
                    pass
                if self._done.wait(5.0):
                    return

        threading.Thread(target=pump, args=(self._log_proc.stdout, stdout),
                         daemon=True, name="docker-log-stdout").start()
        threading.Thread(target=pump, args=(self._log_proc.stderr, stderr),
                         daemon=True, name="docker-log-stderr").start()
        threading.Thread(target=checkpoint, daemon=True,
                         name="docker-log-checkpoint").start()

    def _watch(self) -> None:
        try:
            out = subprocess.run(["docker", "wait", self.container_id],
                                 capture_output=True, text=True,
                                 env=self.docker_env)
            code = int(out.stdout.strip() or 0)
            self._result = WaitResult(exit_code=code)
        # lint: allow(swallow, error is delivered to the waiter in the WaitResult)
        except Exception as e:
            self._result = WaitResult(error=str(e))
        self._done.set()
        # Cleanup belongs HERE, not in kill(): a task that exits on its own
        # never sees kill(), and the reference's docker.cleanup.container
        # default would otherwise leak a stopped container per completed
        # task. `docker wait` has returned, so the container is down.
        if self._log_proc is not None:
            try:  # let the pump drain the final log output first
                self._log_proc.wait(timeout=3.0)
            except subprocess.TimeoutExpired:
                pass
        if self.cleanup_container:
            subprocess.run(["docker", "rm", self.container_id],
                           capture_output=True, env=self.docker_env)
        if self.cleanup_image and self.image:
            # Best-effort: fails harmlessly while other containers use it.
            subprocess.run(["docker", "rmi", self.image],
                           capture_output=True, env=self.docker_env)

    def wait(self, timeout: Optional[float] = None) -> Optional[WaitResult]:
        if not self._done.wait(timeout):
            return None
        return self._result

    def kill(self, kill_timeout: float = 5.0) -> None:
        subprocess.run(["docker", "stop", "-t", str(int(kill_timeout)),
                        self.container_id], capture_output=True,
                       env=self.docker_env)
        if self._log_proc is not None:
            # The container stopping ends the log stream; give the pump a
            # moment to drain the final output before forcing it down.
            try:
                self._log_proc.wait(timeout=3.0)
            except subprocess.TimeoutExpired:
                try:
                    self._log_proc.terminate()
                except OSError:
                    pass

    def stats(self) -> Optional[dict]:
        """One-shot docker stats sample (reference: docker.go stats via the
        daemon's stats API)."""
        if self._done.is_set():
            return None
        return DockerHandle.stats_many([self]).get(self.container_id)

    @staticmethod
    def stats_many(handles: list) -> Dict[str, dict]:
        """One `docker stats` invocation covering many containers: the CLI
        samples twice to compute CPU%, so per-container calls would cost
        seconds each inside the stats HTTP handler."""
        live = [h for h in handles if not h._done.is_set()]
        ids = [h.container_id for h in live]
        if not ids:
            return {}
        try:
            out = subprocess.run(
                ["docker", "stats", "--no-stream", "--format",
                 "{{.ID}} {{.CPUPerc}} {{.MemUsage}}"] + ids,
                capture_output=True, text=True, timeout=15,
                env=live[0].docker_env)
        except Exception as exc:
            logger.debug("docker stats batch failed: %s", exc)
            return {}
        if out.returncode != 0:
            return {}
        results: Dict[str, dict] = {}
        for line in out.stdout.splitlines():
            parts = line.strip().split(" ", 2)
            if len(parts) < 3:
                continue
            cid, cpu_raw, mem_raw = parts
            try:
                cpu = float(cpu_raw.rstrip("%"))
                rss = _parse_mem(mem_raw.split("/")[0].strip())
            except (ValueError, IndexError):
                continue
            # docker prints short ids; match back to the full ones.
            for full in ids:
                if full.startswith(cid) or cid.startswith(full[:12]):
                    results[full] = {"cpu_percent": cpu, "rss_bytes": rss,
                                     "pids": []}
        return results


_MEM_UNITS = (("GiB", 2**30), ("MiB", 2**20), ("KiB", 2**10),
              ("GB", 1000**3), ("MB", 1000**2), ("kB", 1000), ("B", 1))


def _parse_mem(value: str) -> int:
    """Docker human units -> bytes. Longest suffix first: "5.3MiB" must not
    match the bare "B" rule."""
    for suffix, mult in _MEM_UNITS:
        if value.endswith(suffix):
            return int(float(value[: -len(suffix)]) * mult)
    return int(float(value))


class DockerDriver(Driver):
    name = "docker"

    def fingerprint(self, config, node: Node) -> bool:
        if shutil.which("docker") is None:
            node.Attributes.pop("driver.docker", None)
            return False
        try:
            out = subprocess.run(["docker", "version", "--format",
                                  "{{.Server.Version}}"],
                                 capture_output=True, text=True, timeout=10,
                                 env=docker_conn_env(config))
            if out.returncode != 0:
                node.Attributes.pop("driver.docker", None)
                return False
            node.Attributes["driver.docker"] = "1"
            node.Attributes["driver.docker.version"] = out.stdout.strip()
            return True
        # lint: allow(swallow, probe failure means the docker runtime is absent)
        except Exception:
            return False

    # (reference: client/driver/docker.go:167-226 Validate's fields map —
    # the FULL reference key set so reference job specs validate;
    # implemented=False keys are accepted with an "ignored" warning)
    schema = ConfigSchema(
        image=ConfigField("string", required=True),
        command=ConfigField("string"),
        args=ConfigField("list"),
        port_map=ConfigField("map"),
        auth=ConfigField("map"),
        labels=ConfigField("map"),
        network_mode=ConfigField("string"),
        load=ConfigField("list", implemented=False),
        ipc_mode=ConfigField("string", implemented=False),
        pid_mode=ConfigField("string", implemented=False),
        uts_mode=ConfigField("string", implemented=False),
        privileged=ConfigField("bool", implemented=False),
        dns_servers=ConfigField("list", implemented=False),
        dns_search_domains=ConfigField("list", implemented=False),
        hostname=ConfigField("string", implemented=False),
        ssl=ConfigField("bool", implemented=False),
        tty=ConfigField("bool", implemented=False),
        interactive=ConfigField("bool", implemented=False),
        shm_size=ConfigField("int", implemented=False),
    )

    def _options(self):
        cfg = self.ctx.config if self.ctx is not None else None
        conn = docker_conn_env(cfg)
        def opt(name, default):
            if cfg is None:
                return default
            raw = str(cfg.read_option(name, str(default))).lower()
            return raw in ("1", "true")
        return (conn, opt("docker.cleanup.container", True),
                opt("docker.cleanup.image", False))

    def start(self, ctx: ExecContext, task: Task) -> DriverHandle:
        self.validate(task.Config)
        env = ctx.task_env
        image = env.replace(str(task.Config["image"]))
        task_dir = ctx.alloc_dir.task_dirs[task.Name]
        conn_env, cleanup_container, cleanup_image = self._options()
        cmd = ["docker"]
        auth_dir = self._write_auth_config(task, task_dir)
        if auth_dir:
            cmd.extend(["--config", auth_dir])
        cmd.extend(["run", "-d",
                    "-v", f"{ctx.alloc_dir.shared_dir}:/alloc",
                    "-v", f"{task_dir}/local:/local"])
        # (reference: docker.go createContainer's NetworkMode + Labels)
        if task.Config.get("network_mode"):
            cmd.extend(["--network", str(task.Config["network_mode"])])
        for k, v in config_map(task.Config.get("labels")).items():
            cmd.extend(["--label", f"{k}={v}"])
        if task.Resources is not None:
            cmd.extend(["--memory", f"{task.Resources.MemoryMB}m",
                        "--cpu-shares", str(task.Resources.CPU)])
            for net in task.Resources.Networks:
                for label, value in net.port_labels().items():
                    guest = config_map(
                        task.Config.get("port_map")).get(label, value)
                    cmd.extend(["-p", f"{value}:{guest}"])
        for k, v in env.build_env().items():
            cmd.extend(["-e", f"{k}={v}"])
        cmd.append(image)
        if task.Config.get("command"):
            cmd.append(env.replace(str(task.Config["command"])))
            cmd.extend(env.replace(str(a))
                       for a in task.Config.get("args", []))
        from nomad_tpu.resilience import failpoints

        # error/drop both model a failed container launch (drop has no
        # discard semantic for an exec): the restart policy takes over.
        if failpoints.fire("driver.docker.exec") == "drop":
            raise RuntimeError("docker run dropped (failpoint)")
        out = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=300, env=conn_env)
        if auth_dir:
            # The pull happened inside `docker run`; credentials must not
            # stay at rest in the alloc dir.
            shutil.rmtree(auth_dir, ignore_errors=True)
        if out.returncode != 0:
            raise RuntimeError(f"docker run failed: {out.stderr.strip()}")
        log_cfg = task.LogConfig
        return DockerHandle(
            out.stdout.strip(), log_dir=ctx.alloc_dir.log_dir(),
            task_name=task.Name,
            max_files=log_cfg.MaxFiles if log_cfg else 10,
            max_file_size_mb=log_cfg.MaxFileSizeMB if log_cfg else 10,
            docker_env=conn_env, cleanup_container=cleanup_container,
            cleanup_image=cleanup_image, image=image)

    def open(self, ctx: ExecContext, handle_id: str) -> DriverHandle:
        # Daemon connection env is NOT persisted in the id: recomputed from
        # the client options BEFORE the handle's watcher thread starts
        # (reattach must never probe the wrong daemon, even briefly).
        return DockerHandle.from_id(handle_id,
                                    docker_env=self._options()[0])

    @staticmethod
    def _write_auth_config(task: Task, task_dir: str) -> str:
        """Private-registry auth: task config `auth {username, password,
        server_address}` becomes a per-task docker client config passed via
        --config (reference: docker.go:683+ authenticates pulls with
        per-task credentials)."""
        auth = config_map(task.Config.get("auth"))
        if not auth:
            return ""
        import base64
        import os

        user = str(auth.get("username", ""))
        password = str(auth.get("password", ""))
        server = str(auth.get("server_address", "")
                     or "https://index.docker.io/v1/")
        token = base64.b64encode(f"{user}:{password}".encode()).decode()
        cfg_dir = os.path.join(task_dir, "docker-auth")
        os.makedirs(cfg_dir, mode=0o700, exist_ok=True)
        os.chmod(cfg_dir, 0o700)
        cfg_path = os.path.join(cfg_dir, "config.json")
        # 0600 from the first byte: no world-readable window before a chmod.
        fd = os.open(cfg_path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "w") as f:
            json.dump({"auths": {server: {"auth": token}}}, f)
        return cfg_dir
