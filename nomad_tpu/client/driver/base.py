"""Driver interface (reference: client/driver/driver.go:50-172)."""

from __future__ import annotations

import json
import logging
import os
import signal
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from nomad_tpu.client.allocdir import AllocDir
from nomad_tpu.client.env import TaskEnv
from nomad_tpu.resilience.retry import Backoff, RetryPolicy
from nomad_tpu.structs import Allocation, Node, Task


@dataclass
class WaitResult:
    exit_code: int = 0
    signal: int = 0
    error: str = ""

    def successful(self) -> bool:
        return self.exit_code == 0 and self.signal == 0 and not self.error


@dataclass
class DriverContext:
    """Static driver context (reference: driver.go:64-90)."""

    task_name: str = ""
    config: Any = None  # client config
    node: Optional[Node] = None


@dataclass
class ExecContext:
    """Per-task execution context (reference: driver.go:135-152)."""

    alloc_dir: Optional[AllocDir] = None
    alloc_id: str = ""
    task_env: Optional[TaskEnv] = None


class DriverHandle:
    """A running task (reference: driver.go:120-133)."""

    def id(self) -> str:
        raise NotImplementedError

    def wait(self, timeout: Optional[float] = None) -> Optional[WaitResult]:
        """Block until exit (or timeout); None on timeout."""
        raise NotImplementedError

    def update(self, task: Task) -> None:
        pass

    def kill(self, kill_timeout: float = 5.0) -> None:
        raise NotImplementedError

    def stats(self) -> Optional[dict]:
        """Raw usage sample ({pids, user_seconds, system_seconds, rss_bytes}
        or {cpu_percent, rss_bytes}); None when unavailable (reference:
        executor.go pid-tree stats / docker stats API)."""
        return None

    def exec_in_task(self, command: str, args: list, timeout: float
                     ) -> Optional[Tuple[int, str]]:
        """Run a command INSIDE the task's execution context (container /
        chroot) — script health checks use this so a check can't pass on
        the host while the service is broken in its isolation (reference:
        executor/checks.go:31-65 DockerScriptCheck + ExecScriptCheck).
        Returns (exit_code, output), or None when the driver has no
        in-task exec (caller falls back to host cwd/env execution)."""
        return None


class ConfigField:
    """One driver-config field: type + required (reference: the FieldSchema
    entries in helper/fields/type.go). implemented=False accepts a
    reference-valid key this driver does not (yet) act on: the job
    validates — compatibility with reference job specs — but a warning
    records that the option is ignored."""

    __slots__ = ("type", "required", "implemented")

    def __init__(self, type: str, required: bool = False,
                 implemented: bool = True):
        self.type = type
        self.required = required
        self.implemented = implemented


def _field_type_ok(value: Any, ftype: str) -> bool:
    """Weakly-typed like the reference's mapstructure decode
    (helper/fields/decoder.go WeaklyTypedInput): HCL frontends hand over
    strings for scalars, so "512" satisfies an int field."""
    if ftype == "string":
        return isinstance(value, (str, int, float, bool))
    if ftype == "bool":
        return isinstance(value, bool) or (
            isinstance(value, str)
            and value.lower() in ("true", "false", "1", "0"))
    if ftype == "int":
        if isinstance(value, bool):
            return False
        if isinstance(value, int):
            return True
        if isinstance(value, str):
            try:
                int(value)
                return True
            except ValueError:
                return False
        return False
    if ftype == "float":
        if isinstance(value, bool):
            return False
        if isinstance(value, (int, float)):
            return True
        if isinstance(value, str):
            try:
                float(value)
                return True
            except ValueError:
                return False
        return False
    if ftype == "list":
        return isinstance(value, (list, tuple))
    if ftype == "map":
        # HCL decodes `port_map { http = 80 }` as a list of one map.
        return isinstance(value, dict) or (
            isinstance(value, (list, tuple))
            and all(isinstance(v, dict) for v in value))
    if ftype == "duration":
        return isinstance(value, (int, float, str))
    return True


def config_map(value: Any) -> Dict[str, Any]:
    """Normalize a map-typed config value: HCL decodes a repeated block
    (`port_map { http = 80 }`) as a list of dicts; merge them in order
    (later blocks win), matching the reference's mapstructure decode."""
    if value is None:
        return {}
    if isinstance(value, dict):
        return dict(value)
    out: Dict[str, Any] = {}
    for part in value:
        out.update(part)
    return out


def config_bool(value: Any, default: bool = False) -> bool:
    """Coerce a weakly-typed bool config value the way validation accepts
    it: the string \"false\" must mean False, not truthy-string True."""
    if value is None:
        return default
    if isinstance(value, bool):
        return value
    if isinstance(value, str):
        return value.lower() in ("1", "true")
    return bool(value)


_WARNED_IGNORED: set = set()


class ConfigSchema:
    """Mini field-schema for driver task configs (reference:
    helper/fields/type.go FieldSchema maps, used by each driver's
    Validate — e.g. client/driver/docker.go:116-140). Unknown keys are
    REJECTED: a typo'd config key must fail job validation loudly instead
    of silently no-opping at runtime."""

    def __init__(self, **fields: ConfigField):
        self.fields = fields

    def validate(self, config: Dict[str, Any], driver: str = "") -> None:
        errs = []
        tag = f" for {driver} driver" if driver else ""
        for key, f in self.fields.items():
            if f.required and not config.get(key):
                errs.append(f"missing required config key {key!r}{tag}")
        for key, value in (config or {}).items():
            f = self.fields.get(key)
            if f is None:
                errs.append(f"unknown config key {key!r}{tag}")
            elif value is not None and not _field_type_ok(value, f.type):
                errs.append(
                    f"config key {key!r}{tag} must be a {f.type}")
            elif not f.implemented:
                # Once per (driver, key) per process: validation re-runs
                # on every task start/restart, and a crash-looping task
                # must not spam the client log with the same notice.
                mark = (driver, key)
                if mark not in _WARNED_IGNORED:
                    _WARNED_IGNORED.add(mark)
                    logging.getLogger("nomad.driver").warning(
                        "config key %r%s is accepted for reference "
                        "compatibility but not implemented; it is "
                        "ignored", key, tag)
        if errs:
            raise ValueError("; ".join(errs))

    def ignored_keys(self, config: Dict[str, Any]) -> List[str]:
        """Reference-compatible keys present in `config` that this driver
        accepts but does not act on — surfaced to the SUBMITTER as
        job-validate warnings (a client-side log line is invisible to
        whoever wrote the job)."""
        return sorted(
            key for key, value in (config or {}).items()
            if value is not None
            and key in self.fields and not self.fields[key].implemented)


class Driver:
    name = "base"
    # Per-driver config schema; None skips schema validation (base class
    # only — every real driver defines one).
    schema: Optional[ConfigSchema] = None

    def __init__(self, ctx: DriverContext):
        self.ctx = ctx

    def fingerprint(self, config, node: Node) -> bool:
        """Set driver.<name> attribute if available on this machine."""
        raise NotImplementedError

    def validate(self, config: Dict[str, Any]) -> None:
        """Raise ValueError on invalid task config (schema + any
        driver-specific checks layered by subclasses)."""
        if self.schema is not None:
            self.schema.validate(config or {}, driver=self.name)

    def start(self, ctx: ExecContext, task: Task) -> DriverHandle:
        raise NotImplementedError

    def open(self, ctx: ExecContext, handle_id: str) -> DriverHandle:
        """Re-attach to a running task after agent restart."""
        raise NotImplementedError


def run_exec_argv(argv: list, timeout: float, cwd=None, env=None
                  ) -> Tuple[int, str]:
    """Run an in-task exec argv with the shared timeout/error mapping and
    output truncation (one definition for every driver's exec_in_task)."""
    try:
        proc = subprocess.run(argv, capture_output=True, text=True,
                              timeout=timeout, cwd=cwd, env=env)
    except subprocess.TimeoutExpired:
        return 2, f"in-task exec timed out after {timeout:.0f}s"
    except OSError as e:
        return 2, str(e)
    return proc.returncode, (proc.stdout + proc.stderr)[-4096:]


class ExecutorHandle(DriverHandle):
    """Handle over the detached executor process (see client/executor.py)."""

    def __init__(self, state_dir: str, task_name: str, executor_pid: int):
        self.state_dir = state_dir
        self.task_name = task_name
        self.executor_pid = executor_pid
        self._result: Optional[WaitResult] = None
        self._done = threading.Event()
        self._watcher = threading.Thread(target=self._watch, daemon=True,
                                         name=f"driver-watch-{task_name}")
        self._watcher.start()

    # ------------------------------------------------------------- protocol
    def id(self) -> str:
        return json.dumps({"state_dir": self.state_dir,
                           "task_name": self.task_name,
                           "executor_pid": self.executor_pid})

    @staticmethod
    def from_id(handle_id: str) -> "ExecutorHandle":
        data = json.loads(handle_id)
        return ExecutorHandle(data["state_dir"], data["task_name"],
                              data["executor_pid"])

    def exec_in_task(self, command: str, args: list, timeout: float
                     ) -> Optional[Tuple[int, str]]:
        """Execute inside the task's context from its persisted spec: same
        chroot (when the task has one), cwd, and environment (reference:
        ExecScriptCheck runs through the executor, checks.go:31-65)."""
        spec_path = os.path.join(self.state_dir,
                                 f"{self.task_name}.executor_spec.json")
        try:
            with open(spec_path) as f:
                spec = json.load(f)
        except (OSError, ValueError):
            # Missing or mid-rewrite spec (task restarting): host fallback
            # rather than a spurious critical.
            return None
        chroot = spec.get("chroot")
        cwd = spec.get("cwd")
        env = spec.get("env") or None

        argv = [command] + list(args)
        if chroot:
            # chroot(1) rather than a preexec_fn os.chroot: preexec_fn is
            # documented deadlock-prone with threads, and checks run on the
            # service manager's worker pool. Resolved to an ABSOLUTE path
            # with the agent's PATH — the task env has no PATH, and
            # subprocess would otherwise search os.defpath, which misses
            # /usr/sbin (where Debian keeps chroot).
            import shutil as _shutil

            chroot_bin = _shutil.which("chroot") or next(
                (p for p in ("/usr/sbin/chroot", "/sbin/chroot",
                             "/usr/bin/chroot")
                 if os.access(p, os.X_OK)), None)
            if chroot_bin is None:
                return 2, "chroot binary not found on host"
            argv = [chroot_bin, chroot] + argv
            cwd = None  # host cwd is meaningless post-chroot
        return run_exec_argv(argv, timeout, cwd=cwd, env=env)

    # -------------------------------------------------------------- running
    def _exit_path(self) -> str:
        return os.path.join(self.state_dir,
                            f"{self.task_name}.exit_status.json")

    def _state_path(self) -> str:
        return os.path.join(self.state_dir,
                            f"{self.task_name}.executor_state.json")

    def _watch(self) -> None:
        while not self._done.is_set():
            if os.path.exists(self._exit_path()):
                try:
                    with open(self._exit_path()) as f:
                        data = json.load(f)
                    self._result = WaitResult(
                        exit_code=data.get("exit_code", 0),
                        signal=data.get("signal", 0))
                except (OSError, json.JSONDecodeError):
                    self._result = WaitResult(error="failed to read exit status")
                self._done.set()
                return
            if not _pid_alive(self.executor_pid):
                # Executor died without writing status.
                # lint: allow(retry, grace for a just-written exit file)
                time.sleep(0.2)
                if not os.path.exists(self._exit_path()):
                    self._result = WaitResult(
                        error="executor terminated unexpectedly")
                    self._done.set()
                    return
                continue
            # lint: allow(retry, exit-file poll is this supervisor's job)
            time.sleep(0.1)

    def wait(self, timeout: Optional[float] = None) -> Optional[WaitResult]:
        if not self._done.wait(timeout):
            return None
        return self._result

    def kill(self, kill_timeout: float = 5.0) -> None:
        pgid = self._pgid()
        if pgid is None:
            return
        try:
            os.killpg(pgid, signal.SIGTERM)
        except ProcessLookupError:
            return
        if not self._done.wait(kill_timeout):
            try:
                os.killpg(pgid, signal.SIGKILL)
            except ProcessLookupError:
                pass
            self._done.wait(2.0)

    def _pgid(self) -> Optional[int]:
        try:
            with open(self._state_path()) as f:
                return json.load(f).get("pgid")
        except (OSError, json.JSONDecodeError):
            return None

    def stats(self) -> Optional[dict]:
        """Pid-tree usage of the task's process group (reference:
        executor.go:36-41 collects the executor's child pids)."""
        if self._done.is_set():
            return None
        pgid = self._pgid()
        if pgid is None:
            return None
        from nomad_tpu.client.stats import sample_pid_tree

        pids, user, system, rss = sample_pid_tree(pgid)
        if not pids:
            return None
        return {"pids": pids, "user_seconds": user,
                "system_seconds": system, "rss_bytes": rss}


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True


def build_executor_spec(ctx: "ExecContext", task: Task, command: str,
                        args: list) -> Dict[str, Any]:
    """Common executor spec: interpolated command/args, env, cwd, log config.
    Shared by every executor-backed driver."""
    env = ctx.task_env
    task_dir = ctx.alloc_dir.task_dirs[task.Name]
    spec: Dict[str, Any] = {
        "command": env.replace(str(command)),
        "args": [env.replace(str(a)) for a in args],
        "env": env.build_env(),
        "cwd": task_dir,
        "log_dir": ctx.alloc_dir.log_dir(),
        "max_files": task.LogConfig.MaxFiles if task.LogConfig else 10,
        "max_file_size_mb": (task.LogConfig.MaxFileSizeMB
                             if task.LogConfig else 10),
    }
    if task.User:
        spec["user"] = task.User
    return spec


def native_executor_path() -> str:
    """The compiled native supervisor, when present (native/executor.cc,
    built by `make -C native`). Override with NOMAD_TPU_EXECUTOR=/path or
    disable with NOMAD_TPU_EXECUTOR=python."""
    override = os.environ.get("NOMAD_TPU_EXECUTOR", "")
    if override == "python":
        return ""
    if override:
        if not os.access(override, os.X_OK):
            # An explicit override must never silently degrade.
            raise RuntimeError(
                f"NOMAD_TPU_EXECUTOR={override!r} is not an executable file")
        return override
    candidate = os.path.join(_repo_root(), "native", "bin", "nomad-executor")
    return candidate if os.access(candidate, os.X_OK) else ""


def launch_executor(state_dir: str, task_name: str, spec: Dict[str, Any]
                    ) -> ExecutorHandle:
    """Write the spec and start the detached executor — the native C++
    supervisor when built (the reference's executor is likewise a native
    re-exec'd process, client/driver/executor/), the Python implementation
    otherwise. Both speak the same spec/state/exit file contract, so
    reattach works across either."""
    os.makedirs(state_dir, exist_ok=True)
    spec_path = os.path.join(state_dir, f"{task_name}.executor_spec.json")
    spec = dict(spec, task_name=task_name)
    # Atomic write: exec_in_task (script checks) may read the spec while a
    # restart rewrites it.
    tmp_path = spec_path + ".tmp"
    with open(tmp_path, "w") as f:
        json.dump(spec, f)
    os.replace(tmp_path, spec_path)
    # Clear stale exit/state files from a previous run.
    for suffix in ("exit_status.json", "executor_state.json"):
        try:
            os.unlink(os.path.join(state_dir, f"{task_name}.{suffix}"))
        except FileNotFoundError:
            pass
    native = native_executor_path()
    if native:
        cmd = [native, spec_path]
    else:
        cmd = [sys.executable, "-m", "nomad_tpu.client.executor", spec_path]
    proc = subprocess.Popen(
        cmd,
        start_new_session=True,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        env=dict(os.environ,
                 PYTHONPATH=os.pathsep.join(
                     [p for p in [os.environ.get("PYTHONPATH"),
                                  _repo_root()] if p])),
    )
    # Wait for the executor to write its state file: RetryPolicy paces the
    # poll (20-100ms jittered) under a 10s deadline; an early executor
    # death is terminal and surfaces immediately.
    state_path = os.path.join(state_dir, f"{task_name}.executor_state.json")

    class _NotYet(Exception):
        pass

    def check() -> None:
        if os.path.exists(state_path):
            return
        if proc.poll() is not None:
            raise RuntimeError(
                f"executor exited immediately with code {proc.returncode}")
        raise _NotYet()

    policy = RetryPolicy(max_attempts=None, deadline=10.0,
                         backoff=Backoff(base=0.02, cap=0.1),
                         retry_on=(_NotYet,),
                         trace_events=False)  # ms-cadence poll
    try:
        policy.call(check)
    except _NotYet:
        raise RuntimeError("executor failed to start in time")
    return ExecutorHandle(state_dir, task_name, proc.pid)


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
