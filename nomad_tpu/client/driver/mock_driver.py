"""mock driver for tests: runs in-process with scriptable behavior."""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

from nomad_tpu.structs import Node, Task

from .base import (ConfigField, ConfigSchema, Driver, DriverHandle,
                   ExecContext, WaitResult)


def _seconds(value: Any) -> float:
    """Accept plain seconds or HCL duration strings ("2s", "500ms") — the
    jobspec hands driver config through verbatim (reference: the mock
    driver's time.ParseDuration of run_for)."""
    if isinstance(value, (int, float)):
        return float(value)
    from nomad_tpu.jobspec.parse import parse_duration

    return parse_duration(value) / 1e9


class MockHandle(DriverHandle):
    def __init__(self, run_for: float, exit_code: int):
        self._exit_code = exit_code
        self._done = threading.Event()
        self._killed = False
        self._timer = threading.Timer(run_for, self._done.set)
        self._timer.daemon = True
        self._timer.start()

    def id(self) -> str:
        return "mock"

    def wait(self, timeout: Optional[float] = None) -> Optional[WaitResult]:
        if not self._done.wait(timeout):
            return None
        if self._killed:
            return WaitResult(exit_code=0, signal=15)
        return WaitResult(exit_code=self._exit_code)

    def kill(self, kill_timeout: float = 5.0) -> None:
        self._killed = True
        self._timer.cancel()
        self._done.set()


class MockDriver(Driver):
    name = "mock_driver"

    # (reference: client/driver/mock_driver.go's config shape)
    schema = ConfigSchema(
        run_for=ConfigField("duration"),
        exit_code=ConfigField("int"),
        start_error=ConfigField("string"),
        kill_after=ConfigField("duration"),
    )

    def fingerprint(self, config, node: Node) -> bool:
        node.Attributes["driver.mock_driver"] = "1"
        return True

    def start(self, ctx: ExecContext, task: Task) -> DriverHandle:
        cfg = task.Config
        if cfg.get("start_error"):
            raise RuntimeError(str(cfg["start_error"]))
        return MockHandle(_seconds(cfg.get("run_for", 0.1)),
                          int(cfg.get("exit_code", 0)))

    def open(self, ctx: ExecContext, handle_id: str) -> DriverHandle:
        return MockHandle(0.1, 0)
