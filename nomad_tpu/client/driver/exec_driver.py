"""exec driver: isolated process runner (reference: client/driver/exec.go).

Linux-only: requires root + cgroups for resource isolation (the reference
additionally chroots into the task dir; here the chroot applies when running
as root). Falls back unavailable otherwise, exactly like the reference's
fingerprint gate (exec.go:57-76).
"""

from __future__ import annotations

import os
import platform
from typing import Any, Dict

from nomad_tpu.structs import Node, Task

from .base import (ConfigField, ConfigSchema, Driver, DriverHandle,
                   ExecContext, ExecutorHandle, build_executor_spec,
                   config_bool, launch_executor)


class ExecDriver(Driver):
    name = "exec"

    def fingerprint(self, config, node: Node) -> bool:
        if platform.system() != "Linux":
            node.Attributes.pop("driver.exec", None)
            return False
        if os.geteuid() != 0:
            node.Attributes.pop("driver.exec", None)
            return False
        if "unique.cgroup.mountpoint" not in node.Attributes:
            node.Attributes.pop("driver.exec", None)
            return False
        node.Attributes["driver.exec"] = "1"
        return True

    # (reference: client/driver/exec.go Validate's fields map)
    schema = ConfigSchema(
        command=ConfigField("string", required=True),
        args=ConfigField("list"),
        no_chroot=ConfigField("bool"),
    )

    def start(self, ctx: ExecContext, task: Task) -> DriverHandle:
        self.validate(task.Config)
        spec = build_executor_spec(ctx, task, task.Config["command"],
                                   task.Config.get("args", []))
        if task.Resources is not None:
            spec["cgroup"] = {"cpu_shares": task.Resources.CPU,
                              "memory_mb": task.Resources.MemoryMB}
        # Chroot into the task dir with the host system dirs bind-mounted
        # read-only (reference: exec.go + alloc_dir_linux.go Embed). Skipped
        # for non-root (fingerprint already gates on root) and by the
        # operator escape hatches.
        if (os.geteuid() == 0
                and os.environ.get("NOMAD_TPU_EXEC_CHROOT", "1") != "0"
                and not config_bool(task.Config.get("no_chroot"))):
            spec["chroot"] = ctx.alloc_dir.build_chroot(task.Name)
        return launch_executor(ctx.alloc_dir.task_dirs[task.Name],
                               task.Name, spec)

    def open(self, ctx: ExecContext, handle_id: str) -> DriverHandle:
        return ExecutorHandle.from_id(handle_id)
