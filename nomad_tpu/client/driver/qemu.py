"""qemu driver: VM image runner (reference: client/driver/qemu.go)."""

from __future__ import annotations

import os
import shutil
import subprocess
from typing import Any, Dict

from nomad_tpu.structs import Node, Task

from .base import (ConfigField, ConfigSchema, Driver, DriverHandle,
                   ExecContext, ExecutorHandle, build_executor_spec,
                   config_map, launch_executor)


class QemuDriver(Driver):
    name = "qemu"

    def fingerprint(self, config, node: Node) -> bool:
        qemu = shutil.which("qemu-system-x86_64")
        if qemu is None:
            node.Attributes.pop("driver.qemu", None)
            return False
        try:
            out = subprocess.run([qemu, "--version"], capture_output=True,
                                 text=True, timeout=10)
            version = out.stdout.split("version")[-1].split()[0] if out.stdout else ""
        # lint: allow(swallow, probe failure means the qemu runtime is absent)
        except Exception:
            return False
        node.Attributes["driver.qemu"] = "1"
        node.Attributes["driver.qemu.version"] = version
        return True

    # (reference: client/driver/qemu.go Validate's fields map)
    schema = ConfigSchema(
        image_path=ConfigField("string", required=True),
        accelerator=ConfigField("string"),
        port_map=ConfigField("map"),
    )

    def start(self, ctx: ExecContext, task: Task) -> DriverHandle:
        self.validate(task.Config)
        env = ctx.task_env
        task_dir = ctx.alloc_dir.task_dirs[task.Name]
        image = env.replace(str(task.Config["image_path"]))
        mem = task.Resources.MemoryMB if task.Resources else 512
        # (reference: qemu.go's accelerator config, default tcg)
        accel = str(task.Config.get("accelerator") or "tcg")
        args = ["-machine", f"type=pc,accel={accel}", "-name",
                f"nomad_{task.Name}", "-m", f"{mem}M", "-drive",
                f"file={image}", "-nographic", "-nodefaults"]
        # Port forwards (reference: qemu.go port_map handling).
        port_map = config_map(task.Config.get("port_map"))
        if port_map and task.Resources and task.Resources.Networks:
            net = task.Resources.Networks[0]
            forwards = []
            labels = net.port_labels()
            for label, guest_port in port_map.items():
                host_port = labels.get(label)
                if host_port:
                    forwards.append(f"hostfwd=tcp::{host_port}-:{guest_port}")
            if forwards:
                args.extend(["-netdev",
                             "user,id=user.0," + ",".join(forwards),
                             "-device", "virtio-net,netdev=user.0"])
        spec = build_executor_spec(ctx, task, "qemu-system-x86_64", args)
        return launch_executor(task_dir, task.Name, spec)

    def open(self, ctx: ExecContext, handle_id: str) -> DriverHandle:
        return ExecutorHandle.from_id(handle_id)
