"""java driver: jar launcher (reference: client/driver/java.go)."""

from __future__ import annotations

import shutil
import subprocess
from typing import Any, Dict

from nomad_tpu.structs import Node, Task

from .base import (ConfigField, ConfigSchema,
                   Driver, DriverHandle, ExecContext, ExecutorHandle,
                   build_executor_spec, launch_executor)


class JavaDriver(Driver):
    name = "java"

    def fingerprint(self, config, node: Node) -> bool:
        java = shutil.which("java")
        if java is None:
            node.Attributes.pop("driver.java", None)
            return False
        try:
            out = subprocess.run(["java", "-version"], capture_output=True,
                                 text=True, timeout=10)
            version_line = (out.stderr or out.stdout).splitlines()[0]
            version = version_line.split('"')[1] if '"' in version_line else ""
        # lint: allow(swallow, probe failure means the java runtime is absent)
        except Exception:
            return False
        node.Attributes["driver.java"] = "1"
        node.Attributes["driver.java.version"] = version
        node.Attributes["driver.java.runtime"] = version_line
        return True

    # (reference: client/driver/java.go Validate's fields map)
    schema = ConfigSchema(
        jar_path=ConfigField("string", required=True),
        jvm_options=ConfigField("list"),
        args=ConfigField("list"),
    )

    def start(self, ctx: ExecContext, task: Task) -> DriverHandle:
        self.validate(task.Config)
        args = list(task.Config.get("jvm_options", []))
        args += ["-jar", task.Config["jar_path"]]
        args += list(task.Config.get("args", []))
        spec = build_executor_spec(ctx, task, "java", args)
        return launch_executor(ctx.alloc_dir.task_dirs[task.Name],
                               task.Name, spec)

    def open(self, ctx: ExecContext, handle_id: str) -> DriverHandle:
        return ExecutorHandle.from_id(handle_id)
