"""Size-based log rotation (reference: client/driver/logging/rotator.go).

Writes a stream to `<name>.N` files, rotating when a file reaches max_size
and deleting the oldest beyond max_files.
"""

from __future__ import annotations

import os
import re
import threading
from typing import Optional


class FileRotator:
    def __init__(self, path: str, base_name: str, max_files: int,
                 max_size_mb: int):
        self.path = path
        self.base_name = base_name
        self.max_files = max(1, max_files)
        self.max_size = max(1, max_size_mb) * 1024 * 1024
        self._lock = threading.Lock()
        self._index = self._find_latest_index()
        self._fh = None
        self._written = 0
        self._open_current()

    def _find_latest_index(self) -> int:
        pat = re.compile(re.escape(self.base_name) + r"\.(\d+)$")
        best = 0
        try:
            for name in os.listdir(self.path):
                m = pat.match(name)
                if m:
                    best = max(best, int(m.group(1)))
        except OSError:
            pass
        return best

    def _file(self, index: int) -> str:
        return os.path.join(self.path, f"{self.base_name}.{index}")

    def _open_current(self) -> None:
        os.makedirs(self.path, exist_ok=True)
        target = self._file(self._index)
        self._fh = open(target, "ab")
        self._written = self._fh.tell()

    def write(self, data: bytes) -> None:
        with self._lock:
            if self._fh is None:
                return
            if self._written + len(data) > self.max_size:
                self._rotate()
            self._fh.write(data)
            self._fh.flush()
            self._written += len(data)

    def _rotate(self) -> None:
        self._fh.close()
        self._index += 1
        self._open_current()
        # Prune files beyond max_files.
        oldest = self._index - self.max_files + 1
        pat = re.compile(re.escape(self.base_name) + r"\.(\d+)$")
        for name in os.listdir(self.path):
            m = pat.match(name)
            if m and int(m.group(1)) < oldest:
                try:
                    os.unlink(os.path.join(self.path, name))
                except OSError:
                    pass

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
