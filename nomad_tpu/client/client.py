"""Client: the node agent main loop (reference: client/client.go).

Fingerprint -> register -> heartbeat loop; watch allocations via blocking
queries; diff and run/update/remove AllocRunners; batch alloc status updates
back to the servers (200ms batching, reference: client.go:74, 925-970).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from nomad_tpu.resilience import failpoints
from nomad_tpu.resilience.retry import Backoff
from nomad_tpu.structs import Allocation, Node, Resources, generate_uuid
from nomad_tpu.structs.structs import NodeStatusInit, NodeStatusReady

from .alloc_runner import AllocRunner
from .driver import BUILTIN_DRIVERS, DriverContext, new_driver
from .fingerprint import fingerprint_node
from .rpc import ServerChannel

logger = logging.getLogger("nomad.client")

ALLOC_SYNC_INTERVAL = 0.2  # batched status sync (reference: client.go:74)


@dataclass
class ClientConfig:
    """(reference: client/config/config.go)"""

    state_dir: str = "/tmp/nomad_tpu/client"
    alloc_dir: str = "/tmp/nomad_tpu/alloc"
    node_class: str = ""
    node_id: str = ""
    datacenter: str = "dc1"
    region: str = "global"
    meta: Dict[str, str] = field(default_factory=dict)
    options: Dict[str, str] = field(default_factory=dict)
    reserved: Optional[Resources] = None
    network_speed: int = 0
    dev_mode: bool = False

    def read_option(self, key: str, default: str = "") -> str:
        return self.options.get(key, default)


class Client:
    def __init__(self, config: ClientConfig, channel: ServerChannel):
        self.config = config
        self.channel = channel
        os.makedirs(config.state_dir, exist_ok=True)
        os.makedirs(config.alloc_dir, exist_ok=True)
        self.node = self._build_node()
        # Serializes node mutation (periodic fingerprints) against node
        # serialization (register/heartbeat pushes) — and pushes always send
        # a copy so in-process channels never hand a live mutable Node to
        # the FSM.
        self._node_lock = threading.Lock()
        from nomad_tpu.services import ServiceManager

        self.service_manager = ServiceManager(
            self.node, channel.sync_services, self._restart_task)
        self.alloc_runners: Dict[str, AllocRunner] = {}
        self._alloc_lock = threading.Lock()
        self._alloc_updates: Dict[str, Allocation] = {}
        self._updates_lock = threading.Lock()
        self._shutdown = threading.Event()
        self._threads: List[threading.Thread] = []
        self._heartbeat_ttl = 10.0

    # ---------------------------------------------------------------- setup
    def _persistent_node_id(self) -> str:
        """Stable node identity across agent restarts (reference:
        client.go's client-id file in the state dir): without it a
        restarted client registers as a BRAND NEW node, its old node TTLs
        down, and every alloc it was running is marked lost and
        rescheduled instead of reattached."""
        if self.config.node_id:
            return self.config.node_id
        path = os.path.join(self.config.state_dir, "client-id")
        try:
            with open(path) as f:
                nid = f.read().strip()
            if nid:
                return nid
        except (OSError, UnicodeDecodeError, ValueError):
            # Unreadable/corrupt id file: fall through to a fresh identity
            # rather than wedging every future agent start.
            pass
        nid = generate_uuid()
        try:
            os.makedirs(self.config.state_dir, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                f.write(nid)
            os.replace(tmp, path)
        except OSError:
            logger.exception("failed to persist client id")
        return nid

    def _build_node(self) -> Node:
        """(reference: client.go:604-700 setupNode + fingerprint + drivers)"""
        node = Node(
            ID=self._persistent_node_id(),
            Datacenter=self.config.datacenter,
            Status=NodeStatusInit,
            NodeClass=self.config.node_class,
            Meta=dict(self.config.meta),
            Resources=Resources(),
            Reserved=self.config.reserved,
        )
        fingerprint_node(node, self.config)
        # Driver fingerprints.
        for name, cls in BUILTIN_DRIVERS.items():
            try:
                cls(DriverContext(config=self.config)).fingerprint(
                    self.config, node)
            except Exception:
                logger.exception("driver %s fingerprint failed", name)
        return node

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        os.makedirs(self.config.state_dir, exist_ok=True)
        os.makedirs(self.config.alloc_dir, exist_ok=True)
        self._register()
        for target, name in ((self._heartbeat_loop, "client-heartbeat"),
                             (self._watch_allocations, "client-watch"),
                             (self._alloc_sync_loop, "client-sync"),
                             (self._fingerprint_loop, "client-fingerprint")):
            t = threading.Thread(target=target, daemon=True, name=name)
            t.start()
            self._threads.append(t)

    def shutdown(self) -> None:
        self._shutdown.set()
        with self._alloc_lock:
            runners = list(self.alloc_runners.values())
        for r in runners:
            r.destroy_tasks()
        self.service_manager.shutdown()
        close = getattr(self.channel, "close", None)
        if close is not None:
            close()

    def _restart_task(self, alloc_id: str, task_name: str,
                      reason: str) -> None:
        """Health-check-driven restart (services/manager.py)."""
        with self._alloc_lock:
            runner = self.alloc_runners.get(alloc_id)
        if runner is not None:
            logger.warning("client: restarting %s/%s: %s",
                           alloc_id[:8], task_name, reason)
            runner.restart_task(task_name, reason)

    # ------------------------------------------------------------- register
    def _register(self) -> None:
        """(reference: client.go:720-775 registerAndHeartbeat/register)"""
        backoff = Backoff(base=0.5, cap=30.0)
        while not self._shutdown.is_set():
            try:
                if failpoints.fire("client.register") == "drop":
                    # A lost registration RPC: no response, so the retry
                    # loop backs off and re-sends like any failure.
                    raise failpoints.FailpointError("client.register")
                with self._node_lock:
                    snapshot = self.node.copy()
                self._heartbeat_ttl = self.channel.register_node(snapshot)
                self.node.Status = NodeStatusReady
                self.channel.update_node_status(self.node.ID, NodeStatusReady)
                logger.info("client: node %s registered (ttl %.1fs)",
                            self.node.ID[:8], self._heartbeat_ttl)
                return
            except Exception:
                logger.exception("client: registration failed; retrying")
                if self._shutdown.wait(backoff.next()):
                    return

    def _heartbeat_loop(self) -> None:
        while not self._shutdown.is_set():
            wait = max(self._heartbeat_ttl / 2, 0.1)
            if self._shutdown.wait(wait):
                return
            try:
                if failpoints.fire("client.heartbeat") == "drop":
                    continue  # heartbeat lost in transit; TTL keeps ticking
                self._heartbeat_ttl = self.channel.heartbeat(self.node.ID)
            except Exception:
                logger.exception("client: heartbeat failed; re-registering")
                self._register()

    def _fingerprint_loop(self) -> None:
        """Periodic re-fingerprinting: drifting readings (free disk, network)
        push a node update when they materially change (reference:
        client/fingerprint/fingerprint.go:68-77 Periodic fingerprints +
        client.go fingerprintPeriodic)."""
        from .fingerprint import run_periodic_fingerprints

        period = float(self.config.read_option("fingerprint.period", "30"))
        dirty = False  # a change survives a failed push until it lands
        while not self._shutdown.wait(period):
            try:
                with self._node_lock:
                    dirty = run_periodic_fingerprints(self.node,
                                                      self.config) or dirty
                    snapshot = self.node.copy() if dirty else None
                if dirty:
                    logger.info("client: fingerprint changed; updating node")
                    self.channel.register_node(snapshot)
                    dirty = False
            except Exception:
                logger.exception("client: periodic fingerprint failed")

    # ------------------------------------------------------------ alloc sync
    def _watch_allocations(self) -> None:
        """Blocking-query pull loop (reference: client.go:984-1098)."""
        min_index = 0
        while not self._shutdown.is_set():
            try:
                id_to_index, index = self.channel.get_client_allocs(
                    self.node.ID, min_index, max_wait=5.0)
            except Exception:
                logger.exception("client: alloc watch failed")
                if self._shutdown.wait(1.0):
                    return
                continue
            if index <= min_index:
                # Timed-out blocking query (or a stale replica that hasn't
                # caught up): the snapshot may be incomplete, and treating
                # it as authoritative would "remove" — i.e. KILL — live
                # allocations (reference: client.go:1045 skips on unchanged
                # index).
                continue
            min_index = index

            with self._alloc_lock:
                existing = {aid: r.alloc.AllocModifyIndex
                            for aid, r in self.alloc_runners.items()}
            # Only fetch allocations that changed (reference: client.go:1059).
            changed = [aid for aid, idx in id_to_index.items()
                       if existing.get(aid, -1) != idx]
            removed = [aid for aid in existing if aid not in id_to_index]
            if changed:
                try:
                    allocs = self.channel.get_allocs(changed)
                except Exception:
                    logger.exception("client: alloc fetch failed")
                    continue
                self._run_allocs(allocs)
            for aid in removed:
                self._remove_alloc(aid)

    def _run_allocs(self, allocs: List[Allocation]) -> None:
        """(reference: client.go:1127-1216 runAllocs/addAlloc/updateAlloc)"""
        for alloc in allocs:
            with self._alloc_lock:
                runner = self.alloc_runners.get(alloc.ID)
            if runner is None:
                if alloc.terminal_status():
                    continue
                runner = AllocRunner(self.config, alloc.copy(), self.node,
                                     self._on_alloc_status,
                                     service_manager=self.service_manager)
                with self._alloc_lock:
                    self.alloc_runners[alloc.ID] = runner
                threading.Thread(target=runner.run, daemon=True,
                                 name=f"alloc-{alloc.ID[:8]}").start()
            else:
                merged = alloc.copy()
                merged.TaskStates = runner.alloc.TaskStates
                merged.ClientStatus = runner.alloc.ClientStatus
                runner.update(merged)

    def _remove_alloc(self, alloc_id: str) -> None:
        with self._alloc_lock:
            runner = self.alloc_runners.pop(alloc_id, None)
        if runner is not None:
            runner.destroy()

    def _on_alloc_status(self, alloc: Allocation) -> None:
        """Queue a status update for the batched sync."""
        with self._updates_lock:
            self._alloc_updates[alloc.ID] = alloc

    def _alloc_sync_loop(self) -> None:
        """(reference: client.go:925-970 allocSync, 200ms batching)"""
        while not self._shutdown.wait(ALLOC_SYNC_INTERVAL):
            with self._updates_lock:
                if not self._alloc_updates:
                    continue
                batch = list(self._alloc_updates.values())
                self._alloc_updates.clear()
            try:
                if failpoints.fire("client.alloc_sync") == "drop":
                    raise ConnectionError("alloc sync dropped (failpoint)")
                self.channel.update_allocs(batch)
            except Exception:
                logger.exception("client: alloc sync failed; requeueing")
                with self._updates_lock:
                    for alloc in batch:
                        self._alloc_updates.setdefault(alloc.ID, alloc)

    # ------------------------------------------------------------------ api
    def get_alloc_fs(self, alloc_id: str):
        with self._alloc_lock:
            runner = self.alloc_runners.get(alloc_id)
        return runner.alloc_dir if runner is not None else None

    def alloc_stats(self, alloc_id: str) -> dict:
        """(reference: /v1/client/allocation/<id>/stats)"""
        with self._alloc_lock:
            runner = self.alloc_runners.get(alloc_id)
        if runner is None:
            raise KeyError(f"unknown allocation {alloc_id}")
        return runner.stats()

    def stats(self) -> dict:
        """Host stats (reference: client/stats/host.go)."""
        out = {"Timestamp": time.time()}
        try:
            la1, la5, la15 = os.getloadavg()
            out["CPULoad"] = {"1m": la1, "5m": la5, "15m": la15}
        except OSError:
            pass
        try:
            with open("/proc/meminfo") as f:
                mem = {}
                for line in f:
                    parts = line.split(":")
                    if parts[0] in ("MemTotal", "MemFree", "MemAvailable"):
                        mem[parts[0]] = int(parts[1].split()[0]) * 1024
            out["Memory"] = mem
        except OSError:
            pass
        try:
            import shutil as _shutil

            usage = _shutil.disk_usage(self.config.alloc_dir)
            out["DiskUsage"] = {"Total": usage.total, "Free": usage.free}
        except OSError:
            pass
        try:
            with open("/proc/uptime") as f:
                out["Uptime"] = float(f.read().split()[0])
        except OSError:
            pass
        return out
