"""AllocRunner: per-allocation supervisor (reference: client/alloc_runner.go).

Builds the AllocDir, runs one TaskRunner per task, aggregates task states
into the allocation's client status, and persists/restores runner state.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from typing import Callable, Dict, Optional

from nomad_tpu.structs import Allocation, TaskEvent, TaskState
from nomad_tpu.telemetry import trace
from nomad_tpu.structs.structs import (
    AllocClientStatusComplete,
    AllocClientStatusFailed,
    AllocClientStatusPending,
    AllocClientStatusRunning,
    TaskStateDead,
    TaskStatePending,
    TaskStateRunning,
)

from .allocdir import AllocDir
from .driver import ExecContext
from .env import TaskEnv
from .restarts import RestartTracker
from .task_runner import TaskRunner

logger = logging.getLogger("nomad.alloc_runner")


class AllocRunner:
    def __init__(self, client_config, alloc: Allocation, node,
                 on_status_change: Callable[[Allocation], None],
                 service_manager=None):
        self.config = client_config
        self.alloc = alloc
        self.node = node
        self.on_status_change = on_status_change
        self.service_manager = service_manager
        self.alloc_dir: Optional[AllocDir] = None
        self.task_runners: Dict[str, TaskRunner] = {}
        self.task_states: Dict[str, TaskState] = dict(alloc.TaskStates or {})
        self._lock = threading.Lock()
        self._destroyed = False
        from .stats import TaskStatsTracker

        self._stats_tracker = TaskStatsTracker()

    # ------------------------------------------------------------- lifecycle
    def run(self) -> None:
        """(reference: alloc_runner.go:365-464). Resumes the placing
        evaluation's trace (linked by eval id — in-process in dev mode,
        the degraded-but-correct no-op across real processes) so the
        client-side alloc/task startup joins the same trace as the
        server-side scheduling that produced it."""
        with trace.resume(trace.linked("eval", self.alloc.EvalID),
                          "client.alloc_run", alloc=self.alloc.ID,
                          job=self.alloc.JobID):
            # Task runners started below resume via the alloc id.
            trace.link("alloc", self.alloc.ID)
            self._run_inner()

    def _run_inner(self) -> None:
        tg = (self.alloc.Job.lookup_task_group(self.alloc.TaskGroup)
              if self.alloc.Job is not None else None)
        if tg is None:
            logger.error("alloc %s: task group %r not in job", self.alloc.ID,
                         self.alloc.TaskGroup)
            self._set_alloc_status(AllocClientStatusFailed,
                                   "task group missing from job")
            return

        with self._lock:
            if self._destroyed:
                return
            self.alloc_dir = AllocDir(os.path.join(self.config.alloc_dir,
                                                   self.alloc.ID))
        self.alloc_dir.build([t.Name for t in tg.Tasks])

        for task in tg.Tasks:
            task = task.copy()
            # Merge in the scheduler-assigned resources (ports!).
            assigned = self.alloc.TaskResources.get(task.Name)
            if assigned is not None:
                task.Resources = assigned
            env = TaskEnv(node=self.node, task=task, alloc=self.alloc,
                          alloc_dir=self.alloc_dir.shared_dir,
                          task_dir=os.path.join(
                              self.alloc_dir.task_dirs.get(task.Name, ""),
                              "local"))
            exec_ctx = ExecContext(alloc_dir=self.alloc_dir,
                                   alloc_id=self.alloc.ID, task_env=env)
            policy = tg.RestartPolicy
            if policy is None:
                from nomad_tpu.structs import RestartPolicy as RP

                policy = RP.for_job_type(self.alloc.Job.Type) or RP(
                    Attempts=0, Mode="fail")
            tracker = RestartTracker(policy, self.alloc.Job.Type)
            runner = TaskRunner(self.config, self.alloc, task, exec_ctx,
                                self.node, self._on_task_state, tracker)
            with self._lock:
                if self._destroyed:
                    return  # stopped while building: don't start more tasks
                self.task_runners[task.Name] = runner
            saved = self._load_handle(task.Name)
            if saved:
                runner.restore(saved)
            runner.start()

    def update(self, alloc: Allocation) -> None:
        """Server pushed a new version of the alloc (desired status)."""
        with self._lock:
            self.alloc = alloc
        if alloc.terminal_status():
            self.destroy_tasks()
            return
        self._apply_inplace_update(alloc)

    def _apply_inplace_update(self, alloc: Allocation) -> None:
        """In-place updates change non-destructive task fields (services,
        tags, checks) without restarting the task: refresh each runner's
        task definition and re-sync its registrations (reference: the
        consul syncer re-diffs on alloc updates)."""
        tg = (alloc.Job.lookup_task_group(alloc.TaskGroup)
              if alloc.Job is not None else None)
        if tg is None:
            return
        by_name = {t.Name: t for t in tg.Tasks}
        with self._lock:
            runners = dict(self.task_runners)
            states = {name: ts.State for name, ts in self.task_states.items()}
        for name, runner in runners.items():
            new_task = by_name.get(name)
            if new_task is None:
                continue
            new_task = new_task.copy()
            assigned = alloc.TaskResources.get(name)
            if assigned is not None:
                new_task.Resources = assigned
            runner.task = new_task
            if (self.service_manager is not None
                    and states.get(name) == TaskStateRunning):
                try:
                    cwd, env = self._task_check_ctx(name, runner)
                    self.service_manager.register_task(
                        alloc, new_task, cwd=cwd, env=env,
                        exec_fn=self._task_exec_fn(runner))
                except Exception:
                    logger.exception(
                        "alloc %s: service re-sync for %s failed",
                        alloc.ID, name)

    def destroy_tasks(self) -> None:
        with self._lock:
            self._destroyed = True
            runners = list(self.task_runners.values())
        for runner in runners:
            runner.destroy()

    def destroy(self) -> None:
        """Stop tasks and remove the alloc dir (GC)."""
        self.destroy_tasks()
        if self.service_manager is not None:
            self.service_manager.deregister_alloc(self.alloc.ID)
        if self.alloc_dir is not None:
            self.alloc_dir.destroy()

    # ------------------------------------------------------------ aggregation
    def stats(self) -> dict:
        """Live resource usage of this alloc's tasks
        (reference: /v1/client/allocation/<id>/stats, AllocResourceUsage)."""
        with self._lock:
            runners = dict(self.task_runners)
        # Docker containers batch into ONE `docker stats` invocation (the
        # CLI samples twice per call to compute CPU%, seconds per call).
        docker_handles = [r.handle for r in runners.values()
                          if r.handle is not None
                          and hasattr(r.handle, "container_id")]
        docker_samples: dict = {}
        if docker_handles:
            try:
                docker_samples = type(docker_handles[0]).stats_many(
                    docker_handles)
            except Exception as exc:
                logger.debug("alloc %s: docker stats sweep failed: %s",
                             self.alloc.ID, exc)
                docker_samples = {}
        tasks = {}
        agg_rss = 0
        agg_pct = 0.0
        ts = 0
        for name, runner in runners.items():
            handle = runner.handle
            if handle is None:
                continue
            try:
                if hasattr(handle, "container_id"):
                    sample = docker_samples.get(handle.container_id)
                else:
                    sample = handle.stats()
                usage = self._stats_tracker.usage(
                    f"{self.alloc.ID}/{name}", sample)
            except Exception as exc:
                logger.debug("alloc %s: stats for task %s failed: %s",
                             self.alloc.ID, name, exc)
                usage = None
            if usage is None:
                continue
            tasks[name] = usage
            agg_rss += usage["ResourceUsage"]["MemoryStats"]["RSS"]
            agg_pct += usage["ResourceUsage"]["CpuStats"]["Percent"]
            ts = max(ts, usage["Timestamp"])
        return {
            "ResourceUsage": {
                "MemoryStats": {"RSS": agg_rss, "Measured": ["RSS"]},
                "CpuStats": {"Percent": round(agg_pct, 2),
                             "Measured": ["Percent"]},
            },
            "Tasks": tasks,
            "Timestamp": ts,
        }

    def restart_task(self, task_name: str, reason: str) -> None:
        """Health-check restart: route to the task's runner."""
        with self._lock:
            runner = self.task_runners.get(task_name)
        if runner is not None:
            runner.trigger_restart(reason)

    def _on_task_state(self, task_name: str, state: str,
                       event: Optional[TaskEvent]) -> None:
        """(reference: alloc_runner.go:285-335 setTaskState/syncStatus)"""
        self._sync_services(task_name, state)
        with self._lock:
            ts = self.task_states.setdefault(task_name, TaskState())
            ts.State = state
            if event is not None:
                ts.Events.append(event)
                ts.Events = ts.Events[-10:]
            self._persist_handles()
            client_status, desc = self._alloc_status()
        self._push_status(client_status, desc)

    def _sync_services(self, task_name: str, state: str) -> None:
        """Register services when a task starts; deregister when it leaves
        the running state (restart or death)."""
        if state == TaskStateDead:
            self._stats_tracker.forget(f"{self.alloc.ID}/{task_name}")
        if self.service_manager is None:
            return
        with self._lock:
            runner = self.task_runners.get(task_name)
        if runner is None:
            return
        try:
            if state == TaskStateRunning:
                cwd, env = self._task_check_ctx(task_name, runner)
                self.service_manager.register_task(
                    self.alloc, runner.task, cwd=cwd, env=env,
                    exec_fn=self._task_exec_fn(runner))
            else:
                self.service_manager.deregister_task(self.alloc.ID, task_name)
        except Exception:
            logger.exception("alloc %s: service sync for task %s failed",
                             self.alloc.ID, task_name)

    def _task_check_ctx(self, task_name, runner):
        """cwd + env that a task's script checks should run under — the
        task's local dir and its interpolated environment."""
        env = runner.exec_ctx.task_env
        cwd = os.path.join(
            self.alloc_dir.task_dirs.get(task_name, ""), "local") \
            if self.alloc_dir is not None else None
        return cwd, env.build_env() if env is not None else None

    def _task_exec_fn(self, runner):
        """In-task script exec bound to the task's LIVE handle: resolved at
        call time (not capture time) so a restarted task's checks run in
        the new container/chroot, and a dead handle falls back to host
        execution instead of erroring."""
        def exec_fn(command, args, timeout):
            handle = runner.handle
            if handle is None:
                return None
            return handle.exec_in_task(command, args, timeout)
        return exec_fn

    def _alloc_status(self) -> tuple:
        """Aggregate task states -> alloc client status
        (reference: alloc_runner.go:253-283)."""
        pending = running = dead = failed = 0
        for ts in self.task_states.values():
            if ts.State == TaskStateRunning:
                running += 1
            elif ts.State == TaskStatePending:
                pending += 1
            elif ts.State == TaskStateDead:
                if ts.successful():
                    dead += 1
                else:
                    failed += 1
        if failed > 0:
            return AllocClientStatusFailed, "failed tasks"
        if running > 0:
            return AllocClientStatusRunning, "tasks are running"
        if pending > 0:
            return AllocClientStatusPending, "tasks are pending"
        return AllocClientStatusComplete, "all tasks have completed"

    def _set_alloc_status(self, status: str, desc: str) -> None:
        self._push_status(status, desc)

    def _push_status(self, status: str, desc: str) -> None:
        with self._lock:
            updated = self.alloc.copy()
            updated.ClientStatus = status
            updated.ClientDescription = desc
            updated.TaskStates = {k: TaskState(State=v.State,
                                               Events=list(v.Events))
                                  for k, v in self.task_states.items()}
            self.alloc = updated
        self.on_status_change(updated)

    # ------------------------------------------------------------ persistence
    def _state_path(self) -> str:
        return os.path.join(self.config.state_dir,
                            f"alloc_{self.alloc.ID}.json")

    def _persist_handles(self) -> None:
        """Persist driver handle IDs for reattach (reference:
        alloc_runner.go:105-215 + task handle persistence)."""
        try:
            os.makedirs(self.config.state_dir, exist_ok=True)
            data = {"alloc_id": self.alloc.ID,
                    "handles": {name: r.handle_id
                                for name, r in self.task_runners.items()
                                if r.handle_id}}
            tmp = self._state_path() + ".tmp"
            with open(tmp, "w") as f:
                json.dump(data, f)
            os.replace(tmp, self._state_path())
        except OSError:
            logger.exception("alloc %s: failed to persist state", self.alloc.ID)

    def _load_handle(self, task_name: str) -> str:
        try:
            with open(self._state_path()) as f:
                return json.load(f).get("handles", {}).get(task_name, "")
        except (OSError, json.JSONDecodeError):
            return ""
