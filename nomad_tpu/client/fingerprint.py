"""Node fingerprinting (reference: client/fingerprint/).

Each fingerprinter inspects the machine and fills node attributes/resources;
`fingerprint_node` runs them all. Readings come from /proc and the stdlib
(the reference shells out to gopsutil for the same data).
"""

from __future__ import annotations

import multiprocessing
import os
import platform
import shutil
import socket
import time
from typing import Callable, Dict, List

from nomad_tpu import __version__ as NOMAD_TPU_VERSION
from nomad_tpu.structs import NetworkResource, Node, Resources


def _arch(node: Node, config) -> bool:
    node.Attributes["arch"] = platform.machine() or "unknown"
    return True


def _host(node: Node, config) -> bool:
    node.Attributes["os.name"] = platform.system().lower()
    node.Attributes["kernel.name"] = platform.system().lower()
    node.Attributes["kernel.version"] = platform.release()
    node.Attributes["unique.hostname"] = socket.gethostname()
    if not node.Name:
        node.Name = socket.gethostname()
    return True


def _cpu(node: Node, config) -> bool:
    cores = multiprocessing.cpu_count()
    mhz = 1000.0
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.lower().startswith("cpu mhz"):
                    mhz = float(line.split(":")[1])
                    break
                if line.lower().startswith("bogomips"):
                    mhz = float(line.split(":")[1]) / 2
    except OSError:
        pass
    node.Attributes["cpu.numcores"] = str(cores)
    node.Attributes["cpu.frequency"] = f"{mhz:.0f}"
    total = int(cores * mhz)
    node.Attributes["cpu.totalcompute"] = str(total)
    if node.Resources is None:
        node.Resources = Resources()
    if node.Resources.CPU == 0:
        node.Resources.CPU = total
    return True


def _memory(node: Node, config) -> bool:
    total_mb = 0
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total_mb = int(line.split()[1]) // 1024
                    break
    except OSError:
        return False
    node.Attributes["memory.totalbytes"] = str(total_mb * 1024 * 1024)
    if node.Resources is None:
        node.Resources = Resources()
    if node.Resources.MemoryMB == 0:
        node.Resources.MemoryMB = total_mb
    return True


def _storage(node: Node, config) -> bool:
    path = getattr(config, "alloc_dir", None) or "/tmp"
    # The alloc dir may not exist yet at fingerprint time: measure the
    # closest existing ancestor (same filesystem).
    probe = path
    while probe and not os.path.exists(probe):
        parent = os.path.dirname(probe)
        if parent == probe:
            break
        probe = parent
    try:
        usage = shutil.disk_usage(probe or "/")
    except OSError:
        return False
    node.Attributes["unique.storage.volume"] = path
    node.Attributes["unique.storage.bytestotal"] = str(usage.total)
    node.Attributes["unique.storage.bytesfree"] = str(usage.free)
    if node.Resources is None:
        node.Resources = Resources()
    if node.Resources.DiskMB == 0:
        node.Resources.DiskMB = usage.free // (1024 * 1024)
    return True


def _network(node: Node, config) -> bool:
    ip = _default_ip()
    if ip is None:
        return False
    node.Attributes["unique.network.ip-address"] = ip
    if node.Resources is None:
        node.Resources = Resources()
    if not node.Resources.Networks:
        speed = getattr(config, "network_speed", 0) or 1000
        node.Resources.Networks.append(NetworkResource(
            Device="eth0", CIDR=f"{ip}/32", IP=ip, MBits=speed))
    return True


def _default_ip() -> str:
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("10.255.255.255", 1))
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        return "127.0.0.1"


def _nomad(node: Node, config) -> bool:
    node.Attributes["nomad.version"] = NOMAD_TPU_VERSION
    return True


def _cgroup(node: Node, config) -> bool:
    for path in ("/sys/fs/cgroup/cgroup.controllers", "/sys/fs/cgroup/memory"):
        if os.path.exists(path):
            node.Attributes["unique.cgroup.mountpoint"] = "/sys/fs/cgroup"
            return True
    return False


BUILTIN_FINGERPRINTERS: List[Callable] = [
    _arch, _host, _cpu, _memory, _storage, _network, _nomad, _cgroup,
]


def fingerprint_node(node: Node, config=None) -> Dict[str, bool]:
    """Run all fingerprinters; returns name -> applied."""
    results = {}
    for fp in BUILTIN_FINGERPRINTERS:
        name = fp.__name__.lstrip("_")
        try:
            results[name] = bool(fp(node, config))
        except Exception:
            results[name] = False
    return results
