"""Node fingerprinting (reference: client/fingerprint/).

Each fingerprinter inspects the machine and fills node attributes/resources;
`fingerprint_node` runs them all. Readings come from /proc and the stdlib
(the reference shells out to gopsutil for the same data).
"""

from __future__ import annotations

import multiprocessing
import os
import platform
import shutil
import socket
import time
from typing import Callable, Dict, List

from nomad_tpu import __version__ as NOMAD_TPU_VERSION
from nomad_tpu.structs import NetworkResource, Node, Resources


def _arch(node: Node, config) -> bool:
    node.Attributes["arch"] = platform.machine() or "unknown"
    return True


def _host(node: Node, config) -> bool:
    node.Attributes["os.name"] = platform.system().lower()
    node.Attributes["kernel.name"] = platform.system().lower()
    node.Attributes["kernel.version"] = platform.release()
    node.Attributes["unique.hostname"] = socket.gethostname()
    if not node.Name:
        node.Name = socket.gethostname()
    return True


def _cpu(node: Node, config) -> bool:
    cores = multiprocessing.cpu_count()
    mhz = 1000.0
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.lower().startswith("cpu mhz"):
                    mhz = float(line.split(":")[1])
                    break
                if line.lower().startswith("bogomips"):
                    mhz = float(line.split(":")[1]) / 2
    except OSError:
        pass
    node.Attributes["cpu.numcores"] = str(cores)
    node.Attributes["cpu.frequency"] = f"{mhz:.0f}"
    total = int(cores * mhz)
    node.Attributes["cpu.totalcompute"] = str(total)
    if node.Resources is None:
        node.Resources = Resources()
    if node.Resources.CPU == 0:
        node.Resources.CPU = total
    return True


def _memory(node: Node, config) -> bool:
    total_mb = 0
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total_mb = int(line.split()[1]) // 1024
                    break
    except OSError:
        return False
    node.Attributes["memory.totalbytes"] = str(total_mb * 1024 * 1024)
    if node.Resources is None:
        node.Resources = Resources()
    if node.Resources.MemoryMB == 0:
        node.Resources.MemoryMB = total_mb
    return True


def _storage(node: Node, config) -> bool:
    path = getattr(config, "alloc_dir", None) or "/tmp"
    # The alloc dir may not exist yet at fingerprint time: measure the
    # closest existing ancestor (same filesystem).
    probe = path
    while probe and not os.path.exists(probe):
        parent = os.path.dirname(probe)
        if parent == probe:
            break
        probe = parent
    try:
        usage = shutil.disk_usage(probe or "/")
    except OSError:
        return False
    node.Attributes["unique.storage.volume"] = path
    node.Attributes["unique.storage.bytestotal"] = str(usage.total)
    node.Attributes["unique.storage.bytesfree"] = str(usage.free)
    if node.Resources is None:
        node.Resources = Resources()
    if node.Resources.DiskMB == 0:
        node.Resources.DiskMB = usage.free // (1024 * 1024)
    return True


def _network(node: Node, config) -> bool:
    ip = _default_ip()
    if ip is None:
        return False
    node.Attributes["unique.network.ip-address"] = ip
    if node.Resources is None:
        node.Resources = Resources()
    if not node.Resources.Networks:
        speed = getattr(config, "network_speed", 0) or 1000
        node.Resources.Networks.append(NetworkResource(
            Device="eth0", CIDR=f"{ip}/32", IP=ip, MBits=speed))
    return True


def _default_ip() -> str:
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("10.255.255.255", 1))
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        return "127.0.0.1"


def _nomad(node: Node, config) -> bool:
    node.Attributes["nomad.version"] = NOMAD_TPU_VERSION
    return True


def _cgroup(node: Node, config) -> bool:
    for path in ("/sys/fs/cgroup/cgroup.controllers", "/sys/fs/cgroup/memory"):
        if os.path.exists(path):
            node.Attributes["unique.cgroup.mountpoint"] = "/sys/fs/cgroup"
            return True
    return False


_AWS_KEYS = (
    # (metadata path, unique)  (reference: fingerprint/env_aws.go:87-98)
    ("ami-id", False),
    ("instance-id", True),
    ("instance-type", False),
    ("local-hostname", True),
    ("local-ipv4", True),
    ("public-hostname", True),
    ("public-ipv4", True),
    ("placement/availability-zone", False),
)


def _metadata_get(url: str, timeout: float = 0.5,
                  headers: Dict[str, str] = None) -> str:
    import urllib.request

    req = urllib.request.Request(url, headers=headers or {})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.read().decode().strip()


def _env_aws(node: Node, config) -> bool:
    """EC2 metadata service (reference: fingerprint/env_aws.go). The base
    URL is overridable (client option / env var) so tests and non-standard
    environments can point it at a mock."""
    base = ((config.read_option("fingerprint.env_aws.url")
             if config is not None else "")
            or os.environ.get("NOMAD_TPU_AWS_METADATA_URL", ""))
    explicit = bool(base)
    base = base or "http://169.254.169.254/latest/meta-data/"
    if not base.endswith("/"):
        base += "/"
    # IMDSv2 (token-required is the EC2 launch default now): try for a
    # session token; fall back to v1-style unauthenticated GETs.
    headers: Dict[str, str] = {}
    try:
        import urllib.request

        token_url = base.split("/latest/")[0] + "/latest/api/token"
        req = urllib.request.Request(
            token_url, method="PUT",
            headers={"X-aws-ec2-metadata-token-ttl-seconds": "300"})
        with urllib.request.urlopen(req, timeout=0.3) as resp:
            headers = {"X-aws-ec2-metadata-token":
                       resp.read().decode().strip()}
    except Exception:
        pass
    try:
        _metadata_get(base + "ami-id", timeout=2.0 if explicit else 0.3,
                      headers=headers)
    except Exception:
        return False  # not on EC2 (reference: isAWS probe)
    for key, unique in _AWS_KEYS:
        try:
            value = _metadata_get(base + key, headers=headers)
        except Exception:
            continue
        attr = key.replace("/", ".")
        prefix = "unique.platform.aws." if unique else "platform.aws."
        node.Attributes[f"{prefix}{attr}"] = value
    instance = node.Attributes.get("unique.platform.aws.instance-id")
    zone = node.Attributes.get("platform.aws.placement.availability-zone")
    if instance and zone:
        node.Links["aws.ec2"] = f"{zone}.{instance}"
    return True


_GCE_KEYS = (
    ("instance/id", True),
    ("instance/machine-type", False),
    ("instance/zone", False),
    ("instance/hostname", True),
)


def _env_gce(node: Node, config) -> bool:
    """GCE metadata service (reference: fingerprint/env_gce.go); requires
    the Metadata-Flavor header."""
    base = ((config.read_option("fingerprint.env_gce.url")
             if config is not None else "")
            or os.environ.get("NOMAD_TPU_GCE_METADATA_URL", ""))
    explicit = bool(base)
    base = base or "http://169.254.169.254/computeMetadata/v1/"
    if not base.endswith("/"):
        base += "/"
    headers = {"Metadata-Flavor": "Google"}
    try:
        _metadata_get(base + "instance/id",
                      timeout=2.0 if explicit else 0.3, headers=headers)
    except Exception:
        return False
    for key, unique in _GCE_KEYS:
        try:
            value = _metadata_get(base + key, headers=headers)
        except Exception:
            continue
        # zone/machine-type come as full resource paths; keep the leaf.
        value = value.rsplit("/", 1)[-1]
        attr = key.split("/", 1)[1].replace("/", ".")
        prefix = "unique.platform.gce." if unique else "platform.gce."
        node.Attributes[f"{prefix}{attr}"] = value
    instance = node.Attributes.get("unique.platform.gce.id")
    zone = node.Attributes.get("platform.gce.zone")
    if instance and zone:
        node.Links["gce"] = f"{zone}.{instance}"
    return True


BUILTIN_FINGERPRINTERS: List[Callable] = [
    _arch, _host, _cpu, _memory, _storage, _network, _nomad, _cgroup,
    _env_aws, _env_gce,
]

# Fingerprinters whose readings drift and are re-run on the client's
# fingerprint.period interval (reference: Fingerprint.Periodic(),
# client/fingerprint/fingerprint.go:68-77 + client.go fingerprintPeriodic).
PERIODIC_FINGERPRINTERS = frozenset({"storage", "network"})


def fingerprint_node(node: Node, config=None) -> Dict[str, bool]:
    """Run all fingerprinters; returns name -> applied."""
    results = {}
    for fp in BUILTIN_FINGERPRINTERS:
        name = fp.__name__.lstrip("_")
        try:
            results[name] = bool(fp(node, config))
        except Exception:
            results[name] = False
    return results


def run_periodic_fingerprints(node: Node, config=None) -> bool:
    """Re-run the periodic fingerprinters; mutates node and returns True
    when something MATERIAL changed (free-space drift under 10% doesn't
    count — a node update is a consensus write, so continuous readings
    must not re-register every node every period)."""
    before = dict(node.Attributes)
    for fp in BUILTIN_FINGERPRINTERS:
        if fp.__name__.lstrip("_") in PERIODIC_FINGERPRINTERS:
            try:
                fp(node, config)
            except Exception:
                pass
    for key in set(before) | set(node.Attributes):
        old, new = before.get(key), node.Attributes.get(key)
        if old == new:
            continue
        if key == "unique.storage.bytesfree" and old and new:
            try:
                if abs(int(new) - int(old)) < 0.1 * int(old):
                    node.Attributes[key] = old  # suppress minor drift
                    continue
            except ValueError:
                pass
        return True
    return False
