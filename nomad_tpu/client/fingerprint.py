"""Node fingerprinting (reference: client/fingerprint/).

Each fingerprinter inspects the machine and fills node attributes/resources;
`fingerprint_node` runs them all. Readings come from /proc and the stdlib
(the reference shells out to gopsutil for the same data).
"""

from __future__ import annotations

import multiprocessing
import os
import platform
import shutil
import socket
import time
from typing import Callable, Dict, List

from nomad_tpu import __version__ as NOMAD_TPU_VERSION
from nomad_tpu.structs import NetworkResource, Node, Resources


def _arch(node: Node, config) -> bool:
    node.Attributes["arch"] = platform.machine() or "unknown"
    return True


def _host(node: Node, config) -> bool:
    node.Attributes["os.name"] = platform.system().lower()
    node.Attributes["kernel.name"] = platform.system().lower()
    node.Attributes["kernel.version"] = platform.release()
    node.Attributes["unique.hostname"] = socket.gethostname()
    if not node.Name:
        node.Name = socket.gethostname()
    return True


def _cpu(node: Node, config) -> bool:
    cores = multiprocessing.cpu_count()
    mhz = 1000.0
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.lower().startswith("cpu mhz"):
                    mhz = float(line.split(":")[1])
                    break
                if line.lower().startswith("bogomips"):
                    mhz = float(line.split(":")[1]) / 2
    except OSError:
        pass
    node.Attributes["cpu.numcores"] = str(cores)
    node.Attributes["cpu.frequency"] = f"{mhz:.0f}"
    total = int(cores * mhz)
    node.Attributes["cpu.totalcompute"] = str(total)
    if node.Resources is None:
        node.Resources = Resources()
    if node.Resources.CPU == 0:
        node.Resources.CPU = total
    return True


def _memory(node: Node, config) -> bool:
    total_mb = 0
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total_mb = int(line.split()[1]) // 1024
                    break
    except OSError:
        return False
    node.Attributes["memory.totalbytes"] = str(total_mb * 1024 * 1024)
    if node.Resources is None:
        node.Resources = Resources()
    if node.Resources.MemoryMB == 0:
        node.Resources.MemoryMB = total_mb
    return True


def _storage(node: Node, config) -> bool:
    path = getattr(config, "alloc_dir", None) or "/tmp"
    # The alloc dir may not exist yet at fingerprint time: measure the
    # closest existing ancestor (same filesystem).
    probe = path
    while probe and not os.path.exists(probe):
        parent = os.path.dirname(probe)
        if parent == probe:
            break
        probe = parent
    try:
        usage = shutil.disk_usage(probe or "/")
    except OSError:
        return False
    node.Attributes["unique.storage.volume"] = path
    node.Attributes["unique.storage.bytestotal"] = str(usage.total)
    node.Attributes["unique.storage.bytesfree"] = str(usage.free)
    if node.Resources is None:
        node.Resources = Resources()
    if node.Resources.DiskMB == 0:
        node.Resources.DiskMB = usage.free // (1024 * 1024)
    return True


def _network(node: Node, config) -> bool:
    ip = _default_ip()
    if ip is None:
        return False
    node.Attributes["unique.network.ip-address"] = ip
    if node.Resources is None:
        node.Resources = Resources()
    if not node.Resources.Networks:
        speed = getattr(config, "network_speed", 0) or 1000
        node.Resources.Networks.append(NetworkResource(
            Device="eth0", CIDR=f"{ip}/32", IP=ip, MBits=speed))
    else:
        # Periodic re-run after an IP change: the advertised attribute and
        # the schedulable network resource must agree.
        net = node.Resources.Networks[0]
        if net.IP != ip:
            net.IP = ip
            net.CIDR = f"{ip}/32"
    return True


def _default_ip() -> str:
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("10.255.255.255", 1))
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        return "127.0.0.1"


def _nomad(node: Node, config) -> bool:
    node.Attributes["nomad.version"] = NOMAD_TPU_VERSION
    return True


def _cgroup(node: Node, config) -> bool:
    for path in ("/sys/fs/cgroup/cgroup.controllers", "/sys/fs/cgroup/memory"):
        if os.path.exists(path):
            node.Attributes["unique.cgroup.mountpoint"] = "/sys/fs/cgroup"
            return True
    return False


_AWS_KEYS = (
    # (metadata path, unique)  (reference: fingerprint/env_aws.go:87-98)
    ("ami-id", False),
    ("instance-id", True),
    ("instance-type", False),
    ("local-hostname", True),
    ("local-ipv4", True),
    ("public-hostname", True),
    ("public-ipv4", True),
    ("placement/availability-zone", False),
)


def _metadata_get(url: str, timeout: float = 0.5,
                  headers: Dict[str, str] = None) -> str:
    import urllib.request

    req = urllib.request.Request(url, headers=headers or {})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.read().decode().strip()


def _env_metadata_fingerprint(node: Node, config, *, option_key: str,
                              env_var: str, default_base: str,
                              probe: str, keys, headers: Dict[str, str],
                              platform_name: str,
                              attr_of: Callable[[str], str],
                              value_of: Callable[[str], str],
                              link_name: str, link_keys) -> bool:
    """Shared cloud-metadata scaffolding: resolve the (overridable) base
    URL, probe once to detect the platform, then fetch each key into
    platform.<name>.* attributes and assemble the node Link."""
    base = ((config.read_option(option_key)
             if config is not None else "")
            or os.environ.get(env_var, ""))
    explicit = bool(base)
    base = base or default_base
    if not base.endswith("/"):
        base += "/"
    try:
        _metadata_get(base + probe, timeout=2.0 if explicit else 0.3,
                      headers=headers)
    # lint: allow(swallow, metadata probes fail normally off-platform)
    except Exception:
        return False  # not on this platform
    for key, unique in keys:
        try:
            value = value_of(_metadata_get(base + key, headers=headers))
        # lint: allow(swallow, a missing metadata key is a normal partial set)
        except Exception:
            continue
        prefix = (f"unique.platform.{platform_name}." if unique
                  else f"platform.{platform_name}.")
        node.Attributes[f"{prefix}{attr_of(key)}"] = value
    parts = [node.Attributes.get(k) for k in link_keys]
    if all(parts):
        node.Links[link_name] = ".".join(parts)
    return True


def _env_aws(node: Node, config) -> bool:
    """EC2 metadata service (reference: fingerprint/env_aws.go). The base
    URL is overridable (client option / env var) so tests and non-standard
    environments can point it at a mock."""
    # IMDSv2 (token-required is the EC2 launch default now): try for a
    # session token; fall back to v1-style unauthenticated GETs. The token
    # URL derives from the same (overridable) base so mocks stay in charge.
    base = ((config.read_option("fingerprint.env_aws.url")
             if config is not None else "")
            or os.environ.get("NOMAD_TPU_AWS_METADATA_URL", "")
            or "http://169.254.169.254/latest/meta-data/")
    headers: Dict[str, str] = {}
    try:
        import urllib.parse as _parse
        import urllib.request

        root = _parse.urlsplit(base)
        token_url = f"{root.scheme}://{root.netloc}/latest/api/token"
        req = urllib.request.Request(
            token_url, method="PUT",
            headers={"X-aws-ec2-metadata-token-ttl-seconds": "300"})
        with urllib.request.urlopen(req, timeout=0.3) as resp:
            headers = {"X-aws-ec2-metadata-token":
                       resp.read().decode().strip()}
    # lint: allow(swallow, IMDSv1 fallback when the token endpoint is absent)
    except Exception:
        pass
    return _env_metadata_fingerprint(
        node, config, option_key="fingerprint.env_aws.url",
        env_var="NOMAD_TPU_AWS_METADATA_URL",
        default_base="http://169.254.169.254/latest/meta-data/",
        probe="ami-id", keys=_AWS_KEYS, headers=headers,
        platform_name="aws",
        attr_of=lambda key: key.replace("/", "."),
        value_of=lambda v: v,
        link_name="aws.ec2",
        link_keys=("platform.aws.placement.availability-zone",
                   "unique.platform.aws.instance-id"))


_GCE_KEYS = (
    ("instance/id", True),
    ("instance/machine-type", False),
    ("instance/zone", False),
    ("instance/hostname", True),
)


def _env_gce(node: Node, config) -> bool:
    """GCE metadata service (reference: fingerprint/env_gce.go); requires
    the Metadata-Flavor header. zone/machine-type come as full resource
    paths; only the leaf is kept."""
    return _env_metadata_fingerprint(
        node, config, option_key="fingerprint.env_gce.url",
        env_var="NOMAD_TPU_GCE_METADATA_URL",
        default_base="http://169.254.169.254/computeMetadata/v1/",
        probe="instance/id", keys=_GCE_KEYS,
        headers={"Metadata-Flavor": "Google"},
        platform_name="gce",
        attr_of=lambda key: key.split("/", 1)[1].replace("/", "."),
        value_of=lambda v: v.rsplit("/", 1)[-1],
        link_name="gce",
        link_keys=("platform.gce.zone", "unique.platform.gce.id"))


BUILTIN_FINGERPRINTERS: List[Callable] = [
    _arch, _host, _cpu, _memory, _storage, _network, _nomad, _cgroup,
    _env_aws, _env_gce,
]

# Fingerprinters whose readings drift and are re-run on the client's
# fingerprint.period interval (reference: Fingerprint.Periodic(),
# client/fingerprint/fingerprint.go:68-77 + client.go fingerprintPeriodic).
PERIODIC_FINGERPRINTERS = frozenset({"storage", "network"})


def fingerprint_node(node: Node, config=None) -> Dict[str, bool]:
    """Run all fingerprinters; returns name -> applied."""
    results = {}
    for fp in BUILTIN_FINGERPRINTERS:
        name = fp.__name__.lstrip("_")
        try:
            results[name] = bool(fp(node, config))
        # lint: allow(swallow, a crashed fingerprinter records as not-detected)
        except Exception:
            results[name] = False
    return results


def run_periodic_fingerprints(node: Node, config=None) -> bool:
    """Re-run the periodic fingerprinters; mutates node and returns True
    when something MATERIAL changed (free-space drift under 10% doesn't
    count — a node update is a consensus write, so continuous readings
    must not re-register every node every period)."""
    before = dict(node.Attributes)
    for fp in BUILTIN_FINGERPRINTERS:
        if fp.__name__.lstrip("_") in PERIODIC_FINGERPRINTERS:
            try:
                fp(node, config)
            # lint: allow(swallow, a crashed fingerprinter keeps old attrs)
            except Exception:
                pass
    for key in set(before) | set(node.Attributes):
        old, new = before.get(key), node.Attributes.get(key)
        if old == new:
            continue
        if key == "unique.storage.bytesfree" and old and new:
            try:
                if abs(int(new) - int(old)) < 0.1 * int(old):
                    node.Attributes[key] = old  # suppress minor drift
                    continue
            except ValueError:
                pass
        return True
    return False
