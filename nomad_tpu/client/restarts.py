"""Restart policy tracking (reference: client/restarts.go).

Decides whether and when to restart an exited task based on the task group's
RestartPolicy, with jitter, interval windows, and the delay-vs-fail modes.
"""

from __future__ import annotations

import random
import time
from typing import Optional, Tuple

from nomad_tpu.structs import RestartPolicy
from nomad_tpu.structs.structs import (
    JobTypeBatch,
    JobTypeService,
    RestartPolicyModeDelay,
    RestartPolicyModeFail,
    ns_to_seconds,
)

# Decisions (reference: restarts.go:14-21)
NO_RESTART = "no-restart"
RESTART_WAIT = "restart-wait"


class RestartTracker:
    def __init__(self, policy: RestartPolicy, job_type: str,
                 rng: Optional[random.Random] = None):
        self.policy = policy
        self.job_type = job_type
        self.rng = rng or random.Random()
        self.count = 0
        self.start_time = 0.0
        self._wait_time = 0.0
        self._last_exit_success = False

    def set_policy(self, policy: RestartPolicy) -> None:
        self.policy = policy

    def next_restart(self, exit_code: int) -> Tuple[str, float]:
        """Decide (decision, wait_seconds) for an exited task
        (reference: restarts.go:85-147 GetState)."""
        now = time.time()
        # Batch jobs that exited cleanly don't restart.
        if self.job_type == JobTypeBatch and exit_code == 0:
            return NO_RESTART, 0.0

        interval = ns_to_seconds(self.policy.Interval)
        if self.start_time == 0.0 or (interval > 0
                                      and now - self.start_time > interval):
            # New interval window.
            self.start_time = now
            self.count = 0

        self.count += 1
        if self.policy.Attempts > 0 and self.count <= self.policy.Attempts:
            return RESTART_WAIT, self._jitter()

        # Attempts exhausted within the interval.
        if self.policy.Mode == RestartPolicyModeFail:
            return NO_RESTART, 0.0
        if self.policy.Mode == RestartPolicyModeDelay:
            # Wait until the interval rolls over, then restart.
            remaining = max(0.0, (self.start_time + interval) - now)
            self.count = 0
            self.start_time = now + remaining
            return RESTART_WAIT, remaining + self._jitter()
        return NO_RESTART, 0.0

    def _jitter(self) -> float:
        """Delay +/- 25% jitter (reference: restarts.go:150-156)."""
        delay = ns_to_seconds(self.policy.Delay)
        if delay <= 0:
            return 0.0
        return delay + self.rng.random() * delay * 0.25
