"""Task resource usage sampling (reference: client/driver/executor/
executor.go:36-41 pid collection + client/stats/host.go).

The executor's task runs in its own process group; usage is sampled by
walking /proc and aggregating over the group's pid tree (utime/stime ticks,
RSS). CPU percent needs two samples — TaskStatsTracker keeps the previous
tick counts per task and computes deltas against wall time.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Tuple

_CLK_TCK = os.sysconf("SC_CLK_TCK") if hasattr(os, "sysconf") else 100
_PAGE_SIZE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


def sample_pid_tree(pgid: int) -> Tuple[List[int], float, float, int]:
    """Walk /proc for processes in group `pgid`; returns
    (pids, user_seconds_total, system_seconds_total, rss_bytes_total)."""
    pids: List[int] = []
    utime = stime = 0.0
    rss = 0
    try:
        entries = os.listdir("/proc")
    except OSError:
        return pids, 0.0, 0.0, 0
    for entry in entries:
        if not entry.isdigit():
            continue
        try:
            with open(f"/proc/{entry}/stat", "rb") as f:
                raw = f.read().decode("ascii", "replace")
        except OSError:
            continue
        # Field 2 (comm) may contain spaces/parens: split after the last ')'.
        rparen = raw.rfind(")")
        fields = raw[rparen + 2:].split()
        # After comm: state(0) ppid(1) pgrp(2) ... utime(11) stime(12)
        # ... rss(21) — indexes relative to the post-comm split.
        try:
            if int(fields[2]) != pgid:
                continue
            pids.append(int(entry))
            utime += int(fields[11]) / _CLK_TCK
            stime += int(fields[12]) / _CLK_TCK
            rss += int(fields[21]) * _PAGE_SIZE
        except (IndexError, ValueError):
            continue
    return pids, utime, stime, rss


class TaskStatsTracker:
    """Computes per-task ResourceUsage payloads with CPU percent from
    consecutive samples (reference shape: api/nodes.go TaskResourceUsage)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._prev: Dict[str, Tuple[float, float, float]] = {}

    def usage(self, key: str, sample: Optional[dict]) -> Optional[dict]:
        """sample: raw dict from DriverHandle.stats(); returns the usage
        payload or None when the task has no live stats."""
        if sample is None:
            return None
        now = time.time()
        if "cpu_percent" in sample:
            # Driver supplied a ready-made percent (docker stats).
            percent = float(sample["cpu_percent"])
            user = system = 0.0
        else:
            user = float(sample.get("user_seconds", 0.0))
            system = float(sample.get("system_seconds", 0.0))
            with self._lock:
                prev = self._prev.get(key)
                self._prev[key] = (now, user, system)
            percent = 0.0
            if prev is not None:
                dt = now - prev[0]
                if dt > 0:
                    percent = max(
                        0.0, ((user - prev[1]) + (system - prev[2])) / dt
                        * 100.0)
        return {
            "Timestamp": int(now * 1e9),
            "Pids": sample.get("pids", []),
            "ResourceUsage": {
                "MemoryStats": {
                    "RSS": int(sample.get("rss_bytes", 0)),
                    "Measured": ["RSS"],
                },
                "CpuStats": {
                    "Percent": round(percent, 2),
                    "UserMode": round(user, 3),
                    "SystemMode": round(system, 3),
                    "Measured": ["Percent", "User Mode", "System Mode"],
                },
            },
        }

    def forget(self, key: str) -> None:
        with self._lock:
            self._prev.pop(key, None)
