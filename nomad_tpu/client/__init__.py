"""Client agent: the node runtime (reference: client/).

Fingerprints the machine, registers with servers, heartbeats, watches for
allocations via blocking queries, and runs them through alloc/task runners
with pluggable drivers. Task execution happens in a detached executor
process so an agent restart never kills tasks (reference re-exec design:
client/driver/plugins.go, executor/).
"""

from .client import Client, ClientConfig  # noqa: F401
from .rpc import InProcServerChannel, ServerChannel  # noqa: F401
