"""Command line interface (reference: command/, commands.go)."""

from .commands import main  # noqa: F401
