"""CLI entry: python -m nomad_tpu.cli <command> (reference: main.go)."""

import sys

from .commands import main

if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
