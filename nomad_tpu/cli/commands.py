"""CLI commands (reference: command/ — agent, run, status, stop, node-status,
node-drain, alloc-status, eval-status, validate, init, inspect, fs,
server-members, agent-info, system gc).

`run` parses the HCL spec, registers, and monitors the evaluation to
completion (reference: command/run.go + command/monitor.go).
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from nomad_tpu.api import APIError, Client, QueryOptions


def _client(args) -> Client:
    return Client(address=args.address, region=args.region or "")


def _resolve_prefix(kind: str, given: str, list_fn) -> str:
    """Short-ID UX (reference: every command/*.go resolves id prefixes via
    the list endpoint's ?prefix=): a unique prefix resolves to the full
    ID; ambiguity lists the matches and aborts."""
    if len(given) >= 36:  # full UUID
        return given
    matches, _ = list_fn(QueryOptions(prefix=given))
    # Re-check client-side: a server that ignored ?prefix= (or an older
    # one) must fail safe instead of resolving to a wrong ID.
    ids = [m["ID"] for m in matches if m["ID"].startswith(given)]
    if len(ids) == 1:
        return ids[0]
    if not ids:
        print(f"No {kind} found with prefix {given!r}", file=sys.stderr)
    else:
        print(f"Prefix {given!r} matched multiple {kind}s:", file=sys.stderr)
        for i in ids:
            print(f"  {i}", file=sys.stderr)
    raise SystemExit(1)


def _add_meta(p: argparse.ArgumentParser) -> None:
    p.add_argument("-address", default="http://127.0.0.1:4646",
                   help="HTTP API address")
    p.add_argument("-region", default="", help="region to forward to")


def main(argv) -> int:
    parser = argparse.ArgumentParser(
        prog="nomad-tpu", description="TPU-native cluster scheduler")
    sub = parser.add_subparsers(dest="command")

    p = sub.add_parser("agent", help="run an agent")
    p.add_argument("-dev", action="store_true", help="dev mode: server+client")
    p.add_argument("-server", action="store_true")
    p.add_argument("-client", action="store_true")
    p.add_argument("-config", default="", help="HCL/JSON config file")
    # Defaults are None so config-file settings win unless a flag is given.
    p.add_argument("-bind", default=None)
    p.add_argument("-http-port", type=int, default=None)
    p.add_argument("-data-dir", default=None)
    p.add_argument("-node-class", default=None)
    p.add_argument("-dc", default=None)
    p.add_argument("-region", default=None)
    p.add_argument("-rpc-port", type=int, default=None)
    p.add_argument("-serf-port", type=int, default=None)
    p.add_argument("-bootstrap-expect", type=int, default=None)
    p.add_argument("-join", action="append", default=None,
                   help="gossip address of an existing server (repeatable)")
    p.add_argument("-servers", default=None,
                   help="comma-separated server RPC addrs (client mode)")

    p = sub.add_parser("run", help="run a job")
    _add_meta(p)
    p.add_argument("-detach", action="store_true")
    p.add_argument("-output", action="store_true",
                   help="print the JSON job instead of submitting")
    p.add_argument("-check-index", type=int, default=None)
    p.add_argument("jobfile")

    p = sub.add_parser("plan", help="dry-run a job diff")
    _add_meta(p)
    p.add_argument("jobfile")

    p = sub.add_parser("validate", help="validate a job spec")
    p.add_argument("jobfile")

    p = sub.add_parser("init", help="write an example job file")

    p = sub.add_parser("status", help="job status")
    _add_meta(p)
    p.add_argument("job_id", nargs="?")

    p = sub.add_parser("stop", help="stop a job")
    _add_meta(p)
    p.add_argument("-detach", action="store_true")
    p.add_argument("job_id")

    p = sub.add_parser("inspect", help="print a registered job as JSON")
    _add_meta(p)
    p.add_argument("job_id")

    p = sub.add_parser("node-status", help="node status")
    _add_meta(p)
    p.add_argument("node_id", nargs="?")

    p = sub.add_parser("node-drain", help="toggle node drain")
    _add_meta(p)
    grp = p.add_mutually_exclusive_group(required=True)
    grp.add_argument("-enable", action="store_true")
    grp.add_argument("-disable", action="store_true")
    p.add_argument("node_id")

    p = sub.add_parser("alloc-status", help="allocation status")
    _add_meta(p)
    p.add_argument("alloc_id")

    p = sub.add_parser("eval-status", help="evaluation status")
    _add_meta(p)
    p.add_argument("eval_id")

    p = sub.add_parser("fs", help="inspect an allocation's filesystem")
    _add_meta(p)
    p.add_argument("alloc_id")
    p.add_argument("path", nargs="?", default="/")
    p.add_argument("-stat", action="store_true")
    p.add_argument("-cat", action="store_true")

    p = sub.add_parser("server-members", help="server membership")
    _add_meta(p)

    p = sub.add_parser("join", help="join the agent's gossip pool to servers")
    _add_meta(p)
    p.add_argument("addresses", nargs="+",
                   help="gossip host:port of servers to join")

    p = sub.add_parser("force-leave",
                       help="force a gossip member into the left state")
    _add_meta(p)
    p.add_argument("node", help="gossip member name (e.g. host.region)")

    p = sub.add_parser("agent-info", help="agent self info")
    _add_meta(p)

    p = sub.add_parser("faults",
                       help="inspect/arm fault-injection failpoints "
                            "(needs enable_debug on the agent)")
    p.add_argument("spec", nargs="?", default="",
                   help="failpoint spec, e.g. "
                        "'raft.fsync=error:count=5;gossip.send=drop'; "
                        "omit to list sites")
    p.add_argument("--disarm-all", action="store_true",
                   help="heal every armed failpoint")
    _add_meta(p)

    p = sub.add_parser("sched-stats",
                       help="scheduling-pipeline stage timers/counters "
                            "(needs enable_debug on the agent)")
    p.add_argument("-json", action="store_true",
                   help="print the raw JSON payload")
    _add_meta(p)

    p = sub.add_parser("trace",
                       help="evaluation-lifecycle traces "
                            "(needs enable_debug on the agent)")
    p.add_argument("trace_id", nargs="?", default="",
                   help="trace id (or unique prefix) to show; omit to list")
    p.add_argument("-enable", action="store_true",
                   help="turn tracing on")
    p.add_argument("-disable", action="store_true",
                   help="turn tracing off")
    p.add_argument("-ratio", type=float, default=None,
                   help="head-sampling ratio in [0,1] (with -enable)")
    p.add_argument("-export", metavar="FILE", default="",
                   help="write Chrome trace-event JSON (the given trace, "
                        "or all retained ones) for Perfetto")
    p.add_argument("-clear", action="store_true",
                   help="drop all collected traces")
    p.add_argument("-json", action="store_true",
                   help="print the raw JSON payload")
    _add_meta(p)

    p = sub.add_parser("system-gc", help="force garbage collection")
    _add_meta(p)

    p = sub.add_parser("services", help="list registered services")
    _add_meta(p)
    p.add_argument("name", nargs="?",
                   help="show instances of one service")

    p = sub.add_parser("events",
                       help="follow the cluster event stream")
    _add_meta(p)
    p.add_argument("-topic", action="append", default=None,
                   help="Topic or Topic:key filter (repeatable; "
                        "default: all topics)")
    p.add_argument("-index", type=int, default=0,
                   help="resume after this raft index (default 0: "
                        "replay the full retained window, then follow)")
    p.add_argument("-fanout", action="store_true",
                   help="expand AllocationBatch events into per-alloc "
                        "AllocPlaced rows")
    p.add_argument("-json", action="store_true", dest="as_json",
                   help="one JSON object per event")

    p = sub.add_parser("monitor",
                       help="follow an evaluation to completion")
    _add_meta(p)
    p.add_argument("eval_id")

    p = sub.add_parser("client-config",
                       help="show the client agent's server list")
    _add_meta(p)
    p.add_argument("-servers", action="store_true",
                   help="print the known server addresses")

    p = sub.add_parser("lint",
                       help="static concurrency/telemetry lint "
                            "(the `go vet` analogue)")
    p.add_argument("paths", nargs="*",
                   help="files/dirs to lint (default: the installed "
                        "nomad_tpu tree)")
    p.add_argument("-json", action="store_true", dest="as_json",
                   help="machine-readable JSON output")
    p.add_argument("-checker", action="append", default=None,
                   help="run only this checker id (repeatable)")
    p.add_argument("-show-suppressed", action="store_true",
                   help="include suppressed findings in the output")
    p.add_argument("-suppressions", action="store_true",
                   help="audit mode: list every active "
                        "`# lint: allow(...)` with its checker and "
                        "reason instead of running the checkers")

    args = parser.parse_args(argv)
    if args.command is None:
        parser.print_help()
        return 1
    try:
        return globals()[f"cmd_{args.command.replace('-', '_')}"](args)
    except APIError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    except (OSError, ValueError) as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1


# ---------------------------------------------------------------- commands

def dump_telemetry(signum=None, frame=None) -> None:
    """SIGUSR1 handler: dump the in-memory telemetry snapshot to the agent
    log as one JSON line (module-level, not a closure, so tests can drive
    it without an agent process)."""
    import logging

    from nomad_tpu.telemetry import metrics

    logging.getLogger("nomad.agent").info(
        "metrics snapshot: %s", json.dumps(metrics.snapshot()))


def cmd_agent(args) -> int:
    import logging
    import logging.handlers

    # Gated boot logging (reference: gated-writer + command.go:241-281):
    # records buffer in memory until the agent is up, then flush — a failed
    # boot dumps everything, a clean boot prints in one block after the
    # startup banner.
    root = logging.getLogger()
    root.setLevel(logging.INFO)
    stream = logging.StreamHandler()
    stream.setFormatter(logging.Formatter(
        "%(asctime)s [%(levelname)s] %(name)s: %(message)s"))
    gate = logging.handlers.MemoryHandler(capacity=10000,
                                          flushLevel=logging.CRITICAL,
                                          target=stream)
    root.addHandler(gate)
    from nomad_tpu.agent import Agent, AgentConfig

    if args.config:
        from nomad_tpu.agent.config import load_config_file

        config = load_config_file(args.config)
    elif args.dev:
        config = AgentConfig.dev()
    else:
        config = AgentConfig(server_enabled=args.server,
                             client_enabled=args.client)
    if args.bind is not None:
        config.bind_addr = args.bind
    if args.http_port is not None:
        config.http_port = args.http_port
    if args.data_dir is not None:
        config.data_dir = args.data_dir
    if args.node_class is not None:
        config.node_class = args.node_class
    if args.dc is not None:
        config.datacenter = args.dc
    if args.region is not None:
        config.region = args.region
    if args.rpc_port is not None:
        config.rpc_port = args.rpc_port
    if args.serf_port is not None:
        config.serf_port = args.serf_port
    if args.bootstrap_expect is not None:
        config.bootstrap_expect = args.bootstrap_expect
    if args.join is not None:
        config.start_join = list(args.join)
    if args.servers is not None:
        config.servers = [s.strip() for s in args.servers.split(",") if s]

    agent = Agent(config)
    try:
        agent.start()
    finally:
        # Always release the gate — a FAILED boot must dump its buffered
        # logs with the traceback, not swallow them.
        gate.flush()
        root.removeHandler(gate)
        root.addHandler(stream)
    mode = ("dev" if args.dev else
            "+".join(m for m, on in (("server", config.server_enabled),
                                     ("client", config.client_enabled)) if on))
    print(f"==> nomad-tpu agent started ({mode}) on "
          f"http://{config.bind_addr}:{agent.http.port}")
    if getattr(config, "enable_syslog", False):
        try:
            syslog = logging.handlers.SysLogHandler(address="/dev/log")
            syslog.setFormatter(logging.Formatter(
                "nomad-tpu[%(process)d]: %(name)s: %(message)s"))
            root.addHandler(syslog)
        except OSError:
            logging.getLogger("nomad.agent").warning(
                "syslog requested but /dev/log unavailable")

    # SIGHUP: re-read the config file and apply what is reloadable at
    # runtime (telemetry sinks) — reference: command.go handleReload.
    def reload(signum, frame):
        log = logging.getLogger("nomad.agent")
        if not args.config:
            log.info("SIGHUP received; no config file to reload")
            return
        try:
            from nomad_tpu.agent.config import load_config_file

            fresh = load_config_file(args.config)
        except Exception:
            log.exception("SIGHUP reload failed; keeping current config")
            return
        from nomad_tpu.telemetry import metrics, trace

        metrics.configure(statsd_addr=fresh.statsd_addr,
                          collection_interval=fresh.telemetry_interval,
                          host_label=fresh.node_name or config.node_name)
        trace.configure(enabled=fresh.trace_enabled,
                        sample_ratio=fresh.trace_sample_ratio,
                        ring=fresh.trace_ring)
        config.statsd_addr = fresh.statsd_addr
        config.telemetry_interval = fresh.telemetry_interval
        config.trace_enabled = fresh.trace_enabled
        config.trace_sample_ratio = fresh.trace_sample_ratio
        config.trace_ring = fresh.trace_ring
        log.info("SIGHUP: config reloaded (telemetry + tracing applied; "
                 "topology changes need a restart)")

    import signal as _signal

    _signal.signal(_signal.SIGHUP, reload)
    # SIGUSR1: dump the in-memory telemetry snapshot to the log
    # (reference: the in-mem sink's signal-triggered dump).
    _signal.signal(_signal.SIGUSR1, dump_telemetry)
    try:
        while True:
            # lint: allow(retry, foreground agent idles until SIGINT)
            time.sleep(1)
    except KeyboardInterrupt:
        print("==> shutting down")
        agent.shutdown()
    return 0


def cmd_run(args) -> int:
    from nomad_tpu.jobspec import parse_job_file
    from nomad_tpu.structs import to_dict

    job = parse_job_file(args.jobfile)
    job.init_fields()
    errs = job.validate()
    if errs:
        print("Job validation errors:", file=sys.stderr)
        for e in errs:
            print(f"  * {e}", file=sys.stderr)
        return 1
    if args.output:
        print(json.dumps({"Job": to_dict(job)}, indent=2))
        return 0
    client = _client(args)
    eval_id, warnings, meta = client.jobs.register_with_warnings(
        job, enforce_index=args.check_index)
    for w in warnings:
        print(f"Warning: {w}", file=sys.stderr)
    if not eval_id:  # periodic parent
        print(f'Job "{job.ID}" registered (periodic)')
        return 0
    print(f"==> Evaluation {eval_id[:8]} created")
    if args.detach:
        print(eval_id)
        return 0
    return _monitor_eval(client, eval_id)


def _monitor_eval(client: Client, eval_id: str) -> int:
    """(reference: command/monitor.go — tolerates transient not-found and
    leaderless windows while the eval replicates/an election settles)"""
    seen_status = ""
    deadline = time.time() + 300
    grace = time.time() + 10  # slides: resets on every successful poll
    while time.time() < deadline:
        try:
            ev, _ = client.evaluations.info(eval_id)
            grace = time.time() + 10
        except APIError:
            if time.time() < grace:
                # lint: allow(retry, human-paced CLI poll of a remote eval)
                time.sleep(0.25)
                continue
            raise
        if ev["Status"] != seen_status:
            seen_status = ev["Status"]
            print(f'    Evaluation status: {seen_status}')
        if seen_status in ("complete", "failed", "canceled"):
            allocs = client.evaluations.allocations(eval_id)[0]
            for a in allocs:
                print(f'    Allocation {a["ID"][:8]} ({a["Name"]}) on node '
                      f'{a["NodeID"][:8]}: {a["ClientStatus"]}')
            failed = ev.get("FailedTGAllocs") or {}
            for tg, metric in failed.items():
                print(f'    Task group "{tg}" failed to place '
                      f'({metric.get("CoalescedFailures", 0) + 1} failures)')
                if ev.get("BlockedEval"):
                    print(f'    Blocked evaluation {ev["BlockedEval"][:8]} '
                          "waiting for capacity")
            return 0 if seen_status == "complete" else 1
        # lint: allow(retry, human-paced CLI poll of a remote eval)
        time.sleep(0.25)
    print("    Timed out waiting for evaluation")
    return 1


_DIFF_MARK = {"Added": "+", "Deleted": "-", "Edited": "+/-", "None": ""}


def _mark(t: str) -> str:
    m = _DIFF_MARK.get(t, "")
    return f"{m} " if m else ""


def _render_fields(fields, indent: int, out) -> None:
    pad = " " * indent
    for f in fields:
        if f.Type == "None":
            continue
        note = f" ({', '.join(f.Annotations)})" if f.Annotations else ""
        if f.Type == "Added":
            out.append(f'{pad}+ {f.Name}: "{f.New}"{note}')
        elif f.Type == "Deleted":
            out.append(f'{pad}- {f.Name}: "{f.Old}"{note}')
        else:
            out.append(f'{pad}+/- {f.Name}: "{f.Old}" => "{f.New}"{note}')


def _render_objects(objects, indent: int, out) -> None:
    pad = " " * indent
    for o in objects:
        if o.Type == "None":
            continue
        out.append(f"{pad}{_mark(o.Type)}{o.Name} {{")
        _render_fields(o.Fields, indent + 2, out)
        _render_objects(o.Objects, indent + 2, out)
        out.append(f"{pad}}}")


def format_job_diff(diff) -> str:
    """Render a JobDiff the way `nomad plan` does (reference:
    command/plan.go formatJobDiff)."""
    out: list = []
    out.append(f'{_mark(diff.Type)}Job: "{diff.ID}"')
    _render_fields(diff.Fields, 2, out)
    _render_objects(diff.Objects, 2, out)
    for tg in diff.TaskGroups:
        if tg.Type == "None" and not tg.Updates:
            continue
        counts = ", ".join(f"{v} {k}" for k, v in sorted(tg.Updates.items()))
        suffix = f" ({counts})" if counts else ""
        out.append(f'{_mark(tg.Type)}Task Group: "{tg.Name}"{suffix}')
        _render_fields(tg.Fields, 2, out)
        _render_objects(tg.Objects, 2, out)
        for t in tg.Tasks:
            if t.Type == "None":
                continue
            note = f" ({', '.join(t.Annotations)})" if t.Annotations else ""
            out.append(f'  {_mark(t.Type)}Task: "{t.Name}"{note}')
            _render_fields(t.Fields, 4, out)
            _render_objects(t.Objects, 4, out)
    return "\n".join(out)


def cmd_plan(args) -> int:
    """Dry-run a job: show the diff + what the scheduler would do
    (reference: command/plan.go)."""
    from nomad_tpu.jobspec import parse_job_file

    job = parse_job_file(args.jobfile)
    job.init_fields()
    errs = job.validate()
    if errs:
        for e in errs:
            print(f"  * {e}", file=sys.stderr)
        return 255
    client = _client(args)
    try:
        resp, _ = client.jobs.plan(job, diff=True)
    except APIError as e:
        print(f"Error during plan: {e}", file=sys.stderr)
        return 255

    if resp.Diff is not None:
        print(format_job_diff(resp.Diff))
        print()

    print("Scheduler dry-run:")
    if not resp.FailedTGAllocs:
        print("- All tasks successfully allocated.")
    else:
        for tg, metric in sorted(resp.FailedTGAllocs.items()):
            print(f'- WARNING: Failed to place all allocations for task '
                  f'group "{tg}".')
            if getattr(metric, "DimensionExhausted", None):
                for dim, count in sorted(metric.DimensionExhausted.items()):
                    print(f'    * Resources exhausted on {count} nodes: {dim}')
    if resp.NextPeriodicLaunch:
        import datetime

        when = datetime.datetime.fromtimestamp(resp.NextPeriodicLaunch)
        print(f"- If submitted now, next periodic launch would be at {when}.")
    print()
    print(f"Job Modify Index: {resp.JobModifyIndex}")
    print(f"To submit the job with version verification run:")
    print(f"\n  nomad run -check-index {resp.JobModifyIndex} {args.jobfile}")
    changes = resp.Diff is not None and resp.Diff.Type != "None"
    return 1 if changes else 0


def cmd_validate(args) -> int:
    from nomad_tpu.jobspec import parse_job_file

    job = parse_job_file(args.jobfile)
    job.init_fields()
    errs = job.validate()
    # Warnings print on BOTH outcomes: accepted-but-ignored driver keys
    # matter to whoever is fixing the errors too.
    from nomad_tpu.client.driver import job_config_warnings

    for w in job_config_warnings(job):
        print(f"Warning: {w}", file=sys.stderr)
    if errs:
        print("Job validation errors:", file=sys.stderr)
        for e in errs:
            print(f"  * {e}", file=sys.stderr)
        return 1
    print("Job validation successful")
    return 0


EXAMPLE_JOB = '''# Example nomad-tpu job specification
job "example" {
  datacenters = ["dc1"]
  type = "service"

  group "cache" {
    count = 1

    restart {
      attempts = 10
      interval = "5m"
      delay = "25s"
      mode = "delay"
    }

    task "sleeper" {
      driver = "raw_exec"
      config {
        command = "/bin/sleep"
        args = ["300"]
      }
      resources {
        cpu = 100
        memory = 64
        disk = 300
      }
    }
  }
}
'''


def cmd_init(args) -> int:
    import os

    if os.path.exists("example.nomad"):
        print("Error: example.nomad already exists", file=sys.stderr)
        return 1
    with open("example.nomad", "w") as f:
        f.write(EXAMPLE_JOB)
    print("Example job file written to example.nomad")
    return 0


def cmd_status(args) -> int:
    client = _client(args)
    if not args.job_id:
        jobs, _ = client.jobs.list()
        if not jobs:
            print("No running jobs")
            return 0
        print(f"{'ID':<20} {'Type':<10} {'Priority':<9} Status")
        for j in jobs:
            print(f"{j['ID']:<20} {j['Type']:<10} {j['Priority']:<9} "
                  f"{j['Status']}")
        return 0
    job, _ = client.jobs.info(args.job_id)
    print(f"ID          = {job.ID}")
    print(f"Name        = {job.Name}")
    print(f"Type        = {job.Type}")
    print(f"Priority    = {job.Priority}")
    print(f"Datacenters = {','.join(job.Datacenters)}")
    print(f"Status      = {job.Status}")
    allocs, _ = client.jobs.allocations(args.job_id)
    if allocs:
        print("\nAllocations")
        print(f"{'ID':<10} {'Eval ID':<10} {'Node ID':<10} {'Task Group':<12} "
              f"{'Desired':<8} Status")
        for a in allocs:
            print(f"{a['ID'][:8]:<10} {a['EvalID'][:8]:<10} "
                  f"{a['NodeID'][:8]:<10} {a['TaskGroup']:<12} "
                  f"{a['DesiredStatus']:<8} {a['ClientStatus']}")
    return 0


def cmd_stop(args) -> int:
    client = _client(args)
    eval_id, _ = client.jobs.deregister(args.job_id)
    print(f"==> Evaluation {eval_id[:8]} created")
    if args.detach:
        return 0
    return _monitor_eval(client, eval_id)


def cmd_inspect(args) -> int:
    client = _client(args)
    from nomad_tpu.structs import to_dict

    job, _ = client.jobs.info(args.job_id)
    # (reference: command/inspect.go wraps the job for `nomad run` reuse)
    print(json.dumps({"Job": to_dict(job)}, indent=2))
    return 0


def cmd_node_status(args) -> int:
    client = _client(args)
    if not args.node_id:
        nodes, _ = client.nodes.list()
        print(f"{'ID':<10} {'DC':<8} {'Name':<16} {'Class':<12} "
              f"{'Drain':<6} Status")
        for n in nodes:
            print(f"{n['ID'][:8]:<10} {n['Datacenter']:<8} {n['Name']:<16} "
                  f"{n['NodeClass']:<12} {str(n['Drain']).lower():<6} "
                  f"{n['Status']}")
        return 0
    node, _ = client.nodes.info(
        _resolve_prefix("node", args.node_id, client.nodes.list))
    print(f"ID     = {node['ID']}")
    print(f"Name   = {node['Name']}")
    print(f"Class  = {node['NodeClass']}")
    print(f"DC     = {node['Datacenter']}")
    print(f"Drain  = {node['Drain']}")
    print(f"Status = {node['Status']}")
    allocs, _ = client.nodes.allocations(args.node_id)
    if allocs:
        print("\nAllocations")
        for a in allocs:
            print(f"{a['ID'][:8]} {a['JobID']:<20} {a['TaskGroup']:<12} "
                  f"{a['DesiredStatus']:<8} {a['ClientStatus']}")
    return 0


def cmd_node_drain(args) -> int:
    client = _client(args)
    node_id = _resolve_prefix("node", args.node_id, client.nodes.list)
    client.nodes.toggle_drain(node_id, args.enable)
    state = "enabled" if args.enable else "disabled"
    print(f"Node {args.node_id[:8]} drain {state}")
    return 0


def cmd_alloc_status(args) -> int:
    client = _client(args)
    alloc_id = _resolve_prefix("allocation", args.alloc_id,
                               client.allocations.list)
    alloc, _ = client.allocations.info(alloc_id)
    print(f"ID            = {alloc['ID']}")
    print(f"Eval ID       = {alloc['EvalID'][:8]}")
    print(f"Name          = {alloc['Name']}")
    print(f"Node ID       = {alloc['NodeID'][:8]}")
    print(f"Job ID        = {alloc['JobID']}")
    print(f"Client Status = {alloc['ClientStatus']}")
    print(f"Desired       = {alloc['DesiredStatus']}")
    for task, state in (alloc.get("TaskStates") or {}).items():
        print(f"\nTask {task!r} is {state['State']}")
        for ev in state.get("Events", []):
            detail = ev.get("DriverError") or ev.get("Message") or \
                ev.get("ValidationError") or ev.get("DownloadError") or ""
            print(f"  {ev['Type']}: exit={ev.get('ExitCode', 0)} {detail}")
    metrics = alloc.get("Metrics") or {}
    if metrics:
        print(f"\nPlacement Metrics")
        print(f"  Nodes evaluated: {metrics.get('NodesEvaluated', 0)}")
        print(f"  Nodes filtered:  {metrics.get('NodesFiltered', 0)}")
        print(f"  Nodes exhausted: {metrics.get('NodesExhausted', 0)}")
    return 0


def cmd_eval_status(args) -> int:
    client = _client(args)
    eval_id = _resolve_prefix("evaluation", args.eval_id,
                              client.evaluations.list)
    ev, _ = client.evaluations.info(eval_id)
    print(f"ID           = {ev['ID'][:8]}")
    print(f"Status       = {ev['Status']}")
    print(f"Type         = {ev['Type']}")
    print(f"TriggeredBy  = {ev['TriggeredBy']}")
    print(f"Job ID       = {ev['JobID']}")
    print(f"Priority     = {ev['Priority']}")
    for tg, metric in (ev.get("FailedTGAllocs") or {}).items():
        print(f"\nFailed placement: task group {tg!r}")
        print(f"  Nodes evaluated: {metric.get('NodesEvaluated', 0)}")
        for dim, count in (metric.get("DimensionExhausted") or {}).items():
            print(f"  Dimension {dim!r} exhausted on {count} nodes")
    return 0


def cmd_fs(args) -> int:
    client = _client(args)
    args.alloc_id = _resolve_prefix("allocation", args.alloc_id,
                                    client.allocations.list)
    if args.stat:
        info = client.alloc_fs.stat(args.alloc_id, args.path)
        print(f"{info['FileMode']} {info['Size']:>10} {info['Name']}")
        return 0
    if args.cat:
        sys.stdout.write(client.alloc_fs.cat(args.alloc_id, args.path))
        return 0
    for fi in client.alloc_fs.list(args.alloc_id, args.path):
        kind = "d" if fi["IsDir"] else "-"
        print(f"{kind} {fi['FileMode']} {fi['Size']:>10} {fi['Name']}")
    return 0


def cmd_server_members(args) -> int:
    client = _client(args)
    for m in client.agent.members():
        print(f"{m['Name']:<16} {m['Addr']}:{m['Port']} {m['Status']} "
              f"region={m['Tags'].get('region')} dc={m['Tags'].get('dc')}")
    return 0


def cmd_join(args) -> int:
    client = _client(args)
    out = client.agent.join(args.addresses)
    print(f"Joined {out['num_joined']} servers successfully")
    return 0


def cmd_force_leave(args) -> int:
    client = _client(args)
    out = client.agent.force_leave(args.node)
    if not out.get("ok"):
        print(f"Error: unknown member {args.node}", file=sys.stderr)
        return 1
    print(f"Force-leave of {args.node} propagated")
    return 0


def cmd_agent_info(args) -> int:
    client = _client(args)
    info = client.agent.self()
    print(json.dumps(info, indent=2))
    if info.get("config", {}).get("EnableDebug"):
        print("# debug endpoints: /v1/agent/debug/stacks (thread dump), "
              "/v1/agent/debug/profile?seconds=N (CPU profile; save the "
              "body and load with python -m pstats)", file=sys.stderr)
    return 0


def cmd_faults(args) -> int:
    """Fault-injection control (resilience subsystem): list the agent's
    failpoint sites, arm a spec, or heal everything."""
    client = _client(args)
    if args.disarm_all:
        client.agent.disarm_faults()
        print("All failpoints disarmed")
        return 0
    if args.spec:
        out = client.agent.arm_faults(args.spec)
        print("Armed: " + ", ".join(out.get("Touched", [])))
        return 0
    sites = client.agent.faults().get("Sites", {})
    print(f"{'Site':<26} {'Armed':<28} {'Fired':>6}  Description")
    for name, info in sites.items():
        armed = info.get("armed")
        if armed:
            desc = armed["mode"]
            if armed["mode"] == "delay":
                desc += f"({armed['delay']:g})"
            if armed["probability"] < 1.0:
                desc += f":p={armed['probability']:g}"
            if armed.get("remaining") is not None:
                desc += f":count={armed['remaining']}"
        else:
            desc = "-"
        print(f"{name:<26} {desc:<28} {info.get('fired', 0):>6}  "
              f"{info.get('description', '')}")
    return 0


def cmd_sched_stats(args) -> int:
    """Operator view of the served scheduling pipeline: the same stage
    timers and flow counters bench.py prints, live from the leader's
    workers (see the README's stats-key table for what each means)."""
    client = _client(args)
    out = client.agent.sched_stats()
    if args.json:
        print(json.dumps(out, indent=2))
        return 0
    qos = out.get("QoS") or {}
    if qos.get("Enabled"):
        # Per-tier lane health first: queue depth + SLO burn is the
        # "are high-tier deadlines holding" answer an operator wants
        # before any per-worker stage timer.
        depths = qos.get("TierDepths") or {}
        burn = qos.get("SLOBurn") or {}
        print("QoS tiers (ready depth / SLO burn):")
        for name in ("high", "normal", "low"):
            print(f"  {name:<8} {depths.get(name, 0):>6} / "
                  f"{burn.get(name, 0.0):.0%}")
        print(f"  aged-up pops: {qos.get('Promoted', 0)}")
        counters = qos.get("Counters") or {}
        print("  " + "  ".join(f"{k}={v}" for k, v in
                               sorted(counters.items())))
    store = out.get("Store") or {}
    if store:
        # Which commit path storms took: columnar segments by kind
        # ("service" window vs "system" sweep) + promotion pressure.
        batches = store.get("Batches") or {}
        kinds = ("  ".join(f"{k}={v}" for k, v in sorted(batches.items()))
                 or "none")
        print(f"Columnar store: {store.get('Segments', 0)} segments / "
              f"{store.get('LiveRows', 0)} live rows / "
              f"{store.get('PromotedRows', 0)} promoted; batches: {kinds}")
    digest = out.get("Digest")
    if digest:
        # Replica-determinism health: where this replica's chain stands,
        # how far it has been verified against the leader, and whether
        # it ever diverged (README "Replica determinism").
        mode = ("synced" if digest.get("Synced")
                else f"UNSYNCED ({digest.get('UnsyncedReason')})")
        print(f"Replica digest: {mode}, chain @{digest.get('LastIndex', 0)}"
              f" (verified @{digest.get('VerifiedIndex', 0)}, "
              f"interval {digest.get('Interval')})")
        print(f"  folds={digest.get('Folds', 0)}  "
              f"exchanged={digest.get('Exchanged', 0)}  "
              f"diverged={digest.get('Diverged', 0)}")
    workers = out.get("Workers") or []
    if not workers:
        print("No scheduling workers running (agent is not the leader?)")
        return 0
    for w in workers:
        window = f", window {w['Window']}" if w.get("Window") else ""
        name = w.get("Name") or f"worker-{w['Index']}"
        print(f"Worker {name} ({w['Type']}{window})")
        stats = w.get("Stats")
        if not stats:
            print("  (no stats exported)")
            continue
        counters = {k: v for k, v in stats.items()
                    if not k.startswith("t_")}
        print("  " + "  ".join(f"{k}={v}" for k, v in
                               sorted(counters.items())))
        print(f"  {'stage':<20} {'total ms':>12}")
        for k in sorted(k for k in stats if k.startswith("t_")):
            print(f"  {k:<20} {stats[k]:>12.1f}")
    return 0


def _render_span_tree(spans: list, out) -> None:
    """Indent spans by parent relationship, chronological within a level."""
    by_parent: dict = {}
    ids = {s["SpanID"] for s in spans}
    for s in spans:
        parent = s.get("ParentID")
        # Spans whose parent never landed locally (remote/unfinished) sit
        # at the top level rather than vanishing.
        key = parent if parent in ids else None
        by_parent.setdefault(key, []).append(s)

    def emit(parent, depth):
        for s in sorted(by_parent.get(parent, ()),
                        key=lambda x: x["Start"]):
            dur = s.get("DurationMs")
            dur_s = f"{dur:.2f}ms" if dur is not None else "open"
            mark = " !" if s.get("Error") else ""
            out.append(f"{'  ' * depth}{s['Name']:<28} {dur_s:>10}"
                       f"  [{s.get('Thread', '')}]{mark}")
            for ev in s.get("Events", ()):
                attrs = " ".join(f"{k}={v}" for k, v in
                                 (ev.get("Attrs") or {}).items())
                out.append(f"{'  ' * (depth + 1)}@{ev['OffsetMs']:.2f}ms "
                           f"{ev['Name']} {attrs}".rstrip())
            emit(s["SpanID"], depth + 1)

    emit(None, 0)


def cmd_trace(args) -> int:
    """Evaluation-lifecycle traces: list/show/export (Chrome trace-event
    JSON for Perfetto) and toggle collection — same debug-gated pattern as
    `faults` and `sched-stats`."""
    client = _client(args)
    if args.enable or args.disable:
        out = client.agent.configure_trace(
            enabled=args.enable, sample_ratio=args.ratio)
        state = "enabled" if out.get("Enabled") else "disabled"
        print(f"Tracing {state} (sample ratio {out.get('SampleRatio')}, "
              f"ring {out.get('Ring')})")
        return 0
    if args.clear:
        client.agent.clear_traces()
        print("Collected traces cleared")
        return 0
    if args.export:
        if args.trace_id:
            trace_id = _resolve_trace_id(client, args.trace_id)
            payload = client.agent.trace(trace_id, chrome=True)
        else:
            payload = client.agent.trace_export()
        with open(args.export, "w") as f:
            json.dump(payload, f)
        print(f"Wrote {len(payload.get('traceEvents', []))} events to "
              f"{args.export} (load in Perfetto / chrome://tracing)")
        return 0
    if args.trace_id:
        full = client.agent.trace(
            _resolve_trace_id(client, args.trace_id)).get("Trace", {})
        if args.json:
            print(json.dumps(full, indent=2))
            return 0
        print(f"Trace   = {full['TraceID']}")
        print(f"Root    = {full.get('Root', '')}")
        print(f"Error   = {full.get('Error', False)}")
        print(f"Spans   = {len(full.get('Spans', []))}")
        out: list = []
        _render_span_tree(full.get("Spans", []), out)
        for line in out:
            print(line)
        for ev in full.get("Events", ()):
            attrs = " ".join(f"{k}={v}" for k, v in
                             (ev.get("Attrs") or {}).items())
            print(f"* {ev['Name']} {attrs}".rstrip())
        return 0
    out = client.agent.traces()
    if args.json:
        print(json.dumps(out, indent=2))
        return 0
    state = "enabled" if out.get("Enabled") else "disabled"
    print(f"Tracing {state} (sample ratio {out.get('SampleRatio')}, "
          f"ring {out.get('Ring')})")
    traces = out.get("Traces") or []
    if not traces:
        print("No traces collected")
        return 0
    print(f"{'Trace':<34} {'Root':<24} {'Spans':>5} {'ms':>10} "
          f"{'Done':<5} Err")
    for t in traces:
        dur = t.get("DurationMs")
        print(f"{t['TraceID']:<34} {t.get('Root', ''):<24} "
              f"{t.get('Spans', 0):>5} "
              f"{dur if dur is None else round(dur, 2)!s:>10} "
              f"{str(t.get('Complete', False)).lower():<5} "
              f"{'!' if t.get('Error') else ''}")
    return 0


def _resolve_trace_id(client: Client, given: str) -> str:
    """Unique-prefix resolution against the retained trace list, matching
    the node/alloc/eval short-id UX."""
    traces = client.agent.traces().get("Traces") or []
    ids = [t["TraceID"] for t in traces if t["TraceID"].startswith(given)]
    if given in ids or not ids:
        return given  # exact (or unknown: let the server 404)
    if len(ids) > 1:
        print(f"Prefix {given!r} matched multiple traces:", file=sys.stderr)
        for i in ids:
            print(f"  {i}", file=sys.stderr)
        raise SystemExit(1)
    return ids[0]


def cmd_system_gc(args) -> int:
    client = _client(args)
    client.system.garbage_collect()
    print("System GC triggered")
    return 0


def cmd_events(args) -> int:
    """Follow the cluster event stream (reference: command/event.go
    `nomad event` — a topic-filtered follow of the event stream
    endpoint). Runs until interrupted; reconnects and resumes from the
    last seen index automatically (api.Client.event_stream)."""
    client = _client(args)
    try:
        for frame in client.event_stream(topics=args.topic,
                                         from_index=args.index,
                                         fanout=args.fanout):
            if frame.get("Dropped"):
                print(f"... {frame['Dropped']} frame(s) dropped "
                      f"(slow consumer)", file=sys.stderr)
            for ev in frame.get("Events", ()):
                if args.as_json:
                    print(json.dumps(ev), flush=True)
                else:
                    print(f"{ev.get('Index', 0):>8}  "
                          f"{ev.get('Topic', ''):<16} "
                          f"{ev.get('Type', ''):<24} "
                          f"{ev.get('Key', '')}", flush=True)
    except KeyboardInterrupt:
        pass
    return 0


def cmd_monitor(args) -> int:
    """Standalone eval monitor (reference: command/monitor.go — the same
    follower `run` uses after submit)."""
    client = _client(args)
    return _monitor_eval(client, args.eval_id)


def cmd_client_config(args) -> int:
    """(reference: command/client_config.go: -servers prints the client's
    server list; without the flag, the agent's client configuration)"""
    client = _client(args)
    if args.servers:
        for s in client.agent.servers():
            print(s)
        return 0
    info = client.agent.self()
    print(json.dumps(info.get("config", info), indent=2))
    return 0


def cmd_services(args) -> int:
    client = _client(args)
    if args.name:
        regs, _ = client.services.get(args.name)
    else:
        regs, _ = client.services.list()
    if not regs:
        print("No services registered")
        return 0
    print(f"{'Service':<24} {'Status':<10} {'Address':<22} "
          f"{'Node':<10} Task")
    for r in regs:
        addr = f"{r['Address']}:{r['Port']}" if r.get("Port") else r["Address"]
        print(f"{r['ServiceName']:<24} {r['Status']:<10} {addr:<22} "
              f"{r['NodeID'][:8]:<10} {r.get('TaskName') or '-'}")
    return 0


def cmd_lint(args) -> int:
    """Run the static analysis pass (reference intent: the `go vet` /
    race-detector discipline the Go codebase gets for free). Exit 0 on a
    clean tree, 1 when any unsuppressed finding survives."""
    from nomad_tpu.analysis import all_checkers, run_checks

    if args.suppressions:
        return _lint_suppressions(args)
    try:
        findings = run_checks(paths=args.paths or None,
                              checker_ids=args.checker,
                              include_suppressed=args.show_suppressed)
    except ValueError as e:
        print(f"Error: {e}", file=sys.stderr)
        print("known checkers: "
              + ", ".join(c.id for c in all_checkers()), file=sys.stderr)
        return 2
    live = [f for f in findings if not f.suppressed]
    if args.as_json:
        print(json.dumps({"findings": [f.to_dict() for f in findings],
                          "total": len(live)}, indent=2))
    else:
        import os as _os

        for f in findings:
            print(f.render(relative_to=_os.getcwd()))
        print(f"{len(live)} finding(s)"
              + (f" ({len(findings) - len(live)} suppressed)"
                 if len(findings) != len(live) else ""))
    return 1 if live else 0


def _lint_suppressions(args) -> int:
    """`nomad-tpu lint -suppressions`: the purity-boundary audit. Every
    active `# lint: allow(<checker>, <reason>)` in the tree, with its
    location and reason — the reviewable ledger of intentional
    exceptions. Always exits 0: suppressions are declarations, not
    findings."""
    import os as _os

    from nomad_tpu.analysis.findings import parse_suppression_details
    from nomad_tpu.analysis.framework import PKG_ROOT, iter_py_files

    files: list = []
    for p in (args.paths or [PKG_ROOT]):
        p = _os.path.abspath(p)
        if _os.path.isdir(p):
            files.extend(iter_py_files(p))
        else:
            files.append(p)

    rows = []
    for path in files:
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
        except OSError:
            continue
        for lineno, checker, reason in parse_suppression_details(source):
            if args.checker and checker not in args.checker:
                continue
            rows.append({"File": _os.path.relpath(path, _os.getcwd()),
                         "Line": lineno, "Checker": checker,
                         "Reason": reason})
    rows.sort(key=lambda r: (r["File"], r["Line"]))
    if args.as_json:
        print(json.dumps({"suppressions": rows, "total": len(rows)},
                         indent=2))
    else:
        for r in rows:
            print(f"{r['File']}:{r['Line']}: "
                  f"allow({r['Checker']}) — {r['Reason']}")
        print(f"{len(rows)} suppression(s)")
    return 0
