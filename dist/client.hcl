# nomad-tpu client agent (reference shape: dist/client.hcl)
bind_addr = "127.0.0.1"
data_dir = "/var/lib/nomad-tpu"

client {
  enabled = true
  # Static server RPC addresses...
  servers = ["10.1.0.1:4647", "10.1.0.2:4647", "10.1.0.3:4647"]
  # ...or bootstrap them from any agent's HTTP API via the service
  # registry instead:
  # server_discovery_url = "http://10.1.0.1:4646"

  options {
    "driver.raw_exec.enable" = "1"
  }
}
