# nomad-tpu server agent (reference shape: dist/server.hcl)
bind_addr = "0.0.0.0"
data_dir = "/var/lib/nomad-tpu"

ports {
  http = 4646
  rpc = 4647
  serf = 4648
}

# Every server needs a UNIQUE name (defaults to the hostname).
name = "server-1"

server {
  enabled = true
  bootstrap_expect = 3
  # Seed gossip with any existing server's serf address; every server
  # found this way is added to the raft peer set automatically.
  start_join = ["10.1.0.1:4648"]

  # Scheduler engine: windowed device-chained scheduling (the TPU fast
  # path) with this many evals per window; "all" shards the node tensor
  # over every local accelerator (multi-chip serving).
  # scheduler_window = 256
  # scheduler_mesh = "all"
}

# Mutual TLS on the RPC mux (servers AND clients need the same CA):
# tls {
#   rpc = true
#   ca_file = "/etc/nomad-tpu/ca.crt"
#   cert_file = "/etc/nomad-tpu/server.crt"
#   key_file = "/etc/nomad-tpu/server.key"
#   verify_incoming = true
# }

telemetry {
  # statsd_address = "127.0.0.1:8125"
  collection_interval = "10s"
}
