# nomad-tpu server agent (reference shape: dist/server.hcl)
bind_addr = "0.0.0.0"
data_dir = "/var/lib/nomad-tpu"

ports {
  http = 4646
  rpc = 4647
  serf = 4648
}

# Every server needs a UNIQUE name (defaults to the hostname).
name = "server-1"

server {
  enabled = true
  bootstrap_expect = 3
  # Seed gossip with any existing server's serf address; every server
  # found this way is added to the raft peer set automatically.
  start_join = ["10.1.0.1:4648"]
}

telemetry {
  # statsd_address = "127.0.0.1:8125"
  collection_interval = "10s"
}
