#!/usr/bin/env python
"""Benchmark: scheduling throughput, TPU placement path vs CPU reference.

BASELINE.json config 3: 10k nodes x 5k task-group placements with driver +
attribute constraint checkers, 64 node-meta partitions (the reference's
computed-class benchmark shape, scheduler/stack_test.go:13-53). Measures
end-to-end evaluations/sec through the TPU placement path (eligibility
assembly + place_batch scan + host result handling) against the reference
algorithm (iterator chain with class memoization + log2 limit) running
host-side, at identical workloads.

Prints ONE json line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

N_NODES = int(os.environ.get("BENCH_NODES", 10_000))
N_PLACEMENTS = int(os.environ.get("BENCH_PLACEMENTS", 5_000))
PER_EVAL = int(os.environ.get("BENCH_PER_EVAL", 50))
N_PARTITIONS = 64
CPU_REF_EVALS = int(os.environ.get("BENCH_CPU_EVALS", 8))


def build_nodes(n):
    from nomad_tpu import mock
    from nomad_tpu.structs import compute_node_class

    nodes = []
    for i in range(n):
        node = mock.node()
        node.Meta["rack"] = f"r{i % N_PARTITIONS}"  # 64 computed classes
        compute_node_class(node)
        nodes.append(node)
    return nodes


def build_job():
    from nomad_tpu import mock
    from nomad_tpu.structs import Constraint

    job = mock.job()
    tg = job.TaskGroups[0]
    tg.Count = PER_EVAL
    # Driver checker (exec) is already on the mock task; add an attribute
    # constraint so the full checker chain runs (BASELINE config 3).
    job.Constraints.append(
        Constraint(LTarget="${attr.arch}", RTarget="x86", Operand="="))
    # Small asks so 10k nodes absorb 5k placements without exhaustion.
    task = tg.Tasks[0]
    task.Resources.CPU = 20
    task.Resources.MemoryMB = 32
    task.Resources.DiskMB = 10
    task.Resources.Networks = []
    return job


def bench_tpu(nodes, n_evals):
    """TPU throughput path: device-resident usage chaining + streamed
    readbacks (nomad_tpu/scheduler/pipeline.py)."""
    from nomad_tpu.scheduler.pipeline import EvalRequest, PipelinedPlacer
    from nomad_tpu.tensor import TensorIndex

    tindex = TensorIndex()
    for node in nodes:
        tindex.nt.upsert_node(node)

    # Window: one readback drains the whole burst (remote-TPU RTT amortizes
    # across the window); sized to the workload, capped at 128.
    window = min(max(n_evals, 1), 128)

    # Warmup: compile the placement kernel AND the window-stack readback op
    # for this shape bucket (same window size as the measured run).
    warm = PipelinedPlacer(tindex, nodes, rng=random.Random(1), window=window)
    for _ in range(window + 1):
        job = build_job()
        warm.submit(EvalRequest(job=job, tgs=[job.TaskGroups[0]] * PER_EVAL))
    warm.flush()

    placer = PipelinedPlacer(tindex, nodes, rng=random.Random(42),
                             window=window)
    t0 = time.perf_counter()
    for _ in range(n_evals):
        job = build_job()
        placer.submit(EvalRequest(job=job,
                                  tgs=[job.TaskGroups[0]] * PER_EVAL))
    results = placer.flush()
    elapsed = time.perf_counter() - t0
    total_placed = sum(int((r.chosen_rows >= 0).sum()) for r in results)

    # Synchronous single-eval latency (the p50 plan-latency figure).
    lat_placer = PipelinedPlacer(tindex, nodes, rng=random.Random(7))
    latencies = []
    for _ in range(5):
        job = build_job()
        t1 = time.perf_counter()
        lat_placer.submit(EvalRequest(job=job,
                                      tgs=[job.TaskGroups[0]] * PER_EVAL))
        lat_placer.flush()
        latencies.append(time.perf_counter() - t1)
    return n_evals / elapsed, total_placed, float(np.percentile(latencies, 50))


def bench_cpu_reference(nodes, n_evals):
    from nomad_tpu.scheduler.cpu_reference import CPUReferenceStack

    rng = random.Random(42)
    stack = CPUReferenceStack(nodes, batch=False, rng=rng)
    t0 = time.perf_counter()
    total = 0
    for _ in range(n_evals):
        job = build_job()
        stack.set_job(job)
        for o in stack.select_batch([job.TaskGroups[0]] * PER_EVAL):
            if o is not None:
                total += 1
    elapsed = time.perf_counter() - t0
    return n_evals / elapsed, total


def main():
    nodes = build_nodes(N_NODES)
    n_evals = max(1, N_PLACEMENTS // PER_EVAL)

    tpu_evals_sec, tpu_placed, p50 = bench_tpu(nodes, n_evals)
    cpu_evals_sec, _ = bench_cpu_reference(nodes, CPU_REF_EVALS)

    result = {
        "metric": f"placement evals/sec @{N_NODES} nodes x {N_PLACEMENTS} "
                  f"task-groups (driver+attr constraints, {N_PARTITIONS} classes)",
        "value": round(tpu_evals_sec, 2),
        "unit": "evals/sec",
        "vs_baseline": round(tpu_evals_sec / cpu_evals_sec, 2),
        "detail": {
            "placements_per_eval": PER_EVAL,
            "tpu_placed": tpu_placed,
            "tpu_p50_eval_latency_ms": round(p50 * 1e3, 2),
            "cpu_reference_evals_sec": round(cpu_evals_sec, 2),
            "backend": _backend(),
        },
    }
    print(json.dumps(result))


def _backend():
    try:
        import jax

        return str(jax.devices()[0])
    except Exception:
        return "unknown"


if __name__ == "__main__":
    main()
