#!/usr/bin/env python
"""Benchmark: end-to-end scheduling throughput on the SERVED path.

Headline (BASELINE.json config 3): 10k nodes x 5k task-group placements with
driver + attribute constraint checkers, 64 node-meta partitions — measured
END-TO-END through a live server: job_register -> raft apply -> eval broker ->
pipelined worker (device-chained placement windows, server/pipelined_worker.py)
-> plan applier re-verification -> committed allocations in the state store.

Detail additionally reports:
  - the placer-only device-pipeline number (scheduler/pipeline.py) — the
    ceiling the served path is converging to
  - the CPU reference (iterator-chain re-implementation) and the SERVED
    CPU reference (same server, placement engine swapped) for vs_baseline
  - BASELINE.json configs 2 (1k nodes x 500 resource-only placements),
    4 (system scheduler, 10k nodes x 50 jobs), and 5 (50k nodes x 20k
    task groups, multi-DC) — each END-TO-END through the served path

Prints ONE json line: {"metric", "value", "unit", "vs_baseline"}.
"""

import gc
import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

N_NODES = int(os.environ.get("BENCH_NODES", 10_000))
# Headline shape stays BASELINE config 3's node/constraint mix (10k nodes,
# 64 node-meta partitions, driver + attribute checkers); each timed rep is a
# 600-eval x 50-placement registration storm (longer reps + a 9-rep median:
# the remote-attached TPU's round-trip latency stalls unpredictably — a
# single blocked transfer can halve one rep's rate — so reps are long enough
# to amortize stalls and min/median/max are reported alongside).
N_PLACEMENTS = int(os.environ.get("BENCH_PLACEMENTS", 30_000))
PER_EVAL = int(os.environ.get("BENCH_PER_EVAL", 50))
N_PARTITIONS = 64
# Pipelined workers share one device usage chain through the ChainArbiter
# (windows interleave coherently; broker/plan-queue rounds are batched), so
# N workers scale instead of collapsing (pre-arbiter: 2 workers ~30 evals/s
# vs 130-230 for 1 — each kept a private chain the plan applier bounced).
# The worker_scaling sweep below records the measured 1-vs-2 ratio in every
# bench JSON so the trajectory is judged on scaling, not just 1-worker rate.
N_WORKERS = int(os.environ.get("BENCH_WORKERS", 1))
# Worker-scaling sweep shapes: ALWAYS smoke-sized — the sweep judges the
# RATIO, not absolute rate, and two extra full-shape server boots would
# double the bench wall clock.
SCALING_NODES = int(os.environ.get("BENCH_SCALING_NODES", 512))
SCALING_EVALS = int(os.environ.get("BENCH_SCALING_EVALS", 60))
SCALING_REPS = int(os.environ.get("BENCH_SCALING_REPS", 4))
# 64-eval windows measured best end-to-end in round 5: deep (256-eval)
# windows serialize ~4x the scan steps per drain on the device chain,
# while small windows amortize the tunnel RTT via the dispatch-time
# async host-copy. See PROGRESS notes; p50 also improves (~19ms).
WINDOW = int(os.environ.get("BENCH_WINDOW", 64))
# Nine reps: the tunnel's round-trip latency wanders ±15% between reps;
# a 9-sample median is noticeably more stable than 7 for ~3s more wall.
N_REPS = int(os.environ.get("BENCH_REPS", 9))
# >= 24 evals through the reference chain stabilizes the served-vs-served
# denominator to a few percent (round 4 ran 8, the noisiest number in the
# file); still ~4-6s of wall per rep at ~6 evals/s.
CPU_REF_EVALS = int(os.environ.get("BENCH_CPU_EVALS", 24))
C5_NODES = int(os.environ.get("BENCH_C5_NODES", 50_000))
C5_PLACEMENTS = int(os.environ.get("BENCH_C5_PLACEMENTS", 20_000))
RUN_C5 = os.environ.get("BENCH_C5", "1") != "0"
RUN_C2 = os.environ.get("BENCH_C2", "1") != "0"
RUN_C4 = os.environ.get("BENCH_C4", "1") != "0"
# Config 4 (system scheduler) shape: 2 small warmups + one full-size warm
# storm (C4_EVALS) + C4_REPS x C4_EVALS timed + 2 probes = 73 system jobs
# at the defaults (BASELINE names the 50-job storm; the extra warm storm
# is the same compile treatment every served config gets).
C4_EVALS = int(os.environ.get("BENCH_C4_EVALS", 23))
C4_REPS = 2
# Placement-parity gate shape (bench_placement_parity).
PARITY_NODES = 1000
PARITY_EVALS = 40
# QoS slo_storm shape (bench_slo_storm): a saturating LOW-tier storm with
# sparse HIGH-tier arrivals, run interleaved A/B qos-off vs qos-on, per-tier
# latency percentiles recorded. The acceptance frame (ISSUE 8): a high-tier
# eval's storm p99 should be bounded near the idle p50 instead of riding the
# whole low-tier backlog.
SLO_NODES = int(os.environ.get("BENCH_SLO_NODES", 2000))
# Enough low-tier submissions that a real backlog exists when the high
# arrivals land behind it (the tail being measured IS queue wait).
SLO_LOW = int(os.environ.get("BENCH_SLO_LOW", 400))
SLO_HIGH = int(os.environ.get("BENCH_SLO_HIGH", 12))
SLO_REPS = int(os.environ.get("BENCH_SLO_REPS", 3))
RUN_SLO = os.environ.get("BENCH_SLO", "1") != "0"
# Service columnar-commit A/B (bench_service_columnar_ab): the same
# service storm served with columnar commits on vs off, servers live
# simultaneously, reps interleaved with ALTERNATING within-pair order
# (the cgroup quota punishes whoever runs second), max-of-reps.
SVC_AB_NODES = int(os.environ.get("BENCH_SVC_NODES", 2000))
SVC_AB_EVALS = int(os.environ.get("BENCH_SVC_EVALS", 60))
SVC_AB_REPS = int(os.environ.get("BENCH_SVC_REPS", 3))
RUN_SVC_AB = os.environ.get("BENCH_SVC_AB", "1") != "0"
# Smoke gate on the store microbench: columnar service-window commit must
# beat the per-object path by at least this factor (parity-style exit 2).
# Measured ~8-15x on a quiet box; 3x leaves noise headroom.
STORE_SVC_GATE = float(os.environ.get("BENCH_STORE_GATE", 3.0))
# config6_mesh_1m (bench_mesh_1m): the ISSUE-12 headline shape — 1M nodes
# x one wide storm window — as a keyed-kernel 1dev-vs-8dev-mesh A/B with
# per-window latency percentiles. The 8 virtual devices need XLA's
# device-count flag set BEFORE jax initializes, so the measurement runs
# in a clean subprocess (`bench.py --_mesh-child`). Slow-gated: --smoke
# turns it off (a 1M-node compile alone blows the 60s budget; tier-1
# covers the mesh path via tests/test_mesh_keyed_equivalence.py and the
# collective audit, and the multichip dry run reports the full sweep).
MESH_NODES = int(os.environ.get("BENCH_MESH_NODES", 1_048_576))
MESH_P = int(os.environ.get("BENCH_MESH_P", 1024))
MESH_VALID = int(os.environ.get("BENCH_MESH_VALID", 800))
MESH_WINDOWS = int(os.environ.get("BENCH_MESH_WINDOWS", 6))
MESH_REPS = int(os.environ.get("BENCH_MESH_REPS", 3))
RUN_MESH = os.environ.get("BENCH_MESH", "1") != "0"
# failover_storm (bench_failover_storm, ISSUE 13): a real 3-server
# in-process cluster (raft + gossip + QoS lanes + streaming snapshots)
# rides a mixed-priority storm through an induced LEADER KILL, recording
# placements/s and per-tier e2e percentiles THROUGH the election plus
# the measured leader gap. Parity-style exit-2 gate: zero lost evals,
# zero duplicate allocs. --smoke runs the small variant; the full storm
# is the slow-gated shape.
FAILOVER_NODES = int(os.environ.get("BENCH_FAILOVER_NODES", 96))
FAILOVER_JOBS = int(os.environ.get("BENCH_FAILOVER_JOBS", 90))
FAILOVER_PER_JOB = int(os.environ.get("BENCH_FAILOVER_PER_JOB", 4))
RUN_FAILOVER = os.environ.get("BENCH_FAILOVER", "1") != "0"
# config7_federation (bench_federation_storm, ISSUE 14): a mixed-priority
# storm CONCENTRATED in one region of a real 3-region federated cluster
# (gossip + cross-region forwarding + follower-snapshot workers + per-
# region QoS), A/B'd against the all-on-leader baseline — ONE region
# holding the same total fleet, the same total storm, and the same total
# worker count on a single leader (the pre-federation shape the tentpole
# scales out). Reps interleaved with ALTERNATING within-pair order,
# max-of-reps (this box's cgroup quota punishes whoever runs second).
# Records per-region evals/s, cross-region forward p99, per-region
# high-tier p99. Parity-style exit-2 gate: zero lost evals, no duplicate
# allocs, storm-free regions' high-tier p99 within the high SLO
# deadline, and the federated side actually sharing snapshots.
FED_NODES = int(os.environ.get("BENCH_FED_NODES", 48))    # per region
FED_JOBS = int(os.environ.get("BENCH_FED_JOBS", 48))      # storm region
FED_QUIET_HIGH = int(os.environ.get("BENCH_FED_QUIET_HIGH", 6))
FED_PER_JOB = int(os.environ.get("BENCH_FED_PER_JOB", 4))
FED_REPS = int(os.environ.get("BENCH_FED_REPS", 3))
RUN_FED = os.environ.get("BENCH_FED", "1") != "0"
# event_stream (bench_event_stream, ISSUE 18): the SAME service storm
# served with the cluster event broker ARMED (event_buffer_size=4096 +
# one live subscriber draining fan-out rows the whole run) vs DISARMED
# (event_buffer_size=0: no broker object; the apply path pays one
# attribute check). Interleaved reps, alternating order, max-of-reps.
# Records per-side evals/s, the publish overhead %, and the armed
# broker's nomad.events counters (published / dropped / ring depth).
# Parity-style exit-2 gate: both sides place the full storm every rep,
# the subscriber really consumed the storm, and nothing was dropped.
EVENTS_AB_NODES = int(os.environ.get("BENCH_EVENTS_NODES", 2048))
EVENTS_AB_EVALS = int(os.environ.get("BENCH_EVENTS_EVALS", 40))
EVENTS_AB_REPS = int(os.environ.get("BENCH_EVENTS_REPS", 3))
RUN_EVENTS = os.environ.get("BENCH_EVENTS", "1") != "0"

# Replica-digest A/B (bench_digest): the apply-path hash-chain fold
# (digest_interval=64, the deployed default) vs disarmed
# (digest_interval=0: no digest object; apply pays one attribute
# check). Parity-style exit-2 gate: both sides place the full storm
# every rep, the armed chain really folded every commit, and it never
# flagged a divergence against itself.
DIGEST_AB_NODES = int(os.environ.get("BENCH_DIGEST_NODES", 2048))
DIGEST_AB_EVALS = int(os.environ.get("BENCH_DIGEST_EVALS", 40))
DIGEST_AB_REPS = int(os.environ.get("BENCH_DIGEST_REPS", 3))
RUN_DIGEST = os.environ.get("BENCH_DIGEST", "1") != "0"


def _apply_smoke():
    """--smoke: tiny CPU-safe shapes, <60s end to end. Same code path as
    the full bench — live server, pipelined worker, plan applier, and the
    placement-parity quality gate — so perf-path breakage is caught
    in-tree (tests/test_bench_smoke.py) without a TPU bench run. Numbers
    from a smoke run are NOT comparable to the headline shapes."""
    global N_NODES, N_PLACEMENTS, N_REPS, CPU_REF_EVALS
    global RUN_C2, RUN_C4, RUN_C5, PARITY_NODES, PARITY_EVALS
    global SCALING_NODES, SCALING_EVALS, C4_EVALS
    global SLO_NODES, SLO_LOW, SLO_HIGH, SLO_REPS
    global SVC_AB_NODES, SVC_AB_EVALS, SVC_AB_REPS, RUN_MESH
    global FAILOVER_NODES, FAILOVER_JOBS
    global FED_NODES, FED_JOBS, FED_QUIET_HIGH, FED_REPS
    global EVENTS_AB_NODES, EVENTS_AB_EVALS, EVENTS_AB_REPS
    global DIGEST_AB_NODES, DIGEST_AB_EVALS, DIGEST_AB_REPS
    N_NODES = min(N_NODES, 512)
    N_PLACEMENTS = min(N_PLACEMENTS, 2000)   # 40 evals @ PER_EVAL=50
    N_REPS = min(N_REPS, 3)
    CPU_REF_EVALS = min(CPU_REF_EVALS, 6)
    RUN_C2 = RUN_C5 = False
    # The system config STAYS on at smoke scale (512-node sweeps, 4
    # timed evals): the tensor-sweep path has no other in-tree perf
    # gate, so a system-path regression must surface in every smoke
    # JSON, not just full runs. ~5s of the <60s budget.
    RUN_C4 = True
    C4_EVALS = min(C4_EVALS, 4)
    PARITY_NODES, PARITY_EVALS = 200, 10
    # The scaling sweep is already smoke-shaped; trim the node count and
    # rep length so the whole smoke run stays under its 60s budget. The
    # rep COUNT stays at the default: the max-of-reps ratio needs samples
    # more than the budget needs the ~2s back.
    SCALING_NODES = min(SCALING_NODES, 256)
    SCALING_EVALS = min(SCALING_EVALS, 40)
    # The QoS storm STAYS on at smoke scale (parity-gated: qos-off and
    # qos-on must place identically): the tiered broker / deadline-window
    # path has no other in-tree perf gate. A few seconds of budget.
    SLO_NODES = min(SLO_NODES, 256)
    SLO_LOW = min(SLO_LOW, 24)
    SLO_HIGH = min(SLO_HIGH, 6)
    SLO_REPS = min(SLO_REPS, 2)
    # The service columnar A/B STAYS on at smoke scale: the columnar
    # service commit has its in-tree microbench gate (store section), but
    # the e2e interleave is the only place an A/B parity break (columnar
    # placing differently from object) would surface. A few seconds.
    SVC_AB_NODES = min(SVC_AB_NODES, 256)
    SVC_AB_EVALS = min(SVC_AB_EVALS, 20)
    SVC_AB_REPS = min(SVC_AB_REPS, 2)
    # The failover storm STAYS on at smoke scale (the zero-loss gate is
    # the only bench-side check that an election loses nothing); the
    # full 90-job storm is the slow-gated shape. A few seconds.
    FAILOVER_NODES = min(FAILOVER_NODES, 24)
    FAILOVER_JOBS = min(FAILOVER_JOBS, 24)
    # The federation storm STAYS on at smoke scale: its zero-loss /
    # no-duplicate / quiet-region-p99 gate is the only bench-side check
    # of the cross-region forwarding + follower-snapshot path. A few
    # seconds of budget (4 single-raft servers, tiny storms).
    FED_NODES = min(FED_NODES, 12)
    # >= 4 windows of backlog in the storm region (window=8): snapshot
    # REUSE only exists once dequeues stop chasing fresh registrations,
    # and the gate requires proving it happened.
    FED_JOBS = min(FED_JOBS, 27)
    FED_QUIET_HIGH = min(FED_QUIET_HIGH, 3)
    FED_REPS = min(FED_REPS, 2)
    # The event-stream A/B STAYS on at smoke scale: the broker-armed vs
    # disarmed interleave (plus its zero-drop gate) is the only bench-
    # side check that publishing + one live subscriber costs the apply
    # path nothing measurable. A few seconds of budget.
    EVENTS_AB_NODES = min(EVENTS_AB_NODES, 256)
    EVENTS_AB_EVALS = min(EVENTS_AB_EVALS, 16)
    EVENTS_AB_REPS = min(EVENTS_AB_REPS, 2)
    # The replica-digest A/B STAYS on at smoke scale: the fold is ON the
    # apply path for every deployment (digest_interval defaults to 64),
    # so its overhead and its parity gate must surface in every smoke
    # JSON. A few seconds of budget.
    DIGEST_AB_NODES = min(DIGEST_AB_NODES, 256)
    DIGEST_AB_EVALS = min(DIGEST_AB_EVALS, 16)
    DIGEST_AB_REPS = min(DIGEST_AB_REPS, 2)
    # The 1M mesh A/B is slow-gated OUT of smoke (its subprocess compile
    # alone blows the budget); the mesh path's correctness coverage is
    # tier-1 (equivalence gate + collective audit + chaos schedule).
    RUN_MESH = False


def _freeze_heap():
    """Collect, then freeze every survivor out of the collector's view.
    THE one between-rep GC treatment: every timed loop (headline, config
    benches, and the CPU-served denominator) calls this so the
    served-vs-served ratio can never drift onto unequal GC footing."""
    gc.collect()
    gc.freeze()


def _tune_gc():
    """Server-process runtime tuning, applied identically before BOTH
    sides' timed reps (TPU-served and CPU-served): collect, freeze the
    steady-state heap (10k node structs + server machinery) out of the
    collector's view, and raise the gen-0 threshold so a 20k-alloc
    registration storm doesn't trigger full-heap scans mid-rep. The
    analogue of running the Go reference with a tuned GOGC — a deployment
    setting, not a code path. The GIL switch interval rises from its 5ms
    default for the same reason: a scheduling server runs several
    GIL-bound stage threads (N workers x dispatch/drain/build + the plan
    applier), and 200 preemptions/sec of the dispatch loop is measurable
    convoy overhead on a small core count."""
    _freeze_heap()
    gc.set_threshold(50_000, 50, 50)
    sys.setswitchinterval(0.02)


def build_nodes(n, n_dcs=1):
    from nomad_tpu import mock
    from nomad_tpu.structs import compute_node_class

    nodes = []
    for i in range(n):
        node = mock.node()
        node.Meta["rack"] = f"r{i % N_PARTITIONS}"  # 64 computed classes
        if n_dcs > 1:
            node.Datacenter = f"dc{i % n_dcs + 1}"
        compute_node_class(node)
        nodes.append(node)
    return nodes


def build_job(per_eval=PER_EVAL, dcs=None):
    from nomad_tpu import mock
    from nomad_tpu.structs import Constraint

    job = mock.job()
    if dcs:
        job.Datacenters = list(dcs)
    tg = job.TaskGroups[0]
    tg.Count = per_eval
    # Driver checker (exec) is already on the mock task; add an attribute
    # constraint so the full checker chain runs (BASELINE config 3).
    job.Constraints.append(
        Constraint(LTarget="${attr.arch}", RTarget="x86", Operand="="))
    # Small asks so the node pool absorbs the placements without exhaustion.
    task = tg.Tasks[0]
    task.Resources.CPU = 20
    task.Resources.MemoryMB = 32
    task.Resources.DiskMB = 10
    task.Resources.Networks = []
    task.Services = []
    # Keep per-task log storage under the small disk ask (validation:
    # LogConfig total must fit DiskMB).
    if task.LogConfig is not None:
        task.LogConfig.MaxFiles = 1
        task.LogConfig.MaxFileSizeMB = 1
    return job


def _make_storm_runner(srv, job_fn=None):
    """Register `count` jobs and poll until every eval completes — the
    measured unit of work, shared by BOTH sides of the served-vs-served
    ratio so the two benchmarks can never drift apart."""
    from nomad_tpu.structs.structs import EvalStatusComplete

    if job_fn is None:
        job_fn = build_job

    def run(count, poll=0.02, latencies=None):
        t_submit = {}
        eval_ids = []
        for _ in range(count):
            eid = srv.job_register(job_fn())[0]
            t_submit[eid] = time.monotonic()
            eval_ids.append(eid)
        deadline = time.monotonic() + 600
        pending = set(eval_ids)
        while pending and time.monotonic() < deadline:
            now = time.monotonic()
            done = {eid for eid in pending
                    if (e := srv.state.eval_by_id(eid)) is not None
                    and e.Status == EvalStatusComplete}
            if latencies is not None:
                # In-storm per-eval latency, submit -> observed complete.
                # Quantized by the poll period (+poll worst case): fine
                # for storm tails, which sit far above the poll. The
                # windowed design trades tail for throughput — these
                # percentiles are where that trade is visible.
                latencies.extend(now - t_submit[eid] for eid in done)
            pending -= done
            if pending:
                # Coarse poll: the measured path runs in server threads; a
                # hot completion-poll loop would steal interpreter time
                # from the very workers being measured. (Latency probes
                # pass a finer poll so the granularity doesn't dominate.)
                time.sleep(poll)
        if pending:
            raise RuntimeError(f"{len(pending)} evals never completed")
        return eval_ids

    return run


def _pctiles_ms(lats):
    """{p50, p95, p99} in ms from a list of second-latencies."""
    if not lats:
        return {}
    return {f"p{p}": round(float(np.percentile(lats, p)) * 1e3, 2)
            for p in (50, 95, 99)}


def bench_server_e2e(nodes, n_evals):
    """The SERVED path: a live dev-mode server with the pipelined worker.
    Clock runs from first job_register to the last eval completing with its
    allocations committed in the state store."""
    from nomad_tpu.server import Server, ServerConfig

    # Benchmark nodes never heartbeat: park the TTLs out past the run.
    srv = Server(ServerConfig(num_schedulers=N_WORKERS,
                              pipelined_scheduling=True,
                              scheduler_window=WINDOW,
                              min_heartbeat_ttl=24 * 3600.0,
                              heartbeat_grace=24 * 3600.0))
    srv.establish_leadership()
    try:
        for node in nodes:
            srv.node_register(node)

        run = _make_storm_runner(srv)

        # Warmup: two rounds — the first compiles the placement kernels, the
        # second's window observes the first's committed allocs and compiles
        # the dirty-row device refresh program.
        run(3)
        run(3)
        # Compile the remaining dirty-row refresh buckets now: a full rep
        # dirties ~10k usage rows, whose 16384-row refresh program would
        # otherwise compile inside the SECOND timed rep (the first rep rides
        # the chain and skips usage refresh). Compiles are one-time server
        # lifetime costs; the timed reps still pay every refresh TRANSFER.
        srv.tindex.nt.warm_device()
        # One full-size warm storm: deep windows fuse into place_batch_multi
        # at the LARGE eval-pad buckets, whose first compile would otherwise
        # land inside the first timed rep (same one-time-cost rationale).
        run(n_evals)
        _tune_gc()
        # Attribute phase timers to the timed reps only, not warmup compiles.
        # Quiesce first: evals complete (visibly) at the EvalUpdate apply,
        # before the build stage's final stats writes for the window.
        # reset_stats() zeroes the DECLARED schema in place, so this loop
        # cannot drift from the keys the worker actually maintains.
        for w in srv.workers:
            if hasattr(w, "quiesce"):
                w.quiesce(30.0)
            if hasattr(w, "reset_stats"):
                w.reset_stats()

        # Median of N_REPS timed reps: the remote-attached TPU's round-trip
        # latency wanders between runs, and a single sample can be off 2x
        # in either direction. Reps accumulate allocations in the cluster
        # (like a real registration storm would); at the default shapes the
        # node pool has >100x headroom, so fill effects are negligible.
        rates = []
        eval_ids = []
        storm_lats: list = []
        for _ in range(N_REPS):
            t0 = time.perf_counter()
            eval_ids = run(n_evals, latencies=storm_lats)
            rates.append(n_evals / (time.perf_counter() - t0))
            # Freeze each rep's ~30k surviving allocs out of the
            # collector's view BETWEEN reps (untimed): without this,
            # later reps pay growing gen1 scans over every prior rep's
            # live heap and the rate decays ~30% from rep 1 to rep 9 —
            # a measurement artifact, not scheduler behavior. Same
            # steady-state-deployment rationale as _tune_gc.
            _freeze_heap()
        # Lower-middle median: never report the faster of an even pair.
        rate = sorted(rates)[(len(rates) - 1) // 2]

        placed = sum(
            1 for eid in eval_ids
            for a in srv.state.allocs_by_eval(eid))
        stats: dict = {}
        for w in srv.workers:
            if hasattr(w, "quiesce"):
                w.quiesce(30.0)
            for k, v in list(w.stats.items()):
                stats[k] = stats.get(k, 0) + v
        # Counters below cover ALL timed reps (N_REPS x n_evals evals).
        stats["timed_reps"] = len(rates)
        stats["rep_rates"] = [round(r, 1) for r in rates]
        stats["rep_min_med_max"] = [round(min(rates), 1), round(rate, 1),
                                    round(max(rates), 1)]
        # Served-path single-eval latency on an idle broker (the number an
        # interactive `nomad run` pays): registration -> placement ->
        # commit, via the host fast path when the window is shallow.
        lats = []
        for _ in range(5):
            t0 = time.perf_counter()
            run(1, poll=0.002)
            lats.append(time.perf_counter() - t0)
        stats["e2e_p50_eval_latency_ms"] = round(
            float(np.percentile(lats, 50)) * 1e3, 2)
        # In-storm percentiles over every timed rep's evals: an eval's
        # latency under load includes waiting for its window slot — the
        # tail the windowed design trades for throughput.
        stats["e2e_storm_latency_ms"] = _pctiles_ms(storm_lats)
        return rate, placed, stats
    finally:
        srv.shutdown()


def bench_served_config(nodes, job_fn, n_evals, reps=2, warm=3,
                        window=None, latency_probes=3, workers=None):
    """Generic SERVED-path benchmark for one BASELINE config: live server,
    pipelined worker, clock from first register to last commit. Returns
    (median evals/sec, total placed, p50 single-eval latency, rep rates)."""
    from nomad_tpu.server import Server, ServerConfig

    srv = Server(ServerConfig(num_schedulers=workers or N_WORKERS,
                              pipelined_scheduling=True,
                              scheduler_window=window or WINDOW,
                              min_heartbeat_ttl=24 * 3600.0,
                              heartbeat_grace=24 * 3600.0))
    srv.establish_leadership()
    try:
        for node in nodes:
            srv.node_register(node)
        run = _make_storm_runner(srv, job_fn)
        run(warm)
        run(warm)
        srv.tindex.nt.warm_device()
        # Same treatment as the headline bench: one full-size warm storm so
        # the large eval-pad place_batch_multi buckets compile before the
        # first timed rep (symmetric warmup keeps the configs comparable).
        run(n_evals)
        _tune_gc()
        rates = []
        eval_ids = []
        storm_lats: list = []
        for _ in range(reps):
            t0 = time.perf_counter()
            eval_ids = run(n_evals, latencies=storm_lats)
            rates.append(n_evals / (time.perf_counter() - t0))
            # Same between-rep GC treatment as the headline bench (and
            # the CPU-served denominator): freeze each rep's survivors
            # out of the collector's view, untimed.
            _freeze_heap()
        placed = sum(1 for eid in eval_ids
                     for _ in srv.state.allocs_by_eval(eid))
        lats = []
        for _ in range(latency_probes):
            t0 = time.perf_counter()
            run(1, poll=0.002)
            lats.append(time.perf_counter() - t0)
        # Lower-middle for even rep counts: upper-middle would report the
        # FASTER of two reps as "the median" (optimistic bias).
        med = sorted(rates)[(len(rates) - 1) // 2]
        return (med, placed,
                float(np.percentile(lats, 50)) if lats else 0.0,
                [round(r, 2) for r in rates],
                _pctiles_ms(storm_lats))
    finally:
        srv.shutdown()


def bench_worker_scaling():
    """1-vs-2-worker scaling of the served path, at smoke shapes. The
    bench JSON records {workers_1, workers_2, ratio} so a scaling
    regression (a second worker making things SLOWER — the pre-arbiter
    state) is caught by trajectory review, not rediscovered by hand.

    Both servers stay up and the timed reps INTERLEAVE (1w, 2w, 1w, 2w,
    ...): short reps on a box with background load wander ±30%, and
    interleaving puts both sides under the same drift instead of handing
    one config a quiet machine. The reported rate is max-of-reps — the
    ratio compares peak capability, and a max over a handful of short
    reps is far less noisy than their median.

    The sweep forces the DEVICE chain (host_placement=False): N-worker
    scaling is a property of the device-chained architecture — async
    kernel dispatches and GIL-releasing fetches are what one worker's
    stages overlap with another's — and at smoke shapes the host-numpy
    fallback would otherwise swallow the whole window into GIL-bound
    Python, where a second worker can only ever tie (measured: host-path
    ratio ~0.97-1.13 pure noise around parity; device-path ratio >1
    consistently on a 2-core CPU box)."""
    from nomad_tpu.server import Server, ServerConfig

    nodes = build_nodes(SCALING_NODES)
    servers = {}
    out: dict = {"nodes": SCALING_NODES, "evals_per_rep": SCALING_EVALS}
    try:
        for n in (1, 2):
            srv = Server(ServerConfig(num_schedulers=n,
                                      pipelined_scheduling=True,
                                      scheduler_window=WINDOW,
                                      host_placement=False,
                                      min_heartbeat_ttl=24 * 3600.0,
                                      heartbeat_grace=24 * 3600.0))
            srv.establish_leadership()
            for node in nodes:
                srv.node_register(node)
            run = _make_storm_runner(srv)
            run(2)
            run(2)
            srv.tindex.nt.warm_device()
            run(SCALING_EVALS)  # full-size warm storm (compiles)
            servers[n] = (srv, run)
        _tune_gc()
        for n in (1, 2):
            # One untimed pair after the GC tuning: the first post-freeze
            # storm pays one-off collector/cache effects that otherwise
            # land inside whichever config runs first.
            servers[n][1](SCALING_EVALS)
            _freeze_heap()
        rates: dict = {1: [], 2: []}
        for _ in range(SCALING_REPS):
            for n in (1, 2):  # interleaved A/B pair
                srv, run = servers[n]
                for w in srv.workers:
                    if hasattr(w, "quiesce"):
                        w.quiesce(30.0)
                t0 = time.perf_counter()
                eval_ids = run(SCALING_EVALS)
                rates[n].append(
                    round(SCALING_EVALS / (time.perf_counter() - t0), 2))
                _freeze_heap()
                # Per-rep placed counts (not just the last rep's): an
                # under-placing rep is exactly the regression class the
                # sweep exists to surface.
                out.setdefault(f"workers_{n}_placed", []).append(sum(
                    1 for eid in eval_ids
                    for _ in srv.state.allocs_by_eval(eid)))
        for n in (1, 2):
            out[f"workers_{n}"] = max(rates[n])
            out[f"workers_{n}_rep_rates"] = rates[n]
        out["ratio"] = round(out["workers_2"] / out["workers_1"], 3) \
            if out["workers_1"] else None
        return out
    finally:
        for srv, _ in servers.values():
            srv.shutdown()


def build_slo_job(priority, per_eval=8):
    """slo_storm job shape: small placement count so the storm is
    QUEUE-bound (the tails under test come from broker wait, not device
    compute), with an explicit priority tier."""
    job = build_job(per_eval)
    job.Priority = priority
    return job


def bench_slo_storm():
    """QoS mixed-priority storm: a saturating LOW-tier burst with sparse
    HIGH-tier arrivals behind it, measured twice — qos-off (today's FIFO
    path) and qos-on (tiered lanes + deadline windows) — with the timed
    reps INTERLEAVED on live servers like the worker-scaling sweep, so
    both sides see the same machine drift. Records per-tier e2e latency
    percentiles, the qos-on/off throughput ratio (the overhead bound),
    admission + preemption probe counts, and a PARITY gate: with ample
    capacity both modes must place every storm alloc.

    The acceptance frame (ISSUE 8): qos-on high-tier storm p99 bounded
    near the idle e2e p50 instead of riding the whole low-tier backlog —
    reported as high_p99_vs_idle_p50 for trajectory review."""
    from nomad_tpu.qos import QoSConfig
    from nomad_tpu.server import Server, ServerConfig
    from nomad_tpu.structs.structs import EvalStatusComplete

    per_eval = 8
    expect_allocs = (SLO_LOW + SLO_HIGH) * per_eval

    def run_mixed(srv, lats=None):
        """One mixed rep: low burst, then the high arrivals it buries."""
        tiers = {}
        t_submit = {}
        for _ in range(SLO_LOW):
            eid = srv.job_register(build_slo_job(10, per_eval))[0]
            tiers[eid] = "low"
            t_submit[eid] = time.monotonic()
        for _ in range(SLO_HIGH):
            eid = srv.job_register(build_slo_job(90, per_eval))[0]
            tiers[eid] = "high"
            t_submit[eid] = time.monotonic()
        pending = set(tiers)
        deadline = time.monotonic() + 600
        while pending and time.monotonic() < deadline:
            now = time.monotonic()
            done = {eid for eid in pending
                    if (e := srv.state.eval_by_id(eid)) is not None
                    and e.Status == EvalStatusComplete}
            if lats is not None:
                for eid in done:
                    lats[tiers[eid]].append(now - t_submit[eid])
            pending -= done
            if pending:
                # Finer poll than the throughput storms: high-tier
                # latencies are the measurement and can sit near 10ms.
                time.sleep(0.005)
        if pending:
            raise RuntimeError(f"{len(pending)} slo evals never completed")
        return list(tiers)

    nodes = build_nodes(SLO_NODES)
    out = {"nodes": SLO_NODES, "low_jobs": SLO_LOW, "high_jobs": SLO_HIGH,
           "placements_per_eval": per_eval}
    servers = {}
    try:
        for mode in ("qos_off", "qos_on"):
            # burn_shed > 1 disables SLO-burn shedding for the PARITY
            # storm: the gate asserts identical placed counts, so
            # admission must not shed mid-rep on a slow box. The
            # admission probe below exercises shedding deterministically.
            qos = QoSConfig(enabled=mode == "qos_on", burn_shed=2.0)
            srv = Server(ServerConfig(num_schedulers=N_WORKERS,
                                      pipelined_scheduling=True,
                                      scheduler_window=WINDOW,
                                      qos=qos,
                                      min_heartbeat_ttl=24 * 3600.0,
                                      heartbeat_grace=24 * 3600.0))
            srv.establish_leadership()
            for node in nodes:
                srv.node_register(node)
            run_mixed(srv)  # warm (compiles, first snapshots)
            srv.tindex.nt.warm_device()
            servers[mode] = srv
        _tune_gc()
        rates = {"qos_off": [], "qos_on": []}
        lats = {"qos_off": {"high": [], "low": []},
                "qos_on": {"high": [], "low": []}}
        placed = {}
        for _ in range(SLO_REPS):
            for mode in ("qos_off", "qos_on"):  # interleaved A/B pair
                srv = servers[mode]
                for w in srv.workers:
                    if hasattr(w, "quiesce"):
                        w.quiesce(30.0)
                t0 = time.perf_counter()
                eval_ids = run_mixed(srv, lats=lats[mode])
                rates[mode].append(
                    (SLO_LOW + SLO_HIGH) / (time.perf_counter() - t0))
                placed.setdefault(mode, []).append(sum(
                    1 for eid in eval_ids
                    for _ in srv.state.allocs_by_eval(eid)))
                _freeze_heap()
        for mode in ("qos_off", "qos_on"):
            out[mode] = {
                "evals_sec": round(max(rates[mode]), 2),
                "rep_rates": [round(r, 2) for r in rates[mode]],
                "high_ms": _pctiles_ms(lats[mode]["high"]),
                "low_ms": _pctiles_ms(lats[mode]["low"]),
                "placed_per_rep": placed[mode],
            }
        on = servers["qos_on"]
        out["qos_on"]["window_cuts"] = sum(
            w.stats.get("qos_cut", 0) for w in on.workers)
        out["qos_on"]["promoted"] = on.eval_broker.tier_promotions()
        out["throughput_ratio"] = round(
            max(rates["qos_on"]) / max(rates["qos_off"]), 3) \
            if rates["qos_off"] else None
        # Idle-broker single-eval p50 on the qos-on server — the
        # denominator of the tail bound.
        idle = []
        for _ in range(5):
            t0 = time.perf_counter()
            run_mixed_single(on, per_eval)
            idle.append(time.perf_counter() - t0)
        out["idle_p50_ms"] = round(
            float(np.percentile(idle, 50)) * 1e3, 2)
        high_p99 = out["qos_on"]["high_ms"].get("p99")
        out["high_p99_vs_idle_p50"] = round(
            high_p99 / out["idle_p50_ms"], 2) \
            if high_p99 and out["idle_p50_ms"] else None
        off_p99 = out["qos_off"]["high_ms"].get("p99")
        out["high_p99_improvement"] = round(off_p99 / high_p99, 2) \
            if high_p99 and off_p99 else None
        # Parity gate: ample capacity, so BOTH modes must place the full
        # storm every rep — QoS reorders, it must never drop placements.
        out["parity_ok"] = all(
            p == expect_allocs for mode in placed for p in placed[mode])
        out["expected_allocs"] = expect_allocs
    finally:
        for srv in servers.values():
            srv.shutdown()

    out["admission_probe"] = _slo_admission_probe()
    out["preempt_probe"] = _slo_preempt_probe()
    return out


def run_mixed_single(srv, per_eval):
    """One high-tier eval against an idle broker (idle-p50 probe)."""
    from nomad_tpu.structs.structs import EvalStatusComplete

    eid = srv.job_register(build_slo_job(90, per_eval))[0]
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        e = srv.state.eval_by_id(eid)
        if e is not None and e.Status == EvalStatusComplete:
            return [eid]
        time.sleep(0.002)
    raise RuntimeError("idle probe eval never completed")


def _slo_admission_probe():
    """Deterministic admission exercise: a workerless leader (queue depth
    can't drain) with a low-tier depth limit of 1 — the second low-tier
    submission must shed with the typed backpressure error."""
    from nomad_tpu.qos import QoSBackpressureError, QoSConfig
    from nomad_tpu.server import Server, ServerConfig

    srv = Server(ServerConfig(num_schedulers=0,
                              qos=QoSConfig(enabled=True,
                                            admit_depth=(0, 8192, 1)),
                              min_heartbeat_ttl=24 * 3600.0,
                              heartbeat_grace=24 * 3600.0))
    srv.establish_leadership()
    try:
        for node in build_nodes(2):
            srv.node_register(node)
        srv.job_register(build_slo_job(10, 1))
        shed = 0
        try:
            srv.job_register(build_slo_job(10, 1))
        except QoSBackpressureError:
            shed = 1
        counters = srv.qos_counters.snapshot()
        return {"shed": shed, "admitted": counters["admitted"],
                "ok": shed == 1}
    finally:
        srv.shutdown()


def _slo_preempt_probe():
    """Deterministic preemption exercise: two nearly-full nodes of
    low-tier load, then a high-tier job that fits nowhere — it must evict
    exactly one victim and place, atomically."""
    from nomad_tpu.qos import QoSConfig
    from nomad_tpu.server import Server, ServerConfig
    from nomad_tpu.structs.structs import (
        AllocDesiredStatusEvict,
        EvalStatusComplete,
    )

    srv = Server(ServerConfig(num_schedulers=1,
                              qos=QoSConfig(enabled=True),
                              min_heartbeat_ttl=24 * 3600.0,
                              heartbeat_grace=24 * 3600.0))
    srv.establish_leadership()
    try:
        for node in build_nodes(2):
            node.Resources.CPU = 1000
            node.Reserved = None
            srv.node_register(node)

        def fat_job(prio, cpu):
            job = build_slo_job(prio, 1)
            job.TaskGroups[0].Tasks[0].Resources.CPU = cpu
            return job

        def wait(eid):
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                e = srv.state.eval_by_id(eid)
                if e is not None and e.Status == EvalStatusComplete:
                    return True
                time.sleep(0.01)
            return False

        for _ in range(2):
            assert wait(srv.job_register(fat_job(10, 800))[0])
        heid = srv.job_register(fat_job(90, 600))[0]
        ok = wait(heid)
        placed = len(list(srv.state.allocs_by_eval(heid)))
        evicted = sum(1 for a in srv.state.allocs()
                      if a.DesiredStatus == AllocDesiredStatusEvict)
        counters = srv.qos_counters.snapshot()
        return {"placed": placed, "evicted": evicted,
                "preempt_placed": counters["preempt_placed"],
                "preempt_evictions": counters["preempt_evictions"],
                "ok": bool(ok and placed == 1 and evicted >= 1)}
    finally:
        srv.shutdown()


def bench_failover_storm():
    """Zero-downtime gate (ISSUE 13): a mixed-priority storm against a
    REAL 3-server cluster — raft replication, gossip failure detection,
    QoS lanes, streaming snapshots (low threshold so persists run
    mid-storm) — with the leader killed a third of the way in. Records
    placements/s through the whole storm (election included), per-tier
    e2e latency percentiles (the election wait lands in the tails of
    whatever was queued at the kill), the measured kill->new-leader gap,
    and the zero-loss gate: every eval terminal, every job at exactly
    its asked-for live allocs, no duplicate alloc IDs."""
    import random as _random
    import threading as _threading

    from nomad_tpu import mock
    from nomad_tpu.gossip import GossipConfig
    from nomad_tpu.qos import QoSConfig
    from nomad_tpu.raft import RaftConfig
    from nomad_tpu.rpc.cluster import ClusterServer
    from nomad_tpu.server import ServerConfig
    from nomad_tpu.structs import to_dict
    from nomad_tpu.structs.structs import (
        EvalStatusCancelled,
        EvalStatusComplete,
        EvalStatusFailed,
    )

    terminal = (EvalStatusComplete, EvalStatusFailed, EvalStatusCancelled)
    raft_cfg = RaftConfig(heartbeat_interval=0.02,
                          election_timeout_min=0.08,
                          election_timeout_max=0.16, apply_timeout=5.0,
                          snapshot_threshold=30, trailing_logs=32)

    def boot(name, join=None):
        cs = ClusterServer(ServerConfig(
            node_id="", num_schedulers=1, bootstrap_expect=3,
            scheduler_window=8,
            # Election-scale deadlines: the per-tier burn through the
            # kill is the SLO story, not sub-second compute on a loaded
            # bench box.
            qos=QoSConfig(enabled=True,
                          deadlines_s=(10.0, 30.0, 120.0))))
        cs.connect([], raft_config=raft_cfg)
        cs.start()
        ml_join = join
        cs.enable_gossip(name, join=ml_join,
                         gossip_config=GossipConfig.fast())
        return cs

    def leader_of(live):
        for n in live:
            try:
                if n.server is not None and n.server.is_leader() \
                        and n.server._leader:
                    return n
            except Exception:
                pass
        return None

    def rpc(live, method, args, attempts=80, delay=0.1):
        last = None
        for _ in range(attempts):
            targets = [n for n in live if n.endpoints is not None]
            _random.shuffle(targets)
            for cs in targets:
                try:
                    return cs.endpoints.handle(method, dict(args))
                except Exception as e:
                    last = e
            time.sleep(delay)
        raise last if last is not None else RuntimeError("no servers")

    def gaddr(cs):
        ml = cs.membership.memberlist
        return f"{ml.addr}:{ml.port}"

    tiers = (80, 20, 50)
    tier_name = {80: "high", 20: "low", 50: "normal"}
    nodes = [boot("b0")]
    nodes.append(boot("b1", join=[gaddr(nodes[0])]))
    nodes.append(boot("b2", join=[gaddr(nodes[0])]))
    live = list(nodes)
    out = {"nodes": FAILOVER_NODES, "jobs": FAILOVER_JOBS,
           "per_job": FAILOVER_PER_JOB}
    try:
        deadline = time.monotonic() + 30
        while leader_of(live) is None:
            if time.monotonic() > deadline:
                raise RuntimeError("cluster never elected")
            time.sleep(0.05)
        for _ in range(FAILOVER_NODES):
            rpc(live, "Node.Register", {"Node": to_dict(mock.node())})

        jobs, submit_t, eval_of = [], {}, {}
        lat = {}
        watch_stop = _threading.Event()

        def watcher():
            """Record each eval's submit->terminal latency against
            whichever server currently leads."""
            while True:
                ldr = leader_of(live)
                if ldr is not None:
                    state = ldr.server.state
                    now = time.monotonic()
                    for eid in [e for e in list(eval_of) if e not in lat]:
                        ev = state.eval_by_id(eid)
                        if ev is not None and ev.Status in terminal:
                            lat[eid] = now - submit_t[eid]
                if watch_stop.is_set():
                    return
                time.sleep(0.02)

        wt = _threading.Thread(target=watcher, name="failover-watch",
                               daemon=True)
        wt.start()

        kill_at = max(1, FAILOVER_JOBS // 3)
        recovery_s = None
        t0 = time.monotonic()
        for i in range(FAILOVER_JOBS):
            if i == kill_at:
                victim = leader_of(live)
                if victim is not None:
                    live.remove(victim)
                    tk = time.monotonic()
                    victim.shutdown()
                    while leader_of(live) is None:
                        if time.monotonic() - tk > 30:
                            raise RuntimeError("no post-kill leader")
                        time.sleep(0.02)
                    recovery_s = time.monotonic() - tk
            prio = tiers[i % len(tiers)]
            job = build_job(FAILOVER_PER_JOB)
            job.Priority = prio
            jobs.append(job)
            resp = rpc(live, "Job.Register", {"Job": to_dict(job)})
            # submit_t before eval_of: the watcher keys off eval_of.
            submit_t[resp["EvalID"]] = time.monotonic()
            eval_of[resp["EvalID"]] = prio
            time.sleep(0.005)

        drain_deadline = time.monotonic() + 180
        while len(lat) < len(eval_of):
            if time.monotonic() > drain_deadline:
                break
            time.sleep(0.05)
        t_total = time.monotonic() - t0
        watch_stop.set()
        wt.join(timeout=10)

        ldr = leader_of(live)
        end_wait = time.monotonic() + 15
        while ldr is None and time.monotonic() < end_wait:
            # A second election can be mid-flight at sample time.
            time.sleep(0.05)
            ldr = leader_of(live)
        if ldr is None:
            # Emit a failing gate rather than crash: the exit-2 contract
            # is fail-AFTER-emit.
            out["gate"] = {"ok": False, "error": "no leader after drain",
                           "lost_evals": len(eval_of) - len(lat),
                           "duplicate_allocs": None, "placed": None,
                           "expected": len(jobs) * FAILOVER_PER_JOB}
            return out
        state = ldr.server.state
        lost_evals = len(eval_of) - len(lat)
        placed, dup, all_ids = 0, 0, set()
        for job in jobs:
            job_live = [a for a in state.allocs_by_job(job.ID)
                        if not a.terminal_status()]
            placed += len(job_live)
            for a in job_live:
                if a.ID in all_ids:
                    dup += 1
                all_ids.add(a.ID)
            if len(job_live) != FAILOVER_PER_JOB:
                lost_evals = max(lost_evals, 1)  # under/overshoot = loss
        by_tier = {}
        for eid, prio in eval_of.items():
            if eid in lat:
                by_tier.setdefault(tier_name[prio], []).append(lat[eid])
        out.update({
            "placements_sec": round(placed / t_total, 2)
            if t_total > 0 else None,
            "storm_s": round(t_total, 2),
            "recovery_s": round(recovery_s, 3)
            if recovery_s is not None else None,
            "tier_latency_ms": {t: _pctiles_ms(v)
                                for t, v in sorted(by_tier.items())},
            "slo_burn": dict(zip(("high", "normal", "low"),
                                 [round(b, 4) for b in
                                  ldr.server.eval_broker.slo_burn()])),
            "streaming_snapshot": ldr.server.raft.node.log
            .latest_snapshot_chunks() is not None,
            "gate": {
                "ok": lost_evals == 0 and dup == 0
                and placed == len(jobs) * FAILOVER_PER_JOB
                and recovery_s is not None and recovery_s < 30.0,
                "lost_evals": lost_evals,
                "duplicate_allocs": dup,
                "placed": placed,
                "expected": len(jobs) * FAILOVER_PER_JOB,
            },
        })
        return out
    finally:
        for n in nodes:
            try:
                n.shutdown()
            except Exception:
                pass


def bench_federation_storm():
    """config7_federation (ISSUE 14): a mixed-priority storm concentrated
    in ONE region of a real 3-region federated cluster — cross-region
    forwarding at ingress (two thirds of the storm arrives through the
    other regions' edges), follower-snapshot workers, per-region QoS —
    A/B'd against the all-on-leader baseline: the SAME three servers as
    ONE global raft domain (the pre-federation config5_multidc shape),
    where every commit replicates through one consensus group and every
    worker, commit, and watch rides its single leader. Same total
    fleet, same job multiset, same server count — the delta is the
    topology: region-local authority vs global consensus. Reps
    interleaved with ALTERNATING within-pair order, max-of-reps on
    total evals/s.

    Records per-region evals/s, cross-region forward latency
    percentiles, and per-region high-tier submit->terminal p99. Gate
    (exit-2, fail-after-emit like placement parity): zero lost evals,
    zero duplicate allocs, every job at exactly its asked-for live
    allocs in its HOME region only, the storm-free regions' high-tier
    p99 within the high SLO deadline, and the federated side proving it
    actually shared snapshots (SnapshotSource reuse > 0)."""
    from nomad_tpu import mock
    from nomad_tpu.federation import FederationConfig
    from nomad_tpu.gossip import GossipConfig
    from nomad_tpu.qos import QoSConfig
    from nomad_tpu.qos.admission import QoSBackpressureError
    from nomad_tpu.raft import RaftConfig
    from nomad_tpu.rpc.cluster import ClusterServer
    from nomad_tpu.server import ServerConfig
    from nomad_tpu.structs import to_dict
    from nomad_tpu.structs.structs import (
        EvalStatusCancelled,
        EvalStatusComplete,
        EvalStatusFailed,
    )

    terminal = (EvalStatusComplete, EvalStatusFailed, EvalStatusCancelled)
    raft_cfg = RaftConfig(heartbeat_interval=0.02,
                          election_timeout_min=0.08,
                          election_timeout_max=0.16, apply_timeout=5.0)
    # Election-free storm, but a throttled bench box: election-free
    # deadlines would burn the high ring on compute alone. The quiet
    # regions are gated against deadlines_s[0].
    deadlines = (5.0, 15.0, 60.0)
    storm_region = "east"
    quiet_regions = ("west", "north")
    regions = (storm_region,) + quiet_regions
    tiers = (80, 20, 50)

    def gaddr(cs):
        ml = cs.membership.memberlist
        return f"{ml.addr}:{ml.port}"

    def boot(name, region, n_workers, fed, expect=1, join=None):
        cs = ClusterServer(ServerConfig(
            node_id="", region=region, num_schedulers=n_workers,
            scheduler_window=8, bootstrap_expect=expect,
            # Mock nodes never heartbeat; multi-minute A/B reps must not
            # watch the fleet expire mid-rep (same treatment as every
            # standalone served bench).
            min_heartbeat_ttl=24 * 3600.0, heartbeat_grace=24 * 3600.0,
            # DEVICE chain on both sides: N-worker overlap is a property
            # of the device-chained architecture (async dispatch +
            # GIL-releasing fetches); the host-numpy fallback would
            # swallow every window into GIL-bound Python where the
            # leader's 3 workers and the federation's 3 regions can only
            # ever tie (same treatment as the worker_scaling sweep).
            host_placement=False,
            # Tiered queues + per-region SLO tracking ON; burn-shed
            # disarmed (burn can never exceed 1.0): warmup compiles blow
            # tier deadlines and would poison the burn ring into
            # shedding the first timed rep. The shed paths have their
            # own gates (tests/test_federation.py, slo_storm's probes).
            qos=QoSConfig(enabled=True, deadlines_s=deadlines,
                          burn_shed=1.1),
            federation=fed))
        cs.connect([], raft_config=raft_cfg)
        cs.start()
        cs.enable_gossip(name, join=join,
                         gossip_config=GossipConfig.fast())
        return cs

    class _Edge:
        """One federated region server as a submission/read target."""

        def __init__(self, cs):
            self.cs = cs

        def handle(self, method, body):
            return self.cs.endpoints.handle(method, body)

        def eval_by_id(self, eid):
            return self.cs.server.state.eval_by_id(eid)

        def allocs_by_job(self, job_id):
            return self.cs.server.state.allocs_by_job(job_id)

    class _Domain:
        """The baseline's 3-server raft domain as the same target shape:
        submits retry across servers (an election mid-storm is the
        domain's problem, not the client's), reads go to the current
        leader's replicated store."""

        def __init__(self, servers):
            self.servers = servers

        def leader(self):
            for cs in self.servers:
                try:
                    if (cs.server is not None and cs.server.is_leader()
                            and cs.server._leader):
                        return cs
                except Exception:
                    pass
            return None

        def handle(self, method, body, attempts=150, delay=0.05):
            # The failover bench's retry shape: any server may answer;
            # an election or in-flight leader hop retries (backpressure
            # included — submit() counts it via its own layer when the
            # edge is a single region server; here the pooled domain
            # just keeps trying, which is what a real client pool does).
            last = None
            for _ in range(attempts):
                targets = list(self.servers)
                random.shuffle(targets)
                for cs in targets:
                    try:
                        return cs.endpoints.handle(method, dict(body))
                    except Exception as exc:
                        last = exc
                time.sleep(delay)
            raise last if last is not None \
                else RuntimeError("no servers")

        def eval_by_id(self, eid):
            ldr = self.leader()
            return None if ldr is None \
                else ldr.server.state.eval_by_id(eid)

        def allocs_by_job(self, job_id):
            ldr = self.leader()
            return [] if ldr is None \
                else ldr.server.state.allocs_by_job(job_id)

    def submit(edge, job, attempts=40):
        """One registration through a submission target; a QoS/remote-
        shed 429 — raised locally at the edge or crossing the forward
        wire as a typed RPCError — retries like the API client would
        (shed is backpressure, not loss)."""
        from nomad_tpu.rpc.pool import RPCError

        sheds = 0
        for _ in range(attempts):
            try:
                return edge.handle(
                    "Job.Register", {"Job": to_dict(job)}), sheds
            except QoSBackpressureError:
                sheds += 1
            except RPCError as exc:
                if exc.remote_type != "QoSBackpressureError":
                    raise
                sheds += 1
            time.sleep(0.1)
        raise RuntimeError("registration shed past retry budget")

    sides = {}
    all_servers = []
    out = {"regions": list(regions), "nodes_per_region": FED_NODES,
           "storm_jobs": FED_JOBS, "quiet_high_jobs": FED_QUIET_HIGH,
           "per_job": FED_PER_JOB, "reps": FED_REPS,
           "high_deadline_s": deadlines[0]}
    try:
        # ---- boot both sides (live simultaneously, like every A/B here)
        fed_nodes = {}
        # Staleness bound matched to this box's window cadence (~0.3s a
        # window on the throttled CPU, with multi-hundred-ms GC/noise
        # stalls between them): the source must plausibly serve two
        # consecutive windows or the "shared snapshot" side degrades to
        # a fresh pin per window. reject_after_s scales with it.
        fed_cfg = dict(enabled=True, max_staleness_s=1.5,
                       reject_after_s=10.0)
        first = boot("fed-east", storm_region, 1,
                     FederationConfig(**fed_cfg))
        fed_nodes[storm_region] = first
        for r in quiet_regions:
            fed_nodes[r] = boot(f"fed-{r}", r, 1,
                                FederationConfig(**fed_cfg),
                                join=[gaddr(first)])
        all_servers.extend(fed_nodes.values())
        sides["federated"] = {r: _Edge(cs)
                              for r, cs in fed_nodes.items()}
        # The all-on-leader baseline: the SAME THREE SERVERS as one
        # global raft domain — every commit replicates to two followers
        # over real RPC, all workers run on whichever server leads.
        base_servers = [boot("base-0", storm_region, len(regions),
                             None, expect=len(regions))]
        for i in (1, 2):
            base_servers.append(boot(f"base-{i}", storm_region,
                                     len(regions), None,
                                     expect=len(regions),
                                     join=[gaddr(base_servers[0])]))
        all_servers.extend(base_servers)
        domain = _Domain(base_servers)
        sides["leader"] = {storm_region: domain}
        for cs in fed_nodes.values():
            deadline = time.monotonic() + 30
            while not cs.server.is_leader():
                if time.monotonic() > deadline:
                    raise RuntimeError("region never elected")
                time.sleep(0.02)
        deadline = time.monotonic() + 30
        while domain.leader() is None:
            if time.monotonic() > deadline:
                raise RuntimeError("baseline domain never elected")
            time.sleep(0.02)
        # Gossip convergence: every federated region must know the rest
        # before the first cross-region forward.
        deadline = time.monotonic() + 30
        while any(
                not fed_nodes[r].membership.region_servers(other)
                for r in regions for other in regions if other != r):
            if time.monotonic() > deadline:
                raise RuntimeError("regions never converged")
            time.sleep(0.05)
        # ---- fleets: each region its own; the baseline domain ALL of it
        for r in regions:
            for node in build_nodes(FED_NODES):
                fed_nodes[r].endpoints.handle(
                    "Node.Register", {"Node": to_dict(node)})
        for node in build_nodes(FED_NODES * len(regions)):
            domain.handle("Node.Register", {"Node": to_dict(node)})

        def storm_plan(side):
            """The rep's job multiset: (job, home region, edge server).
            Same shapes/priorities on both sides; the baseline's home is
            always its one region and every submit is local."""
            cluster = sides[side]
            fed = side == "federated"
            plan = []
            for i in range(FED_JOBS):
                job = build_job(FED_PER_JOB)
                job.Priority = tiers[i % len(tiers)]
                home = storm_region
                edge = regions[i % len(regions)] if fed else storm_region
                job.Region = home if fed else ""
                plan.append((job, home, cluster[edge], cluster[home]))
            for r in quiet_regions:
                home = r if fed else storm_region
                for _ in range(FED_QUIET_HIGH):
                    job = build_job(FED_PER_JOB)
                    job.Priority = 80
                    job.Region = home if fed else ""
                    plan.append((job, home,
                                 cluster[home], cluster[home]))
            return plan

        def run_rep(side, fwd_lats, tier_lats, shed_count):
            """Submit one full storm CONCURRENTLY (one submitter lane
            per edge server — wire hops overlap scheduling, as real
            clients would — the same lane count on both sides), drain
            it, and return (total_rate, per_region_rate, rep_checks)."""
            import threading as _threading

            plan = storm_plan(side)
            # Same submit concurrency on BOTH sides (3 client lanes);
            # only the fed side's entries carry cross-region edges.
            lanes: dict = {}
            for i, entry in enumerate(plan):
                lanes.setdefault(i % len(regions), []).append(entry)
            submit_t, eval_home, eval_meta = {}, {}, {}
            meta_lock = _threading.Lock()

            def lane(entries):
                for job, home, edge, home_cs in entries:
                    ts = time.monotonic()
                    resp, sheds = submit(edge, job)
                    now = time.monotonic()
                    with meta_lock:
                        shed_count[0] += sheds
                        if edge is not home_cs:
                            fwd_lats.append(now - ts)
                        eid = resp["EvalID"]
                        submit_t[eid] = ts
                        eval_home[eid] = home
                        eval_meta[eid] = (job, home_cs)

            t0 = time.monotonic()
            threads = [_threading.Thread(target=lane, args=(ents,),
                                         name=f"fed-submit-{i}")
                       for i, ents in enumerate(lanes.values())]
            for t in threads:
                t.start()
            lat, done_at = {}, {}
            deadline = time.monotonic() + 180
            while time.monotonic() < deadline:
                now = time.monotonic()
                with meta_lock:
                    pending = [(eid, meta)
                               for eid, meta in eval_meta.items()
                               if eid not in lat]
                for eid, (job, home_cs) in pending:
                    ev = home_cs.eval_by_id(eid)
                    if ev is not None and ev.Status in terminal:
                        lat[eid] = now - submit_t[eid]
                        done_at[eid] = now
                if (not any(t.is_alive() for t in threads)
                        and len(lat) == len(eval_meta)):
                    break
                time.sleep(0.02)
            for t in threads:
                t.join(timeout=10)
            t_total = (max(done_at.values()) - t0) if done_at else 0.0
            lost = len(submit_t) - len(lat)
            per_region = {}
            for eid in lat:
                per_region.setdefault(eval_home[eid], 0)
                per_region[eval_home[eid]] += 1
                job, home_cs = eval_meta[eid]
                tier_lats.setdefault(eval_home[eid], {}).setdefault(
                    job.Priority, []).append(lat[eid])
            placed, dup, misplaced, all_ids = 0, 0, 0, set()
            for eid, (job, home_cs) in eval_meta.items():
                live = [a for a in home_cs.allocs_by_job(job.ID)
                        if not a.terminal_status()]
                placed += len(live)
                for a in live:
                    if a.ID in all_ids:
                        dup += 1
                    all_ids.add(a.ID)
                if side == "federated":
                    for r, cs in fed_nodes.items():
                        if r != eval_home[eid] \
                                and cs.server.state.job_by_id(job.ID):
                            misplaced += 1
            checks = {"lost": lost, "dup": dup, "placed": placed,
                      "expected": len(plan) * FED_PER_JOB,
                      "misplaced": misplaced}
            rate = round(len(lat) / t_total, 2) if t_total else 0.0
            rates_r = {r: round(n / t_total, 2) if t_total else 0.0
                       for r, n in sorted(per_region.items())}
            return rate, rates_r, checks

        # ---- warm both sides (compile/caches), then interleaved reps
        for side in ("federated", "leader"):
            run_rep(side, [], {}, [0])
        _tune_gc()
        rates = {"federated": [], "leader": []}
        region_rates = {"federated": [], "leader": []}
        fwd_lats, shed_count = [], [0]
        tier_lats = {"federated": {}, "leader": {}}
        checks_all = []
        for rep in range(FED_REPS):
            order = (("federated", "leader") if rep % 2 == 0
                     else ("leader", "federated"))
            for side in order:
                rate, rates_r, checks = run_rep(
                    side, fwd_lats if side == "federated" else [],
                    tier_lats[side], shed_count)
                rates[side].append(rate)
                region_rates[side].append(rates_r)
                checks["side"] = side
                checks_all.append(checks)
                _freeze_heap()

        def tier_pct(side):
            name = {80: "high", 20: "low", 50: "normal"}
            return {r: {name[p]: _pctiles_ms(v)
                        for p, v in sorted(by_prio.items())}
                    for r, by_prio in sorted(tier_lats[side].items())}

        fed_srcs = {r: cs.server.fed_source.stats()
                    for r, cs in fed_nodes.items()}
        quiet_p99 = max(
            float(np.percentile(
                tier_lats["federated"].get(r, {}).get(80) or [0.0], 99))
            for r in quiet_regions)
        lost = sum(c["lost"] for c in checks_all)
        dup = sum(c["dup"] for c in checks_all)
        misplaced = sum(c["misplaced"] for c in checks_all)
        placed_ok = all(c["placed"] == c["expected"] for c in checks_all)
        reused = sum(s["Reused"] for s in fed_srcs.values())
        out.update({
            "federated": {
                "evals_sec": max(rates["federated"]),
                "rep_rates": rates["federated"],
                "per_region_evals_sec": region_rates["federated"],
                "tier_latency_ms": tier_pct("federated"),
                "snapshot_sources": fed_srcs,
                "forward_latency_ms": _pctiles_ms(fwd_lats),
                "forwards": len(fwd_lats),
                "backpressure_sheds": shed_count[0],
            },
            "leader": {
                "evals_sec": max(rates["leader"]),
                "rep_rates": rates["leader"],
                "tier_latency_ms": tier_pct("leader"),
            },
            "speedup": (speedup := (round(max(rates["federated"])
                                          / max(rates["leader"]), 3)
                                    if max(rates["leader"]) else None)),
            "quiet_high_p99_ms": round(quiet_p99 * 1e3, 2),
            "gate": {
                "ok": (lost == 0 and dup == 0 and misplaced == 0
                       and placed_ok and reused > 0
                       and quiet_p99 <= deadlines[0]
                       and speedup is not None and speedup >= 1.0),
                "lost_evals": lost,
                "duplicate_allocs": dup,
                "misplaced_jobs": misplaced,
                "placed_ok": placed_ok,
                "snapshot_reuse": reused,
                "quiet_high_p99_within_slo": quiet_p99 <= deadlines[0],
                "beats_all_on_leader": speedup is not None
                and speedup >= 1.0,
            },
        })
        return out
    finally:
        for cs in all_servers:
            try:
                cs.shutdown()
            except Exception:
                pass


def build_plain_job(per_eval=PER_EVAL):
    """BASELINE config 2's shape: resource-only bin-packing, no constraint
    checkers at all."""
    job = build_job(per_eval)
    job.Constraints = []
    for tg in job.TaskGroups:
        tg.Constraints = []
        for task in tg.Tasks:
            task.Constraints = []
    return job


def build_system_job():
    """BASELINE config 4's shape: one alloc per eligible node, full
    feasibility chain (driver + implicit constraints)."""
    from nomad_tpu import mock

    job = mock.system_job()
    task = job.TaskGroups[0].Tasks[0]
    task.Resources.CPU = 20
    task.Resources.MemoryMB = 16
    task.Resources.DiskMB = 150
    task.Resources.Networks = []
    task.Services = []
    if task.LogConfig is not None:
        task.LogConfig.MaxFiles = 1
        task.LogConfig.MaxFileSizeMB = 1
    return job


def _capture_sweep_plan(n_nodes):
    """One fixed-seed system sweep plan (with its columnar descriptor)
    captured WITHOUT committing — the input both store-commit paths
    replay."""
    import logging
    from nomad_tpu import mock
    from nomad_tpu.scheduler.system_sched import SystemScheduler
    from nomad_tpu.state.state_store import StateStore
    from nomad_tpu.structs import PlanResult
    from nomad_tpu.structs.structs import (
        EvalStatusPending,
        EvalTriggerJobRegister,
    )
    from nomad_tpu.tensor import TensorIndex

    class _Capture:
        def __init__(self):
            self.plans = []

        def plan_queue_depth(self):
            return 0

        def submit_plan(self, plan):
            self.plans.append(plan)
            r = PlanResult()
            r.NodeUpdate = dict(plan.NodeUpdate)
            r.NodeAllocation = dict(plan.NodeAllocation)
            r.AllocIndex = 1
            return r, None

        def update_eval(self, ev):
            pass

        def create_eval(self, ev):
            pass

        def reblock_eval(self, ev):
            pass

    store = StateStore()
    tindex = TensorIndex.attach(store)
    idx = 0
    for node in build_nodes(n_nodes):
        idx += 1
        store.upsert_node(idx, node)
    job = build_system_job()
    idx += 1
    store.upsert_job(idx, job)
    ev = mock.eval()
    ev.JobID = job.ID
    ev.Type = job.Type
    ev.TriggeredBy = EvalTriggerJobRegister
    ev.Status = EvalStatusPending
    planner = _Capture()
    SystemScheduler(store, planner, tindex,
                    logging.getLogger("bench.store"),
                    rng=random.Random(7)).process(ev)
    return planner.plans[0]


def bench_store_commit(n_nodes, reps=3):
    """State-store commit microbench (the `store` section): the SAME
    fixed-seed system sweep committed per-object (the pre-columnar path,
    one upsert per alloc) and columnar (one ApplySweepBatch scatter) into
    fresh FSMs. Reports per-alloc commit µs for both paths, the columnar
    batch scatter ms, and the raft entry bytes of both encodings (the
    wire cost of a chunk). Max-of-reps (min time) like the A/B protocol —
    the commit is deterministic CPU, so the best rep is the least-noisy
    one."""
    import msgpack
    from nomad_tpu.server.fsm import FSM, MessageType
    from nomad_tpu.server.plan_apply import _encode_result
    from nomad_tpu.structs import PlanResult, to_dict

    plan = _capture_sweep_plan(n_nodes)
    allocs = [a for placed in plan.NodeAllocation.values() for a in placed]
    n = len(allocs)
    obj_payload = {"Job": plan.Job, "Alloc": allocs}
    result = PlanResult(NodeAllocation=dict(plan.NodeAllocation))
    result._sweep = plan._sweep
    element, is_sweep = _encode_result(plan, result)
    assert is_sweep, "sweep plan lost its columnar descriptor"
    col_payload = {"Batch": [element]}
    # Entry bytes BEFORE any apply mutates the payload objects (the
    # object path stamps Job/indexes into the shared allocs).
    obj_bytes = len(msgpack.packb(
        (int(MessageType.AllocUpdate), to_dict(obj_payload)),
        use_bin_type=True))
    col_bytes = len(msgpack.packb(
        (int(MessageType.ApplySweepBatch), to_dict(col_payload)),
        use_bin_type=True))

    def timed(msg, payload):
        best = float("inf")
        for _ in range(reps):
            fsm = FSM()
            t0 = time.perf_counter()
            fsm.apply(1, msg, payload)
            best = min(best, time.perf_counter() - t0)
        return best

    t_obj = timed(MessageType.AllocUpdate, obj_payload)
    t_col = timed(MessageType.ApplySweepBatch, col_payload)
    return {
        "nodes": n_nodes,
        "allocs": n,
        "object_per_alloc_us": round(t_obj / n * 1e6, 2),
        "columnar_per_alloc_us": round(t_col / n * 1e6, 3),
        "columnar_batch_scatter_ms": round(t_col * 1e3, 3),
        "commit_speedup": round(t_obj / t_col, 1) if t_col else None,
        "raft_entry_bytes": {"object": obj_bytes, "columnar": col_bytes,
                             "ratio": round(obj_bytes / col_bytes, 1)
                             if col_bytes else None},
    }


def _capture_service_plans(n_nodes, per_eval=PER_EVAL, n_plans=1):
    """Fixed-seed service-window plans (each with its columnar service
    descriptor) captured through the pipelined fast path's build —
    prepare_batch -> host placement kernel -> compact -> collect_build —
    nothing committed. One store/tensor boot serves every capture; the
    plans are the input both store-commit paths replay."""
    import logging

    from nomad_tpu import mock
    from nomad_tpu.scheduler import kernels
    from nomad_tpu.scheduler.context import EvalContext
    from nomad_tpu.scheduler.stack import GenericStack, WindowAccumulator
    from nomad_tpu.scheduler.util import (
        diff_allocs,
        materialize_task_groups,
        ready_nodes_in_dcs,
    )
    from nomad_tpu.state.state_store import StateStore
    from nomad_tpu.structs.structs import EvalTriggerJobRegister
    from nomad_tpu.tensor import ClassEligibility, TensorIndex

    store = StateStore()
    tindex = TensorIndex.attach(store)
    idx = 0
    for node in build_nodes(n_nodes):
        idx += 1
        store.upsert_node(idx, node)
    plans = []
    for k in range(n_plans):
        job = build_job(per_eval)
        idx += 1
        store.upsert_job(idx, job)
        ev = mock.eval()
        ev.JobID = job.ID
        ev.Type = job.Type
        ev.TriggeredBy = EvalTriggerJobRegister
        snap = store.snapshot()
        plan = ev.make_plan(job, copy_job=False)
        ctx = EvalContext(snap, plan, logging.getLogger("bench.store"))
        stack = GenericStack(ctx, tindex, batch=False,
                             rng=random.Random(7 + k))
        diff = diff_allocs(job, {}, materialize_task_groups(job), [])
        nodes, _ = ready_nodes_in_dcs(snap, job.Datacenters)
        nt = tindex.nt
        cand_mask = np.zeros(nt.n_rows, dtype=bool)
        for n in nodes:
            row = nt.row_of.get(n.ID)
            if row is not None:
                cand_mask[row] = True
        stack.job = job
        stack.adopt_nodes({n.ID: n for n in nodes}, cand_mask,
                          ClassEligibility(nt, nodes))
        prep = stack.prepare_batch([t.TaskGroup for t in diff.place])
        res = stack.dispatch_host(prep)
        cr = kernels.compact_host(np.asarray(res.packed), prep.n_valid)
        ok = stack.collect_build(prep, cr, ev.ID, job, diff.place, plan,
                                 {}, WindowAccumulator(nt.n_rows))
        assert ok and getattr(plan, "_sweep", None) is not None, \
            "service window lost its columnar descriptor"
        plans.append(plan)
    return plans


def bench_store_commit_window(per_eval=PER_EVAL, reps=5):
    """Commit A/B at the SERVICE window shapes: the SAME fixed-seed
    service-window plans committed per-object (the pre-columnar service
    path, one upsert per alloc) and columnar (ApplySweepBatch scatter)
    into fresh FSMs. Two shapes: one lone plan (the idle-broker commit)
    and the applier's 16-plan group entry (_APPLY_BATCH — what a storm
    window actually commits as; the per-entry fixed costs amortize
    there, which is where the --smoke gate holds the speedup)."""
    import msgpack
    from nomad_tpu.server.fsm import FSM, MessageType
    from nomad_tpu.server.plan_apply import _APPLY_BATCH, _encode_result
    from nomad_tpu.structs import PlanResult, to_dict

    plans = _capture_service_plans(min(N_NODES, 2048), per_eval,
                                   n_plans=_APPLY_BATCH)
    elements = []
    obj_groups = []
    for plan in plans:
        result = PlanResult(NodeAllocation=dict(plan.NodeAllocation))
        result._sweep = plan._sweep
        element, is_sweep = _encode_result(plan, result)
        assert is_sweep, "service plan lost its columnar descriptor"
        elements.append(element)
        obj_groups.append({"Job": plan.Job,
                           "Alloc": [a for v in plan.NodeAllocation.values()
                                     for a in v]})
    obj_bytes = len(msgpack.packb(
        (int(MessageType.AllocUpdate), to_dict(obj_groups[0])),
        use_bin_type=True))
    col_bytes = len(msgpack.packb(
        (int(MessageType.ApplySweepBatch),
         to_dict({"Batch": [elements[0]]})),
        use_bin_type=True))

    def timed(msg, payload):
        best = float("inf")
        for _ in range(reps):
            fsm = FSM()
            t0 = time.perf_counter()
            fsm.apply(1, msg, payload)
            best = min(best, time.perf_counter() - t0)
        return best

    t_obj = timed(MessageType.AllocUpdate, obj_groups[0])
    t_col = timed(MessageType.ApplySweepBatch, {"Batch": [elements[0]]})
    n_storm = per_eval * len(plans)
    ts_obj = timed(MessageType.AllocUpdate, {"Batch": obj_groups})
    ts_col = timed(MessageType.ApplySweepBatch, {"Batch": elements})
    return {
        "allocs": per_eval,
        "object_per_alloc_us": round(t_obj / per_eval * 1e6, 2),
        "columnar_per_alloc_us": round(t_col / per_eval * 1e6, 3),
        "columnar_batch_scatter_ms": round(t_col * 1e3, 3),
        "commit_speedup": round(t_obj / t_col, 1) if t_col else None,
        "raft_entry_bytes": {"object": obj_bytes, "columnar": col_bytes,
                             "ratio": round(obj_bytes / col_bytes, 1)
                             if col_bytes else None},
        "storm_group": {
            "plans": len(plans),
            "allocs": n_storm,
            "object_per_alloc_us": round(ts_obj / n_storm * 1e6, 2),
            "columnar_per_alloc_us": round(ts_col / n_storm * 1e6, 3),
            "commit_speedup": round(ts_obj / ts_col, 1) if ts_col else None,
        },
    }


def bench_service_columnar_ab():
    """Service-path commit A/B end to end: the SAME storm served with
    columnar service commits on (ApplySweepBatch + SweepSegment scatter)
    vs off (per-object upserts, the pre-columnar path). Both servers live
    simultaneously, timed reps interleaved with the within-pair order
    ALTERNATING each rep (this box's cgroup quota punishes whoever runs
    second), max-of-reps compared. Records per-side rates + storm latency
    percentiles, the columnar server's segment counters (the proof the
    storm took the new path), and a parity gate: both sides must place
    the full storm every rep."""
    from nomad_tpu.server import Server, ServerConfig

    nodes = build_nodes(SVC_AB_NODES)
    out = {"nodes": SVC_AB_NODES, "evals_per_rep": SVC_AB_EVALS}
    servers = {}
    try:
        for mode, columnar in (("columnar", True), ("object", False)):
            srv = Server(ServerConfig(num_schedulers=N_WORKERS,
                                      pipelined_scheduling=True,
                                      scheduler_window=WINDOW,
                                      service_columnar=columnar,
                                      min_heartbeat_ttl=24 * 3600.0,
                                      heartbeat_grace=24 * 3600.0))
            srv.establish_leadership()
            for node in nodes:
                srv.node_register(node)
            run = _make_storm_runner(srv)
            run(3)
            run(3)
            srv.tindex.nt.warm_device()
            run(SVC_AB_EVALS)  # full-size warm storm (compiles)
            servers[mode] = (srv, run)
        _tune_gc()
        # Baseline the cumulative segment counter AFTER warmups so the
        # parity gate proves the TIMED reps took the columnar path (a
        # silent fallback-to-object mid-rep would otherwise hide behind
        # warmup segments).
        base_service = servers["columnar"][0].state.columnar_stats()[
            "Batches"].get("service", 0)
        rates = {"columnar": [], "object": []}
        lats = {"columnar": [], "object": []}
        placed = {"columnar": [], "object": []}
        for rep in range(SVC_AB_REPS):
            order = (("columnar", "object") if rep % 2 == 0
                     else ("object", "columnar"))
            for mode in order:
                srv, run = servers[mode]
                for w in srv.workers:
                    if hasattr(w, "quiesce"):
                        w.quiesce(30.0)
                t0 = time.perf_counter()
                eval_ids = run(SVC_AB_EVALS, latencies=lats[mode])
                rates[mode].append(
                    round(SVC_AB_EVALS / (time.perf_counter() - t0), 2))
                _freeze_heap()
                placed[mode].append(sum(
                    1 for eid in eval_ids
                    for _ in srv.state.allocs_by_eval(eid)))
        for mode in ("columnar", "object"):
            out[mode] = {"evals_sec": max(rates[mode]),
                         "rep_rates": rates[mode],
                         "storm_latency_ms": _pctiles_ms(lats[mode]),
                         "placed_per_rep": placed[mode]}
        out["speedup"] = round(max(rates["columnar"])
                               / max(rates["object"]), 3) \
            if rates["object"] else None
        out["columnar_store"] = servers["columnar"][0].state.columnar_stats()
        out["object_store_batches"] = \
            servers["object"][0].state.columnar_stats()["Batches"]
        out["timed_service_batches"] = \
            out["columnar_store"]["Batches"].get("service", 0) - base_service
        want = SVC_AB_EVALS * PER_EVAL
        out["parity_ok"] = bool(
            all(p == want for mode in placed for p in placed[mode])
            and out["timed_service_batches"] >= 1
            and not out["object_store_batches"])
        out["expected_allocs"] = want
        return out
    finally:
        for srv, _ in servers.values():
            srv.shutdown()


def bench_event_stream():
    """Event-broker overhead A/B end to end: the SAME storm served with
    the event stream ARMED (broker on the FSM apply path + ONE live
    subscriber draining fan-out rows for the whole run — the realistic
    deployed shape) vs DISARMED (event_buffer_size=0: no broker object;
    apply pays one attribute check). Both servers live simultaneously,
    timed reps interleaved with ALTERNATING within-pair order,
    max-of-reps compared. Records per-side rates + storm tails, the
    armed broker's counters (published / dropped / ring depth — the
    nomad.events.* stats keys), and a parity gate: both sides place the
    full storm every rep, the subscriber consumed real traffic, and the
    bounded queue never dropped."""
    import threading

    from nomad_tpu.server import Server, ServerConfig

    nodes = build_nodes(EVENTS_AB_NODES)
    out = {"nodes": EVENTS_AB_NODES, "evals_per_rep": EVENTS_AB_EVALS}
    servers = {}
    stop = threading.Event()
    consumed = {"frames": 0, "events": 0}
    drainer = None
    try:
        for mode, buf in (("armed", 4096), ("disarmed", 0)):
            srv = Server(ServerConfig(num_schedulers=N_WORKERS,
                                      pipelined_scheduling=True,
                                      scheduler_window=WINDOW,
                                      event_buffer_size=buf,
                                      min_heartbeat_ttl=24 * 3600.0,
                                      heartbeat_grace=24 * 3600.0))
            srv.establish_leadership()
            for node in nodes:
                srv.node_register(node)
            run = _make_storm_runner(srv)
            run(3)
            run(3)
            srv.tindex.nt.warm_device()
            run(EVENTS_AB_EVALS)  # full-size warm storm (compiles)
            servers[mode] = (srv, run)
        broker = servers["armed"][0].fsm.events
        sub = broker.subscribe(from_index=0, fanout=True,
                               queue_size=262_144)

        def drain_live():
            while not stop.is_set():
                frame = sub.next(timeout=0.2)
                if frame is None:
                    continue
                consumed["frames"] += 1
                consumed["events"] += len(frame["Events"])

        drainer = threading.Thread(target=drain_live,
                                   name="bench-events-sub", daemon=True)
        drainer.start()
        _tune_gc()
        rates = {"armed": [], "disarmed": []}
        lats = {"armed": [], "disarmed": []}
        placed = {"armed": [], "disarmed": []}
        for rep in range(EVENTS_AB_REPS):
            order = (("armed", "disarmed") if rep % 2 == 0
                     else ("disarmed", "armed"))
            for mode in order:
                srv, run = servers[mode]
                for w in srv.workers:
                    if hasattr(w, "quiesce"):
                        w.quiesce(30.0)
                t0 = time.perf_counter()
                eval_ids = run(EVENTS_AB_EVALS, latencies=lats[mode])
                rates[mode].append(
                    round(EVENTS_AB_EVALS / (time.perf_counter() - t0), 2))
                _freeze_heap()
                placed[mode].append(sum(
                    1 for eid in eval_ids
                    for _ in srv.state.allocs_by_eval(eid)))
        # Let the drainer catch the tail of the last rep before the
        # drop/consumption accounting freezes.
        deadline = time.monotonic() + 10
        while (broker.stats()["Tail"] > sub.last_index
               and time.monotonic() < deadline):
            time.sleep(0.05)
        stop.set()
        drainer.join(timeout=5)
        stats = broker.stats()
        for mode in ("armed", "disarmed"):
            out[mode] = {"evals_sec": max(rates[mode]),
                         "rep_rates": rates[mode],
                         "storm_latency_ms": _pctiles_ms(lats[mode]),
                         "placed_per_rep": placed[mode]}
        out["overhead_pct"] = round(
            (1.0 - max(rates["armed"]) / max(rates["disarmed"]))
            * 100.0, 2) if rates["disarmed"] else None
        out["events"] = {"published": stats["Published"],
                         "dropped": stats["Dropped"],
                         "ring_depth": stats["Depth"],
                         "ring_size": stats["Size"],
                         "subscriber_frames": consumed["frames"],
                         "subscriber_events": consumed["events"]}
        want = EVENTS_AB_EVALS * PER_EVAL
        out["parity_ok"] = bool(
            all(p == want for mode in placed for p in placed[mode])
            and stats["Dropped"] == 0
            and consumed["events"] > 0
            and servers["disarmed"][0].fsm.events is None)
        out["expected_allocs"] = want
        return out
    finally:
        stop.set()
        if drainer is not None:
            drainer.join(timeout=5)
        for srv, _ in servers.values():
            srv.shutdown()


def bench_digest():
    """Replica-digest overhead A/B end to end: the SAME storm served
    with the state hash chain ARMED (digest_interval=64, the deployed
    default — every committed entry folds its post-apply readback into
    the blake2b chain, checkpoints on interval buckets) vs DISARMED
    (digest_interval=0: no digest object; apply pays one attribute
    check). Both servers live simultaneously, timed reps interleaved
    with ALTERNATING within-pair order, max-of-reps compared. Records
    per-side rates + storm tails, the armed chain's counters (folds /
    checkpoints / sync mode — the nomad.fsm.digest.* stats keys), and a
    parity gate: both sides place the full storm every rep, the armed
    chain folded every commit, and it never diverged."""
    from nomad_tpu.server import Server, ServerConfig

    nodes = build_nodes(DIGEST_AB_NODES)
    out = {"nodes": DIGEST_AB_NODES, "evals_per_rep": DIGEST_AB_EVALS}
    servers = {}
    try:
        for mode, interval in (("armed", 64), ("disarmed", 0)):
            srv = Server(ServerConfig(num_schedulers=N_WORKERS,
                                      pipelined_scheduling=True,
                                      scheduler_window=WINDOW,
                                      digest_interval=interval,
                                      min_heartbeat_ttl=24 * 3600.0,
                                      heartbeat_grace=24 * 3600.0))
            srv.establish_leadership()
            for node in nodes:
                srv.node_register(node)
            run = _make_storm_runner(srv)
            run(3)
            run(3)
            srv.tindex.nt.warm_device()
            run(DIGEST_AB_EVALS)  # full-size warm storm (compiles)
            servers[mode] = (srv, run)
        _tune_gc()
        rates = {"armed": [], "disarmed": []}
        lats = {"armed": [], "disarmed": []}
        placed = {"armed": [], "disarmed": []}
        for rep in range(DIGEST_AB_REPS):
            order = (("armed", "disarmed") if rep % 2 == 0
                     else ("disarmed", "armed"))
            for mode in order:
                srv, run = servers[mode]
                for w in srv.workers:
                    if hasattr(w, "quiesce"):
                        w.quiesce(30.0)
                t0 = time.perf_counter()
                eval_ids = run(DIGEST_AB_EVALS, latencies=lats[mode])
                rates[mode].append(
                    round(DIGEST_AB_EVALS / (time.perf_counter() - t0), 2))
                _freeze_heap()
                placed[mode].append(sum(
                    1 for eid in eval_ids
                    for _ in srv.state.allocs_by_eval(eid)))
        for mode in ("armed", "disarmed"):
            out[mode] = {"evals_sec": max(rates[mode]),
                         "rep_rates": rates[mode],
                         "storm_latency_ms": _pctiles_ms(lats[mode]),
                         "placed_per_rep": placed[mode]}
        out["overhead_pct"] = round(
            (1.0 - max(rates["armed"]) / max(rates["disarmed"]))
            * 100.0, 2) if rates["disarmed"] else None
        stats = servers["armed"][0].fsm.digest.stats()
        out["digest"] = {"folds": stats["Folds"],
                         "chain_index": stats["LastIndex"],
                         "checkpoints": len(stats["Checkpoints"]),
                         "synced": stats["Synced"],
                         "diverged": stats["Diverged"]}
        want = DIGEST_AB_EVALS * PER_EVAL
        # Folds can trail LastIndex: a handler that RAISES skips its
        # fold by contract (every replica skips the same entry), so the
        # gate checks the chain advanced and stayed healthy, not an
        # exact count.
        out["parity_ok"] = bool(
            all(p == want for mode in placed for p in placed[mode])
            and stats["Folds"] > 0
            and stats["LastIndex"] >= stats["Folds"]
            and stats["Synced"] and stats["Diverged"] == 0
            and servers["disarmed"][0].fsm.digest is None)
        out["expected_allocs"] = want
        return out
    finally:
        for srv, _ in servers.values():
            srv.shutdown()


def bench_placer(nodes, n_evals, per_eval=PER_EVAL, dcs=None):
    """Placer-only device pipeline: the ceiling (no raft/plan-apply)."""
    from nomad_tpu.scheduler.pipeline import EvalRequest, PipelinedPlacer
    from nomad_tpu.tensor import TensorIndex

    tindex = TensorIndex()
    for node in nodes:
        tindex.nt.upsert_node(node)

    window = min(max(n_evals, 1), 128)

    warm = PipelinedPlacer(tindex, nodes, rng=random.Random(1), window=window)
    for _ in range(window + 1):
        job = build_job(per_eval, dcs)
        warm.submit(EvalRequest(job=job, tgs=[job.TaskGroups[0]] * per_eval))
    warm.flush()

    placer = PipelinedPlacer(tindex, nodes, rng=random.Random(42),
                             window=window)
    t0 = time.perf_counter()
    for _ in range(n_evals):
        job = build_job(per_eval, dcs)
        placer.submit(EvalRequest(job=job,
                                  tgs=[job.TaskGroups[0]] * per_eval))
    results = placer.flush()
    elapsed = time.perf_counter() - t0
    total_placed = sum(int((r.chosen_rows >= 0).sum()) for r in results)

    # Synchronous single-eval latency (the p50 plan-latency figure).
    lat_placer = PipelinedPlacer(tindex, nodes, rng=random.Random(7))
    latencies = []
    for _ in range(5):
        job = build_job(per_eval, dcs)
        t1 = time.perf_counter()
        lat_placer.submit(EvalRequest(job=job,
                                      tgs=[job.TaskGroups[0]] * per_eval))
        lat_placer.flush()
        latencies.append(time.perf_counter() - t1)
    return n_evals / elapsed, total_placed, float(np.percentile(latencies, 50))


def bench_cpu_reference(nodes, n_evals):
    from nomad_tpu.scheduler.cpu_reference import CPUReferenceStack

    rng = random.Random(42)
    stack = CPUReferenceStack(nodes, batch=False, rng=rng)
    t0 = time.perf_counter()
    total = 0
    for _ in range(n_evals):
        job = build_job()
        stack.set_job(job)
        for o in stack.select_batch([job.TaskGroups[0]] * PER_EVAL):
            if o is not None:
                total += 1
    elapsed = time.perf_counter() - t0
    return n_evals / elapsed, total


def bench_cpu_served(nodes, n_evals, reps=3):
    """The apples-to-apples denominator: the reference's host-side iterator
    chain served through the SAME server path as the headline number
    (register -> raft -> broker -> worker -> plan applier -> committed),
    with only the placement engine swapped (scheduler_impl)."""
    from nomad_tpu.server import Server, ServerConfig

    srv = Server(ServerConfig(num_schedulers=1, pipelined_scheduling=False,
                              scheduler_impl="cpu-reference",
                              min_heartbeat_ttl=24 * 3600.0,
                              heartbeat_grace=24 * 3600.0))
    srv.establish_leadership()
    try:
        for node in nodes:
            srv.node_register(node)

        run = _make_storm_runner(srv)
        run(2)  # warmup (imports, first snapshots)
        _tune_gc()  # same runtime tuning as the TPU side (honest ratio)
        rates = []
        for _ in range(reps):
            t0 = time.perf_counter()
            eval_ids = run(n_evals)
            rates.append(n_evals / (time.perf_counter() - t0))
            # Identical between-rep GC treatment to the TPU side: the
            # served-vs-served ratio must not hide a GC-decay asymmetry.
            _freeze_heap()
        placed = sum(1 for eid in eval_ids
                     for a in srv.state.allocs_by_eval(eid))
        return sorted(rates)[(len(rates) - 1) // 2], placed, \
            [round(r, 2) for r in rates]
    finally:
        srv.shutdown()


def bench_placement_parity(n_evals=None, n_nodes=None):
    """BASELINE's ratio is defined \"at identical placement quality\": the
    same storm (identical node fleet, identical jobs) runs served through
    the TPU engine and the reference CPU chain, and the committed
    placements' bin-pack scores are compared. The TPU path's global argmax
    must score AT LEAST as well as the reference's sampled max — a drop
    beyond f32/noise tolerance means the fast path is trading placement
    quality for throughput, and the bench fails loudly."""
    from nomad_tpu.server import Server, ServerConfig

    if n_evals is None:
        n_evals = PARITY_EVALS
    if n_nodes is None:
        n_nodes = PARITY_NODES
    out = {}
    for impl in ("tpu", "cpu-reference"):
        nodes = build_nodes(n_nodes)  # same seed => identical fleets
        srv = Server(ServerConfig(num_schedulers=1,
                                  pipelined_scheduling=impl == "tpu",
                                  scheduler_impl=impl,
                                  min_heartbeat_ttl=24 * 3600.0,
                                  heartbeat_grace=24 * 3600.0))
        srv.establish_leadership()
        try:
            for node in nodes:
                srv.node_register(node)
            run = _make_storm_runner(srv)
            eval_ids = run(n_evals)
            scores = []
            placed = 0
            for eid in eval_ids:
                for a in srv.state.allocs_by_eval(eid):
                    placed += 1
                    s = ((a.Metrics.Scores or {}).get(
                        f"{a.NodeID}.binpack")
                        if a.Metrics is not None else None)
                    if s is not None:
                        scores.append(float(s))
            out[impl] = {
                "placed": placed,
                "scored": len(scores),
                "mean_score": round(float(np.mean(scores)), 5)
                if scores else None,
            }
        finally:
            srv.shutdown()
    tpu, cpu = out["tpu"], out["cpu-reference"]
    want = n_evals * PER_EVAL
    delta = (round(tpu["mean_score"] - cpu["mean_score"], 5)
             if tpu["mean_score"] is not None
             and cpu["mean_score"] is not None else None)
    # Noise tie-break adds <=1e-3 to TPU scores; everything else is f32.
    ok = (tpu["placed"] == cpu["placed"] == want
          and delta is not None and delta >= -2e-3)
    return {"tpu": tpu, "cpu_reference": cpu,
            "mean_score_delta": delta, "storm_placements": want,
            "ok": bool(ok)}


def _mesh_child():
    """Child half of bench_mesh_1m: runs under the 8-virtual-device XLA
    flag, measures the keyed kernel 1dev-vs-mesh at MESH_NODES x one
    MESH_P-wide storm window, prints ONE json line on stdout."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    from nomad_tpu.parallel import pow2_prefix, scheduling_mesh
    from nomad_tpu.scheduler import kernels

    n, p, nv = MESH_NODES, MESH_P, min(MESH_VALID, MESH_P)
    w, reps, t = MESH_WINDOWS, MESH_REPS, 1
    devices = pow2_prefix(jax.devices())
    n_dev = len(devices)

    def setup(devs):
        rng = np.random.default_rng(1)
        mesh = scheduling_mesh(devs)
        axis = mesh.axis_names[0]
        node_sh = NamedSharding(mesh, PartitionSpec(axis))
        mask_sh = NamedSharding(mesh, PartitionSpec(None, axis))
        d = {k: jax.device_put(v, node_sh) for k, v in {
            "capacity": rng.uniform(1000, 4000, (n, 5)).astype(np.float32),
            "score_cap": rng.uniform(800, 3800, (n, 2)).astype(np.float32),
            "usage": rng.uniform(0, 200, (n, 5)).astype(np.float32),
            "job_counts": np.zeros(n, np.int32),
            "noise": (rng.random(n) * 1e-3).astype(np.float32),
            "banned0": np.zeros(n, bool),
        }.items()}
        tg_masks = jax.device_put(rng.random((t, n)) < 0.9, mask_sh)
        kd = rng.uniform(5, 40, (t, 5)).astype(np.float32)
        tg_ids = rng.integers(0, t, p).astype(np.int32)
        valid = np.zeros(p, bool)
        valid[:nv] = True
        reset = np.zeros(p, bool)
        reset[::64] = True
        penalty = np.float32(10.0)
        distinct = np.asarray(False)
        jax.block_until_ready(list(d.values()))

        def fn(u):
            return kernels.place_batch_keyed(
                mesh if len(devs) > 1 else None, d["capacity"],
                d["score_cap"], u, tg_masks, d["job_counts"], kd, tg_ids,
                valid, d["noise"], penalty, distinct, d["banned0"], reset,
                nv)

        res = fn(d["usage"])  # compile + warm (one cold + warm program)
        res = fn(res.usage_after)
        jax.block_until_ready(res.packed)
        return fn, d["usage"]

    def rate_rep(fn, u0):
        t0 = time.perf_counter()
        u, res = u0, None
        for _ in range(w):
            res = fn(u)
            u = res.usage_after
        jax.block_until_ready(res.packed)
        return w / (time.perf_counter() - t0)

    def lat_rep(fn, u0):
        # Per-window latency: each window blocks to the host, the way a
        # lone interactive eval pays it. The chain restarts at u0 first,
        # so index 0 is the COLD window (rebuild + exchange) and the
        # rest are warm — the percentiles honestly mix both, like a
        # served storm does across rebases.
        lats, u = [], u0
        for _ in range(w):
            t0 = time.perf_counter()
            res = fn(u)
            jax.block_until_ready(res.packed)
            lats.append(time.perf_counter() - t0)
            u = res.usage_after
        return lats

    sides = {"one_dev": setup(devices[:1]), "mesh": setup(devices)}
    kernels.mesh_stats_drain()
    rates = {k: [] for k in sides}
    lats = {k: [] for k in sides}
    # Interleaved A/B, alternating within-pair order, max-of-reps (the
    # cgroup-throttle methodology: a throttled rep loses a sample, never
    # skews the ratio). Latency reps ride the same alternation.
    for i in range(reps):
        order = list(sides) if i % 2 == 0 else list(reversed(sides))
        for side in order:
            fn, u0 = sides[side]
            rates[side].append(rate_rep(fn, u0))
            lats[side].extend(lat_rep(fn, u0))
    ms = kernels.mesh_stats_drain()
    out = {
        "nodes": n, "window_p": p, "valid_per_window": nv,
        "windows_per_rep": w, "reps": reps, "devices": n_dev,
        "one_dev": {"windows_sec": round(max(rates["one_dev"]), 2),
                    "rep_rates": [round(r, 2) for r in rates["one_dev"]],
                    "window_latency_ms": _pctiles_ms(lats["one_dev"])},
        "mesh": {"windows_sec": round(max(rates["mesh"]), 2),
                 "rep_rates": [round(r, 2) for r in rates["mesh"]],
                 "window_latency_ms": _pctiles_ms(lats["mesh"]),
                 "mesh_windows": ms["windows"],
                 "warm_windows": ms["warm_windows"],
                 "exchange_bytes": ms["candidate_bytes"]},
    }
    out["ratio"] = round(out["mesh"]["windows_sec"]
                         / out["one_dev"]["windows_sec"], 2)
    print(json.dumps(out))


def bench_mesh_1m():
    """config6_mesh_1m: the trajectory's millions-of-users shape — 1M
    nodes x a wide storm window — measured as a keyed-kernel A/B on the
    8-virtual-CPU-device mesh in a clean subprocess (the device-count
    flag must precede jax init). The served mesh path itself is
    equivalence- and chaos-gated in tier-1; this records the RATE and
    per-window latency tails at the headline scale in every BENCH JSON."""
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["NOMAD_TPU_FORCE_CPU"] = "1"
    xf = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in xf:
        env["XLA_FLAGS"] = \
            (xf + " --xla_force_host_platform_device_count=8").strip()
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--_mesh-child"],
        env=env, capture_output=True, text=True, timeout=900)
    if proc.returncode != 0:
        return {"error": proc.stderr[-800:]}
    return json.loads(proc.stdout.strip().splitlines()[-1])


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(
        description="nomad-tpu end-to-end served-path benchmark")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CPU-safe shapes (<60s) with the parity "
                         "gate; for in-tree perf-path regression checks")
    ap.add_argument("--_mesh-child", action="store_true",
                    dest="mesh_child", help=argparse.SUPPRESS)
    args = ap.parse_args(argv)
    if args.mesh_child:
        _mesh_child()
        return
    if args.smoke:
        _apply_smoke()
    nodes = build_nodes(N_NODES)
    n_evals = max(1, N_PLACEMENTS // PER_EVAL)

    e2e_evals_sec, e2e_placed, worker_stats = bench_server_e2e(nodes, n_evals)
    placer_evals_sec, _, p50 = bench_placer(nodes, n_evals)
    cpu_evals_sec, _ = bench_cpu_reference(nodes, CPU_REF_EVALS)
    cpu_served_evals_sec, cpu_served_placed, cpu_served_rates = \
        bench_cpu_served(nodes, CPU_REF_EVALS)

    detail = {
        "placements_per_eval": PER_EVAL,
        "e2e_placed": e2e_placed,
        "e2e_worker_stats": worker_stats,
        "e2e_placements_sec": (e2e_psec := round(e2e_evals_sec * PER_EVAL,
                                                 2)),
        "placer_only_evals_sec": round(placer_evals_sec, 2),
        "placer_p50_eval_latency_ms": round(p50 * 1e3, 2),
        # Served-path idle-broker latency (host fast path): what one
        # interactive job registration pays end-to-end.
        "e2e_p50_eval_latency_ms": worker_stats.get(
            "e2e_p50_eval_latency_ms"),
        "cpu_reference_evals_sec": round(cpu_evals_sec, 2),
        # Served-vs-served: the honest apples-to-apples ratio (same server,
        # broker, applier, raft on both sides; only the placement engine
        # differs).
        "cpu_served_evals_sec": round(cpu_served_evals_sec, 2),
        "cpu_served_rep_rates": cpu_served_rates,
        "cpu_served_placed": cpu_served_placed,
        "served_vs_served_ratio": round(
            e2e_evals_sec / cpu_served_evals_sec, 2),
        # Absolute anchor (a RATIO): the reference's C1M challenge
        # sustained ~3,300 placements/sec across a 5,000-host cluster
        # (BASELINE.md). This is ONE chip driving a full commit path vs
        # their whole fleet.
        "e2e_vs_c1m_ratio": round(e2e_psec / 3300.0, 2),
        "backend": _backend(),
    }

    # The remaining BASELINE configs, each END-TO-END through the served
    # path (register -> raft -> broker -> worker -> plan apply -> commit).
    if RUN_C2:
        c2_nodes = build_nodes(1000)
        rate, placed, p50, rep_rates, storm_pct = bench_served_config(
            c2_nodes, build_plain_job, n_evals=10, reps=3)
        detail["config2_resource_only"] = {
            "path": "served", "nodes": 1000, "placements": 500,
            "evals_sec": round(rate, 2),
            "placements_sec": round(rate * PER_EVAL, 2),
            "placed_per_rep": placed,
            "p50_eval_latency_ms": round(p50 * 1e3, 2),
            "storm_latency_ms": storm_pct,
            "rep_rates": rep_rates,
        }

    if RUN_C4:
        # Reuse the headline node set (same 10k-node shape; 512 at
        # --smoke). 2 warm + 2x23 timed + 2 probes = 50 system jobs
        # total at full shape, per BASELINE.
        rate, placed, p50, rep_rates, storm_pct = bench_served_config(
            nodes, build_system_job, n_evals=C4_EVALS, reps=C4_REPS,
            warm=1, latency_probes=2)
        detail["config4_system"] = {
            "path": "served", "nodes": N_NODES,
            "system_jobs": 2 + C4_REPS * C4_EVALS + 2 + C4_EVALS,
            "evals_sec": round(rate, 2),
            "placements_sec": round(rate * N_NODES, 2),
            "placed_per_rep": placed,
            "p50_eval_latency_ms": round(p50 * 1e3, 2),
            "storm_latency_ms": storm_pct,
            "rep_rates": rep_rates,
        }

    if RUN_C5:
        c5_nodes = build_nodes(C5_NODES, n_dcs=4)
        c5_evals = max(1, C5_PLACEMENTS // PER_EVAL)
        dcs = ["dc1", "dc2", "dc3", "dc4"]
        rate, placed, p50, rep_rates, storm_pct = bench_served_config(
            c5_nodes, lambda: build_job(PER_EVAL, dcs), n_evals=c5_evals,
            reps=2)
        detail["config5_multidc"] = {
            "path": "served", "nodes": C5_NODES,
            "placements": C5_PLACEMENTS,
            "evals_sec": round(rate, 2),
            "placements_sec": round(rate * PER_EVAL, 2),
            "placed_per_rep": placed,
            "p50_eval_latency_ms": round(p50 * 1e3, 2),
            "storm_latency_ms": storm_pct,
            "rep_rates": rep_rates,
        }

    # State-store commit microbench (`store` section): per-alloc commit
    # µs / batch scatter ms / raft entry bytes, object vs columnar at
    # BOTH commit shapes — the sweep shape feeds config4 (and any system
    # storm), the window shape feeds the headline/config2/config5 service
    # configs (columnar service commits since ISSUE 11).
    detail["store"] = (store := {
        "config4_system": bench_store_commit(N_NODES),
        "service_window": bench_store_commit_window(),
    })

    # Service columnar-commit A/B: end-to-end evals/s + storm tails with
    # columnar service commits on vs off, interleaved/alternating reps.
    svc_ab = None
    if RUN_SVC_AB:
        detail["service_columnar"] = (svc_ab := bench_service_columnar_ab())

    # event_stream: broker-armed (+1 live subscriber) vs disarmed A/B,
    # publish overhead % + nomad.events counters, zero-drop/parity
    # exit-2 gated.
    ev_stream = None
    if RUN_EVENTS:
        detail["event_stream"] = (ev_stream := bench_event_stream())

    # digest: replica hash-chain armed (interval 64) vs disarmed A/B,
    # fold overhead % + nomad.fsm.digest counters, parity exit-2 gated.
    digest_ab = None
    if RUN_DIGEST:
        detail["digest"] = (digest_ab := bench_digest())

    # The millions-of-users shape: 1M nodes x a wide storm window,
    # keyed kernel 1dev-vs-mesh with latency percentiles (subprocess;
    # slow-gated out of --smoke).
    if RUN_MESH:
        detail["config6_mesh_1m"] = bench_mesh_1m()

    # Horizontal worker scaling: always recorded (smoke shapes), so every
    # BENCH file carries the 1-vs-2 ratio next to the single-worker rate.
    detail["worker_scaling"] = bench_worker_scaling()

    # QoS slo_storm: per-tier latency tails under mixed-priority load,
    # qos-on vs qos-off interleaved, + admission/preemption probes.
    slo = None
    if RUN_SLO:
        detail["slo_storm"] = (slo := bench_slo_storm())

    # failover_storm: placements/s + per-tier tails through an induced
    # leader election on a real 3-server cluster, zero-loss gated.
    failover = None
    if RUN_FAILOVER:
        detail["failover_storm"] = (failover := bench_failover_storm())

    # config7_federation: 3-region federated storm vs the all-on-leader
    # baseline, zero-loss / no-duplicate / quiet-region-SLO gated.
    fed_storm = None
    if RUN_FED:
        detail["config7_federation"] = (fed_storm :=
                                        bench_federation_storm())

    detail["placement_parity"] = (parity := bench_placement_parity())

    result = {
        "metric": f"end-to-end server evals/sec @{N_NODES} nodes x "
                  f"{N_PLACEMENTS} task-groups (register->broker->worker->"
                  f"plan-apply->committed)",
        "value": round(e2e_evals_sec, 2),
        "unit": "evals/sec",
        # Apples-to-apples: BOTH sides of this ratio run end-to-end through
        # the same served path; only the placement engine differs.
        "vs_baseline": round(e2e_evals_sec / cpu_served_evals_sec, 2),
        "detail": detail,
    }
    print(json.dumps(result))
    if not parity["ok"]:
        # Quality gate: the ratio above is only meaningful at >= reference
        # placement quality. Fail AFTER emitting the JSON so the metric is
        # still recorded alongside the failure.
        sys.stderr.write(
            f"PLACEMENT PARITY FAILED: {json.dumps(parity)}\n")
        sys.exit(2)
    if slo is not None and not (slo["parity_ok"]
                                and slo["admission_probe"]["ok"]
                                and slo["preempt_probe"]["ok"]):
        # QoS gate: qos-on must place the full storm (reordering never
        # drops work), admission must shed when told to, preemption must
        # place atomically. Same fail-after-emit contract as above.
        sys.stderr.write(f"QOS SLO GATE FAILED: {json.dumps(slo)}\n")
        sys.exit(2)
    if failover is not None and not failover["gate"]["ok"]:
        # Zero-downtime gate: an election may slow the storm but must
        # never lose or duplicate work. Same fail-after-emit contract.
        sys.stderr.write(
            f"FAILOVER STORM GATE FAILED: {json.dumps(failover)}\n")
        sys.exit(2)
    if fed_storm is not None and not fed_storm["gate"]["ok"]:
        # Federation gate: forwarding/routing may add hops but must
        # never lose or duplicate work, a quiet region's high tier must
        # hold its SLO through another region's storm, and the
        # follower-snapshot source must actually be exercised. Same
        # fail-after-emit contract.
        sys.stderr.write(
            f"FEDERATION STORM GATE FAILED: {json.dumps(fed_storm)}\n")
        sys.exit(2)
    svc_store = store["service_window"]
    if (svc_store["storm_group"]["commit_speedup"] or 0) < STORE_SVC_GATE:
        # Columnar-commit gate: at the storm commit unit (the applier's
        # 16-plan group entry) the service-window FSM commit must stay
        # >= STORE_SVC_GATE x faster than the per-object path (the whole
        # point of the columnar service path). Deterministic CPU, so a
        # miss is a regression, not noise. Same fail-after-emit contract.
        sys.stderr.write(
            f"SERVICE COLUMNAR STORE GATE FAILED "
            f"(want >= {STORE_SVC_GATE}x): {json.dumps(svc_store)}\n")
        sys.exit(2)
    if svc_ab is not None and not svc_ab["parity_ok"]:
        # Columnar A/B parity: both commit paths place the full storm and
        # the columnar server really committed service segments.
        sys.stderr.write(
            f"SERVICE COLUMNAR AB GATE FAILED: {json.dumps(svc_ab)}\n")
        sys.exit(2)
    if ev_stream is not None and not ev_stream["parity_ok"]:
        # Event-stream parity: armed and disarmed place identically-sized
        # storms, the live subscriber saw real traffic, and the bounded
        # queue never dropped. Same fail-after-emit contract.
        sys.stderr.write(
            f"EVENT STREAM AB GATE FAILED: {json.dumps(ev_stream)}\n")
        sys.exit(2)
    if digest_ab is not None and not digest_ab["parity_ok"]:
        # Replica-digest parity: armed and disarmed place identically-
        # sized storms, the chain folded every committed entry, and the
        # armed replica never saw itself diverge. Same fail-after-emit
        # contract.
        sys.stderr.write(
            f"DIGEST AB GATE FAILED: {json.dumps(digest_ab)}\n")
        sys.exit(2)


def _backend():
    try:
        import jax

        return str(jax.devices()[0])
    except Exception:
        return "unknown"


if __name__ == "__main__":
    main()
