"""Declarative chaos schedules: the one bespoke cluster-chaos test
generalized into a family. Each test arms failpoints on a timeline
(ChaosSchedule), applies load, heals, and asserts the SAME invariants
(terminal evals, no lost/duplicated allocations, no oversubscription,
index monotonicity, post-heal convergence) via resilience.chaos.

The smoke schedule runs unconditionally at tier-1 speed; the
multi-second storms are @pytest.mark.slow (run them with
`pytest -m slow` or as part of a NOMAD_TPU_SOAK sweep)."""

import time

import pytest

from nomad_tpu import mock
from nomad_tpu.resilience import failpoints
from nomad_tpu.resilience.chaos import (
    ChaosSchedule,
    IndexProbe,
    assert_invariants,
)
from nomad_tpu.rpc.cluster import ClusterServer
from nomad_tpu.server.server import Server, ServerConfig
from nomad_tpu.structs import to_dict
from nomad_tpu.structs.structs import (
    EvalStatusCancelled,
    EvalStatusComplete,
    EvalStatusFailed,
    NodeStatusDown,
    NodeStatusReady,
)

from helpers import wait_for  # noqa: E402
from test_cluster_chaos import (  # noqa: E402
    FAST,
    PER_JOB,
    _gaddr,
    _rpc_retry,
    boot,
    leader_of,
    make_job,
)

pytestmark = pytest.mark.timing_retry

TERMINAL = (EvalStatusComplete, EvalStatusFailed, EvalStatusCancelled)


@pytest.fixture(autouse=True)
def _heal_failpoints():
    failpoints.disarm_all()
    yield
    failpoints.disarm_all()


def _all_terminal(state, eval_ids):
    return all(
        (ev := state.eval_by_id(eid)) is not None and ev.Status in TERMINAL
        for eid in eval_ids)


def _boot_single():
    cs = ClusterServer(ServerConfig(node_id="", num_schedulers=1,
                                    scheduler_window=8))
    cs.connect([cs.addr], raft_config=FAST)
    cs.start()
    return cs


class TestSmokeSchedule:
    """Tier-1-speed schedule: runs unconditionally on every suite pass so
    the failpoint seams and the harness itself can't silently rot."""

    def test_dequeue_drop_and_commit_error_burst(self):
        cs = _boot_single()
        try:
            assert wait_for(lambda: cs.server.is_leader(), timeout=15)
            for _ in range(10):
                cs.endpoints.handle("Node.Register",
                                    {"Node": to_dict(mock.node())})
            jobs = [make_job() for _ in range(6)]
            eval_ids = []
            probe = IndexProbe()
            with ChaosSchedule(name="smoke") \
                    .arm(0.0, "worker.dequeue=drop:p=0.5") \
                    .arm(0.0, "plan.apply.commit=error:count=2") \
                    .heal(0.6, "worker.dequeue") as sched:
                for job in jobs:
                    resp = cs.endpoints.handle("Job.Register",
                                               {"Job": to_dict(job)})
                    eval_ids.append(resp["EvalID"])
                    probe.sample(cs.server.state)
                    time.sleep(0.05)
                sched.join(5.0)
            snap = failpoints.snapshot()
            assert snap["worker.dequeue"]["fired"] \
                + snap["plan.apply.commit"]["fired"] >= 1, \
                "schedule never hit a seam — sites renamed?"
            assert wait_for(
                lambda: _all_terminal(cs.server.state, eval_ids),
                timeout=30, interval=0.1,
                msg="evals terminal after smoke chaos")
            probe.sample(cs.server.state)
            assert not probe.violations, probe.violations
            assert_invariants(cs.server.state, jobs, per_job=PER_JOB,
                              eval_ids=eval_ids)
        finally:
            cs.shutdown()


class TestSnapshotPersistSchedule:
    """ROADMAP candidate site: state snapshot persist. An injected
    persist failure must degrade gracefully — FSM intact, log NOT
    truncated, apply loop alive — and the snapshot must land once the
    fault heals (the counter re-arms, so the next apply retries)."""

    def test_persist_failure_keeps_log_then_recovers(self):
        cs = _boot_single()
        try:
            assert wait_for(lambda: cs.server.is_leader(), timeout=15)
            for _ in range(4):
                cs.endpoints.handle("Node.Register",
                                    {"Node": to_dict(mock.node())})
            raft = cs.server.raft.node
            jobs = [make_job() for _ in range(3)]
            eval_ids = []
            with ChaosSchedule(name="snap-persist") \
                    .arm(0.0, "raft.snapshot.persist=error") as sched:
                sched.join(5.0)
                for job in jobs:
                    resp = cs.endpoints.handle("Job.Register",
                                               {"Job": to_dict(job)})
                    eval_ids.append(resp["EvalID"])
                assert wait_for(
                    lambda: _all_terminal(cs.server.state, eval_ids),
                    timeout=30, interval=0.1,
                    msg="evals terminal while snapshot persist is failing")
                first = raft.log.first_index()
                snap_before = raft.take_snapshot()
                # Degraded, not broken: the persist failed, so the log
                # kept every entry and no snapshot index advanced.
                assert raft.log.first_index() == first
                assert snap_before == 0
                assert failpoints.snapshot()[
                    "raft.snapshot.persist"]["fired"] >= 1
            # Healed (context exit disarms): the forced snapshot lands.
            snap_after = raft.take_snapshot()
            assert snap_after > 0
            assert_invariants(cs.server.state, jobs, per_job=PER_JOB,
                              eval_ids=eval_ids)
        finally:
            cs.shutdown()


class TestWindowDrainSchedule:
    """ISSUE 5 site: the pipelined worker's window drain fetch. A worker
    killed mid-window (the drain blows up under it) must nack the whole
    window so the broker redelivers its evals EXACTLY ONCE — no lost
    evals, no double-placed allocs — and the tainted chain must rebase
    onto committed state before the redelivered window dispatches."""

    def test_drain_kill_redelivers_window_exactly_once(self):
        srv = Server(ServerConfig(num_schedulers=1, scheduler_window=8))
        srv.establish_leadership()
        try:
            for _ in range(8):
                srv.node_register(mock.node())
            jobs = [make_job() for _ in range(6)]
            eval_ids = []
            with ChaosSchedule(name="window-drain") \
                    .arm(0.0, "worker.window.drain=error:count=1") as sched:
                sched.join(2.0)
                for job in jobs:
                    eval_ids.append(srv.job_register(job)[0])
                assert wait_for(
                    lambda: _all_terminal(srv.state, eval_ids),
                    timeout=30, interval=0.05,
                    msg="evals terminal after a window-drain kill")
            snap = failpoints.snapshot()
            assert snap["worker.window.drain"]["fired"] == 1, \
                "the drain seam never fired — site renamed?"
            # Exactly-once redelivery: every eval terminal, every job at
            # exactly its asked-for live allocs (a double delivery would
            # overshoot, a lost window would undershoot), no duplicate
            # alloc IDs, no node oversubscribed.
            assert_invariants(srv.state, jobs, per_job=PER_JOB,
                              eval_ids=eval_ids)
            # The killed window's chain was tainted; the redelivered
            # window rebased onto committed usage instead of inheriting
            # the dead window's phantom tail.
            assert srv.workers[0].stats["rebases"] >= 1
        finally:
            srv.shutdown()


class TestMeshExchangeSchedule:
    """ISSUE 12 site: the sharded mesh winner-row exchange. A window
    whose candidate exchange is silently lost (`drop` poisons the
    chain's exactness certificate — the observable a real ICI loss
    would produce) must fail at the drain-stage certificate check, nack
    the WHOLE window, taint + rebase the chain through the ChainArbiter,
    and redeliver every eval exactly once — no lost evals, no duplicate
    allocs."""

    def test_exchange_kill_rebases_and_redelivers_exactly_once(self):
        import jax

        if len(jax.devices()) < 8:
            pytest.skip("needs 8 (virtual) devices")
        # Mesh serving: node axis sharded over all devices, device
        # kernels forced (the host fast path would absorb these shallow
        # windows and never cross the exchange seam).
        srv = Server(ServerConfig(num_schedulers=1, scheduler_window=8,
                                  pipelined_scheduling=True,
                                  scheduler_mesh="all",
                                  host_placement=False))
        srv.establish_leadership()
        try:
            for _ in range(8):
                srv.node_register(mock.node())
            jobs = [make_job() for _ in range(6)]
            eval_ids = []
            with ChaosSchedule(name="mesh-exchange") \
                    .arm(0.0, "tensor.mesh.exchange=drop:count=1") as sched:
                sched.join(2.0)
                for job in jobs:
                    eval_ids.append(srv.job_register(job)[0])
                assert wait_for(
                    lambda: _all_terminal(srv.state, eval_ids),
                    timeout=60, interval=0.05,
                    msg="evals terminal after a mesh-exchange kill")
            snap = failpoints.snapshot()
            assert snap["tensor.mesh.exchange"]["fired"] == 1, \
                "the exchange seam never fired — mesh path not taken?"
            stats = srv.workers[0].stats
            # The poisoned window really was a sharded-mesh window, and
            # its certificate failure is what killed it.
            assert stats["mesh_windows"] >= 1, stats
            assert stats["mesh_cert_miss"] >= 1, stats
            # Exactly-once redelivery: every eval terminal, every job at
            # exactly its asked-for live allocs (a double delivery would
            # overshoot, a lost window would undershoot), no duplicate
            # alloc IDs, no node oversubscribed.
            assert_invariants(srv.state, jobs, per_job=PER_JOB,
                              eval_ids=eval_ids)
            # The killed window's chain was tainted; the redelivered
            # window rebased through the ChainArbiter onto committed
            # state instead of inheriting the poisoned tail.
            assert stats["rebases"] >= 1, stats
        finally:
            srv.shutdown()


class TestSystemEmitSchedule:
    """ISSUE 6 site: the system sweep's bulk placement emit
    (`sched.system.emit`, scheduler/system_sweep.py). A sweep killed at
    the emit seam dies BEFORE anything is submitted, so the worker nacks
    and the broker must redeliver the eval exactly once — the re-run
    places one alloc per node with no duplicates and no lost nodes."""

    N_NODES = 6

    def _system_job(self):
        job = mock.system_job()
        t = job.TaskGroups[0].Tasks[0]
        t.Resources.CPU = 20
        t.Resources.MemoryMB = 16
        t.Resources.DiskMB = 150
        t.Resources.Networks = []
        t.Services = []
        if t.LogConfig is not None:
            t.LogConfig.MaxFiles = 1
            t.LogConfig.MaxFileSizeMB = 1
        job.init_fields()
        return job

    def test_emit_kill_redelivers_sweep_exactly_once(self):
        srv = Server(ServerConfig(num_schedulers=1, scheduler_window=8))
        srv.establish_leadership()
        try:
            for _ in range(self.N_NODES):
                srv.node_register(mock.node())
            jobs = [self._system_job() for _ in range(3)]
            eval_ids = []
            with ChaosSchedule(name="system-emit") \
                    .arm(0.0, "sched.system.emit=error:count=1") as sched:
                sched.join(2.0)
                for job in jobs:
                    eval_ids.append(srv.job_register(job)[0])
                assert wait_for(
                    lambda: _all_terminal(srv.state, eval_ids),
                    timeout=30, interval=0.05,
                    msg="evals terminal after an emit-seam kill")
            snap = failpoints.snapshot()
            assert snap["sched.system.emit"]["fired"] == 1, \
                "the emit seam never fired — site renamed?"
            # Exactly-once redelivery: every job at exactly one live
            # alloc per node, no duplicate alloc IDs, no node carrying
            # the same job twice, every eval terminal.
            assert_invariants(srv.state, jobs, per_job=self.N_NODES,
                              eval_ids=eval_ids)
            for job in jobs:
                live = [a for a in srv.state.allocs_by_job(job.ID)
                        if not a.terminal_status()]
                per_node = {}
                for a in live:
                    per_node[a.NodeID] = per_node.get(a.NodeID, 0) + 1
                assert len(live) == self.N_NODES
                assert all(c == 1 for c in per_node.values()), per_node
        finally:
            srv.shutdown()


class TestStoreCommitSchedule:
    """ISSUE 9 site: the columnar sweep-batch state commit
    (`state.store.commit`, server/fsm.py ApplySweepBatch). The failpoint
    fires BEFORE any row lands, so a killed bulk commit fails the whole
    raft entry atomically: the worker nacks, the broker redelivers the
    eval exactly once, and a batch is never torn — every job ends at
    exactly one live alloc per node with no duplicates."""

    N_NODES = 6

    def _system_job(self):
        job = mock.system_job()
        t = job.TaskGroups[0].Tasks[0]
        t.Resources.CPU = 20
        t.Resources.MemoryMB = 16
        t.Resources.DiskMB = 150
        t.Resources.Networks = []
        t.Services = []
        if t.LogConfig is not None:
            t.LogConfig.MaxFiles = 1
            t.LogConfig.MaxFileSizeMB = 1
        job.init_fields()
        return job

    def test_bulk_commit_kill_redelivers_exactly_once(self):
        # Fired counts are process-cumulative (the equivalence gate also
        # exercises this site); assert the DELTA this schedule causes.
        fired_before = failpoints.snapshot().get(
            "state.store.commit", {}).get("fired", 0)
        srv = Server(ServerConfig(num_schedulers=1, scheduler_window=8))
        srv.establish_leadership()
        try:
            for _ in range(self.N_NODES):
                srv.node_register(mock.node())
            jobs = [self._system_job() for _ in range(3)]
            eval_ids = []
            with ChaosSchedule(name="store-commit") \
                    .arm(0.0, "state.store.commit=error:count=1") as sched:
                sched.join(2.0)
                for job in jobs:
                    eval_ids.append(srv.job_register(job)[0])
                assert wait_for(
                    lambda: _all_terminal(srv.state, eval_ids),
                    timeout=30, interval=0.05,
                    msg="evals terminal after a bulk-commit kill")
            snap = failpoints.snapshot()
            assert snap["state.store.commit"]["fired"] - fired_before == 1, \
                "the bulk-commit seam never fired — site renamed?"
            # Exactly-once redelivery + no torn batch: every job at
            # exactly one live alloc per node (a torn batch would leave a
            # partial node set; a double delivery would duplicate), no
            # duplicate alloc IDs, no oversubscription.
            assert_invariants(srv.state, jobs, per_job=self.N_NODES,
                              eval_ids=eval_ids)
            for job in jobs:
                live = [a for a in srv.state.allocs_by_job(job.ID)
                        if not a.terminal_status()]
                per_node = {}
                for a in live:
                    per_node[a.NodeID] = per_node.get(a.NodeID, 0) + 1
                assert len(live) == self.N_NODES
                assert all(c == 1 for c in per_node.values()), per_node
        finally:
            srv.shutdown()


class TestServiceStoreCommitSchedule:
    """ISSUE 11 site: the SERVICE columnar commit rides the same
    `state.store.commit` seam as the sweep path — a pipelined window's
    plans group into one ApplySweepBatch entry once the window build
    attaches service descriptors. A kill at the seam fires BEFORE the
    entry is proposed to consensus: the waiting window's evals fall back
    to the exact per-eval path, every eval still terminates, and no
    batch is ever torn or double-committed."""

    def test_service_bulk_commit_kill_redelivers_exactly_once(self):
        # Fired counts are process-cumulative (the equivalence gate also
        # exercises this site); assert the DELTA this schedule causes.
        fired_before = failpoints.snapshot().get(
            "state.store.commit", {}).get("fired", 0)
        srv = Server(ServerConfig(num_schedulers=1, scheduler_window=8))
        srv.establish_leadership()
        try:
            for _ in range(8):
                srv.node_register(mock.node())
            jobs = [make_job() for _ in range(6)]
            eval_ids = []
            with ChaosSchedule(name="svc-store-commit") \
                    .arm(0.0, "state.store.commit=error:count=1") as sched:
                sched.join(2.0)
                for job in jobs:
                    eval_ids.append(srv.job_register(job)[0])
                assert wait_for(
                    lambda: _all_terminal(srv.state, eval_ids),
                    timeout=30, interval=0.05,
                    msg="evals terminal after a service bulk-commit kill")
            snap = failpoints.snapshot()
            assert snap["state.store.commit"]["fired"] - fired_before == 1, \
                "the bulk-commit seam never fired for a service window"
            # Exactly-once: every job at exactly its asked-for live
            # allocs (the killed entry committed NOTHING; the fallback
            # re-runs placed fresh UUIDs once), no duplicates, no
            # oversubscription.
            assert_invariants(srv.state, jobs, per_job=PER_JOB,
                              eval_ids=eval_ids)
            # (No assertion on the segment count here: how many windows
            # the storm split into — and therefore whether any committed
            # columnar before/after the killed entry — is timing-
            # dependent. The invariants above already prove the killed
            # entry landed NOTHING.) Healed, the next storm must go
            # columnar again.
            heal_jobs = [make_job() for _ in range(2)]
            heal_ids = [srv.job_register(job)[0] for job in heal_jobs]
            assert wait_for(
                lambda: _all_terminal(srv.state, heal_ids),
                timeout=30, interval=0.05,
                msg="post-heal service storm never completed")
            assert srv.state.columnar_stats()["Batches"].get(
                "service", 0) >= 1
            assert_invariants(srv.state, jobs + heal_jobs, per_job=PER_JOB,
                              eval_ids=eval_ids + heal_ids)
        finally:
            srv.shutdown()


class TestRegionForwardSchedule:
    """ISSUE 14 site: the cross-region forward (rpc.forward_region,
    federation/routing.py). A region link killed mid-forward — in BOTH
    halves: before the request leaves (error) and after delivery with
    the response lost (drop, the ambiguous WAN failure) — must yield
    EXACTLY-ONCE registration in the home region: one job, ONE eval (no
    duplicates from the replay), the full placement, and nothing in the
    forwarding region."""

    @staticmethod
    def _boot_region(name, region, join=None):
        from nomad_tpu.federation import FederationConfig
        from nomad_tpu.gossip import GossipConfig

        cs = ClusterServer(ServerConfig(
            node_id="", region=region, num_schedulers=1,
            scheduler_window=8, bootstrap_expect=1,
            federation=FederationConfig(enabled=True)))
        cs.connect([], raft_config=FAST)
        cs.start()
        cs.enable_gossip(name, join=join,
                         gossip_config=GossipConfig.fast())
        return cs

    def test_link_killed_mid_forward_registers_exactly_once(self):
        a = self._boot_region("a0", "alpha")
        b = None
        try:
            assert wait_for(lambda: a.server.is_leader(), timeout=15)
            b = self._boot_region(
                "b0", "beta",
                join=[f"{a.membership.memberlist.addr}:"
                      f"{a.membership.memberlist.port}"])
            assert wait_for(lambda: b.server.is_leader(), timeout=15)
            assert wait_for(
                lambda: b.membership.region_servers("alpha"), timeout=15)
            for _ in range(4):
                a.endpoints.handle("Node.Register",
                                   {"Node": to_dict(mock.node())})

            # Half 1: response lost AFTER delivery (drop) — the replay
            # must dedupe on alpha's side.
            job1 = make_job()
            job1.Region = "alpha"
            with ChaosSchedule(name="region-forward-drop") \
                    .arm(0.0, "rpc.forward_region=drop:count=1") as sched:
                sched.join(2.0)
                resp1 = b.endpoints.handle("Job.Register",
                                           {"Job": to_dict(job1)})
            # Half 2: link failed BEFORE send (error) — plain retry.
            job2 = make_job()
            job2.Region = "alpha"
            with ChaosSchedule(name="region-forward-error") \
                    .arm(0.0, "rpc.forward_region=error:count=1") as sched:
                sched.join(2.0)
                resp2 = b.endpoints.handle("Job.Register",
                                           {"Job": to_dict(job2)})
            snap = failpoints.snapshot()
            assert snap["rpc.forward_region"]["fired"] >= 2, \
                "the forward seam never fired — site renamed?"

            state = a.server.state
            for job, resp in ((job1, resp1), (job2, resp2)):
                assert resp["EvalID"], resp
                # Exactly-once registration: ONE eval for the job in the
                # home region (a replayed register would mint a second).
                assert wait_for(
                    lambda j=job: state.job_by_id(j.ID) is not None,
                    timeout=15)
                evals = state.evals_by_job(job.ID)
                assert len(evals) == 1, [e.ID for e in evals]
                assert evals[0].ID == resp["EvalID"]
                assert evals[0].Region == "alpha"
                # ...and the forwarding region owns nothing.
                assert b.server.state.job_by_id(job.ID) is None
                assert b.server.state.evals_by_job(job.ID) == []
            assert wait_for(
                lambda: _all_terminal(state,
                                      [resp1["EvalID"], resp2["EvalID"]]),
                timeout=30, msg="forwarded evals terminal")
            assert_invariants(state, [job1, job2], per_job=PER_JOB,
                              eval_ids=[resp1["EvalID"], resp2["EvalID"]])
        finally:
            if b is not None:
                b.shutdown()
            a.shutdown()


class TestBlockedWakeupSchedule:
    """ROADMAP candidate site: the blocked-evals capacity wakeup. A lost
    wakeup event (dropped at the seam) strands parked evals ONLY until
    the next real capacity change — the recorded unblock indexes are the
    recovery net, and nothing is lost or duplicated."""

    def test_lost_wakeup_recovers_on_next_capacity_change(self):
        srv = Server(ServerConfig(num_schedulers=1, scheduler_window=8))
        srv.establish_leadership()
        try:
            first = mock.node()
            first.Resources.CPU = 1000
            first.Reserved = None
            srv.node_register(first)
            job = make_job()
            job.TaskGroups[0].Count = 4
            job.TaskGroups[0].Tasks[0].Resources.CPU = 600
            eval_id, _, _ = srv.job_register(job)
            assert wait_for(
                lambda: (ev := srv.state.eval_by_id(eval_id)) is not None
                and ev.Status in TERMINAL and ev.BlockedEval,
                timeout=30, msg="exhaustion never spawned a blocked eval")

            def live_allocs():
                return [a for a in srv.state.allocs_by_job(job.ID)
                        if not a.terminal_status()]

            placed_before = len(live_allocs())
            assert placed_before < 4
            with ChaosSchedule(name="lost-wakeup") \
                    .arm(0.0, "server.blocked.unblock=drop") as sched:
                sched.join(5.0)
                # Capacity arrives but the wakeup event is dropped: the
                # parked eval must stay parked (nothing schedules).
                srv.node_register(mock.node())
                time.sleep(0.5)
                assert len(live_allocs()) == placed_before, \
                    "a dropped wakeup still scheduled work"
                assert failpoints.snapshot()[
                    "server.blocked.unblock"]["fired"] >= 1
            # Healed: the NEXT capacity change delivers its wakeup and the
            # blocked eval places the remainder.
            srv.node_register(mock.node())
            assert wait_for(lambda: len(live_allocs()) == 4, timeout=30,
                            msg="blocked eval never recovered after heal")
            assert_invariants(srv.state, [job], per_job=4)
        finally:
            srv.shutdown()


class TestServiceSyncSchedule:
    """ROADMAP candidate site: the service-registry sync seam
    (services/manager.py `services.sync`). An injected sync failure must
    degrade gracefully — registrations re-queue and land once the fault
    heals — and the triggered fault must show up as an event on the
    active trace span (resilience <-> tracing integration)."""

    def test_sync_failure_requeues_then_heals_and_traces(self):
        import threading

        from nomad_tpu import mock
        from nomad_tpu.services.manager import ServiceManager
        from nomad_tpu.telemetry import trace

        synced: list = []
        delivered = threading.Event()

        def sync_fn(upserts, deletes):
            synced.append((list(upserts), list(deletes)))
            if upserts:
                delivered.set()

        trace.configure(enabled=True, sample_ratio=1.0)
        trace.clear()
        mgr = None
        try:
            mgr = ServiceManager(mock.node(), sync_fn)
            alloc = mock.alloc()
            task = alloc.Job.TaskGroups[0].Tasks[0]
            from nomad_tpu.structs import Service

            task.Services = [Service(Name="traced-svc")]
            with ChaosSchedule(name="svc-sync") \
                    .arm(0.0, "services.sync=error:count=2") as sched:
                sched.join(5.0)
                mgr.register_task(alloc, task)
                # Degraded: the armed flushes fail and re-queue; once the
                # count exhausts (self-heals), the batch must land.
                assert wait_for(delivered.is_set, timeout=30,
                                msg="sync batch never landed after heal")
            assert failpoints.snapshot()["services.sync"]["fired"] >= 1
            regs = [r for ups, _ in synced for r in ups]
            assert any(r.ServiceName == "traced-svc" for r in regs)

            # The triggered fault is an event on the sync span's trace.
            def fault_span():
                for t in trace.traces():
                    full = trace.get_trace(t["TraceID"])
                    for s in full["Spans"]:
                        if s["Name"] != "client.services.sync":
                            continue
                        for ev in s["Events"]:
                            if ev["Name"] == "failpoint" and \
                                    ev["Attrs"].get("site") == \
                                    "services.sync":
                                return s
                return None

            assert wait_for(lambda: fault_span() is not None, timeout=10,
                            msg="failpoint event never landed on the "
                                "client.services.sync span")
        finally:
            if mgr is not None:
                mgr.shutdown()
            trace.configure(enabled=False)
            trace.clear()


@pytest.mark.slow
class TestStormSchedules:
    """Multi-second storms against the networked 3-server cluster —
    excluded from tier-1 (`-m 'not slow'`); the soak entry point runs
    them alongside TestExtendedSoak."""

    def _boot_three(self):
        nodes = [boot("c0")]
        nodes.append(boot("c1", join=[_gaddr(nodes[0])]))
        nodes.append(boot("c2", join=[_gaddr(nodes[0])]))
        assert wait_for(lambda: leader_of(nodes) is not None, timeout=30)
        return nodes

    def _storm(self, live, n_jobs, pause=0.05):
        jobs = [make_job() for _ in range(n_jobs)]
        eval_ids = []
        for job in jobs:
            resp = _rpc_retry(live, "Job.Register", {"Job": to_dict(job)})
            eval_ids.append(resp["EvalID"])
            time.sleep(pause)
        return jobs, eval_ids

    def _assert_converged(self, live, jobs, eval_ids, fired_site):
        assert failpoints.snapshot()[fired_site]["fired"] >= 1, \
            f"storm never hit {fired_site}"
        assert wait_for(
            lambda: (ldr := leader_of(live)) is not None
            and _all_terminal(ldr.server.state, eval_ids),
            timeout=120, interval=0.25,
            msg="evals terminal after storm heal")
        assert_invariants(leader_of(live).server.state, jobs,
                          per_job=PER_JOB, eval_ids=eval_ids)

    def test_raft_message_loss_burst(self):
        """Leader->peer AppendEntries/RequestVote datagrams drop at p=0.6
        for two seconds mid-storm; replication stalls and elections churn,
        then the burst heals and every eval must still land exactly
        once."""
        nodes = self._boot_three()
        try:
            for _ in range(20):
                _rpc_retry(nodes, "Node.Register",
                           {"Node": to_dict(mock.node())})
            with ChaosSchedule(name="raft-loss") \
                    .arm(0.5, "raft.append_entries=drop:p=0.6") \
                    .arm(0.5, "raft.request_vote=drop:p=0.3") \
                    .heal(2.5, "raft.append_entries",
                          "raft.request_vote") as sched:
                jobs, eval_ids = self._storm(nodes, 20)
                sched.join(10.0)
            self._assert_converged(nodes, jobs, eval_ids,
                                   "raft.append_entries")
        finally:
            for n in nodes:
                n.shutdown()

    def test_rpc_drop_and_heal(self):
        """The wire itself goes bad: pooled client calls and server-side
        dispatch both black-hole a fraction of traffic (lost connections,
        not clean errors), driving the failover + retry paths, then
        heal."""
        nodes = self._boot_three()
        try:
            for _ in range(20):
                _rpc_retry(nodes, "Node.Register",
                           {"Node": to_dict(mock.node())})
            with ChaosSchedule(name="rpc-drop") \
                    .arm(0.3, "rpc.pool.call=drop:p=0.4") \
                    .arm(0.3, "rpc.server.handle=drop:p=0.3") \
                    .heal(2.0, "rpc.pool.call",
                          "rpc.server.handle") as sched:
                jobs, eval_ids = self._storm(nodes, 20)
                sched.join(10.0)
            self._assert_converged(nodes, jobs, eval_ids,
                                   "rpc.server.handle")
        finally:
            for n in nodes:
                n.shutdown()


@pytest.mark.slow
class TestHeartbeatDelayStorm:
    """A real client's heartbeats are delayed past the server's TTL: the
    node must degrade to down (TTL expiry), the client must recover it
    via re-registration once the storm heals, and scheduling must work
    afterwards — the full graceful-degradation round trip."""

    def test_node_flaps_down_then_recovers(self, tmp_path):
        from nomad_tpu.client.client import Client, ClientConfig
        from nomad_tpu.client.rpc import InProcServerChannel

        srv = Server(ServerConfig(num_schedulers=1,
                                  min_heartbeat_ttl=0.3,
                                  heartbeat_grace=0.2))
        srv.establish_leadership()
        cfg = ClientConfig(
            state_dir=str(tmp_path / "state"),
            alloc_dir=str(tmp_path / "alloc"),
            options={"driver.raw_exec.enable": "true"})
        client = Client(cfg, InProcServerChannel(srv))
        client.start()
        try:
            assert wait_for(
                lambda: (n := srv.state.node_by_id(client.node.ID))
                is not None and n.Status == NodeStatusReady, timeout=15)

            went_down = []
            with ChaosSchedule(name="hb-delay") \
                    .arm(0.2, "client.heartbeat=delay(1.0)") \
                    .heal(2.4, "client.heartbeat") as sched:
                # Degradation: a 1s delay against a ~0.5s TTL+grace
                # budget must knock the node down at least once.
                assert wait_for(
                    lambda: srv.state.node_by_id(
                        client.node.ID).Status == NodeStatusDown,
                    timeout=10, interval=0.05,
                    msg="delayed heartbeats never expired the TTL")
                went_down.append(True)
                sched.join(10.0)
            assert failpoints.snapshot()["client.heartbeat"]["fired"] >= 1

            # Recovery: the down-node heartbeat is rejected, the client
            # re-registers, and the node settles back to ready.
            assert wait_for(
                lambda: srv.state.node_by_id(
                    client.node.ID).Status == NodeStatusReady,
                timeout=15, interval=0.1,
                msg="node never re-registered after the storm healed")

            # And the recovered node still schedules work.
            from nomad_tpu.jobspec import parse_job

            job = parse_job('''
job "post-storm" {
  datacenters = ["dc1"]
  type = "service"
  group "g" {
    count = 2
    task "t" {
      driver = "raw_exec"
      config { command = "/bin/sh" args = ["-c", "sleep 3600"] }
      resources { cpu = 20 memory = 16 disk = 300 }
    }
  }
}
''')
            eval_id, _, _ = srv.job_register(job)
            assert wait_for(
                lambda: _all_terminal(srv.state, [eval_id]),
                timeout=30, msg="post-storm eval terminal")
            assert wait_for(
                lambda: len([a for a in srv.state.allocs_by_job(job.ID)
                             if not a.terminal_status()]) == 2,
                timeout=30, msg="post-storm allocs placed")
            assert_invariants(srv.state, [job], per_job=2,
                              eval_ids=[eval_id])
        finally:
            client.shutdown()
            srv.shutdown()


class TestLeaderFailoverSchedule:
    """ISSUE 13 tentpole gate: the leader dies mid-storm — including
    mid-sweep-commit (`state.store.commit` armed at the kill) and
    mid-snapshot-persist (a streaming persist in flight, every chunk
    slowed by `raft.snapshot.chunk`) — and failover must be a BOUNDED,
    measured event: a new leader within the election bound, no lost
    evals, no duplicate allocs, no oversubscription, per-tier SLO burn
    bounded through the election, and the survivors' streaming-snapshot
    machinery still running (a chunked snapshot lands during the storm).
    """

    N_NODES = 24
    N_JOBS = 36
    KILL_AT = 14
    TIERS = (80, 20, 50)  # round-robin job priorities (high/low/normal)

    def _boot(self, name, join=None):
        from nomad_tpu.gossip import GossipConfig
        from nomad_tpu.qos import QoSConfig
        from nomad_tpu.raft import RaftConfig

        cs = ClusterServer(ServerConfig(
            node_id="", num_schedulers=1, bootstrap_expect=3,
            scheduler_window=8,
            # Election-scale deadlines: the burn bound below asserts the
            # failover stays well inside them, not that completions are
            # sub-second on a loaded CI box.
            qos=QoSConfig(enabled=True, deadlines_s=(10.0, 30.0, 120.0))))
        cs.connect([], raft_config=RaftConfig(
            heartbeat_interval=0.02, election_timeout_min=0.08,
            election_timeout_max=0.16, apply_timeout=5.0,
            snapshot_threshold=30, trailing_logs=32))
        cs.start()
        cs.enable_gossip(name, join=join,
                         gossip_config=GossipConfig.fast())
        return cs

    def _cluster(self):
        from test_cluster_chaos import _gaddr as gaddr

        nodes = [self._boot("f0")]
        nodes.append(self._boot("f1", join=[gaddr(nodes[0])]))
        nodes.append(self._boot("f2", join=[gaddr(nodes[0])]))
        return nodes

    def test_leader_kill_mid_storm_bounded_recovery(self):
        commit_fired_before = failpoints.snapshot().get(
            "state.store.commit", {}).get("fired", 0)
        nodes = self._cluster()
        live = list(nodes)
        try:
            assert wait_for(lambda: leader_of(live) is not None,
                            timeout=30)
            for _ in range(self.N_NODES):
                _rpc_retry(live, "Node.Register",
                           {"Node": to_dict(mock.node())})
            jobs = []
            for i in range(self.N_JOBS):
                job = make_job()
                job.Priority = self.TIERS[i % len(self.TIERS)]
                jobs.append(job)
            eval_ids = []
            recovery_s = None
            with ChaosSchedule(name="leader-failover") \
                    .arm(0.0, "raft.snapshot.chunk=delay(0.005)") as sched:
                sched.join(2.0)
                for i, job in enumerate(jobs):
                    if i == self.KILL_AT:
                        # Mid-sweep-commit: the NEXT columnar commit —
                        # wherever the election leaves it — dies once.
                        failpoints.arm_from_spec(
                            "state.store.commit=error:count=1")
                        victim = leader_of(live)
                        assert victim is not None
                        live.remove(victim)
                        t_kill = time.monotonic()
                        victim.shutdown()
                        assert wait_for(
                            lambda: leader_of(live) is not None,
                            timeout=30, msg="post-kill election")
                        recovery_s = time.monotonic() - t_kill
                    resp = _rpc_retry(live, "Job.Register",
                                      {"Job": to_dict(job)})
                    eval_ids.append(resp["EvalID"])
                    time.sleep(0.01)

                def settled():
                    ldr = leader_of(live)
                    return ldr is not None and _all_terminal(
                        ldr.server.state, eval_ids)

                assert wait_for(settled, timeout=120, interval=0.25,
                                msg="storm terminal through the election")

            # Bounded recovery: the measured leader gap, not a vibe.
            assert recovery_s is not None and recovery_s < 30.0, recovery_s

            ldr = leader_of(live)
            state = ldr.server.state
            # No lost evals, no duplicate allocs, no oversubscription —
            # through a leader kill + a killed bulk commit.
            assert_invariants(state, jobs, per_job=PER_JOB,
                              eval_ids=eval_ids)
            assert failpoints.snapshot().get("state.store.commit", {}).get(
                "fired", 0) - commit_fired_before >= 1, \
                "the mid-commit fault never landed"

            # Bounded per-tier SLO burn through the election: the new
            # leader's high tier stayed inside its 10s deadline for at
            # least half its completions (ages ride the warm re-seed, so
            # an unbounded election would show up here).
            burn = ldr.server.eval_broker.slo_burn()
            assert burn[0] <= 0.5, f"high-tier SLO burn {burn}"
            assert all(b <= 0.9 for b in burn), burn

            # The storm crossed the streaming-snapshot threshold: the
            # new leader persisted a CHUNKED snapshot while serving (the
            # slowed chunk seam fired), and its apply loop kept up.
            assert failpoints.snapshot().get(
                "raft.snapshot.chunk", {}).get("fired", 0) >= 1
            assert wait_for(
                lambda: leader_of(live) is not None
                and leader_of(live).server.raft.node.log
                .latest_snapshot_chunks() is not None,
                timeout=30, msg="streaming snapshot landed mid-storm")
        finally:
            for n in nodes:
                try:
                    n.shutdown()
                except Exception:
                    pass

    def test_leader_kill_mid_snapshot_persist(self):
        """The kill lands WHILE the leader is streaming a snapshot to
        its log store (every chunk slowed, persist forced in a side
        thread): the cluster must elect, keep serving, and lose nothing
        — and the dying persist must not wedge shutdown."""
        import threading as _threading

        nodes = self._cluster()
        live = list(nodes)
        try:
            assert wait_for(lambda: leader_of(live) is not None,
                            timeout=30)
            for _ in range(12):
                _rpc_retry(live, "Node.Register",
                           {"Node": to_dict(mock.node())})
            jobs = [make_job() for _ in range(8)]
            eval_ids = [
                _rpc_retry(live, "Job.Register",
                           {"Job": to_dict(job)})["EvalID"]
                for job in jobs]

            def settled():
                ldr = leader_of(live)
                return ldr is not None and _all_terminal(
                    ldr.server.state, eval_ids)

            assert wait_for(settled, timeout=60, interval=0.1,
                            msg="pre-kill storm terminal")

            victim = leader_of(live)
            with ChaosSchedule(name="mid-persist-kill") \
                    .arm(0.0, "raft.snapshot.chunk=delay(0.03)") as sched:
                sched.join(2.0)
                persist = _threading.Thread(
                    target=victim.server.raft.node.take_snapshot,
                    name="test-persist", daemon=True)
                persist.start()
                time.sleep(0.06)  # a couple of chunks into the stream
                live.remove(victim)
                victim.shutdown()
                persist.join(timeout=30)
                assert not persist.is_alive(), \
                    "mid-persist shutdown wedged the snapshot thread"
                assert wait_for(lambda: leader_of(live) is not None,
                                timeout=30, msg="post-kill election")
                post = make_job()
                post_eval = _rpc_retry(live, "Job.Register",
                                       {"Job": to_dict(post)})["EvalID"]
                assert wait_for(
                    lambda: (ldr := leader_of(live)) is not None
                    and _all_terminal(ldr.server.state,
                                      eval_ids + [post_eval]),
                    timeout=60, interval=0.1,
                    msg="post-kill job served")
            ldr = leader_of(live)
            assert_invariants(ldr.server.state, jobs + [post],
                              per_job=PER_JOB,
                              eval_ids=eval_ids + [post_eval])
        finally:
            for n in nodes:
                try:
                    n.shutdown()
                except Exception:
                    pass


class TestEventStreamFailoverSchedule:
    """ISSUE 18 headline gate: an event-stream subscriber is mid-stream
    when the leader dies. Every replica's FSM feeds an identical broker
    (builders are deterministic functions of the committed entry), so
    the subscriber drains what the dead leader delivered, reconnects to
    the NEW leader with ``from_index=<last seen frame>``, and must
    observe a gapless, duplicate-free continuation whose fold matches
    the surviving store — chaos-gated mid-storm, with the
    ``events.publish`` seam armed (delay) across the whole timeline so
    the kill lands while the publish path is actively exercised.
    """

    N_NODES = 12
    N_JOBS = 20
    KILL_AT = 8

    def _boot(self, name, join=None):
        from nomad_tpu.gossip import GossipConfig
        from nomad_tpu.raft import RaftConfig

        cs = ClusterServer(ServerConfig(
            node_id="", num_schedulers=1, bootstrap_expect=3,
            scheduler_window=8))
        # No snapshot compaction in this storm: an install-snapshot on a
        # follower legitimately resets its broker floor (forcing a
        # subscriber re-list), which is the OTHER contract — this gate
        # pins the gapless-resume one.
        cs.connect([], raft_config=RaftConfig(
            heartbeat_interval=0.02, election_timeout_min=0.08,
            election_timeout_max=0.16, apply_timeout=5.0,
            snapshot_threshold=100_000))
        cs.start()
        cs.enable_gossip(name, join=join,
                         gossip_config=GossipConfig.fast())
        return cs

    def _cluster(self):
        nodes = [self._boot("e0")]
        nodes.append(self._boot("e1", join=[_gaddr(nodes[0])]))
        nodes.append(self._boot("e2", join=[_gaddr(nodes[0])]))
        return nodes

    def test_subscriber_resumes_on_new_leader_gapless(self):
        from test_event_equivalence import drain, fold

        publish_fired_before = failpoints.snapshot().get(
            "events.publish", {}).get("fired", 0)
        nodes = self._cluster()
        live = list(nodes)
        try:
            assert wait_for(lambda: leader_of(live) is not None,
                            timeout=30)
            for _ in range(self.N_NODES):
                _rpc_retry(live, "Node.Register",
                           {"Node": to_dict(mock.node())})
            src = leader_of(live)
            assert src is not None
            # Subscribe mid-stream on the CURRENT leader, before the
            # storm: the replay window (node registrations) plus the
            # live feed both ride this one subscription.
            sub = src.server.fsm.events.subscribe(
                from_index=0, fanout=True, queue_size=65536)
            jobs = []
            eval_ids = []
            with ChaosSchedule(name="event-stream-failover") \
                    .arm(0.0, "events.publish=delay(0.0005)") as sched:
                sched.join(2.0)
                for i in range(self.N_JOBS):
                    if i == self.KILL_AT:
                        victim = leader_of(live)
                        assert victim is src, \
                            "leadership moved before the kill"
                        live.remove(victim)
                        victim.shutdown()
                        assert wait_for(
                            lambda: leader_of(live) is not None,
                            timeout=30, msg="post-kill election")
                    job = make_job()
                    jobs.append(job)
                    resp = _rpc_retry(live, "Job.Register",
                                      {"Job": to_dict(job)})
                    eval_ids.append(resp["EvalID"])
                    time.sleep(0.01)

                def settled():
                    ldr = leader_of(live)
                    return ldr is not None and _all_terminal(
                        ldr.server.state, eval_ids)

                assert wait_for(settled, timeout=120, interval=0.25,
                                msg="storm terminal through the election")

            # Phase 1: the dead leader's broker closed the subscription
            # on shutdown, but everything it delivered first is retained
            # in the queue — drain it and record the splice point.
            frames1 = drain(sub, idle=0.3, timeout=30)
            assert frames1, "subscriber saw nothing before the kill"
            assert sub.status()[0], "victim shutdown left the sub open"
            last_seen = frames1[-1]["Index"]

            # Phase 2: resume on the NEW leader from the last frame the
            # old one delivered. Wait for its apply loop to cross the
            # splice first — a reconnect that races ahead of replication
            # would re-receive the tail as live frames.
            ldr = leader_of(live)
            broker2 = ldr.server.fsm.events
            assert wait_for(
                lambda: broker2.stats()["Tail"] >= last_seen,
                timeout=30, msg="new leader crossed the splice index")
            assert wait_for(
                lambda: broker2.stats()["Tail"]
                >= ldr.server.state.latest_index(),
                timeout=30, msg="new leader stream caught up to store")
            sub2 = broker2.subscribe(from_index=last_seen, fanout=True,
                                     queue_size=65536)
            frames2 = drain(sub2, idle=0.4, timeout=30)
            broker2.unsubscribe(sub2)

            # Gapless + duplicate-free splice: frame indexes strictly
            # increase ACROSS the reconnect (fold asserts this), every
            # resumed frame is past the splice, and no (Index, Topic,
            # Type, Key) identity repeats anywhere in the union.
            assert all(f["Index"] > last_seen for f in frames2)
            seen = [(f["Index"], e["Topic"], e["Type"], e["Key"])
                    for f in frames1 + frames2 for e in f["Events"]]
            assert len(seen) == len(set(seen)), "duplicate events"
            shadow = fold(frames1 + frames2)

            # Storm totals match the surviving store: the spliced stream
            # reconstructs membership and placement exactly.
            state = ldr.server.state
            assert set(shadow.nodes) == {n.ID for n in state.nodes()}
            assert set(shadow.jobs) == {j.ID for j in state.jobs()}
            assert {aid: d["NodeID"]
                    for aid, d in shadow.allocs.items()} \
                == {a.ID: a.NodeID for a in state.allocs()}
            store_evals = {e.ID: e.Status for e in state.evals()}
            assert shadow.evals == store_evals
            assert set(eval_ids) <= set(shadow.evals)

            assert_invariants(state, jobs, per_job=PER_JOB,
                              eval_ids=eval_ids)
            # The publish seam really fired through the storm.
            assert failpoints.snapshot().get("events.publish", {}).get(
                "fired", 0) - publish_fired_before >= 1
        finally:
            for n in nodes:
                try:
                    n.shutdown()
                except Exception:
                    pass


class TestDigestDivergenceSchedule:
    """ISSUE 19 chaos gate: silent store corruption lands on follower
    replicas mid-storm (`fsm.digest.mutate=drop` — the seam corrupts the
    just-written row in place, bypassing indexes, on non-leader replicas
    only). The cross-replica digest exchange must DETECT it (the
    corrupted follower's verify raises against the leader's piggybacked
    checkpoint), quarantine the follower to snapshot-reinstall recovery,
    and reconverge the whole cluster onto the leader's verified state —
    with zero divergence alarms before the fault and none after the
    heal, and the leader's invariants intact throughout."""

    N_NODES = 12
    N_JOBS = 16
    CORRUPT_AT = 6

    def _boot(self, name, join=None):
        from nomad_tpu.gossip import GossipConfig
        from nomad_tpu.raft import RaftConfig

        cs = ClusterServer(ServerConfig(
            node_id="", num_schedulers=1, bootstrap_expect=3,
            scheduler_window=8, digest_interval=16))
        # Small snapshot threshold: the quarantined follower's recovery
        # path is a chunked InstallSnapshot (whose header reseeds its
        # digest chain), not a full log replay.
        cs.connect([], raft_config=RaftConfig(
            heartbeat_interval=0.02, election_timeout_min=0.08,
            election_timeout_max=0.16, apply_timeout=5.0,
            snapshot_threshold=30, trailing_logs=32))
        cs.start()
        cs.enable_gossip(name, join=join,
                         gossip_config=GossipConfig.fast())
        return cs

    def _cluster(self):
        nodes = [self._boot("d0")]
        nodes.append(self._boot("d1", join=[_gaddr(nodes[0])]))
        nodes.append(self._boot("d2", join=[_gaddr(nodes[0])]))
        return nodes

    def test_corrupted_follower_detected_and_reinstalled(self):
        mutate_fired_before = failpoints.snapshot().get(
            "fsm.digest.mutate", {}).get("fired", 0)
        nodes = self._cluster()

        def diverged_total():
            return sum(cs.server.fsm.digest.stats()["Diverged"]
                       for cs in nodes)

        try:
            assert wait_for(lambda: leader_of(nodes) is not None,
                            timeout=30)
            for _ in range(self.N_NODES):
                _rpc_retry(nodes, "Node.Register",
                           {"Node": to_dict(mock.node())})
            # Zero false positives on the clean warm-up applies.
            assert diverged_total() == 0

            jobs = []
            eval_ids = []
            for i in range(self.N_JOBS):
                if i == self.CORRUPT_AT:
                    # Corrupt every follower apply until detection: the
                    # seam skips leaders, so the reference state — and
                    # the recovery snapshot — stays clean.
                    failpoints.arm_from_spec("fsm.digest.mutate=drop")
                job = make_job()
                jobs.append(job)
                resp = _rpc_retry(nodes, "Job.Register",
                                  {"Job": to_dict(job)})
                eval_ids.append(resp["EvalID"])
                time.sleep(0.01)

            # Detection: the checkpoint exchange flags the corruption
            # within one interval of piggybacked AppendEntries.
            assert wait_for(lambda: diverged_total() >= 1, timeout=30,
                            interval=0.05,
                            msg="injected divergence never detected")
            failpoints.disarm("fsm.digest.mutate")
            assert failpoints.snapshot().get(
                "fsm.digest.mutate", {}).get("fired", 0) \
                - mutate_fired_before >= 1

            ldr = leader_of(nodes)
            assert ldr is not None
            assert wait_for(
                lambda: _all_terminal(ldr.server.state, eval_ids),
                timeout=120, interval=0.25,
                msg="storm terminal through the quarantine")
            # Heal phase: fresh entries so catch-up has new indexes to
            # verify against, and a NEW NODE — the capacity change
            # re-enqueues any eval a follower worker parked as blocked
            # while its store was still corrupt (infeasible chaos-marked
            # nodes), so placement liveness recovers scheduler-side.
            _rpc_retry(nodes, "Node.Register",
                       {"Node": to_dict(mock.node())})
            heal = [make_job() for _ in range(3)]
            for job in heal:
                resp = _rpc_retry(nodes, "Job.Register",
                                  {"Job": to_dict(job)})
                eval_ids.append(resp["EvalID"])
            assert wait_for(
                lambda: (lead := leader_of(nodes)) is not None
                and _all_terminal(lead.server.state, eval_ids),
                timeout=60, interval=0.25, msg="heal evals terminal")

            def short_jobs():
                lead = leader_of(nodes)
                if lead is None:
                    return jobs + heal
                live: dict = {}
                for a in lead.server.state.allocs():
                    if a.DesiredStatus == "run":
                        live[a.JobID] = live.get(a.JobID, 0) + 1
                return [j for j in jobs + heal
                        if live.get(j.ID, 0) < PER_JOB]

            # A follower worker that scheduled from a corrupt (or
            # quarantine-wiped) snapshot can complete an eval WITHOUT
            # its placements — the digest detects the corruption, it
            # does not resurrect evals the corruption already ate. The
            # operator remedy is re-evaluation (`nomad job eval`):
            # re-register any shorted job and let the clean post-heal
            # cluster place the missing allocs.
            for _ in range(4):
                missing = short_jobs()
                if not missing:
                    break
                retry_ids = []
                for job in missing:
                    resp = _rpc_retry(nodes, "Job.Register",
                                      {"Job": to_dict(job)})
                    retry_ids.append(resp["EvalID"])
                eval_ids.extend(retry_ids)
                wait_for(
                    lambda: (lead := leader_of(nodes)) is not None
                    and _all_terminal(lead.server.state, retry_ids),
                    timeout=30, interval=0.25)
            assert not short_jobs(), \
                "jobs still unplaced after post-heal re-evaluation"

            def converged():
                lead = leader_of(nodes)
                if lead is None:
                    return False
                state = lead.server.state
                want_nodes = {(n.ID, n.Status) for n in state.nodes()}
                want_evals = {(e.ID, e.Status) for e in state.evals()}
                for cs in nodes:
                    s = cs.server.state
                    if {(n.ID, n.Status) for n in s.nodes()} != want_nodes:
                        return False
                    if {(e.ID, e.Status) for e in s.evals()} != want_evals:
                        return False
                return True

            assert wait_for(converged, timeout=60, interval=0.25,
                            msg="replicas reconverged after quarantine")

            # Clean recovery: the corruption marker survives NOWHERE,
            # every replica's digest is back in verified mode, and the
            # leader's storm invariants held through the whole episode.
            for cs in nodes:
                s = cs.server.state
                assert all(e.Status != "chaos-diverged" for e in s.evals())
                assert all(n.Status != "chaos-diverged" for n in s.nodes())
                assert cs.server.fsm.digest.stats()["Synced"]
            ldr = leader_of(nodes)
            assert ldr.server.fsm.digest.stats()["Diverged"] == 0, \
                "the leader must never see itself as diverged"
            assert_invariants(ldr.server.state, jobs + heal,
                              per_job=PER_JOB, eval_ids=eval_ids)
        finally:
            for n in nodes:
                try:
                    n.shutdown()
                except Exception:
                    pass
