"""Job diff + plan annotations + Job.Plan dry-run (reference:
nomad/structs/diff_test.go, scheduler/annotate_test.go,
nomad/job_endpoint.go:422 Job.Plan)."""

import pytest

from nomad_tpu import mock
from nomad_tpu.scheduler.annotate import (
    AnnotationForcesCreate,
    AnnotationForcesDestroy,
    AnnotationForcesDestructiveUpdate,
    AnnotationForcesInplaceUpdate,
    UpdateTypeCreate,
    annotate,
)
from nomad_tpu.structs import Constraint, DesiredUpdates, PlanAnnotations
from nomad_tpu.structs.diff import (
    DiffTypeAdded,
    DiffTypeDeleted,
    DiffTypeEdited,
    DiffTypeNone,
    job_diff,
    task_diff,
    task_group_diff,
)


def _field(diff, name):
    return next((f for f in diff.Fields if f.Name == name), None)


class TestJobDiff:
    def test_identical_jobs_none(self):
        j = mock.job()
        assert job_diff(j, j.copy()).Type == DiffTypeNone

    def test_added_and_deleted(self):
        j = mock.job()
        added = job_diff(None, j)
        assert added.Type == DiffTypeAdded
        assert added.ID == j.ID
        assert _field(added, "Priority").New == str(j.Priority)

        deleted = job_diff(j, None)
        assert deleted.Type == DiffTypeDeleted
        assert _field(deleted, "Priority").Old == str(j.Priority)

    def test_mismatched_ids_raise(self):
        a, b = mock.job(), mock.job()
        with pytest.raises(ValueError):
            job_diff(a, b)

    def test_primitive_field_edit(self):
        old = mock.job()
        new = old.copy()
        new.Priority = old.Priority + 10
        d = job_diff(old, new)
        assert d.Type == DiffTypeEdited
        f = _field(d, "Priority")
        assert f.Type == DiffTypeEdited
        assert (f.Old, f.New) == (str(old.Priority), str(new.Priority))

    def test_meta_map_diff(self):
        old = mock.job()
        new = old.copy()
        new.Meta["team"] = "team-x"
        d = job_diff(old, new)
        f = _field(d, "Meta[team]")
        assert f.Type == DiffTypeAdded and f.New == "team-x"

    def test_datacenter_list_diff(self):
        old = mock.job()
        new = old.copy()
        new.Datacenters = list(old.Datacenters) + ["dc2"]
        d = job_diff(old, new)
        idx = len(old.Datacenters)
        f = _field(d, f"Datacenters[{idx}]")
        assert f is not None and f.Type == DiffTypeAdded

    def test_constraint_added(self):
        old = mock.job()
        new = old.copy()
        new.Constraints.append(
            Constraint(LTarget="${attr.cpu.arch}", RTarget="amd64",
                       Operand="="))
        d = job_diff(old, new)
        cons = [o for o in d.Objects if o.Name == "Constraint"]
        assert any(o.Type == DiffTypeAdded for o in cons)

    def test_filtered_bookkeeping_fields_ignored(self):
        old = mock.job()
        new = old.copy()
        new.Status = "dead"
        new.ModifyIndex = 999
        new.JobModifyIndex = 999
        assert job_diff(old, new).Type == DiffTypeNone

    def test_contextual_includes_unchanged(self):
        old = mock.job()
        new = old.copy()
        new.Priority += 1
        d = job_diff(old, new, contextual=True)
        f = _field(d, "Type")
        assert f is not None and f.Type == DiffTypeNone


class TestTaskGroupDiff:
    def test_count_change(self):
        old = mock.job().TaskGroups[0]
        new = old.copy()
        new.Count = old.Count + 3
        d = task_group_diff(old, new)
        assert d.Type == DiffTypeEdited
        assert _field(d, "Count").Type == DiffTypeEdited

    def test_task_added_bubbles_up(self):
        old = mock.job().TaskGroups[0]
        new = old.copy()
        extra = new.Tasks[0].copy()
        extra.Name = "sidecar"
        new.Tasks.append(extra)
        d = task_group_diff(old, new)
        assert d.Type == DiffTypeEdited
        added = [t for t in d.Tasks if t.Type == DiffTypeAdded]
        assert [t.Name for t in added] == ["sidecar"]


class TestTaskDiff:
    def test_resources_diff(self):
        old = mock.job().TaskGroups[0].Tasks[0]
        new = old.copy()
        new.Resources.CPU += 100
        d = task_diff(old, new)
        assert d.Type == DiffTypeEdited
        res = next(o for o in d.Objects if o.Name == "Resources")
        cpu = next(f for f in res.Fields if f.Name == "CPU")
        assert cpu.Type == DiffTypeEdited

    def test_service_check_diff(self):
        old = mock.job().TaskGroups[0].Tasks[0]
        if not old.Services or not old.Services[0].Checks:
            pytest.skip("mock task has no service checks")
        new = old.copy()
        new.Services[0].Checks[0].Interval += 5_000_000_000
        d = task_diff(old, new)
        svc = next(o for o in d.Objects if o.Name == "Service")
        chk = next(o for o in svc.Objects if o.Name == "Check")
        assert chk.Type == DiffTypeEdited

    def test_port_only_change_visible_noncontextual(self):
        from nomad_tpu.structs import Port

        old = mock.job().TaskGroups[0].Tasks[0]
        new = old.copy()
        new.Resources.Networks[0].ReservedPorts.append(Port("db", 5432))
        d = task_diff(old, new)  # contextual=False default
        assert d.Type == DiffTypeEdited
        res = next(o for o in d.Objects if o.Name == "Resources")
        net = next(o for o in res.Objects if o.Name == "Network")
        port = next(o for o in net.Objects if o.Name == "Static Port")
        assert port.Type == DiffTypeAdded

    def test_duplicate_key_artifacts_not_collapsed(self):
        from nomad_tpu.structs import TaskArtifact

        old = mock.job().TaskGroups[0].Tasks[0]
        old.Artifacts = [
            TaskArtifact(GetterSource="http://x/a.tgz", RelativeDest="a/"),
            TaskArtifact(GetterSource="http://x/a.tgz", RelativeDest="b/"),
        ]
        new = old.copy()
        del new.Artifacts[0]
        d = task_diff(old, new)
        assert d.Type == DiffTypeEdited
        deleted = [o for o in d.Objects
                   if o.Name == "Artifact" and o.Type == DiffTypeDeleted]
        assert len(deleted) == 1

    def test_env_edit(self):
        old = mock.job().TaskGroups[0].Tasks[0]
        new = old.copy()
        new.Env["NEW_VAR"] = "1"
        d = task_diff(old, new)
        f = _field(d, "Env[NEW_VAR]")
        assert f.Type == DiffTypeAdded


class TestAnnotate:
    def _diff(self, mutate):
        old = mock.job()
        new = old.copy()
        mutate(new)
        return job_diff(old, new, contextual=True)

    def test_count_up_forces_create(self):
        d = self._diff(lambda j: setattr(j.TaskGroups[0], "Count",
                                         j.TaskGroups[0].Count + 5))
        annotate(d, None)
        count = _field(d.TaskGroups[0], "Count")
        assert AnnotationForcesCreate in count.Annotations

    def test_count_down_forces_destroy(self):
        old = mock.job()
        old.TaskGroups[0].Count = 5
        new = old.copy()
        new.TaskGroups[0].Count = 2
        d = job_diff(old, new, contextual=True)
        annotate(d, None)
        count = _field(d.TaskGroups[0], "Count")
        assert AnnotationForcesDestroy in count.Annotations

    def test_desired_updates_copied(self):
        d = self._diff(lambda j: setattr(j.TaskGroups[0], "Count",
                                         j.TaskGroups[0].Count + 1))
        ann = PlanAnnotations(DesiredTGUpdates={
            d.TaskGroups[0].Name: DesiredUpdates(Place=1, Ignore=2)})
        annotate(d, ann)
        assert d.TaskGroups[0].Updates[UpdateTypeCreate] == 1
        assert d.TaskGroups[0].Updates["ignore"] == 2

    def test_driver_change_is_destructive(self):
        d = self._diff(lambda j: setattr(j.TaskGroups[0].Tasks[0],
                                         "Driver", "other"))
        annotate(d, None)
        task = d.TaskGroups[0].Tasks[0]
        assert AnnotationForcesDestructiveUpdate in task.Annotations

    def test_kill_timeout_change_is_destructive(self):
        # Every primitive-field edit is destructive in plan annotations
        # (reference: annotate.go:161-165).
        d = self._diff(lambda j: setattr(j.TaskGroups[0].Tasks[0],
                                         "KillTimeout", 99_000_000_000))
        annotate(d, None)
        task = d.TaskGroups[0].Tasks[0]
        assert AnnotationForcesDestructiveUpdate in task.Annotations

    def test_constraint_change_is_inplace(self):
        # LogConfig/Service/Constraint object edits go in place
        # (reference: annotate.go:168-177).
        from nomad_tpu.structs import Constraint

        d = self._diff(lambda j: j.TaskGroups[0].Tasks[0].Constraints.append(
            Constraint(LTarget="${attr.kernel.name}", RTarget="linux",
                       Operand="=")))
        annotate(d, None)
        task = d.TaskGroups[0].Tasks[0]
        assert AnnotationForcesInplaceUpdate in task.Annotations

    def test_task_meta_change_is_destructive(self):
        # Must match the reconciler: tasks_updated treats Meta edits as
        # destructive (scheduler/util.py).
        d = self._diff(
            lambda j: j.TaskGroups[0].Tasks[0].Meta.update(x="1"))
        annotate(d, None)
        task = d.TaskGroups[0].Tasks[0]
        assert AnnotationForcesDestructiveUpdate in task.Annotations


class TestJobPlanEndpoint:
    """Server-side dry run (reference: job_endpoint.go:422-526)."""

    @pytest.fixture()
    def server(self):
        from nomad_tpu.server.server import Server, ServerConfig

        srv = Server(ServerConfig(num_schedulers=1))
        yield srv
        srv.shutdown()

    def test_plan_new_job(self, server):
        for _ in range(3):
            node = mock.node()
            server.node_register(node)
            server.node_update_status(node.ID, "ready")
        job = mock.job()
        resp = server.job_plan(job, want_diff=True)
        assert resp.Diff.Type == DiffTypeAdded
        assert resp.JobModifyIndex == 0
        # No state was mutated by the dry run.
        assert server.state.job_by_id(job.ID) is None
        assert server.state.allocs_by_job(job.ID) == []
        ann = resp.Annotations.DesiredTGUpdates[job.TaskGroups[0].Name]
        assert ann.Place == job.TaskGroups[0].Count

    def test_plan_update_reports_diff_and_index(self, server):
        for _ in range(3):
            node = mock.node()
            server.node_register(node)
            server.node_update_status(node.ID, "ready")
        job = mock.job()
        server.job_register(job.copy())
        existing = server.state.job_by_id(job.ID)

        updated = job.copy()
        updated.TaskGroups[0].Count += 2
        resp = server.job_plan(updated, want_diff=True)
        assert resp.JobModifyIndex == existing.JobModifyIndex
        assert resp.Diff.Type == DiffTypeEdited
        count = _field(resp.Diff.TaskGroups[0], "Count")
        assert AnnotationForcesCreate in count.Annotations

    def test_plan_does_not_corrupt_live_state(self, server):
        # Dry-run upserts into the scratch store must not restamp indexes
        # on live objects shared via snapshot reads.
        node = mock.node()
        server.node_register(node)
        server.node_update_status(node.ID, "ready")
        other = mock.job()
        server.job_register(other.copy())
        live = server.state.job_by_id(other.ID)
        jmi_before = live.JobModifyIndex
        node_mi_before = server.state.node_by_id(node.ID).ModifyIndex

        server.job_plan(mock.job(), want_diff=False)

        assert server.state.job_by_id(other.ID).JobModifyIndex == jmi_before
        assert server.state.node_by_id(node.ID).ModifyIndex == node_mi_before

    def test_plan_periodic_skips_scheduler(self, server):
        # Register never evaluates periodic parents; plan must not claim
        # placements that submission would not perform.
        job = mock.periodic_job()
        resp = server.job_plan(job, want_diff=True)
        assert resp.Annotations is None
        assert not resp.FailedTGAllocs
        assert resp.Diff.Type == DiffTypeAdded
        assert resp.NextPeriodicLaunch > 0

    def test_plan_no_nodes_reports_failures(self, server):
        job = mock.job()
        resp = server.job_plan(job, want_diff=False)
        assert resp.Diff is None
        assert resp.FailedTGAllocs
        tg_name = job.TaskGroups[0].Name
        assert tg_name in resp.FailedTGAllocs
