"""Agent + HTTP API + api client + jobspec tests (shaped after reference
command/agent/*_test.go and api/*_test.go — black-box dev-mode agent)."""

import threading
import time

import pytest

from nomad_tpu.agent import Agent, AgentConfig
from nomad_tpu.api import APIError, Client as APIClient, QueryOptions
from nomad_tpu.jobspec import parse_duration, parse_job
from nomad_tpu.structs.structs import SECOND, MINUTE


from helpers import wait_for  # noqa: E402


@pytest.fixture(scope="module")
def dev_agent(tmp_path_factory):
    config = AgentConfig.dev()
    config.http_port = 0  # ephemeral
    config.data_dir = str(tmp_path_factory.mktemp("agent"))
    agent = Agent(config)
    agent.start()
    api = APIClient(address=f"http://127.0.0.1:{agent.http.port}")
    yield agent, api
    agent.shutdown()


BATCH_JOB = '''
job "httpjob" {
  datacenters = ["dc1"]
  type = "batch"
  group "g" {
    task "t" {
      driver = "raw_exec"
      config { command = "/bin/sh" args = ["-c", "echo api > ${NOMAD_TASK_DIR}/api.txt; sleep 1"] }
      resources { cpu = 50 memory = 32 disk = 300 }
    }
  }
}
'''


class TestHTTPAPI:
    def test_agent_self_and_members(self, dev_agent):
        agent, api = dev_agent
        self_info = api.agent.self()
        assert self_info["config"]["Server"] is True
        assert self_info["config"]["Client"] is True
        members = api.agent.members()
        assert members[0]["Status"] == "alive"
        assert api.regions.list() == ["global"]

    def test_agent_metrics_endpoint(self, dev_agent):
        agent, api = dev_agent
        # Force one FSM apply into the current collection interval so the
        # assertion is deterministic regardless of interval rotation.
        from nomad_tpu import mock
        node = mock.node()
        agent.server.node_register(node)
        try:
            snap = api.agent.metrics()
            assert set(snap) == {"Timestamp", "Gauges", "Counters",
                                 "Samples"}
            # Entry shapes (reference: go-metrics DisplayMetrics): gauges
            # are {Name, Value}; counters and samples are aggregates.
            for g in snap["Gauges"]:
                assert set(g) == {"Name", "Value"}
            for agg in list(snap["Counters"]) + list(snap["Samples"]):
                assert set(agg) == {"Name", "Count", "Sum", "Min", "Max",
                                    "Mean"}
                assert agg["Count"] >= 1
                assert agg["Min"] <= agg["Mean"] <= agg["Max"]
            # The HTTP snapshot shows the current interval; the sample we
            # just forced may land either side of a rotation boundary, so
            # assert against the sink's retained intervals.
            from nomad_tpu.telemetry import registry
            assert any("nomad.fsm.register_node" in iv["samples"]
                       for iv in registry.inmem._intervals)
        finally:
            # Leave the shared dev agent's node list as we found it.
            agent.server.node_deregister(node.ID)

    def test_nodes_listed(self, dev_agent):
        agent, api = dev_agent
        assert wait_for(lambda: len(api.nodes.list()[0]) == 1)
        nodes, meta = api.nodes.list()
        assert meta.last_index > 0
        node, _ = api.nodes.info(nodes[0]["ID"])
        assert node["Status"] == "ready"
        assert node["Attributes"]["driver.raw_exec"] == "1"

    def test_job_lifecycle_over_http(self, dev_agent):
        agent, api = dev_agent
        job = parse_job(BATCH_JOB)
        job.init_fields()
        eval_id, meta = api.jobs.register(job)
        assert eval_id
        # Eval completes.
        assert wait_for(lambda: api.evaluations.info(eval_id)[0]["Status"]
                        == "complete")
        # Allocation visible via job + eval + node queries.
        allocs, _ = api.jobs.allocations("httpjob")
        assert len(allocs) == 1
        assert wait_for(lambda: api.jobs.allocations("httpjob")[0][0]
                        ["ClientStatus"] == "complete", timeout=40)
        alloc_id = allocs[0]["ID"]
        full, _ = api.allocations.info(alloc_id)
        assert full["Job"]["ID"] == "httpjob"
        # fs API reads the task output through the agent.
        content = api.alloc_fs.cat(alloc_id, "t/local/api.txt")
        assert content.strip() == "api"
        listing = api.alloc_fs.list(alloc_id, "alloc/logs")
        assert any(f["Name"].startswith("t.stdout") for f in listing)
        # Job listing + info.
        jobs, _ = api.jobs.list()
        assert any(j["ID"] == "httpjob" for j in jobs)
        info, _ = api.jobs.info("httpjob")
        assert info.TaskGroups[0].Tasks[0].Driver == "raw_exec"
        # Stop.
        api.jobs.deregister("httpjob")
        with pytest.raises(APIError) as exc:
            api.jobs.info("httpjob")
        assert exc.value.code == 404

    def test_blocking_query_wakes_on_change(self, dev_agent):
        agent, api = dev_agent
        _, meta = api.jobs.list()
        result = {}

        def blocked():
            jobs, m = api.jobs.list(QueryOptions(wait_index=meta.last_index,
                                                 wait_time=10))
            result["jobs"] = jobs
            result["index"] = m.last_index

        t = threading.Thread(target=blocked)
        t.start()
        time.sleep(0.3)
        job = parse_job(BATCH_JOB)
        job.ID = job.Name = "blocker"
        job.TaskGroups[0].Tasks[0].Config = {"command": "/bin/true"}
        api.jobs.register(job)
        t.join(timeout=10)
        assert not t.is_alive(), "blocking query never woke"
        assert result["index"] > meta.last_index
        api.jobs.deregister("blocker")

    def test_job_plan_over_http(self, dev_agent):
        agent, api = dev_agent
        job = parse_job(BATCH_JOB.replace("httpjob", "planjob"))
        job.init_fields()
        resp, _ = api.jobs.plan(job, diff=True)
        assert resp.Diff is not None and resp.Diff.Type == "Added"
        assert resp.JobModifyIndex == 0
        # Dry run must not register the job.
        with pytest.raises(APIError):
            api.jobs.info("planjob")
        updates = resp.Annotations.DesiredTGUpdates["g"]
        assert updates.Place == 1

    def test_error_codes(self, dev_agent):
        agent, api = dev_agent
        with pytest.raises(APIError) as exc:
            api.jobs.info("nonexistent-job")
        assert exc.value.code == 404
        with pytest.raises(APIError) as exc:
            api.request("GET", "/v1/bogus/path")
        assert exc.value.code == 404

    def test_system_gc(self, dev_agent):
        agent, api = dev_agent
        api.system.garbage_collect()  # must not error


class TestJobspec:
    def test_parse_duration(self):
        assert parse_duration("30s") == 30 * SECOND
        assert parse_duration("5m") == 5 * MINUTE
        assert parse_duration("1h30m") == 90 * MINUTE
        assert parse_duration("250ms") == 250 * 1_000_000
        with pytest.raises(ValueError):
            parse_duration("banana")

    def test_constraint_sugar(self):
        job = parse_job('''
job "x" {
  datacenters = ["dc1"]
  constraint { attribute = "${attr.nomad.version}" version = ">= 0.1" }
  constraint { attribute = "${attr.arch}" regexp = "x86.*" }
  constraint { distinct_hosts = true }
  group "g" { task "t" { driver = "raw_exec"
    config { command = "/bin/true" } } }
}''')
        ops = [c.Operand for c in job.Constraints]
        assert ops == ["version", "regexp", "distinct_hosts"]

    def test_multiple_groups_and_tasks(self):
        job = parse_job('''
job "multi" {
  datacenters = ["dc1"]
  group "a" {
    count = 2
    task "t1" { driver = "raw_exec" config { command = "/bin/true" } }
    task "t2" { driver = "raw_exec" config { command = "/bin/true" } }
  }
  group "b" { task "t3" { driver = "raw_exec" config { command = "/bin/true" } } }
}''')
        assert [g.Name for g in job.TaskGroups] == ["a", "b"]
        assert [t.Name for t in job.TaskGroups[0].Tasks] == ["t1", "t2"]
        assert job.TaskGroups[0].Count == 2


def test_debug_stacks(dev_agent):
    """Thread-stack dump endpoint (the pprof-analogue debug hook; enabled
    in dev mode, gated behind enable_debug otherwise)."""
    agent, api = dev_agent
    stacks, _ = api.get("/v1/agent/debug/stacks")
    assert any("MainThread" in k for k in stacks)
    assert all(isinstance(v, list) for v in stacks.values())


def test_agent_monitor_ring(dev_agent):
    """Recent-log endpoint with incremental polling."""
    import logging

    agent, api = dev_agent
    logging.getLogger("nomad.test").warning("monitor-marker-1")
    out, _ = api.get("/v1/agent/monitor")
    assert any("monitor-marker-1" in l for l in out["Lines"])
    seq = out["Seq"]
    logging.getLogger("nomad.test").warning("monitor-marker-2")
    out2, _ = api.get(f"/v1/agent/monitor?after={seq}")
    assert any("monitor-marker-2" in l for l in out2["Lines"])
    assert not any("monitor-marker-1" in l for l in out2["Lines"])


class TestGzip:
    def test_large_responses_gzip_when_accepted(self, dev_agent):
        """(reference: every handler gzip-wrapped, command/agent/http.go:
        70-80) — large list responses compress; clients that don't accept
        gzip get identity; the API client decodes transparently."""
        import gzip
        import json as _json
        import urllib.request

        agent, api = dev_agent
        base = f"http://127.0.0.1:{agent.http.port}"
        # Find an endpoint whose identity payload clears the 1KB gzip
        # floor (metrics accumulates counters; agent/self dumps config).
        fat = None
        for path in ("/v1/agent/metrics", "/v1/agent/self", "/v1/nodes"):
            with urllib.request.urlopen(base + path, timeout=10) as resp:
                if len(resp.read()) >= 1024:
                    fat = path
                    break
        assert fat is not None, "no endpoint over the gzip floor"
        req = urllib.request.Request(base + fat)
        req.add_header("Accept-Encoding", "gzip")
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert resp.headers.get("Content-Encoding") == "gzip"
            body = _json.loads(gzip.decompress(resp.read()))
        assert body

        # Identity for clients that don't ask for gzip.
        req2 = urllib.request.Request(base + "/v1/nodes")
        with urllib.request.urlopen(req2, timeout=10) as resp:
            assert resp.headers.get("Content-Encoding") is None
            _json.loads(resp.read())

        # The API client path round-trips (it sends Accept-Encoding: gzip).
        nodes, _ = api.request("GET", "/v1/nodes")
        assert isinstance(nodes, list)


class TestConfigKnobs:
    def test_server_scheduler_and_tls_blocks_parse(self, tmp_path):
        from nomad_tpu.agent.config import load_config_file

        p = tmp_path / "srv.hcl"
        p.write_text('''
server {
  enabled = true
  scheduler_window = 128
  pipelined_scheduling = true
  scheduler_mesh = "all"
}
''')
        cfg = load_config_file(str(p))
        assert cfg.scheduler_window == 128
        assert cfg.pipelined_scheduling is True
        assert cfg.scheduler_mesh == "all"


def test_debug_profile_returns_loadable_pstats(dev_agent, tmp_path):
    """CPU-profile capture endpoint (the pprof CPU analogue,
    reference http.go:133-139): the body is a pstats-compatible marshal
    blob loadable with pstats.Stats."""
    import pstats
    import urllib.request

    agent, api = dev_agent
    url = (f"http://127.0.0.1:{agent.http.port}"
           "/v1/agent/debug/profile?seconds=0.3")
    with urllib.request.urlopen(url) as resp:
        assert resp.headers["Content-Type"] == "application/octet-stream"
        blob = resp.read()
    path = tmp_path / "profile.pstats"
    path.write_bytes(blob)
    st = pstats.Stats(str(path))
    # The server's own threads were sampled: some known module shows up.
    files = {f for (f, _, _) in st.stats}
    assert any("nomad_tpu" in f or "threading" in f for f in files), files


def test_debug_sched_stats_exports_worker_schema(dev_agent):
    """/v1/agent/debug/sched-stats: the operator surface for the
    pipelined worker's stage timers/counters — every key of the declared
    stats schema must be present (no lazily-created keys that appear only
    after the stage first runs)."""
    from nomad_tpu.server.pipelined_worker import (
        STATS_COUNTERS,
        STATS_TIMERS_MS,
    )

    agent, api = dev_agent
    out = api.agent.sched_stats()
    workers = out["Workers"]
    assert workers, "leader must export its scheduling workers"
    pipelined = [w for w in workers if w["Type"] == "PipelinedWorker"]
    assert pipelined, [w["Type"] for w in workers]
    for w in pipelined:
        assert w["Window"] >= 1
        stats = w["Stats"]
        for key in STATS_COUNTERS + STATS_TIMERS_MS:
            assert key in stats, f"schema key {key} missing from endpoint"
    # Per-worker stats keyed by WORKER NAME (scaling regressions — one
    # worker starved, one convoying on the chain lease — are invisible
    # in the aggregate), names unique.
    assert all(w["Name"] for w in workers)
    assert len({w["Name"] for w in workers}) == len(workers)
    by_worker = out["ByWorker"]
    for w in pipelined:
        assert by_worker[w["Name"]] == w["Stats"]
    totals = out["Totals"]
    assert totals["windows"] == sum(
        w["Stats"]["windows"] for w in pipelined)
    # Columnar-store block: segment/live-row/promotion counts plus the
    # per-commit-path batch counters (service vs system), present even
    # when zero so operators can rely on the shape.
    store = out["Store"]
    for key in ("Segments", "LiveRows", "PromotedRows", "Batches"):
        assert key in store, f"Store key {key} missing from endpoint"
    assert isinstance(store["Batches"], dict)
    # Replica-digest block: chain position / verification watermark /
    # sync mode / flow counters (README "Replica determinism").
    digest = out["Digest"]
    for key in ("Interval", "LastIndex", "Chain", "Synced", "Folds",
                "Exchanged", "Diverged", "VerifiedIndex"):
        assert key in digest, f"Digest key {key} missing from endpoint"
    assert digest["Diverged"] == 0


def test_debug_profile_rejects_malformed_seconds(dev_agent):
    """Malformed ?seconds must be a client error (400), not an unhandled
    ValueError surfacing as a 500."""
    agent, api = dev_agent
    with pytest.raises(APIError) as ei:
        api.get("/v1/agent/debug/profile?seconds=banana")
    assert ei.value.code == 400
    assert "banana" in str(ei.value)


class TestFaultsEndpoint:
    """/v1/agent/debug/faults: the HTTP arming surface for the failpoint
    registry (debug-gated like stacks/profile)."""

    @pytest.fixture(autouse=True)
    def _heal(self):
        from nomad_tpu.resilience import failpoints

        failpoints.disarm_all()
        yield
        failpoints.disarm_all()

    def test_lists_known_sites_when_disarmed(self, dev_agent):
        agent, api = dev_agent
        sites = api.agent.faults()["Sites"]
        assert "raft.fsync" in sites and "rpc.pool.call" in sites
        assert len(sites) >= 10
        assert all(info["armed"] is None or info["fired"] >= 0
                   for info in sites.values())

    def test_arm_inspect_disarm_round_trip(self, dev_agent):
        agent, api = dev_agent
        out = api.agent.arm_faults("gossip.send=drop:p=0.5;raft.fsync=off")
        assert out["Touched"] == ["gossip.send", "raft.fsync"]
        armed = out["Sites"]["gossip.send"]["armed"]
        assert armed["mode"] == "drop" and armed["probability"] == 0.5
        assert api.agent.disarm_faults()["DisarmedAll"] is True
        assert api.agent.faults()["Sites"]["gossip.send"]["armed"] is None

    def test_malformed_spec_is_a_400(self, dev_agent):
        agent, api = dev_agent
        with pytest.raises(APIError) as ei:
            api.agent.arm_faults("gossip.send=explode")
        assert ei.value.code == 400

    def test_missing_spec_is_a_400(self, dev_agent):
        agent, api = dev_agent
        with pytest.raises(APIError) as ei:
            api.put("/v1/agent/debug/faults", {})
        assert ei.value.code == 400

    def test_non_string_spec_is_a_400(self, dev_agent):
        agent, api = dev_agent
        with pytest.raises(APIError) as ei:
            api.put("/v1/agent/debug/faults", {"Spec": 5})
        assert ei.value.code == 400
        assert "string" in str(ei.value)


class TestTracePagination:
    """/v1/agent/debug/trace list pagination: limit/after cursor over
    the newest-last summary list (the ring is bounded, so stale cursors
    restart from the oldest retained entry instead of erroring)."""

    @pytest.fixture(autouse=True)
    def _traced(self, dev_agent):
        agent, api = dev_agent
        api.agent.configure_trace(enabled=True, sample_ratio=1.0)
        api.agent.clear_traces()
        yield
        api.agent.configure_trace(enabled=False)
        api.agent.clear_traces()

    def _seed_traces(self, agent, api, n=5):
        from nomad_tpu import mock
        from nomad_tpu.structs import to_dict

        for _ in range(n):
            agent.rpc("Node.Register", {"Node": to_dict(mock.node())})
        wait_for(lambda: len(api.agent.traces().get("Traces", ())) >= n,
                 timeout=20, msg="seed traces never retained")

    def test_limit_after_walks_the_full_list(self, dev_agent):
        agent, api = dev_agent
        self._seed_traces(agent, api)
        full = [t["TraceID"] for t in api.agent.traces()["Traces"]]
        page = api.agent.traces(limit=2)
        assert [t["TraceID"] for t in page["Traces"]] == full[:2]
        assert page["NextAfter"] == full[1]
        # Summary schema holds on a paginated response.
        for t in page["Traces"]:
            assert set(t) >= {"TraceID", "Root", "Start", "DurationMs",
                              "Spans", "Complete", "Error"}
        # Cursor-walk the whole list: background traffic may APPEND new
        # traces while we walk, but the captured prefix must come back
        # exactly once, in order.
        seen, after = [], ""
        while True:
            p = api.agent.traces(limit=2, after=after)
            seen.extend(t["TraceID"] for t in p["Traces"])
            after = p.get("NextAfter", "")
            if not after:
                break
        assert seen[:len(full)] == full
        assert len(seen) == len(set(seen))
        # An un-truncated page carries no cursor.
        assert "NextAfter" not in api.agent.traces(limit=10_000)

    def test_stale_cursor_restarts_from_oldest(self, dev_agent):
        agent, api = dev_agent
        self._seed_traces(agent, api)
        full = [t["TraceID"] for t in api.agent.traces()["Traces"]]
        p = api.agent.traces(limit=2, after="f" * 32)
        assert [t["TraceID"] for t in p["Traces"]] == full[:2]

    def test_malformed_limit_is_a_400(self, dev_agent):
        agent, api = dev_agent
        for bad in ("nope", "0", "-3"):
            with pytest.raises(APIError) as ei:
                api.request("GET", "/v1/agent/debug/trace",
                            {"limit": bad})
            assert ei.value.code == 400


def test_register_surfaces_ignored_driver_config_warnings(dev_agent):
    """Accepted-but-unimplemented docker config keys must come back to
    the SUBMITTER as registration warnings, not vanish into a
    once-per-process client log line."""
    from nomad_tpu import mock

    agent, api = dev_agent
    job = mock.job()
    task = job.TaskGroups[0].Tasks[0]
    task.Driver = "docker"
    task.Config = {"image": "busybox", "privileged": True,
                   "dns_servers": ["8.8.8.8"]}
    try:
        eval_id, warnings, meta = api.jobs.register_with_warnings(job)
        assert any("privileged" in w for w in warnings), warnings
        assert any("dns_servers" in w for w in warnings), warnings
        # The plain register keeps its 2-tuple shape for callers that
        # don't care about warnings.
        eval_id2, meta2 = api.jobs.register(job)
        assert eval_id2
    finally:
        api.jobs.deregister(job.ID)
