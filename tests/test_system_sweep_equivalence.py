"""Fixed-seed exact-vs-vectorized system sweep equivalence.

The tensor-sweep path (scheduler/system_sweep.py) must produce the SAME
scheduling decision as the exact per-node path it replaced: same stops
with the same descriptions, same placements (node, instance name, task
group, resource values), same in-place updates, same FailedTGAllocs
metrics — across tainted nodes, partially-allocated fleets, destructive
and in-place updates, and infeasible nodes. Network-ask groups must
route onto the exact path on BOTH sides (port bitmaps are host state),
and duplicate node entries must not double-place (the diff's `emitted`
guard, structural in the tensor path).

Both paths run against the SAME store through a capture-only planner
(nothing commits), so the comparison is a pure function of the fixed
seed state.
"""

import logging
import random

import numpy as np
import pytest

from nomad_tpu import mock
from nomad_tpu.scheduler.system_sched import SystemScheduler
from nomad_tpu.scheduler.util import diff_system_allocs, tainted_nodes
from nomad_tpu.state.state_store import StateStore
from nomad_tpu.structs import Constraint, PlanResult, compute_node_class
from nomad_tpu.structs.structs import (
    EvalStatusPending,
    EvalTriggerJobRegister,
    EvalTriggerNodeUpdate,
)
from nomad_tpu.tensor import TensorIndex, alloc_vec

logger = logging.getLogger("test.sweep")


class CapturePlanner:
    """Planner that records plans and echoes full commits WITHOUT touching
    the store — both paths then schedule against identical state."""

    def __init__(self):
        self.plans = []
        self.evals = []

    def plan_queue_depth(self):
        return 0

    def submit_plan(self, plan):
        self.plans.append(plan)
        r = PlanResult()
        r.NodeUpdate = dict(plan.NodeUpdate)
        r.NodeAllocation = dict(plan.NodeAllocation)
        r.AllocIndex = 1
        return r, None

    def update_eval(self, ev):
        self.evals.append(ev)

    def create_eval(self, ev):
        self.evals.append(ev)

    def reblock_eval(self, ev):
        self.evals.append(ev)


def make_node(i, cpu=4000, dc="dc1"):
    n = mock.node()
    n.ID = f"node-{i:04d}"
    n.Name = f"node-{i:04d}"
    n.Datacenter = dc
    n.Resources.CPU = cpu
    compute_node_class(n)
    return n


def sys_job(job_id="sysjob", cpu=100, networks=False):
    job = mock.system_job()
    job.ID = job_id
    job.Name = job_id
    t = job.TaskGroups[0].Tasks[0]
    t.Resources.CPU = cpu
    t.Resources.MemoryMB = 32
    t.Resources.DiskMB = 150
    if not networks:
        t.Resources.Networks = []
    t.Services = []
    job.init_fields()
    return job


def make_eval(job, trigger=EvalTriggerJobRegister):
    ev = mock.eval()
    ev.JobID = job.ID
    ev.Type = job.Type
    ev.TriggeredBy = trigger
    ev.Status = EvalStatusPending
    return ev


def run_path(store, tindex, job, vectorized, trigger=EvalTriggerJobRegister):
    planner = CapturePlanner()
    sched = SystemScheduler(store, planner, tindex, logger,
                            rng=random.Random(7), vectorized=vectorized)
    sched.process(make_eval(job, trigger))
    return planner, sched


def summarize(planner):
    placed = sorted(
        (a.NodeID, a.Name, a.TaskGroup, a.DesiredStatus,
         tuple(alloc_vec(a).tolist()))
        for p in planner.plans for v in p.NodeAllocation.values()
        for a in v)
    stops = sorted(
        (a.ID, a.DesiredStatus, a.DesiredDescription)
        for p in planner.plans for v in p.NodeUpdate.values() for a in v)
    return placed, stops


def failed_metrics(planner):
    out = {}
    for ev in planner.evals:
        for name, m in (ev.FailedTGAllocs or {}).items():
            out[name] = (m.NodesEvaluated, m.NodesFiltered,
                         m.NodesExhausted, m.CoalescedFailures,
                         dict(m.DimensionExhausted))
    return out


def assert_equivalent(store, tindex, job, trigger=EvalTriggerJobRegister):
    pv, sv = run_path(store, tindex, job, True, trigger)
    pe, se = run_path(store, tindex, job, False, trigger)
    assert summarize(pv) == summarize(pe)
    assert failed_metrics(pv) == failed_metrics(pe)
    return pv, pe


class TestSweepEquivalence:
    def _store(self, n_nodes=24):
        store = StateStore()
        tindex = TensorIndex.attach(store)
        idx = 0
        for i in range(n_nodes):
            idx += 1
            store.upsert_node(idx, make_node(i))
        return store, tindex, idx

    def test_fresh_register_mixed_fleet(self):
        """Infeasible (too-small), drained, and down nodes in one fleet:
        placements land only on the healthy ones and the failed metrics
        (exhaustion dimensions, coalesced counts) match exactly."""
        store, tindex, idx = self._store(12)
        tiny = make_node(100, cpu=60)       # exhausts on cpu
        idx += 1
        store.upsert_node(idx, tiny)
        drained = make_node(101)
        drained.Drain = True
        idx += 1
        store.upsert_node(idx, drained)
        job = sys_job(cpu=100)
        idx += 1
        store.upsert_job(idx, job)

        pv, pe = assert_equivalent(store, tindex, job)
        placed, _ = summarize(pv)
        assert len(placed) == 12  # the tiny node exhausts, drained skipped
        nodes_placed = {p[0] for p in placed}
        assert drained.ID not in nodes_placed
        assert tiny.ID not in nodes_placed
        assert failed_metrics(pv)  # the exhaustion was recorded

    def test_partially_allocated_fleet(self):
        """Half the fleet already carries the job (a prior sweep), then
        new nodes join: only the missing nodes get placements and the
        existing allocs are untouched on both paths."""
        store, tindex, idx = self._store(8)
        job = sys_job()
        idx += 1
        store.upsert_job(idx, job)
        planner = CapturePlanner()
        sched = SystemScheduler(store, planner, tindex, logger,
                                rng=random.Random(7))
        sched.process(make_eval(job))
        allocs = [a for p in planner.plans
                  for v in p.NodeAllocation.values() for a in v]
        # Commit HALF the sweep: a partially-allocated fleet.
        half = [a for a in allocs if int(a.NodeID.split("-")[1]) % 2 == 0]
        for a in half:
            a.Job = job
        idx += 1
        store.upsert_allocs(idx, half)

        pv, pe = assert_equivalent(store, tindex, job,
                                   EvalTriggerNodeUpdate)
        placed, stops = summarize(pv)
        assert stops == []
        assert len(placed) == 8 - len(half)
        assert all(int(p[0].split("-")[1]) % 2 == 1 for p in placed)

    def test_tainted_nodes_stop_with_desc(self):
        """Drained nodes with live allocs: stops carry the tainted
        description; no replacement lands on the drained node."""
        store, tindex, idx = self._store(6)
        job = sys_job()
        idx += 1
        store.upsert_job(idx, job)
        planner = CapturePlanner()
        sched = SystemScheduler(store, planner, tindex, logger,
                                rng=random.Random(7))
        sched.process(make_eval(job))
        allocs = [a for p in planner.plans
                  for v in p.NodeAllocation.values() for a in v]
        for a in allocs:
            a.Job = job
        idx += 1
        store.upsert_allocs(idx, allocs)
        idx += 1
        store.update_node_drain(idx, "node-0002", True)

        pv, pe = assert_equivalent(store, tindex, job,
                                   EvalTriggerNodeUpdate)
        placed, stops = summarize(pv)
        assert placed == []
        assert len(stops) == 1
        assert "tainted" in stops[0][2]

    def test_destructive_update_replaces_everywhere(self):
        """A changed task config stops + replaces on every node; the
        replacement rides the same plan and both paths agree."""
        store, tindex, idx = self._store(5)
        job = sys_job()
        idx += 1
        store.upsert_job(idx, job)
        planner = CapturePlanner()
        sched = SystemScheduler(store, planner, tindex, logger,
                                rng=random.Random(7))
        sched.process(make_eval(job))
        allocs = [a for p in planner.plans
                  for v in p.NodeAllocation.values() for a in v]
        for a in allocs:
            a.Job = job
        idx += 1
        store.upsert_allocs(idx, allocs)

        update = job.copy()
        update.TaskGroups[0].Tasks[0].Config = {"command": "/bin/other"}
        update.init_fields()
        idx += 1
        store.upsert_job(idx, update)
        update = store.job_by_id(job.ID)

        pv, pe = assert_equivalent(store, tindex, update)
        placed, stops = summarize(pv)
        assert len(placed) == 5
        assert len(stops) == 5
        assert all("updated" in s[2] for s in stops)

    def test_inplace_update_keeps_allocs(self):
        """A non-destructive change (added constraint) updates in place:
        no stops, the same alloc IDs are re-planned on both paths."""
        store, tindex, idx = self._store(4)
        job = sys_job()
        idx += 1
        store.upsert_job(idx, job)
        planner = CapturePlanner()
        sched = SystemScheduler(store, planner, tindex, logger,
                                rng=random.Random(7))
        sched.process(make_eval(job))
        allocs = [a for p in planner.plans
                  for v in p.NodeAllocation.values() for a in v]
        for a in allocs:
            a.Job = job
        idx += 1
        store.upsert_allocs(idx, allocs)

        update = job.copy()
        update.Constraints = list(update.Constraints) + [Constraint(
            LTarget="${attr.kernel.name}", RTarget="linux", Operand="=")]
        update.init_fields()
        idx += 1
        store.upsert_job(idx, update)
        update = store.job_by_id(job.ID)

        pv, pe = assert_equivalent(store, tindex, update)
        placed, stops = summarize(pv)
        assert stops == []
        inplace_ids = sorted(
            a.ID for p in pv.plans
            for v in p.NodeAllocation.values() for a in v)
        assert inplace_ids == sorted(a.ID for a in allocs)

    def test_inplace_update_with_new_node_joining(self):
        """The eval that both updates in place (existing nodes) and
        places fresh (a node that joined since): the sweep agrees with
        the oracle, and the SweepBatch excludes the in-place nodes —
        their remove-then-add accounting belongs to the exact verify."""
        store, tindex, idx = self._store(3)
        job = sys_job()
        idx += 1
        store.upsert_job(idx, job)
        planner = CapturePlanner()
        sched = SystemScheduler(store, planner, tindex, logger,
                                rng=random.Random(7))
        sched.process(make_eval(job))
        allocs = [a for p in planner.plans
                  for v in p.NodeAllocation.values() for a in v]
        for a in allocs:
            a.Job = job
        idx += 1
        store.upsert_allocs(idx, allocs)

        update = job.copy()
        update.Constraints = list(update.Constraints) + [Constraint(
            LTarget="${attr.kernel.name}", RTarget="linux", Operand="=")]
        update.init_fields()
        idx += 1
        store.upsert_job(idx, update)
        update = store.job_by_id(job.ID)
        newcomer = make_node(50)
        idx += 1
        store.upsert_node(idx, newcomer)

        pv, pe = assert_equivalent(store, tindex, update,
                                   EvalTriggerNodeUpdate)
        placed, stops = summarize(pv)
        assert stops == []
        assert len(placed) == 4  # 3 in-place re-plans + 1 fresh
        fresh = [p for p in placed if p[0] == newcomer.ID]
        assert len(fresh) == 1
        sweep = getattr(pv.plans[0], "_sweep", None)
        assert sweep is not None
        # Only the newcomer's row is bulk-verifiable.
        assert sweep.node_ids == [newcomer.ID]

    def test_multi_instance_group_places_count_per_node(self):
        """A system TG with Count=2 places BOTH instances on every node;
        the descriptor folds them into one per-row demand."""
        store, tindex, idx = self._store(4)
        job = sys_job()
        job.TaskGroups[0].Count = 2
        job.init_fields()
        idx += 1
        store.upsert_job(idx, job)
        pv, pe = assert_equivalent(store, tindex, job)
        placed, _ = summarize(pv)
        assert len(placed) == 8
        names = {p[1] for p in placed}
        assert len(names) == 2  # tg[0] and tg[1]
        sweep = getattr(pv.plans[0], "_sweep", None)
        assert sweep is not None
        assert len(sweep.node_ids) == 4
        a = next(iter(pv.plans[0].NodeAllocation.values()))[0]
        assert np.allclose(sweep.delta[0], 2 * alloc_vec(a))

    def test_network_ask_group_forces_exact_path(self):
        """A group asking for ports is NOT sweep-applicable: both runs
        take the exact per-node path and still agree (ports are assigned
        host-side on each)."""
        from nomad_tpu.scheduler import system_sweep

        store, tindex, idx = self._store(4)
        job = sys_job(networks=True)
        assert not system_sweep.sweep_applicable(job, tindex)
        idx += 1
        store.upsert_job(idx, job)
        pv, pe = assert_equivalent(store, tindex, job)
        placed, _ = summarize(pv)
        assert len(placed) == 4
        allocs = [a for p in pv.plans
                  for v in p.NodeAllocation.values() for a in v]
        assert all(
            r.Networks for a in allocs for r in a.TaskResources.values())

    def test_duplicate_node_entries_place_once(self):
        """The exact diff's `emitted` guard dedupes a duplicated node
        list; the tensor path is structurally deduped (one row per node).
        Both produce one placement per distinct node."""
        store, tindex, idx = self._store(3)
        job = sys_job()
        idx += 1
        store.upsert_job(idx, job)
        nodes = list(store.nodes())
        dup = nodes + nodes  # duplicated entries
        diff = diff_system_allocs(job, dup, {}, [])
        per_node = {}
        for tup in diff.place:
            per_node.setdefault(tup.Alloc.NodeID, []).append(tup.Name)
        assert all(len(v) == 1 for v in per_node.values())

        pv, _ = run_path(store, tindex, job, True)
        placed, _ = summarize(pv)
        assert len(placed) == 3
        assert len({p[0] for p in placed}) == 3

    def test_deregister_stops_all_on_both_paths(self):
        """Job gone: both paths stop every alloc (the sweep declines —
        job None — and the exact stop-all walk serves both)."""
        store, tindex, idx = self._store(3)
        job = sys_job()
        idx += 1
        store.upsert_job(idx, job)
        planner = CapturePlanner()
        sched = SystemScheduler(store, planner, tindex, logger,
                                rng=random.Random(7))
        sched.process(make_eval(job))
        allocs = [a for p in planner.plans
                  for v in p.NodeAllocation.values() for a in v]
        for a in allocs:
            a.Job = job
        idx += 1
        store.upsert_allocs(idx, allocs)
        store.delete_job(idx + 1, job.ID)

        pv, pe = assert_equivalent(store, tindex, job)
        placed, stops = summarize(pv)
        assert placed == []
        assert len(stops) == 3

    def test_sweep_batch_descriptor_shape(self):
        """The emitted plan carries a SweepBatch covering every placed
        node with the per-row demand the applier fit-checks against."""
        store, tindex, idx = self._store(6)
        job = sys_job()
        idx += 1
        store.upsert_job(idx, job)
        pv, _ = run_path(store, tindex, job, True)
        plan = pv.plans[0]
        sweep = getattr(plan, "_sweep", None)
        assert sweep is not None
        assert len(sweep.node_ids) == len(plan.NodeAllocation) == 6
        assert sweep.rows.shape == (6,)
        assert sweep.delta.shape == (6, 5)
        a = next(iter(plan.NodeAllocation.values()))[0]
        assert np.allclose(sweep.delta[0], alloc_vec(a))
        assert sweep.n_rows == tindex.nt.n_rows
        assert sweep.epoch == tindex.nt.row_epoch
