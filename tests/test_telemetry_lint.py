"""Static telemetry/tracing lint: catches silent key drift between code
and the declared registries/docs.

* Every ``failpoints.fire("...")`` literal in the source tree must be
  declared in ``failpoints.KNOWN_SITES`` (a renamed seam that keeps its
  old registry entry would list as armable but never fire) — and every
  declared site must still be referenced in source (a deleted seam must
  lose its registry entry).
* Every literal metrics key must follow the documented ``nomad.*``
  naming scheme (tuple of lowercase dotted segments).
* Every literal trace span name must follow the ``subsystem.operation``
  scheme the README's tracing section documents.
"""

import ast
import os
import re

import nomad_tpu
from nomad_tpu.resilience import failpoints

PKG_ROOT = os.path.dirname(os.path.abspath(nomad_tpu.__file__))

_METRIC_FNS = {"set_gauge", "incr_counter", "add_sample", "measure",
               "measure_since"}
_TRACE_SPAN_FNS = {"span", "root_span", "resume", "start_from"}
_SEGMENT_RE = re.compile(r"^[a-z0-9_]+$")
_SPAN_NAME_RE = re.compile(
    r"^[a-z][a-z0-9_]*(\.[A-Za-z][A-Za-z0-9_]*)+$")


def _py_files():
    for dirpath, dirnames, filenames in os.walk(PKG_ROOT):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def _parsed():
    for path in _py_files():
        with open(path, encoding="utf-8") as f:
            yield path, ast.parse(f.read(), filename=path)


def _call_name(node: ast.Call) -> str:
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _receiver(node: ast.Call) -> str:
    func = node.func
    if isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name):
        return func.value.id
    return ""


def test_every_fired_site_is_declared_and_vice_versa():
    fired = set()
    for path, tree in _parsed():
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or _call_name(node) != "fire":
                continue
            if _receiver(node) not in ("failpoints", ""):
                continue
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                fired.add(node.args[0].value)
    undeclared = fired - set(failpoints.KNOWN_SITES)
    assert not undeclared, \
        f"failpoint sites fired in source but missing from " \
        f"KNOWN_SITES: {sorted(undeclared)}"
    unreferenced = set(failpoints.KNOWN_SITES) - fired
    assert not unreferenced, \
        f"KNOWN_SITES entries no source location fires (renamed seam?): " \
        f"{sorted(unreferenced)}"


def test_metric_key_literals_follow_nomad_scheme():
    bad = []
    for path, tree in _parsed():
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            if _call_name(node) not in _METRIC_FNS:
                continue
            if _receiver(node) not in ("metrics", "telemetry", "registry",
                                       "reg", ""):
                continue
            if not node.args or not isinstance(node.args[0], ast.Tuple):
                continue
            elts = node.args[0].elts
            consts = [e.value for e in elts
                      if isinstance(e, ast.Constant)
                      and isinstance(e.value, str)]
            if not consts:
                continue
            rel = os.path.relpath(path, PKG_ROOT)
            if isinstance(elts[0], ast.Constant) and consts[0] != "nomad":
                bad.append((rel, node.lineno, tuple(consts),
                            "first segment must be 'nomad'"))
                continue
            # Dynamic trailing segments (ev.Type, RPC method names) are
            # exempt; every CONSTANT segment must match the scheme.
            for seg in consts:
                if seg != "nomad" and not all(
                        _SEGMENT_RE.match(p) for p in seg.split(".")):
                    bad.append((rel, node.lineno, tuple(consts),
                                f"segment {seg!r} breaks [a-z0-9_]"))
                    break
    assert not bad, f"metric key literals off the nomad.* scheme: {bad}"


def test_trace_span_name_literals_follow_scheme():
    bad = []
    for path, tree in _parsed():
        if os.path.relpath(path, PKG_ROOT) == os.path.join("telemetry",
                                                           "trace.py"):
            continue  # the implementation's docstrings/internals
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name_arg = None
            fn = _call_name(node)
            recv = _receiver(node)
            if recv not in ("trace", "_trace"):
                continue
            if fn in _TRACE_SPAN_FNS:
                # span(name)/root_span(name) take name first;
                # resume/start_from take (carrier, name).
                idx = 0 if fn in ("span", "root_span") else 1
                if len(node.args) > idx:
                    name_arg = node.args[idx]
            elif fn == "record_span" and len(node.args) > 1:
                name_arg = node.args[1]
            if name_arg is None or not isinstance(name_arg, ast.Constant) \
                    or not isinstance(name_arg.value, str):
                continue  # dynamic names ("rpc." + method) are exempt
            if not _SPAN_NAME_RE.match(name_arg.value):
                bad.append((os.path.relpath(path, PKG_ROOT), node.lineno,
                            name_arg.value))
    assert not bad, f"trace span literals off the a.b scheme: {bad}"
