"""TimeTable parity grid (reference: nomad/timetable_test.go — nearest
index/time lookups, granularity coalescing, serialize round-trip, and
the retention-limit overflow)."""

from nomad_tpu.server.timetable import TimeTable


class TestTimeTable:
    def test_nearest_lookups(self):
        """(reference: TestTimeTable)"""
        tt = TimeTable(granularity=1.0, limit=60.0 * 60 * 24)
        start = 1_700_000_000.0

        assert tt.nearest_index(start) == 0
        assert tt.nearest_time(1000) == 0.0

        plus_one = start + 60
        plus_two = start + 120
        plus_five = start + 300
        plus_thirty = start + 1800
        plus_hour = start + 3600
        witnesses = [(2, start), (10, plus_one), (20, plus_two),
                     (30, plus_five), (40, plus_thirty), (50, plus_hour)]
        for index, when in witnesses:
            # Double-witness like the reference: granularity coalesces
            # the repeat, so the table holds one entry per slot.
            tt.witness(index, when)
            tt.witness(index, when)
        assert len(tt.serialize()) == len(witnesses)

        cases = [
            # (when -> expected index, index -> expected when)
            (start, 2, 2, start),                       # exact matches
            (plus_one, 10, 10, plus_one),
            (plus_hour, 50, 50, plus_hour),
            (plus_hour + 1800, 50, 51, plus_hour),      # beyond newest
            (0.0, 0, 1, 0.0),                           # before oldest
            (start + 180, 20, 25, plus_two),            # mid range
        ]
        for when, want_index, index, want_when in cases:
            assert tt.nearest_index(when) == want_index, when
            assert tt.nearest_time(index) == want_when, index

    def test_serialize_round_trip(self):
        """(reference: TestTimeTable_SerializeDeserialize)"""
        import msgpack

        tt = TimeTable(granularity=1.0, limit=3600.0)
        start = 1_700_000_000.0
        for index, when in ((2, start), (10, start + 60),
                            (20, start + 120), (30, start + 300)):
            tt.witness(index, when)
        blob = msgpack.packb(tt.serialize())
        tt2 = TimeTable(granularity=1.0, limit=3600.0)
        tt2.deserialize(msgpack.unpackb(blob))
        assert tt2.serialize() == tt.serialize()

    def test_overflow_prunes_beyond_limit(self):
        """(reference: TestTimeTable_Overflow): entries older than the
        retention limit fall off, and lookups below the pruned range
        return the zero values."""
        tt = TimeTable(granularity=1.0, limit=3.0)
        start = 1_700_000_000.0
        tt.witness(10, start)
        tt.witness(20, start + 1)
        tt.witness(30, start + 2)
        tt.witness(40, start + 3)
        assert len(tt.serialize()) == 3
        assert tt.nearest_index(start) == 0
        assert tt.nearest_time(15) == 0.0

    def test_granularity_coalesces(self):
        """Witnesses within one granularity slot keep the FIRST entry
        (reference: timetable.go Witness's limit check)."""
        tt = TimeTable(granularity=10.0, limit=3600.0)
        start = 1_700_000_000.0
        tt.witness(5, start)
        tt.witness(6, start + 1)   # same slot: dropped
        tt.witness(7, start + 11)  # next slot: kept
        table = tt.serialize()
        assert [i for i, _ in table] == [7, 5]
