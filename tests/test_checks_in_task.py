"""Script health checks run INSIDE the task's execution context.

A check that passes on the host while the service is broken in its
chroot/container (or vice versa) is exactly the false signal health checks
exist to prevent (reference: client/driver/executor/checks.go:31-65 runs
script checks through the executor / docker exec). These tests build a real
chroot, start a real exec-driver task in it, and prove the IN-TASK result
wins over what host execution would have said.
"""

import os
import platform
import subprocess
import tempfile
import time

import pytest

from nomad_tpu.client.allocdir import AllocDir
from nomad_tpu.client.driver.base import (
    ExecContext,
    build_executor_spec,
    launch_executor,
)
from nomad_tpu.client.env import TaskEnv
from nomad_tpu.services.checks import run_check
from nomad_tpu.structs import ServiceCheck, Task
from nomad_tpu.structs.structs import (
    CheckStatusCritical,
    CheckStatusPassing,
    ServiceCheckScript,
)

SEC = 1_000_000_000  # ns


def _can_chroot() -> bool:
    if platform.system() != "Linux" or os.geteuid() != 0:
        return False
    probe = tempfile.mkdtemp(prefix="mountprobe-")
    target = os.path.join(probe, "bin")
    os.makedirs(target)
    try:
        ok = subprocess.run(["mount", "--bind", "/bin", target],
                            capture_output=True).returncode == 0
        if ok:
            subprocess.run(["umount", target], capture_output=True)
        return ok
    finally:
        subprocess.run(["umount", "-l", target], capture_output=True)
        os.rmdir(target)
        os.rmdir(probe)


pytestmark = pytest.mark.skipif(
    not _can_chroot(), reason="needs root + bind mounts (linux)")


def script_check(command, args):
    return ServiceCheck(Name="sc", Type=ServiceCheckScript,
                        Command=command, Args=list(args),
                        Interval=1 * SEC, Timeout=5 * SEC)


class TestChrootBuild:
    def test_build_and_destroy_preserves_host(self, tmp_path):
        ad = AllocDir(str(tmp_path / "alloc1"))
        ad.build(["t"])
        root = ad.build_chroot("t")
        try:
            # A shell resolves inside the chroot.
            assert os.path.exists(os.path.join(root, "bin"))
            r = subprocess.run(
                ["chroot", root, "/bin/sh", "-c", "echo from-chroot"],
                capture_output=True, text=True)
            assert r.returncode == 0 and "from-chroot" in r.stdout
            # Read-only: writing into the bind-mounted /bin fails.
            r = subprocess.run(
                ["chroot", root, "/bin/sh", "-c",
                 "touch /bin/___nomad_probe 2>/dev/null"],
                capture_output=True)
            assert r.returncode != 0
        finally:
            ad.destroy()
        # Host /bin intact, mounts gone, alloc dir removed.
        assert os.path.exists("/bin/sh")
        assert not os.path.exists(str(tmp_path / "alloc1"))


class TestInTaskScriptChecks:
    def _start_task(self, tmp_path):
        ad = AllocDir(str(tmp_path / "alloc2"))
        ad.build(["web"])
        task = Task(Name="web", Driver="exec",
                    Config={"command": "/bin/sleep", "args": ["60"]})
        env = TaskEnv()
        ctx = ExecContext(alloc_dir=ad, alloc_id="a1", task_env=env)
        spec = build_executor_spec(ctx, task, "/bin/sleep", ["60"])
        spec["chroot"] = ad.build_chroot("web")
        handle = launch_executor(ad.task_dirs["web"], "web", spec)
        return ad, handle

    def test_in_task_result_wins_over_host(self, tmp_path):
        """The marker exists only at the chroot's root: host execution says
        critical, in-task execution says passing — the in-task result must
        be the one recorded."""
        ad, handle = self._start_task(tmp_path)
        try:
            marker = os.path.join(ad.task_dirs["web"], "in_task_marker")
            open(marker, "w").write("x")
            check = script_check("/bin/sh",
                                 ["-c", "test -f /in_task_marker || exit 2"])

            # Host-side execution (no exec_fn): the path doesn't exist.
            status_host, _ = run_check(check, "127.0.0.1", 0, cwd="/")
            assert status_host == CheckStatusCritical

            # In-task execution through the handle: sees the chroot root.
            status, _ = run_check(check, "127.0.0.1", 0, cwd="/",
                                  exec_fn=handle.exec_in_task)
            assert status == CheckStatusPassing
        finally:
            handle.kill(kill_timeout=1.0)
            ad.destroy()

    def test_host_pass_task_fail_detected(self, tmp_path):
        """Inverse direction: a file that exists on the host but not in the
        chroot — the host would report healthy, the in-task check reports
        the truth (critical)."""
        ad, handle = self._start_task(tmp_path)
        host_marker = str(tmp_path / "host_only_marker")
        open(host_marker, "w").write("x")
        try:
            check = script_check("/bin/sh",
                                 ["-c", f"test -f {host_marker} || exit 2"])
            status_host, _ = run_check(check, "127.0.0.1", 0)
            assert status_host == CheckStatusPassing
            status, _ = run_check(check, "127.0.0.1", 0,
                                  exec_fn=handle.exec_in_task)
            assert status == CheckStatusCritical
        finally:
            handle.kill(kill_timeout=1.0)
            ad.destroy()

    def test_task_env_reaches_in_task_check(self, tmp_path):
        """The executor spec's env is the check's env (reference: checks run
        with the task environment)."""
        ad = AllocDir(str(tmp_path / "alloc3"))
        ad.build(["web"])
        task = Task(Name="web", Driver="raw_exec",
                    Config={"command": "/bin/sleep", "args": ["60"]})
        env = TaskEnv()
        env.env["MY_MARKER"] = "hello42"
        ctx = ExecContext(alloc_dir=ad, alloc_id="a2", task_env=env)
        spec = build_executor_spec(ctx, task, "/bin/sleep", ["60"])
        handle = launch_executor(ad.task_dirs["web"], "web", spec)
        try:
            check = script_check(
                "/bin/sh", ["-c", 'test "$MY_MARKER" = hello42'])
            status, _ = run_check(check, "127.0.0.1", 0,
                                  exec_fn=handle.exec_in_task)
            assert status == CheckStatusPassing
        finally:
            handle.kill(kill_timeout=1.0)
            ad.destroy()


class TestChrootRestart:
    def test_rebuild_is_idempotent_and_destroy_clean(self, tmp_path):
        """A restarting exec task calls build_chroot again: the existing
        chroot is reused (no stacked mounts) and destroy still removes the
        alloc dir cleanly."""
        ad = AllocDir(str(tmp_path / "alloc4"))
        ad.build(["t"])
        ad.build_chroot("t")
        n_mounts = len(ad._mounts)
        root2 = ad.build_chroot("t")  # restart path
        assert len(ad._mounts) == n_mounts, "mounts stacked on rebuild"
        assert root2 == ad.task_dirs["t"]
        ad.destroy()
        assert not os.path.exists(str(tmp_path / "alloc4"))
        assert os.path.exists("/bin/sh")
