"""Test config: force JAX onto a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding tests run against
8 virtual CPU devices (the driver separately dry-runs the multi-chip path via
__graft_entry__.dryrun_multichip). The axon TPU plugin overrides
JAX_PLATFORMS from sitecustomize, so the config must be forced
programmatically before any backend initializes.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# Build the native executor once if the toolchain is present; tests fall
# back to the Python supervisor when it isn't (same file contract).
def _ensure_native_executor():
    import shutil
    import subprocess

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    binary = os.path.join(root, "native", "bin", "nomad-executor")
    stamp = os.path.join(root, "native", "bin", ".build_failed")
    source = os.path.join(root, "native", "executor.cc")
    if os.path.exists(binary) or shutil.which("g++") is None:
        return
    # Don't re-pay a failed build on every pytest start: skip while the
    # failure stamp is newer than the source.
    try:
        if os.path.getmtime(stamp) >= os.path.getmtime(source):
            return
    except OSError:
        pass
    try:
        out = subprocess.run(["make", "-C", os.path.join(root, "native")],
                             capture_output=True, text=True, timeout=120)
        if out.returncode != 0:
            os.makedirs(os.path.dirname(stamp), exist_ok=True)
            with open(stamp, "w") as f:
                f.write(out.stderr[-4000:])
            print("WARNING: native executor build failed; driver tests use "
                  f"the Python supervisor (see {stamp})")
    except Exception:
        pass


_ensure_native_executor()
