"""Test config: force JAX onto a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding tests run against
8 virtual CPU devices (the driver separately dry-runs the multi-chip path via
__graft_entry__.dryrun_multichip). The axon TPU plugin overrides
JAX_PLATFORMS from sitecustomize, so the config must be forced
programmatically before any backend initializes.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Runtime lock diagnostics (opt-in): NOMAD_TPU_DEBUG_LOCKS=1 swaps
# threading.Lock/RLock for order-tracking wrappers BEFORE any test
# constructs a broker/raft/gossip object, so the chaos/cluster suites run
# under the lock-order detector. Default-off: zero overhead when unset.
from nomad_tpu.analysis import debug_locks as _debug_locks  # noqa: E402

_debug_locks.install_from_env()


# Build the native executor once if the toolchain is present; tests fall
# back to the Python supervisor when it isn't (same file contract).
def _ensure_native_executor():
    import shutil
    import subprocess

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    binary = os.path.join(root, "native", "bin", "nomad-executor")
    liblog = os.path.join(root, "native", "bin", "liblogstore.so")
    stamp = os.path.join(root, "native", "bin", ".build_failed")
    sources = [os.path.join(root, "native", f)
               for f in ("executor.cc", "logstore.cc", "Makefile")]
    if (os.path.exists(binary) and os.path.exists(liblog)) \
            or shutil.which("g++") is None:
        return
    # Don't re-pay a failed build on every pytest start: skip while the
    # failure stamp is newer than the source.
    try:
        if os.path.getmtime(stamp) >= max(os.path.getmtime(s)
                                          for s in sources):
            return
    except OSError:
        pass
    try:
        out = subprocess.run(["make", "-C", os.path.join(root, "native")],
                             capture_output=True, text=True, timeout=120)
        if out.returncode != 0:
            os.makedirs(os.path.dirname(stamp), exist_ok=True)
            with open(stamp, "w") as f:
                f.write(out.stderr[-4000:])
            print("WARNING: native executor build failed; driver tests use "
                  f"the Python supervisor (see {stamp})")
    except Exception:
        pass


_ensure_native_executor()


# One retry for timing-sensitive tests that OPT IN via
# @pytest.mark.timing_retry (or a module-level `pytestmark`): they assert
# distributed properties (elections, gossip convergence, task execution)
# under real threads and real sockets, and a loaded CI machine can stretch
# past any fixed margin. A genuine regression fails both attempts; a
# scheduler hiccup doesn't fail `pytest -x`. Reruns are reported loudly.
# Marker-based (not per-file) so that new deterministic logic in a file
# that merely CONTAINS some timing tests isn't laundered through a rerun.
# Deliberately UNMARKED: test_server.py, test_services.py,
# test_pipelined_worker.py — the subsystems under heaviest active change;
# a new ~50% race there must fail CI, not pass on the second try. Mark
# individual tests in those files if a specific assertion proves flaky.


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "timing_retry: retry this timing-sensitive test once on failure")
    config.addinivalue_line(
        "markers",
        "slow: multi-second storm/soak runs excluded from the tier-1 "
        "sweep (`-m 'not slow'`); run with `-m slow` or NOMAD_TPU_SOAK=1")


def pytest_runtest_protocol(item, nextitem):
    if item.get_closest_marker("timing_retry") is None:
        return None
    from _pytest.runner import runtestprotocol

    item.ihook.pytest_runtest_logstart(nodeid=item.nodeid,
                                       location=item.location)
    reports = runtestprotocol(item, nextitem=nextitem, log=False)
    # Retry only setup/call failures; a teardown ERROR (leaked resource)
    # must surface, not be laundered through a clean second run — attempt
    # 1's teardown failures are re-logged alongside attempt 2.
    if any(r.failed for r in reports if r.when in ("setup", "call")):
        print(f"\nRETRYING (timing-sensitive): {item.nodeid}")
        teardown_errors = [r for r in reports
                           if r.when == "teardown" and r.failed]
        if hasattr(item, "_initrequest"):
            # Reset funcargs so fixtures REBUILD: without this the rerun
            # reuses attempt 1's torn-down fixture values (pytest's
            # _fillfixtures skips argnames already present) — the same
            # reset pytest-rerunfailures performs per rerun.
            item._initrequest()
        reports = teardown_errors + runtestprotocol(item, nextitem=nextitem,
                                                    log=False)
    for report in reports:
        item.ihook.pytest_runtest_logreport(report=report)
    item.ihook.pytest_runtest_logfinish(nodeid=item.nodeid,
                                        location=item.location)
    return True
