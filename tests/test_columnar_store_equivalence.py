"""Fixed-seed columnar-vs-object state-store commit equivalence.

The columnar commit path (plan applier -> ApplySweepBatch raft entry ->
SweepSegment scatter-apply -> lazy materialization) must be
indistinguishable from the per-object path it optimizes: identical
allocs_by_node/-job/-eval results, identical alloc_by_id values,
identical client pull maps, identical snapshot->restore state — and any
MUTATION (client status update, stop/preemption eviction, GC) must
promote the row onto the exact object path with the same end state the
object commit would have produced.

One fixed-seed system sweep is generated ONCE (capture-only planner),
then the same verified result is committed twice — once as the columnar
raft entry (through a real msgpack round-trip, the wire shape), once as
the reference AllocUpdate object entry — into two fresh FSMs, and every
read surface is compared as plain data.

TestServiceColumnarEquivalence holds the SERVICE window path (the
pipelined fast path's all-placed build, kind="service") to the same
gate, including the mixed-window exclusions: failed placements, network
asks, and vanished nodes must keep the exact per-object path.
"""

import logging
import random
import types

import msgpack
import pytest

from nomad_tpu import mock
from nomad_tpu.resilience import failpoints
from nomad_tpu.scheduler.system_sched import SystemScheduler
from nomad_tpu.server.fsm import FSM, MessageType
from nomad_tpu.server.plan_apply import _encode_result
from nomad_tpu.state.state_store import StateStore
from nomad_tpu.structs import PlanResult, compute_node_class, to_dict
from nomad_tpu.structs.structs import (
    AllocClientStatusRunning,
    AllocDesiredStatusEvict,
    EvalStatusPending,
    EvalTriggerJobRegister,
)
from nomad_tpu.tensor import TensorIndex

logger = logging.getLogger("test.columnar")

APPLY_INDEX = 100


class CapturePlanner:
    def __init__(self):
        self.plans = []
        self.evals = []

    def plan_queue_depth(self):
        return 0

    def submit_plan(self, plan):
        self.plans.append(plan)
        r = PlanResult()
        r.NodeUpdate = dict(plan.NodeUpdate)
        r.NodeAllocation = dict(plan.NodeAllocation)
        r.AllocIndex = 1
        return r, None

    def update_eval(self, ev):
        self.evals.append(ev)

    def create_eval(self, ev):
        self.evals.append(ev)

    def reblock_eval(self, ev):
        self.evals.append(ev)


def make_node(i):
    n = mock.node()
    n.ID = f"node-{i:04d}"
    n.Name = n.ID
    compute_node_class(n)
    return n


def sys_job(count=2):
    job = mock.system_job()
    t = job.TaskGroups[0].Tasks[0]
    t.Resources.CPU = 50
    t.Resources.MemoryMB = 32
    t.Resources.DiskMB = 150
    t.Resources.Networks = []
    t.Services = []
    job.TaskGroups[0].Count = count
    job.init_fields()
    return job


def sweep_plan(n_nodes=8, count=2):
    """One fixed-seed system sweep plan (with its columnar descriptor)
    against a capture-only planner — nothing committed."""
    store = StateStore()
    tindex = TensorIndex.attach(store)
    idx = 0
    for i in range(n_nodes):
        idx += 1
        store.upsert_node(idx, make_node(i))
    job = sys_job(count)
    idx += 1
    store.upsert_job(idx, job)
    ev = mock.eval()
    ev.JobID = job.ID
    ev.Type = job.Type
    ev.TriggeredBy = EvalTriggerJobRegister
    ev.Status = EvalStatusPending
    planner = CapturePlanner()
    sched = SystemScheduler(store, planner, tindex, logger,
                            rng=random.Random(7))
    sched.process(ev)
    [plan] = planner.plans
    assert getattr(plan, "_sweep", None) is not None
    assert plan._sweep.alloc_ids  # per-alloc columns present
    return job, plan


def commit_columnar(plan):
    """Commit the sweep through the REAL columnar entry, including a
    msgpack round-trip (the consensus wire shape)."""
    result = PlanResult(NodeUpdate=dict(plan.NodeUpdate),
                        NodeAllocation=dict(plan.NodeAllocation))
    result._sweep = plan._sweep
    element, is_sweep = _encode_result(plan, result)
    assert is_sweep
    blob = msgpack.packb(
        (int(MessageType.ApplySweepBatch), to_dict({"Batch": [element]})),
        use_bin_type=True)
    msg, payload = msgpack.unpackb(blob, raw=False)
    fsm = FSM()
    fsm.apply(APPLY_INDEX, MessageType(msg), payload)
    assert fsm.state._col_segments, "sweep did not commit columnar"
    return fsm


def commit_objects(plan):
    """The reference per-object commit of the SAME result."""
    blob = msgpack.packb(
        (int(MessageType.AllocUpdate),
         to_dict({"Job": plan.Job,
                  "Alloc": [a for placed in plan.NodeAllocation.values()
                            for a in placed]})),
        use_bin_type=True)
    msg, payload = msgpack.unpackb(blob, raw=False)
    fsm = FSM()
    fsm.apply(APPLY_INDEX, MessageType(msg), payload)
    assert not fsm.state._col_segments
    return fsm


def visible(state, job, plan):
    """Every read surface as plain data, sorted for comparison."""
    def dump(allocs):
        return sorted((to_dict(a) for a in allocs), key=lambda d: d["ID"])

    eval_id = plan.EvalID
    node_ids = sorted(plan.NodeAllocation)
    out = {
        "all": dump(state.allocs()),
        "by_job": dump(state.allocs_by_job(job.ID)),
        "by_eval": dump(state.allocs_by_eval(eval_id)),
        "by_node": {nid: dump(state.allocs_by_node(nid))
                    for nid in node_ids},
        "by_node_live": {nid: dump(state.allocs_by_node_terminal(nid,
                                                                 False))
                         for nid in node_ids},
        "index": state.get_index("allocs"),
    }
    out["by_id"] = {d["ID"]: d for d in out["all"]}
    if hasattr(state, "client_alloc_map"):
        out["client"] = {nid: state.client_alloc_map(nid)
                         for nid in node_ids}
    return out


def assert_same_state(fsm_col, fsm_obj, job, plan):
    vc = visible(fsm_col.state, job, plan)
    vo = visible(fsm_obj.state, job, plan)
    assert vc == vo


def roundtrip(fsm):
    blob = msgpack.packb(fsm.snapshot(), use_bin_type=True)
    out = FSM()
    out.restore(msgpack.unpackb(blob, raw=False))
    return out


@pytest.fixture(autouse=True)
def _heal_failpoints():
    failpoints.disarm_all()
    yield
    failpoints.disarm_all()


class TestColumnarEquivalence:
    def test_commit_reads_identical(self):
        """The same sweep committed columnar and per-object is
        indistinguishable through every read surface."""
        job, plan = sweep_plan()
        fsm_col = commit_columnar(plan)
        fsm_obj = commit_objects(plan)
        assert_same_state(fsm_col, fsm_obj, job, plan)
        # And the columnar side really stayed lazy at commit: no chain
        # entries were created for the sweep's allocs.
        assert not fsm_col.state._tables["allocs"].current

    def test_snapshot_restore_identical(self):
        """snapshot->restore round-trips the columnar tables columnar and
        lands byte-identical client-visible state."""
        job, plan = sweep_plan()
        fsm_col = commit_columnar(plan)
        fsm_obj = commit_objects(plan)
        snap = fsm_col.snapshot()
        assert snap["columnar_allocs"] and not snap["allocs"]
        r_col = roundtrip(fsm_col)
        r_obj = roundtrip(fsm_obj)
        assert r_col.state._col_segments  # still columnar after restore
        assert_same_state(r_col, r_obj, job, plan)
        # Restored-columnar == live-object too (transitivity check).
        assert visible(r_col.state, job, plan)["by_id"] \
            == visible(fsm_obj.state, job, plan)["by_id"]

    def test_client_update_promotes_row(self):
        """A client status update on a sweep-committed alloc promotes the
        row onto the exact object path; both stores converge to the same
        mutated state and the row leaves the columnar table."""
        job, plan = sweep_plan()
        fsm_col = commit_columnar(plan)
        fsm_obj = commit_objects(plan)
        target = plan._sweep.alloc_ids[3]
        seg = fsm_col.state._col_segments[0]
        live_before = seg.n_live
        for fsm in (fsm_col, fsm_obj):
            running = fsm.state.alloc_by_id(target).copy()
            running.ClientStatus = AllocClientStatusRunning
            running.ClientDescription = "started"
            fsm.apply(APPLY_INDEX + 1, MessageType.AllocClientUpdate,
                      {"Alloc": [running]})
        assert seg.n_live == live_before - 1
        assert fsm_col.state._tables["allocs"].current[target] is not None
        assert_same_state(fsm_col, fsm_obj, job, plan)
        got = fsm_col.state.alloc_by_id(target)
        assert got.ClientStatus == AllocClientStatusRunning
        assert got.CreateIndex == APPLY_INDEX  # promotion kept identity
        # Snapshot/restore still identical after a promotion.
        assert_same_state(roundtrip(fsm_col), roundtrip(fsm_obj), job, plan)

    def test_preemption_eviction_promotes_and_matches(self):
        """A preemption-style eviction (stop upsert of a columnar row)
        promotes the victim and commits the same terminal state the
        object path produces — including the terminal/live split reads."""
        job, plan = sweep_plan()
        fsm_col = commit_columnar(plan)
        fsm_obj = commit_objects(plan)
        victim_id = plan._sweep.alloc_ids[0]
        for fsm in (fsm_col, fsm_obj):
            victim = fsm.state.alloc_by_id(victim_id).copy()
            victim.DesiredStatus = AllocDesiredStatusEvict
            victim.DesiredDescription = "preempted"
            fsm.apply(APPLY_INDEX + 2, MessageType.AllocUpdate,
                      {"Job": None, "Alloc": [victim]})
        assert_same_state(fsm_col, fsm_obj, job, plan)
        got = fsm_col.state.alloc_by_id(victim_id)
        assert got.terminal_status()
        node = got.NodeID
        assert victim_id not in {
            a.ID for a in fsm_col.state.allocs_by_node_terminal(node,
                                                                False)}

    def test_gc_delete_matches(self):
        """delete_eval GC of columnar rows promotes + tombstones exactly
        like the object path."""
        job, plan = sweep_plan()
        fsm_col = commit_columnar(plan)
        fsm_obj = commit_objects(plan)
        doomed = list(plan._sweep.alloc_ids[:3])
        for fsm in (fsm_col, fsm_obj):
            fsm.apply(APPLY_INDEX + 3, MessageType.EvalDelete,
                      {"Evals": [], "Allocs": list(doomed)})
        assert_same_state(fsm_col, fsm_obj, job, plan)
        for aid in doomed:
            assert fsm_col.state.alloc_by_id(aid) is None

    def test_killed_commit_is_atomic(self):
        """An injected kill at the bulk-commit seam fires BEFORE the
        entry is proposed to consensus (like plan.apply.commit): the
        raft log never carries the batch, so no replica — and no log
        replay after the redelivered eval commits fresh UUIDs — can ever
        land it. No torn batch: zero rows visible, zero segments, log
        index unmoved."""
        from nomad_tpu.server.fsm import DevRaft
        from nomad_tpu.server.plan_apply import PlanApplier
        from nomad_tpu.server.plan_queue import PlanQueue

        job, plan = sweep_plan()
        fsm = FSM()
        raft = DevRaft(fsm)
        # The applier verifies against real state: give the store the
        # same (deterministic-ID) node fleet the plan targets.
        for i in range(8):
            fsm.state.upsert_node(i + 1, make_node(i))
        index_before = raft.last_index
        failpoints.arm_from_spec("state.store.commit=error:count=1")
        queue = PlanQueue()
        queue.set_enabled(True)
        applier = PlanApplier(queue, raft)
        queue.enqueue(plan)
        with pytest.raises(failpoints.FailpointError):
            applier.apply_one(queue.dequeue(timeout=1))
        assert raft.last_index == index_before  # never entered the log
        assert not fsm.state._col_segments
        assert not fsm.state.allocs_by_job(job.ID)
        queue.set_enabled(False)

    def test_tensor_listener_epoch_fallback(self):
        """The usage listener's row-addressed scatter must decline on an
        epoch mismatch and fall back to the id-addressed path — same
        final usage either way (regression: the fallback once executed
        orphaned per-event code and raised NameError)."""
        import numpy as np
        from nomad_tpu.tensor.node_table import RES_DIMS

        store = StateStore()
        tindex = TensorIndex.attach(store)
        node = make_node(0)
        store.upsert_node(1, node)
        row = tindex.nt.row_of[node.ID]
        base = tindex.nt.usage[row].copy()
        delta = np.ones((1, RES_DIMS), dtype=np.float32)
        # Current epoch: row-addressed path.
        tindex.on_sweep_batch([node.ID], np.asarray([row]), delta,
                              tindex.nt.row_epoch)
        assert np.allclose(tindex.nt.usage[row], base + 1)
        # Stale epoch: id-addressed fallback, same result.
        tindex.on_sweep_batch([node.ID], np.asarray([row]), delta,
                              tindex.nt.row_epoch - 1)
        assert np.allclose(tindex.nt.usage[row], base + 2)
        # And the ordinary per-event batch listener is still wired (the
        # store's _emit prefers it).
        assert callable(getattr(tindex, "on_change_batch"))

    def test_entry_with_updates_is_one_transaction(self):
        """A sweep element carrying exact-path stops (Updates) commits
        stops AND placements in the same entry; afterwards both are
        visible together (stop-then-place order inside one
        transaction)."""
        job, plan = sweep_plan()
        fsm = commit_columnar(plan)
        # Build a second sweep entry for the same job whose element also
        # carries a stop of one previously committed alloc.
        victim = fsm.state.alloc_by_id(plan._sweep.alloc_ids[0]).copy()
        victim.DesiredStatus = AllocDesiredStatusEvict
        victim.DesiredDescription = "preempted"
        job2, plan2 = sweep_plan()
        result = PlanResult(NodeUpdate={victim.NodeID: [victim]},
                            NodeAllocation=dict(plan2.NodeAllocation))
        result._sweep = plan2._sweep
        element, is_sweep = _encode_result(plan2, result)
        assert is_sweep and "Updates" in element
        fsm.apply(APPLY_INDEX + 5, MessageType.ApplySweepBatch,
                  {"Batch": [element]})
        got = fsm.state.alloc_by_id(victim.ID)
        assert got.terminal_status()
        assert len(fsm.state.allocs_by_job(job2.ID)) \
            == len(plan2._sweep.alloc_ids)

    def test_chunk_slices_cover_batch(self):
        """Descriptor slices (the chunked submit path) partition the
        per-alloc columns exactly: committing the slices equals
        committing the whole batch."""
        job, plan = sweep_plan(n_nodes=9, count=2)
        sweep = plan._sweep
        mid = len(sweep.node_ids) // 2
        parts = [sweep.slice(0, mid),
                 sweep.slice(mid, len(sweep.node_ids))]
        assert sum(len(p.alloc_ids) for p in parts) == len(sweep.alloc_ids)
        assert [i for p in parts for i in p.alloc_ids] == sweep.alloc_ids
        fsm_whole = commit_columnar(plan)
        fsm_parts = FSM()
        for k, part in enumerate(parts):
            chunk = PlanResult(NodeAllocation={
                nid: plan.NodeAllocation[nid] for nid in part.node_ids})
            chunk._sweep = part
            element, is_sweep = _encode_result(plan, chunk)
            assert is_sweep
            fsm_parts.apply(APPLY_INDEX + k, MessageType.ApplySweepBatch,
                            {"Batch": [element]})
        whole = {a.ID for a in fsm_whole.state.allocs_by_job(job.ID)}
        split = {a.ID for a in fsm_parts.state.allocs_by_job(job.ID)}
        assert whole == split == set(sweep.alloc_ids)


# --------------------------------------------------- service window path
def svc_job(count=5, cpu=50, networks=False):
    """Service job for the window harness: small asks, no networks by
    default (the storm shape); networks=True keeps mock.job's dynamic
    port ask so the window must take the exact per-object path."""
    job = mock.job()
    tg = job.TaskGroups[0]
    tg.Count = count
    t = tg.Tasks[0]
    t.Resources.CPU = cpu
    t.Resources.MemoryMB = 32
    t.Resources.DiskMB = 10
    if not networks:
        t.Resources.Networks = []
    t.Services = []
    if t.LogConfig is not None:
        t.LogConfig.MaxFiles = 1
        t.LogConfig.MaxFileSizeMB = 1
    job.init_fields()
    return job


def service_window(job, n_nodes=6, seed=7, vanish=False):
    """One fixed-seed service eval through the pipelined fast path's
    build — prepare_batch -> host placement kernel -> compact ->
    collect_build — the exact recipe _try_dispatch_fast/_finish_fast run,
    minus the stage threads. Returns a namespace with the plan (carrying
    its service SweepBatch when the window stayed columnar), the build
    verdict, and the store/tensor the window ran against."""
    import numpy as np

    from nomad_tpu.scheduler import kernels
    from nomad_tpu.scheduler.context import EvalContext
    from nomad_tpu.scheduler.stack import GenericStack, WindowAccumulator
    from nomad_tpu.scheduler.util import (
        diff_allocs,
        materialize_task_groups,
        ready_nodes_in_dcs,
    )
    from nomad_tpu.tensor import ClassEligibility

    store = StateStore()
    tindex = TensorIndex.attach(store)
    idx = 0
    for i in range(n_nodes):
        idx += 1
        store.upsert_node(idx, make_node(i))
    idx += 1
    store.upsert_job(idx, job)
    ev = mock.eval()
    ev.JobID = job.ID
    ev.Type = job.Type
    ev.TriggeredBy = EvalTriggerJobRegister
    snap = store.snapshot()
    plan = ev.make_plan(job, copy_job=False)
    ctx = EvalContext(snap, plan, logger)
    stack = GenericStack(ctx, tindex, batch=False, rng=random.Random(seed))
    diff = diff_allocs(job, {}, materialize_task_groups(job), [])
    nodes, by_dc = ready_nodes_in_dcs(snap, job.Datacenters)
    nt = tindex.nt
    nodes_by_id = {n.ID: n for n in nodes}
    cand_mask = np.zeros(nt.n_rows, dtype=bool)
    for n in nodes:
        row = nt.row_of.get(n.ID)
        if row is not None:
            cand_mask[row] = True
    stack.job = job
    stack.adopt_nodes(nodes_by_id, cand_mask, ClassEligibility(nt, nodes))
    ctx.metrics.NodesAvailable = by_dc
    prep = stack.prepare_batch([t.TaskGroup for t in diff.place])
    res = stack.dispatch_host(prep)
    cr = kernels.compact_host(np.asarray(res.packed), prep.n_valid)
    if vanish:
        # A node vanishing between dispatch and build: the window-level
        # lookup must fail and route the eval onto the exact path.
        nodes_by_id.pop(nt.node_id_array()[cr.chosen[0]])
    failed = {}
    ok = stack.collect_build(prep, cr, ev.ID, job, diff.place, plan,
                             failed, WindowAccumulator(nt.n_rows))
    return types.SimpleNamespace(job=job, plan=plan, ok=ok, failed=failed,
                                 store=store, tindex=tindex)


class TestServiceColumnarEquivalence:
    def test_service_commit_reads_identical(self):
        """A service window committed columnar and per-object is
        indistinguishable through every read surface, and the columnar
        side stays fully lazy at commit."""
        ns = service_window(svc_job())
        assert ns.ok and not ns.failed
        sweep = ns.plan._sweep
        assert sweep is not None and sweep.kind == "service"
        assert sweep.alloc_ids and sorted(sweep.node_ids) \
            == sorted(ns.plan.NodeAllocation)
        fsm_col = commit_columnar(ns.plan)
        fsm_obj = commit_objects(ns.plan)
        assert fsm_col.state._col_segments[0].kind == "service"
        assert_same_state(fsm_col, fsm_obj, ns.job, ns.plan)
        assert not fsm_col.state._tables["allocs"].current

    def test_service_snapshot_restore_identical(self):
        """snapshot->restore keeps service segments columnar (Kind
        round-trips) and lands identical client-visible state."""
        ns = service_window(svc_job())
        fsm_col = commit_columnar(ns.plan)
        fsm_obj = commit_objects(ns.plan)
        snap = fsm_col.snapshot()
        assert snap["columnar_allocs"] and not snap["allocs"]
        r_col = roundtrip(fsm_col)
        assert r_col.state._col_segments[0].kind == "service"
        assert_same_state(r_col, roundtrip(fsm_obj), ns.job, ns.plan)

    def test_service_client_update_promotes_row(self):
        """A client status update on a service-window row promotes it
        onto the object chain; both stores converge and the promotion
        shows in the operator counters."""
        ns = service_window(svc_job())
        fsm_col = commit_columnar(ns.plan)
        fsm_obj = commit_objects(ns.plan)
        target = ns.plan._sweep.alloc_ids[2]
        for fsm in (fsm_col, fsm_obj):
            running = fsm.state.alloc_by_id(target).copy()
            running.ClientStatus = AllocClientStatusRunning
            running.ClientDescription = "started"
            fsm.apply(APPLY_INDEX + 1, MessageType.AllocClientUpdate,
                      {"Alloc": [running]})
        assert_same_state(fsm_col, fsm_obj, ns.job, ns.plan)
        got = fsm_col.state.alloc_by_id(target)
        assert got.ClientStatus == AllocClientStatusRunning
        assert got.CreateIndex == APPLY_INDEX
        stats = fsm_col.state.columnar_stats()
        assert stats["PromotedRows"] == 1
        assert stats["Batches"] == {"service": 1}

    def test_service_descriptor_bulk_verifies(self):
        """The applier's vectorized verify admits a full-coverage service
        descriptor wholesale and attaches it to the result — the
        precondition for the columnar raft encode."""
        from nomad_tpu.server.plan_apply import (
            OptimisticSnapshot,
            evaluate_plan,
        )

        ns = service_window(svc_job())
        opt = OptimisticSnapshot(ns.store.snapshot(), nt=ns.tindex.nt)
        result = evaluate_plan(opt, ns.plan, None, nt=ns.tindex.nt)
        assert getattr(result, "_sweep", None) is ns.plan._sweep
        full, _, _ = result.full_commit(ns.plan)
        assert full

    def test_service_multi_alloc_rows_fold(self):
        """Count > nodes: several instances land on one node row, so the
        descriptor folds them — counts/starts must partition the
        row-sorted alloc columns exactly, and the commit must still read
        identical to the object path."""
        ns = service_window(svc_job(count=5), n_nodes=2)
        assert ns.ok and not ns.failed
        sweep = ns.plan._sweep
        assert sweep is not None and len(sweep.rows) <= 2
        assert int(sweep.counts.sum()) == 5
        assert sweep.starts[-1] == len(sweep.alloc_ids) == 5
        # Each row's alloc slice really sits on that row's node.
        by_node = {nid: {a.ID for a in v}
                   for nid, v in ns.plan.NodeAllocation.items()}
        for k, nid in enumerate(sweep.node_ids):
            s, e = int(sweep.starts[k]), int(sweep.starts[k + 1])
            assert set(sweep.alloc_ids[s:e]) == by_node[nid]
        assert_same_state(commit_columnar(ns.plan),
                          commit_objects(ns.plan), ns.job, ns.plan)

    def test_service_mixed_window_stays_object(self):
        """Failed placements route the whole eval through the exact
        per-object build: no descriptor, the placed rows commit as plain
        objects, and the failures coalesce into FailedTGAllocs."""
        ns = service_window(svc_job(count=4, cpu=2000), n_nodes=2)
        assert ns.ok and ns.failed  # built exact, with coalesced failures
        assert getattr(ns.plan, "_sweep", None) is None
        placed = sum(len(v) for v in ns.plan.NodeAllocation.values())
        assert placed == 2  # one 2000-CPU alloc fits per 3900-free node
        element, is_sweep = _encode_result(
            ns.plan, PlanResult(NodeAllocation=dict(ns.plan.NodeAllocation)))
        assert not is_sweep and "Alloc" in element
        fsm = commit_objects(ns.plan)
        assert len(fsm.state.allocs_by_job(ns.job.ID)) == placed

    def test_service_network_asks_stay_object(self):
        """Port asks keep the exact per-object path (offers are
        sequential host state): no descriptor even when fully placed."""
        ns = service_window(svc_job(count=3, networks=True))
        assert ns.ok and not ns.failed
        assert getattr(ns.plan, "_sweep", None) is None
        placed = [a for v in ns.plan.NodeAllocation.values() for a in v]
        assert len(placed) == 3
        # The exact build really assigned ports.
        assert any(r.Networks for a in placed
                   for r in a.TaskResources.values())

    def test_service_vanished_node_falls_back(self):
        """A winner row whose node vanished mid-window fails the build —
        the caller re-runs the eval on the exact path — and never leaves
        a descriptor on the abandoned plan."""
        ns = service_window(svc_job(), vanish=True)
        assert ns.ok is False
        assert getattr(ns.plan, "_sweep", None) is None

    def test_served_service_storm_commits_columnar(self):
        """End to end through a live server: a service storm commits as
        service-kind segments (no chain objects), every read surface and
        the client pull map serve the placements, and the sched-stats
        Store counters record the path taken."""
        import time

        from nomad_tpu.server import Server, ServerConfig
        from nomad_tpu.structs.structs import EvalStatusComplete

        srv = Server(ServerConfig(num_schedulers=1, scheduler_window=8,
                                  min_heartbeat_ttl=3600.0,
                                  heartbeat_grace=3600.0))
        srv.establish_leadership()
        try:
            for _ in range(6):
                srv.node_register(mock.node())
            eval_ids = [srv.job_register(svc_job())[0] for _ in range(4)]
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                if all((e := srv.state.eval_by_id(eid)) is not None
                       and e.Status == EvalStatusComplete
                       for eid in eval_ids):
                    break
                time.sleep(0.02)
            else:
                raise AssertionError("service storm never completed")
            state = srv.state
            stats = state.columnar_stats()
            assert stats["Batches"].get("service", 0) >= 1
            assert not stats["Batches"].get("system")
            placed = [a for eid in eval_ids
                      for a in state.allocs_by_eval(eid)]
            assert len(placed) == 4 * 5
            assert len({a.ID for a in placed}) == len(placed)
            # The pull signal answers from the columns.
            pulled = {}
            for node in state.nodes():
                pulled.update(state.client_alloc_map(node.ID)[0])
            assert set(pulled) == {a.ID for a in placed}
        finally:
            srv.shutdown()


class TestChunkedSnapshotAtomicity:
    """Streaming-snapshot coverage (ISSUE 13): the chunked persist path
    must be read-equivalent to the monolithic snapshot — including the
    row-slicing of over-large columnar segments — and a restore killed
    at ANY chunk boundary must leave the store bit-identical to its
    pre-restore state (the Restore's staging tables only land at the
    single atomic commit())."""

    def _mutated_fsm(self):
        """A store with every shape a snapshot carries: a columnar
        segment, a promoted row (object chain), and a client update."""
        job, plan = sweep_plan()
        fsm = commit_columnar(plan)
        target = plan._sweep.alloc_ids[3]
        running = fsm.state.alloc_by_id(target).copy()
        running.ClientStatus = AllocClientStatusRunning
        fsm.apply(APPLY_INDEX + 1, MessageType.AllocClientUpdate,
                  {"Alloc": [running]})
        fsm.timetable.witness(APPLY_INDEX + 1, 1000.0)
        return job, plan, fsm

    def test_chunked_roundtrip_identical_to_monolithic(self):
        """snapshot_chunks -> restore_chunks == snapshot -> restore, at a
        chunk size small enough to force BOTH the multi-chunk table path
        and the columnar segment row-slicing path."""
        job, plan, fsm = self._mutated_fsm()
        chunks = list(fsm.snapshot_chunks(chunk_items=3))
        assert len(chunks) > 4  # really streamed
        # The 16-row segment must have been sliced into several.
        seg_chunks = [c for c in chunks if c["kind"] == "columnar_allocs"]
        assert sum(len(c["items"]) for c in seg_chunks) > 1
        # Through the wire shape: msgpack each chunk independently.
        wire = [msgpack.packb(c, use_bin_type=True) for c in chunks]
        r_chunked = FSM()
        r_chunked.restore_chunks(
            msgpack.unpackb(b, raw=False) for b in wire)
        r_mono = roundtrip(fsm)
        assert visible(r_chunked.state, job, plan) \
            == visible(r_mono.state, job, plan)
        assert r_chunked.timetable.serialize() \
            == fsm.timetable.serialize()
        # Sliced segments re-snapshot to the same visible state again
        # (idempotent round-trip, not just one hop).
        r2 = FSM()
        r2.restore_chunks(r_chunked.snapshot_chunks(chunk_items=3))
        assert visible(r2.state, job, plan) \
            == visible(r_mono.state, job, plan)

    def test_restore_killed_at_every_chunk_boundary_keeps_state(self):
        """Kill the chunk stream after k chunks, for EVERY k: the live
        store (and timetable) must stay bit-identical to its pre-restore
        state; only the complete stream lands."""
        job_a, plan_a, fsm_a = self._mutated_fsm()
        chunks = list(fsm_a.snapshot_chunks(chunk_items=3))

        # The victim store has its OWN different prior state.
        job_b, plan_b = sweep_plan(n_nodes=4, count=1)
        fsm_b = commit_columnar(plan_b)
        fsm_b.timetable.witness(APPLY_INDEX, 500.0)
        before_vis = visible(fsm_b.state, job_b, plan_b)
        before_snap = fsm_b.snapshot()
        before_tt = fsm_b.timetable.serialize()

        class Torn(Exception):
            pass

        def torn_stream(n):
            for c in chunks[:n]:
                yield c
            raise Torn(f"stream killed after chunk {n}")

        for k in range(len(chunks)):
            with pytest.raises(Torn):
                fsm_b.restore_chunks(torn_stream(k))
            assert visible(fsm_b.state, job_b, plan_b) == before_vis, \
                f"store mutated by a stream torn after {k} chunks"
            assert fsm_b.snapshot() == before_snap
            assert fsm_b.timetable.serialize() == before_tt

        # The complete stream still installs (the torn attempts left no
        # wedged staging state behind).
        fsm_b.restore_chunks(iter(chunks))
        assert visible(fsm_b.state, job_a, plan_a) \
            == visible(roundtrip(fsm_a).state, job_a, plan_a)
        assert fsm_b.timetable.serialize() == fsm_a.timetable.serialize()
