"""Fixed-seed columnar-vs-object state-store commit equivalence.

The columnar commit path (plan applier -> ApplySweepBatch raft entry ->
SweepSegment scatter-apply -> lazy materialization) must be
indistinguishable from the per-object path it optimizes: identical
allocs_by_node/-job/-eval results, identical alloc_by_id values,
identical client pull maps, identical snapshot->restore state — and any
MUTATION (client status update, stop/preemption eviction, GC) must
promote the row onto the exact object path with the same end state the
object commit would have produced.

One fixed-seed system sweep is generated ONCE (capture-only planner),
then the same verified result is committed twice — once as the columnar
raft entry (through a real msgpack round-trip, the wire shape), once as
the reference AllocUpdate object entry — into two fresh FSMs, and every
read surface is compared as plain data.
"""

import logging
import random

import msgpack
import pytest

from nomad_tpu import mock
from nomad_tpu.resilience import failpoints
from nomad_tpu.scheduler.system_sched import SystemScheduler
from nomad_tpu.server.fsm import FSM, MessageType
from nomad_tpu.server.plan_apply import _encode_result
from nomad_tpu.state.state_store import StateStore
from nomad_tpu.structs import PlanResult, compute_node_class, to_dict
from nomad_tpu.structs.structs import (
    AllocClientStatusRunning,
    AllocDesiredStatusEvict,
    EvalStatusPending,
    EvalTriggerJobRegister,
)
from nomad_tpu.tensor import TensorIndex

logger = logging.getLogger("test.columnar")

APPLY_INDEX = 100


class CapturePlanner:
    def __init__(self):
        self.plans = []
        self.evals = []

    def plan_queue_depth(self):
        return 0

    def submit_plan(self, plan):
        self.plans.append(plan)
        r = PlanResult()
        r.NodeUpdate = dict(plan.NodeUpdate)
        r.NodeAllocation = dict(plan.NodeAllocation)
        r.AllocIndex = 1
        return r, None

    def update_eval(self, ev):
        self.evals.append(ev)

    def create_eval(self, ev):
        self.evals.append(ev)

    def reblock_eval(self, ev):
        self.evals.append(ev)


def make_node(i):
    n = mock.node()
    n.ID = f"node-{i:04d}"
    n.Name = n.ID
    compute_node_class(n)
    return n


def sys_job(count=2):
    job = mock.system_job()
    t = job.TaskGroups[0].Tasks[0]
    t.Resources.CPU = 50
    t.Resources.MemoryMB = 32
    t.Resources.DiskMB = 150
    t.Resources.Networks = []
    t.Services = []
    job.TaskGroups[0].Count = count
    job.init_fields()
    return job


def sweep_plan(n_nodes=8, count=2):
    """One fixed-seed system sweep plan (with its columnar descriptor)
    against a capture-only planner — nothing committed."""
    store = StateStore()
    tindex = TensorIndex.attach(store)
    idx = 0
    for i in range(n_nodes):
        idx += 1
        store.upsert_node(idx, make_node(i))
    job = sys_job(count)
    idx += 1
    store.upsert_job(idx, job)
    ev = mock.eval()
    ev.JobID = job.ID
    ev.Type = job.Type
    ev.TriggeredBy = EvalTriggerJobRegister
    ev.Status = EvalStatusPending
    planner = CapturePlanner()
    sched = SystemScheduler(store, planner, tindex, logger,
                            rng=random.Random(7))
    sched.process(ev)
    [plan] = planner.plans
    assert getattr(plan, "_sweep", None) is not None
    assert plan._sweep.alloc_ids  # per-alloc columns present
    return job, plan


def commit_columnar(plan):
    """Commit the sweep through the REAL columnar entry, including a
    msgpack round-trip (the consensus wire shape)."""
    result = PlanResult(NodeUpdate=dict(plan.NodeUpdate),
                        NodeAllocation=dict(plan.NodeAllocation))
    result._sweep = plan._sweep
    element, is_sweep = _encode_result(plan, result)
    assert is_sweep
    blob = msgpack.packb(
        (int(MessageType.ApplySweepBatch), to_dict({"Batch": [element]})),
        use_bin_type=True)
    msg, payload = msgpack.unpackb(blob, raw=False)
    fsm = FSM()
    fsm.apply(APPLY_INDEX, MessageType(msg), payload)
    assert fsm.state._col_segments, "sweep did not commit columnar"
    return fsm


def commit_objects(plan):
    """The reference per-object commit of the SAME result."""
    blob = msgpack.packb(
        (int(MessageType.AllocUpdate),
         to_dict({"Job": plan.Job,
                  "Alloc": [a for placed in plan.NodeAllocation.values()
                            for a in placed]})),
        use_bin_type=True)
    msg, payload = msgpack.unpackb(blob, raw=False)
    fsm = FSM()
    fsm.apply(APPLY_INDEX, MessageType(msg), payload)
    assert not fsm.state._col_segments
    return fsm


def visible(state, job, plan):
    """Every read surface as plain data, sorted for comparison."""
    def dump(allocs):
        return sorted((to_dict(a) for a in allocs), key=lambda d: d["ID"])

    eval_id = plan.EvalID
    node_ids = sorted(plan.NodeAllocation)
    out = {
        "all": dump(state.allocs()),
        "by_job": dump(state.allocs_by_job(job.ID)),
        "by_eval": dump(state.allocs_by_eval(eval_id)),
        "by_node": {nid: dump(state.allocs_by_node(nid))
                    for nid in node_ids},
        "by_node_live": {nid: dump(state.allocs_by_node_terminal(nid,
                                                                 False))
                         for nid in node_ids},
        "index": state.get_index("allocs"),
    }
    out["by_id"] = {d["ID"]: d for d in out["all"]}
    if hasattr(state, "client_alloc_map"):
        out["client"] = {nid: state.client_alloc_map(nid)
                         for nid in node_ids}
    return out


def assert_same_state(fsm_col, fsm_obj, job, plan):
    vc = visible(fsm_col.state, job, plan)
    vo = visible(fsm_obj.state, job, plan)
    assert vc == vo


def roundtrip(fsm):
    blob = msgpack.packb(fsm.snapshot(), use_bin_type=True)
    out = FSM()
    out.restore(msgpack.unpackb(blob, raw=False))
    return out


@pytest.fixture(autouse=True)
def _heal_failpoints():
    failpoints.disarm_all()
    yield
    failpoints.disarm_all()


class TestColumnarEquivalence:
    def test_commit_reads_identical(self):
        """The same sweep committed columnar and per-object is
        indistinguishable through every read surface."""
        job, plan = sweep_plan()
        fsm_col = commit_columnar(plan)
        fsm_obj = commit_objects(plan)
        assert_same_state(fsm_col, fsm_obj, job, plan)
        # And the columnar side really stayed lazy at commit: no chain
        # entries were created for the sweep's allocs.
        assert not fsm_col.state._tables["allocs"].current

    def test_snapshot_restore_identical(self):
        """snapshot->restore round-trips the columnar tables columnar and
        lands byte-identical client-visible state."""
        job, plan = sweep_plan()
        fsm_col = commit_columnar(plan)
        fsm_obj = commit_objects(plan)
        snap = fsm_col.snapshot()
        assert snap["columnar_allocs"] and not snap["allocs"]
        r_col = roundtrip(fsm_col)
        r_obj = roundtrip(fsm_obj)
        assert r_col.state._col_segments  # still columnar after restore
        assert_same_state(r_col, r_obj, job, plan)
        # Restored-columnar == live-object too (transitivity check).
        assert visible(r_col.state, job, plan)["by_id"] \
            == visible(fsm_obj.state, job, plan)["by_id"]

    def test_client_update_promotes_row(self):
        """A client status update on a sweep-committed alloc promotes the
        row onto the exact object path; both stores converge to the same
        mutated state and the row leaves the columnar table."""
        job, plan = sweep_plan()
        fsm_col = commit_columnar(plan)
        fsm_obj = commit_objects(plan)
        target = plan._sweep.alloc_ids[3]
        seg = fsm_col.state._col_segments[0]
        live_before = seg.n_live
        for fsm in (fsm_col, fsm_obj):
            running = fsm.state.alloc_by_id(target).copy()
            running.ClientStatus = AllocClientStatusRunning
            running.ClientDescription = "started"
            fsm.apply(APPLY_INDEX + 1, MessageType.AllocClientUpdate,
                      {"Alloc": [running]})
        assert seg.n_live == live_before - 1
        assert fsm_col.state._tables["allocs"].current[target] is not None
        assert_same_state(fsm_col, fsm_obj, job, plan)
        got = fsm_col.state.alloc_by_id(target)
        assert got.ClientStatus == AllocClientStatusRunning
        assert got.CreateIndex == APPLY_INDEX  # promotion kept identity
        # Snapshot/restore still identical after a promotion.
        assert_same_state(roundtrip(fsm_col), roundtrip(fsm_obj), job, plan)

    def test_preemption_eviction_promotes_and_matches(self):
        """A preemption-style eviction (stop upsert of a columnar row)
        promotes the victim and commits the same terminal state the
        object path produces — including the terminal/live split reads."""
        job, plan = sweep_plan()
        fsm_col = commit_columnar(plan)
        fsm_obj = commit_objects(plan)
        victim_id = plan._sweep.alloc_ids[0]
        for fsm in (fsm_col, fsm_obj):
            victim = fsm.state.alloc_by_id(victim_id).copy()
            victim.DesiredStatus = AllocDesiredStatusEvict
            victim.DesiredDescription = "preempted"
            fsm.apply(APPLY_INDEX + 2, MessageType.AllocUpdate,
                      {"Job": None, "Alloc": [victim]})
        assert_same_state(fsm_col, fsm_obj, job, plan)
        got = fsm_col.state.alloc_by_id(victim_id)
        assert got.terminal_status()
        node = got.NodeID
        assert victim_id not in {
            a.ID for a in fsm_col.state.allocs_by_node_terminal(node,
                                                                False)}

    def test_gc_delete_matches(self):
        """delete_eval GC of columnar rows promotes + tombstones exactly
        like the object path."""
        job, plan = sweep_plan()
        fsm_col = commit_columnar(plan)
        fsm_obj = commit_objects(plan)
        doomed = list(plan._sweep.alloc_ids[:3])
        for fsm in (fsm_col, fsm_obj):
            fsm.apply(APPLY_INDEX + 3, MessageType.EvalDelete,
                      {"Evals": [], "Allocs": list(doomed)})
        assert_same_state(fsm_col, fsm_obj, job, plan)
        for aid in doomed:
            assert fsm_col.state.alloc_by_id(aid) is None

    def test_killed_commit_is_atomic(self):
        """An injected kill at the bulk-commit seam fires BEFORE the
        entry is proposed to consensus (like plan.apply.commit): the
        raft log never carries the batch, so no replica — and no log
        replay after the redelivered eval commits fresh UUIDs — can ever
        land it. No torn batch: zero rows visible, zero segments, log
        index unmoved."""
        from nomad_tpu.server.fsm import DevRaft
        from nomad_tpu.server.plan_apply import PlanApplier
        from nomad_tpu.server.plan_queue import PlanQueue

        job, plan = sweep_plan()
        fsm = FSM()
        raft = DevRaft(fsm)
        # The applier verifies against real state: give the store the
        # same (deterministic-ID) node fleet the plan targets.
        for i in range(8):
            fsm.state.upsert_node(i + 1, make_node(i))
        index_before = raft.last_index
        failpoints.arm_from_spec("state.store.commit=error:count=1")
        queue = PlanQueue()
        queue.set_enabled(True)
        applier = PlanApplier(queue, raft)
        queue.enqueue(plan)
        with pytest.raises(failpoints.FailpointError):
            applier.apply_one(queue.dequeue(timeout=1))
        assert raft.last_index == index_before  # never entered the log
        assert not fsm.state._col_segments
        assert not fsm.state.allocs_by_job(job.ID)
        queue.set_enabled(False)

    def test_tensor_listener_epoch_fallback(self):
        """The usage listener's row-addressed scatter must decline on an
        epoch mismatch and fall back to the id-addressed path — same
        final usage either way (regression: the fallback once executed
        orphaned per-event code and raised NameError)."""
        import numpy as np
        from nomad_tpu.tensor.node_table import RES_DIMS

        store = StateStore()
        tindex = TensorIndex.attach(store)
        node = make_node(0)
        store.upsert_node(1, node)
        row = tindex.nt.row_of[node.ID]
        base = tindex.nt.usage[row].copy()
        delta = np.ones((1, RES_DIMS), dtype=np.float32)
        # Current epoch: row-addressed path.
        tindex.on_sweep_batch([node.ID], np.asarray([row]), delta,
                              tindex.nt.row_epoch)
        assert np.allclose(tindex.nt.usage[row], base + 1)
        # Stale epoch: id-addressed fallback, same result.
        tindex.on_sweep_batch([node.ID], np.asarray([row]), delta,
                              tindex.nt.row_epoch - 1)
        assert np.allclose(tindex.nt.usage[row], base + 2)
        # And the ordinary per-event batch listener is still wired (the
        # store's _emit prefers it).
        assert callable(getattr(tindex, "on_change_batch"))

    def test_entry_with_updates_is_one_transaction(self):
        """A sweep element carrying exact-path stops (Updates) commits
        stops AND placements in the same entry; afterwards both are
        visible together (stop-then-place order inside one
        transaction)."""
        job, plan = sweep_plan()
        fsm = commit_columnar(plan)
        # Build a second sweep entry for the same job whose element also
        # carries a stop of one previously committed alloc.
        victim = fsm.state.alloc_by_id(plan._sweep.alloc_ids[0]).copy()
        victim.DesiredStatus = AllocDesiredStatusEvict
        victim.DesiredDescription = "preempted"
        job2, plan2 = sweep_plan()
        result = PlanResult(NodeUpdate={victim.NodeID: [victim]},
                            NodeAllocation=dict(plan2.NodeAllocation))
        result._sweep = plan2._sweep
        element, is_sweep = _encode_result(plan2, result)
        assert is_sweep and "Updates" in element
        fsm.apply(APPLY_INDEX + 5, MessageType.ApplySweepBatch,
                  {"Batch": [element]})
        got = fsm.state.alloc_by_id(victim.ID)
        assert got.terminal_status()
        assert len(fsm.state.allocs_by_job(job2.ID)) \
            == len(plan2._sweep.alloc_ids)

    def test_chunk_slices_cover_batch(self):
        """Descriptor slices (the chunked submit path) partition the
        per-alloc columns exactly: committing the slices equals
        committing the whole batch."""
        job, plan = sweep_plan(n_nodes=9, count=2)
        sweep = plan._sweep
        mid = len(sweep.node_ids) // 2
        parts = [sweep.slice(0, mid),
                 sweep.slice(mid, len(sweep.node_ids))]
        assert sum(len(p.alloc_ids) for p in parts) == len(sweep.alloc_ids)
        assert [i for p in parts for i in p.alloc_ids] == sweep.alloc_ids
        fsm_whole = commit_columnar(plan)
        fsm_parts = FSM()
        for k, part in enumerate(parts):
            chunk = PlanResult(NodeAllocation={
                nid: plan.NodeAllocation[nid] for nid in part.node_ids})
            chunk._sweep = part
            element, is_sweep = _encode_result(plan, chunk)
            assert is_sweep
            fsm_parts.apply(APPLY_INDEX + k, MessageType.ApplySweepBatch,
                            {"Batch": [element]})
        whole = {a.ID for a in fsm_whole.state.allocs_by_job(job.ID)}
        split = {a.ID for a in fsm_parts.state.allocs_by_job(job.ID)}
        assert whole == split == set(sweep.alloc_ids)
