"""Runtime lock-order detector (nomad_tpu/analysis/debug_locks): the
dynamic half of the concurrency pass. Exercised here exactly the way
NOMAD_TPU_DEBUG_LOCKS=1 wires it in conftest — install() swaps the
threading lock factories and time.sleep — then seeded misuse must be
reported and clean usage must stay silent."""

import threading
import time

import pytest

from nomad_tpu.analysis import debug_locks


@pytest.fixture
def detector():
    debug_locks.clear_findings()
    debug_locks.install()
    try:
        yield debug_locks
    finally:
        debug_locks.uninstall()
        debug_locks.clear_findings()


def test_install_swaps_factories_and_uninstall_restores(detector):
    assert isinstance(threading.Lock(), debug_locks.DebugLock)
    assert isinstance(threading.RLock(), debug_locks.DebugRLock)
    detector.uninstall()
    assert not isinstance(threading.Lock(), debug_locks.DebugLock)
    assert not isinstance(threading.RLock(), debug_locks.DebugRLock)


def test_lock_order_inversion_is_reported(detector):
    a = debug_locks.DebugLock("inv-A")
    b = debug_locks.DebugLock("inv-B")
    with a:
        with b:
            pass
    assert detector.runtime_findings("lock_order_inversion") == []
    with b:
        with a:  # A->B then B->A: the seeded deadlock pattern
            pass
    findings = detector.runtime_findings("lock_order_inversion")
    assert len(findings) == 1
    assert set(findings[0].locks) == {"inv-A", "inv-B"}


def test_consistent_order_stays_silent(detector):
    a = debug_locks.DebugLock("ord-A")
    b = debug_locks.DebugLock("ord-B")
    for _ in range(3):
        with a:
            with b:
                pass
    assert detector.runtime_findings("lock_order_inversion") == []


def test_blocking_call_under_lock_is_reported(detector):
    lock = threading.Lock()  # a DebugLock via the patched factory
    with lock:
        time.sleep(0.001)    # the patched sleep sees the held lock
    findings = detector.runtime_findings("blocking_under_lock")
    assert len(findings) == 1
    assert findings[0].locks == (lock.name,)
    # ... and sleeping with nothing held is fine.
    detector.clear_findings()
    time.sleep(0.001)
    assert detector.runtime_findings("blocking_under_lock") == []


def test_long_hold_is_reported(detector, monkeypatch):
    # The threshold is cached at install() (reading the env on every
    # release would inflate the measured holds) — override the cache.
    monkeypatch.setattr(debug_locks, "hold_threshold_s", 0.01)
    lock = debug_locks.DebugLock("holder")
    with lock:
        debug_locks._REAL_SLEEP(0.05)
    kinds = {f.locks for f in detector.runtime_findings("long_hold")}
    assert ("holder",) in kinds


def test_rlock_recursion_counts_as_one_hold(detector):
    rl = debug_locks.DebugRLock("re-entrant")
    with rl:
        with rl:
            assert len(debug_locks._held()) == 1
        assert len(debug_locks._held()) == 1
    assert debug_locks._held() == []


def test_condition_wait_releases_the_held_stack(detector):
    cond = threading.Condition()  # backed by a DebugRLock post-install
    parked = threading.Event()
    hit = []

    def waiter():
        with cond:
            parked.set()  # set just before wait: the notifier can only
            #               acquire cond once wait() has released it
            cond.wait(timeout=5.0)
            hit.append(len(debug_locks._held()))

    t = threading.Thread(target=waiter, name="dbglock-waiter")
    t.start()
    assert parked.wait(timeout=5.0)
    # Acquiring cond here proves the waiter's wait() RELEASED the lock
    # (through _release_save on the debug wrapper).
    with cond:
        cond.notify_all()
    t.join(timeout=5.0)
    assert not t.is_alive()
    assert hit == [1]  # re-acquired on wake, balanced afterwards


def test_detector_reports_through_metrics(detector):
    from nomad_tpu.telemetry import metrics

    a = debug_locks.DebugLock("met-A")
    b = debug_locks.DebugLock("met-B")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    snap = metrics.snapshot()
    names = [c["Name"] for c in snap["Counters"]]
    assert "nomad.analysis.lock_order_inversion" in names


def test_install_from_env_honors_the_flag(monkeypatch):
    # The exact wiring conftest uses for NOMAD_TPU_DEBUG_LOCKS=1.
    monkeypatch.delenv(debug_locks.ENV_VAR, raising=False)
    assert debug_locks.install_from_env() is False
    assert not debug_locks.installed()
    monkeypatch.setenv(debug_locks.ENV_VAR, "1")
    try:
        assert debug_locks.install_from_env() is True
        assert debug_locks.installed()
    finally:
        debug_locks.uninstall()
        debug_locks.clear_findings()


def test_default_off_leaves_threading_untouched():
    # This test runs WITHOUT the detector fixture: the ambient state must
    # be the raw stdlib (tier-1 runs with NOMAD_TPU_DEBUG_LOCKS unset).
    import os

    if os.environ.get(debug_locks.ENV_VAR) == "1":
        pytest.skip("suite running in debug-locks mode")
    assert not debug_locks.installed()
    assert not isinstance(threading.Lock(), debug_locks.DebugLock)
