"""Resilience subsystem unit tests: failpoint modes (armed and the
disarmed fast path), the spec grammar shared by env var/CLI/HTTP, the
unified retry/backoff policy (jitter bounds, deadline expiry), the
circuit breaker's closed/open/half-open cycle, the rpcproxy quarantine
built on it, and the chaos-schedule runner itself."""

import threading
import time
import types

import pytest

from nomad_tpu.resilience import failpoints
from nomad_tpu.resilience.chaos import ChaosSchedule
from nomad_tpu.resilience.retry import Backoff, CircuitBreaker, RetryPolicy


@pytest.fixture(autouse=True)
def _clean_failpoints():
    """A leaked armed failpoint would poison every later test in the
    process; heal unconditionally around each one."""
    failpoints.disarm_all()
    yield
    failpoints.disarm_all()


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# ------------------------------------------------------------- failpoints
class TestFailpointModes:
    def test_disarmed_fast_path_returns_none(self):
        assert failpoints.fire("nonexistent.site") is None
        # The fast path must not record anything either.
        assert failpoints.snapshot().get("nonexistent.site") is None

    def test_error_mode_raises_with_site(self):
        failpoints.arm("t.err", "error", message="boom")
        with pytest.raises(failpoints.FailpointError) as ei:
            failpoints.fire("t.err")
        assert ei.value.site == "t.err"
        assert "boom" in str(ei.value)

    def test_delay_mode_sleeps_then_proceeds(self):
        failpoints.arm("t.delay", "delay", delay=0.05)
        t0 = time.monotonic()
        assert failpoints.fire("t.delay") is None
        assert time.monotonic() - t0 >= 0.04

    def test_drop_mode_returns_drop(self):
        failpoints.arm("t.drop", "drop")
        assert failpoints.fire("t.drop") == "drop"

    def test_count_auto_disarms(self):
        failpoints.arm("t.once", "drop", count=2)
        assert failpoints.fire("t.once") == "drop"
        assert failpoints.fire("t.once") == "drop"
        assert failpoints.fire("t.once") is None  # spent
        assert failpoints.snapshot()["t.once"]["armed"] is None
        assert failpoints.snapshot()["t.once"]["fired"] == 2

    def test_probability_gates_triggering(self, monkeypatch):
        rolls = iter([0.9, 0.1, 0.9, 0.1])
        monkeypatch.setattr(
            failpoints, "random",
            types.SimpleNamespace(random=lambda: next(rolls)))
        failpoints.arm("t.p", "drop", probability=0.5)
        assert failpoints.fire("t.p") is None      # 0.9 >= 0.5: no trigger
        assert failpoints.fire("t.p") == "drop"    # 0.1 <  0.5: trigger
        assert failpoints.fire("t.p") is None
        assert failpoints.fire("t.p") == "drop"
        assert failpoints.snapshot()["t.p"]["fired"] == 2

    def test_untriggered_probability_does_not_consume_count(
            self, monkeypatch):
        monkeypatch.setattr(failpoints, "random",
                            types.SimpleNamespace(random=lambda: 0.99))
        failpoints.arm("t.pc", "drop", probability=0.5, count=1)
        for _ in range(5):
            assert failpoints.fire("t.pc") is None
        assert failpoints.snapshot()["t.pc"]["armed"] is not None

    def test_disarm_and_disarm_all(self):
        failpoints.arm("t.a", "drop")
        failpoints.arm("t.b", "drop")
        assert failpoints.disarm("t.a") is True
        assert failpoints.disarm("t.a") is False
        assert failpoints.fire("t.a") is None
        failpoints.disarm_all()
        assert failpoints.fire("t.b") is None

    def test_invalid_specs_rejected(self):
        for bad in ["x.y=explode", "x.y=delay", "x.y=error:p=abc",
                    "x.y=drop:count=0", "x.y=drop:wat=1", "=error", "x.y="]:
            with pytest.raises(ValueError):
                failpoints.arm_from_spec(bad)
        with pytest.raises(ValueError):
            failpoints.arm("x.y", "drop", probability=1.5)

    def test_spec_grammar_round_trip(self):
        touched = failpoints.arm_from_spec(
            "a.b=error(boom):count=2; c.d=delay(0.25):p=0.5 ;e.f=drop:once")
        assert touched == ["a.b", "c.d", "e.f"]
        snap = failpoints.snapshot()
        assert snap["a.b"]["armed"]["mode"] == "error"
        assert snap["a.b"]["armed"]["remaining"] == 2
        assert snap["c.d"]["armed"] == {"mode": "delay", "delay": 0.25,
                                        "probability": 0.5,
                                        "remaining": None, "hits": 0}
        assert snap["e.f"]["armed"]["remaining"] == 1
        failpoints.arm_from_spec("a.b=off;c.d=off;e.f=off")
        # Never-fired ad-hoc sites drop out of the snapshot entirely once
        # disarmed; either way nothing fires.
        assert all(
            failpoints.snapshot().get(s, {"armed": None})["armed"] is None
            for s in ("a.b", "c.d", "e.f"))

    def test_malformed_clause_arms_nothing(self):
        """A rejected spec (HTTP 400) must leave NO clause armed — an
        operator who sees the request fail must not discover later that
        the first half of it took effect."""
        with pytest.raises(ValueError):
            failpoints.arm_from_spec(
                "atomic.ok=error;atomic.bad=explode")
        assert failpoints.snapshot().get(
            "atomic.ok", {"armed": None})["armed"] is None
        assert failpoints.fire("atomic.ok") is None

    def test_env_arming(self):
        sites = failpoints.arm_from_env(
            {failpoints.ENV_VAR: "env.site=drop:count=1"})
        assert sites == ["env.site"]
        assert failpoints.fire("env.site") == "drop"
        assert failpoints.arm_from_env({}) == []

    def test_malformed_env_spec_does_not_crash_import(self):
        """Every entry point imports this module transitively; a typo'd
        NOMAD_TPU_FAILPOINTS must warn on stderr, not raise at import
        (which would even kill `faults --disarm-all`)."""
        import os
        import subprocess
        import sys

        proc = subprocess.run(
            [sys.executable, "-c",
             "from nomad_tpu.resilience import failpoints; "
             "print('alive')"],
            env={**os.environ, failpoints.ENV_VAR: "raft.fsync=explode"},
            capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0, proc.stderr
        assert "alive" in proc.stdout
        assert "ignoring malformed" in proc.stderr

    def test_known_sites_cover_five_subsystems(self):
        """The acceptance floor: >= 10 sites spanning rpc, raft, gossip,
        server-side scheduling, and the client/driver layer."""
        sites = failpoints.known_sites()
        assert len(sites) >= 10
        prefixes = {s.split(".")[0] for s in sites}
        assert {"rpc", "raft", "gossip", "client", "driver",
                "plan", "worker"} <= prefixes


class TestFailpointSitesFire:
    """Each production seam actually consults its failpoint (grep-proof:
    arming the documented name changes behavior at that layer)."""

    def test_rpc_pool_call_drop(self):
        from nomad_tpu.rpc.pool import ConnError, ConnPool

        failpoints.arm("rpc.pool.call", "drop")
        with pytest.raises(ConnError):
            ConnPool().call("127.0.0.1:1", "Any.Method", {})

    def test_rpc_server_handle_drop(self):
        from nomad_tpu.rpc.cluster import ClusterServer
        from nomad_tpu.rpc.pool import ConnError
        from nomad_tpu.server.server import ServerConfig

        cs = ClusterServer(ServerConfig(bootstrap_expect=1,
                                        num_schedulers=0))
        cs.connect([])
        cs.start()
        try:
            failpoints.arm("rpc.server.handle", "drop", count=1)
            with pytest.raises(ConnError):
                cs.endpoints.handle("Status.Ping", {})
            cs.endpoints.handle("Status.Ping", {})  # healed after count
        finally:
            cs.shutdown()

    def test_drop_kills_connection_but_real_conn_error_serializes(self):
        """Only the INJECTED DroppedRPCError may kill the client
        connection; a real ConnError escaping a handler (a dead leader
        forward) must serialize as a remote error exactly as it did
        before failpoints existed — otherwise every stale-leader-hint
        forward failure would masquerade as a dead follower and feed the
        client's breakers."""
        from nomad_tpu.rpc.cluster import ClusterServer
        from nomad_tpu.rpc.pool import (
            ConnError,
            ConnPool,
            DroppedRPCError,
            RPCError,
        )
        from nomad_tpu.server.server import ServerConfig

        cs = ClusterServer(ServerConfig(bootstrap_expect=1,
                                        num_schedulers=0))
        cs.connect([])
        cs.start()
        pool = ConnPool()
        try:
            def dead_forward(body):
                raise ConnError("connection refused (dead leader)")

            cs.endpoints._methods["Status.Ping"] = dead_forward
            with pytest.raises(RPCError):
                pool.call(cs.addr, "Status.Ping", {}, timeout=10)

            def injected(body):
                raise DroppedRPCError("blackholed")

            cs.endpoints._methods["Status.Ping"] = injected
            with pytest.raises(ConnError):
                pool.call(cs.addr, "Status.Ping", {}, timeout=10)
        finally:
            pool.close()
            cs.shutdown()

    def test_raft_fsync_error_and_drop(self, tmp_path):
        from nomad_tpu.raft.log import FileLogStore, LogEntry

        store = FileLogStore(str(tmp_path))
        store.store_entries([LogEntry(Index=1, Term=1, Type=0, Data=b"a")])
        failpoints.arm("raft.fsync", "error")
        with pytest.raises(failpoints.FailpointError):
            store.store_entries(
                [LogEntry(Index=2, Term=1, Type=0, Data=b"b")])
        failpoints.arm_from_spec("raft.fsync=drop")
        # Lying-disk mode: append succeeds, fsync silently skipped.
        store.store_entries([LogEntry(Index=3, Term=1, Type=0, Data=b"c")])
        assert store.last_index() == 3

    def test_gossip_send_drop_loses_datagram(self):
        from nomad_tpu.gossip.memberlist import GossipConfig, Memberlist

        ml = Memberlist("fp-test", port=0, config=GossipConfig.fast())
        try:
            failpoints.arm("gossip.send", "drop")
            # Must swallow the send entirely — no socket error, no traffic.
            ml._send_udp(("127.0.0.1", 9), [{"t": "ping"}])
            assert failpoints.snapshot()["gossip.send"]["fired"] == 1
        finally:
            ml.shutdown()


# ---------------------------------------------------------------- backoff
class TestBackoff:
    def test_jitter_stays_within_bounds(self):
        import random as _random

        bo = Backoff(base=0.1, cap=2.0, rng=_random.Random(42))
        prev = bo.base
        for _ in range(200):
            d = bo.next()
            assert 0.1 <= d <= 2.0
            assert d <= min(2.0, prev * 3) + 1e-9
            prev = d

    def test_reset_restarts_sequence(self):
        bo = Backoff(base=1.0, cap=100.0)
        first = bo.next()
        assert first <= 3.0  # uniform(base, 3*base) on the first draw
        for _ in range(10):
            bo.next()
        bo.reset()
        assert bo.next() <= 3.0

    def test_validation(self):
        with pytest.raises(ValueError):
            Backoff(base=0.0)
        with pytest.raises(ValueError):
            Backoff(base=1.0, cap=0.5)


# ----------------------------------------------------------- retry policy
class TestRetryPolicy:
    def test_succeeds_after_transient_failures(self):
        sleeps = []
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise ConnectionError("transient")
            return "ok"

        policy = RetryPolicy(max_attempts=5, sleep=sleeps.append)
        assert policy.call(flaky) == "ok"
        assert calls["n"] == 3
        assert len(sleeps) == 2

    def test_attempts_exhausted_reraises_last(self):
        calls = {"n": 0}

        def always():
            calls["n"] += 1
            raise ValueError(f"attempt {calls['n']}")

        policy = RetryPolicy(max_attempts=3, sleep=lambda d: None)
        with pytest.raises(ValueError, match="attempt 3"):
            policy.call(always)
        assert calls["n"] == 3

    def test_deadline_expiry(self):
        clock = FakeClock()

        def ticking_sleep(d):
            clock.advance(d)

        policy = RetryPolicy(max_attempts=None, deadline=1.0,
                             backoff=Backoff(base=0.4, cap=0.4),
                             sleep=ticking_sleep, clock=clock)
        calls = {"n": 0}

        def always():
            calls["n"] += 1
            raise ConnectionError("down")

        with pytest.raises(ConnectionError):
            policy.call(always)
        # ~0.4s per retry against a 1.0s budget: a handful of attempts,
        # not an unbounded loop, and never a sleep past the deadline.
        assert 2 <= calls["n"] <= 5
        assert clock.t <= 1.0 + 0.4

    def test_non_retryable_exception_surfaces_immediately(self):
        calls = {"n": 0}

        def bad():
            calls["n"] += 1
            raise TypeError("never retry me")

        policy = RetryPolicy(max_attempts=5, retry_on=(ValueError,),
                             sleep=lambda d: None)
        with pytest.raises(TypeError):
            policy.call(bad)
        assert calls["n"] == 1

    def test_should_retry_filter(self):
        policy = RetryPolicy(
            max_attempts=5, sleep=lambda d: None,
            should_retry=lambda e: "retryable" in str(e))
        calls = {"n": 0}

        def terminal():
            calls["n"] += 1
            raise RuntimeError("terminal")

        with pytest.raises(RuntimeError):
            policy.call(terminal)
        assert calls["n"] == 1

    def test_on_retry_hook_observes_each_retry(self):
        seen = []
        policy = RetryPolicy(
            max_attempts=3, sleep=lambda d: None,
            on_retry=lambda exc, attempt, delay: seen.append(
                (type(exc).__name__, attempt, delay)))
        with pytest.raises(OSError):
            policy.call(self._always_oserror)
        assert [(n, a) for n, a, _ in seen] == [("OSError", 1),
                                               ("OSError", 2)]
        assert all(d > 0 for _, _, d in seen)

    @staticmethod
    def _always_oserror():
        raise OSError("io")

    def test_shutdown_aware_sleep_aborts(self):
        """A set Event passed as `sleep` stops the loop mid-budget — the
        pattern client loops use so shutdown isn't stuck in a backoff."""
        ev = threading.Event()
        ev.set()
        policy = RetryPolicy(max_attempts=100, sleep=ev.wait)
        calls = {"n": 0}

        def always():
            calls["n"] += 1
            raise ConnectionError("down")

        with pytest.raises(ConnectionError):
            policy.call(always)
        assert calls["n"] == 1

    def test_needs_some_bound(self):
        with pytest.raises(ValueError):
            RetryPolicy(max_attempts=None, deadline=None)


# -------------------------------------------------------- circuit breaker
class TestCircuitBreaker:
    def test_closed_until_threshold(self):
        clock = FakeClock()
        cb = CircuitBreaker(failure_threshold=3, reset_timeout=10.0,
                            clock=clock)
        assert cb.state == CircuitBreaker.CLOSED
        cb.record_failure()
        cb.record_failure()
        assert cb.allow()
        cb.record_failure()
        assert cb.state == CircuitBreaker.OPEN
        assert not cb.allow()

    def test_half_open_allows_single_probe(self):
        clock = FakeClock()
        cb = CircuitBreaker(failure_threshold=1, reset_timeout=10.0,
                            clock=clock)
        cb.record_failure()
        assert not cb.allow()
        clock.advance(10.0)
        assert cb.state == CircuitBreaker.HALF_OPEN
        assert cb.allow()       # the one probe
        assert not cb.allow()   # concurrent callers held out

    def test_probe_failure_reopens_and_restarts_timer(self):
        clock = FakeClock()
        cb = CircuitBreaker(failure_threshold=1, reset_timeout=10.0,
                            clock=clock)
        cb.record_failure()
        clock.advance(10.0)
        assert cb.allow()
        cb.record_failure()
        assert cb.state == CircuitBreaker.OPEN
        clock.advance(5.0)  # old timer would have expired; new one didn't
        assert not cb.allow()
        clock.advance(5.0)
        assert cb.allow()

    def test_probe_success_closes(self):
        clock = FakeClock()
        cb = CircuitBreaker(failure_threshold=2, reset_timeout=10.0,
                            clock=clock)
        cb.record_failure()
        cb.record_failure()
        clock.advance(10.0)
        assert cb.allow()
        cb.record_success()
        assert cb.state == CircuitBreaker.CLOSED
        assert cb.allow() and cb.allow()  # fully closed, not probing

    def test_success_resets_failure_streak(self):
        cb = CircuitBreaker(failure_threshold=2, reset_timeout=10.0,
                            clock=FakeClock())
        cb.record_failure()
        cb.record_success()
        cb.record_failure()
        assert cb.state == CircuitBreaker.CLOSED


class TestRpcProxyQuarantine:
    def test_dead_server_skipped_then_degrades_gracefully(self):
        from nomad_tpu.client.rpc import RpcProxy

        proxy = RpcProxy(["a:1", "b:1"])
        for _ in range(RpcProxy.BREAKER_FAILURES):
            proxy.notify_failed("a:1")
        assert proxy.quarantined() == ["a:1"]
        assert proxy.find_server() == "b:1"
        # Now the whole fleet looks dead: serve the head anyway instead
        # of turning a transient total outage into a permanent one.
        for _ in range(RpcProxy.BREAKER_FAILURES):
            proxy.notify_failed("b:1")
        assert proxy.find_server() is not None
        # A success (e.g. the outage ends) lifts the quarantine.
        proxy.notify_success("b:1")
        assert proxy.find_server() == "b:1"
        assert "b:1" not in proxy.quarantined()

    def test_update_prunes_breakers_for_removed_servers(self):
        from nomad_tpu.client.rpc import RpcProxy

        proxy = RpcProxy(["a:1", "b:1"])
        for _ in range(RpcProxy.BREAKER_FAILURES):
            proxy.notify_failed("a:1")
        proxy.update(["b:1", "c:1"])
        # "a:1" left the fleet: re-adding it starts with a clean breaker.
        proxy.update(["a:1", "b:1", "c:1"])
        assert proxy.quarantined() == []

    def test_rebalance_feeds_breakers(self):
        """A successful rebalance ping is a health probe: it must close
        the target's breaker (a quarantined-but-recovered server becomes
        routable immediately, not after the reset window), and a failed
        ping must count as breaker evidence."""
        from nomad_tpu.client.rpc import RpcProxy

        proxy = RpcProxy(["a:1", "b:1"])
        for _ in range(RpcProxy.BREAKER_FAILURES):
            proxy.notify_failed("a:1")
        assert proxy.quarantined() == ["a:1"]
        assert proxy.rebalance(lambda addr: addr == "a:1") == "a:1"
        assert proxy.quarantined() == []
        assert proxy.find_server() == "a:1"
        # And a failed ping is breaker evidence: an all-dead sweep pings
        # every server, so BREAKER_FAILURES sweeps quarantine them all.
        for _ in range(RpcProxy.BREAKER_FAILURES):
            assert proxy.rebalance(lambda addr: False) is None
        assert proxy.quarantined() == ["a:1", "b:1"]


# --------------------------------------------------------- chaos schedule
class TestChaosSchedule:
    def test_events_fire_in_order_and_heal_on_exit(self):
        with ChaosSchedule(name="t") \
                .arm(0.0, "sched.x=drop", name="arm-x") \
                .heal(0.05, "sched.x") \
                .arm(0.1, "sched.y=drop", name="arm-y") as sched:
            sched.join(5.0)
        assert sched.fired == ["arm-x", "heal sched.x", "arm-y"]
        # Context exit healed sched.y even though no heal event did.
        assert failpoints.fire("sched.y") is None

    def test_heals_even_when_body_throws(self):
        with pytest.raises(RuntimeError):
            with ChaosSchedule().arm(0.0, "sched.z=drop") as sched:
                sched.join(5.0)
                raise RuntimeError("test body exploded")
        assert failpoints.fire("sched.z") is None

    def test_stop_cancels_pending_events(self):
        sched = ChaosSchedule().arm(30.0, "sched.never=drop").start()
        sched.stop()
        assert sched.fired == []
        assert failpoints.fire("sched.never") is None

    def test_custom_actions_run_on_schedule(self):
        hits = []
        with ChaosSchedule().call(0.0, lambda: hits.append("a")) \
                .call(0.02, lambda: hits.append("b")) as sched:
            sched.join(5.0)
        assert hits == ["a", "b"]


# ----------------------------------------------- partial-commit accounting
class TestPartialPlanAccounting:
    def test_submit_plans_accounts_committed_prefix(self):
        """A mid-sweep failure must keep the committed chunks' results
        (they ARE in raft) and extend the refresh wait over them, so the
        retrying scheduler sees the partial commit instead of
        double-placing it (the ADVICE.md partial-commit leftover)."""
        from nomad_tpu.server.worker import PartialPlanError, Worker
        from nomad_tpu.structs.structs import Plan, PlanResult

        committed = PlanResult(RefreshIndex=7)
        committed.AllocIndex = 9

        class Backend:
            def submit_plans(self, plans):
                raise PartialPlanError([committed],
                                       RuntimeError("applier died"))

        waited = []
        w = Worker.__new__(Worker)
        w.backend = Backend()
        w._token = "tok"
        w.raft = types.SimpleNamespace(
            fsm=types.SimpleNamespace(
                state=types.SimpleNamespace(snapshot=lambda: "SNAP")))
        w._wait_for_index = waited.append

        results, state = w.submit_plans([Plan(), Plan(), Plan()])
        assert results == [committed, None, None]
        assert waited == [9]  # covers the committed AllocIndex, not just 7
        assert state == "SNAP"

    def test_total_failure_still_raises(self):
        """Zero chunks committed = nothing to account: the sweep must
        raise so the worker nacks and the broker redelivers, instead of
        burning the eval's retry budget against the same stale
        snapshot."""
        from nomad_tpu.server.worker import PartialPlanError, Worker
        from nomad_tpu.structs.structs import Plan

        class Backend:
            def submit_plans(self, plans):
                raise PartialPlanError([], RuntimeError("applier down"))

        w = Worker.__new__(Worker)
        w.backend = Backend()
        w._token = "tok"
        with pytest.raises(PartialPlanError):
            w.submit_plans([Plan(), Plan()])

        class SeqBackend:
            def submit_plan(self, plan):
                raise RuntimeError("applier down")

        w.backend = SeqBackend()
        with pytest.raises(RuntimeError):
            w.submit_plans([Plan(), Plan()])

    def test_local_backend_attaches_partial_results(self):
        """LocalBackend.submit_plans must not drop already-committed chunk
        results when a later wait raises."""
        from nomad_tpu.server.worker import LocalBackend, PartialPlanError

        class PendingOK:
            plan = types.SimpleNamespace(EvalID="e", EvalToken="t")

            def wait(self, timeout=None):
                return "r0"

            def cancel(self):
                pass

        class PendingBoom(PendingOK):
            def wait(self, timeout=None):
                raise RuntimeError("apply failed")

            def __init__(self):
                self.cancelled = False

            def cancel(self):
                self.cancelled = True

        class PendingTail(PendingOK):
            def __init__(self):
                self.cancelled = False

            def cancel(self):
                self.cancelled = True

        boom, tail = PendingBoom(), PendingTail()

        class Queue:
            def __init__(self):
                self._q = [PendingOK(), boom, tail]

            def enqueue(self, plan):
                return self._q.pop(0)

        class Broker:
            def outstanding_reset(self, eval_id, token):
                pass

        backend = LocalBackend.__new__(LocalBackend)
        backend.plan_queue = Queue()
        backend.eval_broker = Broker()

        plans = [types.SimpleNamespace(EvalID="e", EvalToken="t")
                 for _ in range(3)]
        with pytest.raises(PartialPlanError) as ei:
            backend.submit_plans(plans)
        assert ei.value.results == ["r0"]
        assert boom.cancelled is False  # it already left the queue
        assert tail.cancelled is True   # still queued: must not commit
