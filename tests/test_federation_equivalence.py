"""Federation equivalence gates (ISSUE 14 satellite).

Three fixed-seed gates, same discipline as tests/test_qos.py and
tests/test_columnar_store_equivalence.py:

1. A follower-snapshot-scheduled storm (workers placing through the
   staleness-bounded SnapshotSource) places IDENTICALLY to the
   leader-scheduled oracle (fresh per-eval live-store snapshots): same
   nodes, same scores — on both the synchronous exact path and the live
   pipelined served path.
2. A deliberately-staled snapshot (pinned far past the bound) gets its
   plan REJECTED by the applier (StaleSnapshotError) and the eval
   redelivered exactly once onto a fresh snapshot — no lost evals, no
   duplicate allocs.
3. ``federation=None`` is bit-identical to the pre-federation path
   (placements, completion order, and the disarmed internals: no
   release floors, no Region stamps, no plan birth stamps).
"""

import time

import pytest

from nomad_tpu import mock
from nomad_tpu.federation import FederationConfig, StaleSnapshotError
from nomad_tpu.server.server import Server, ServerConfig
from nomad_tpu.server.worker import Worker
from nomad_tpu.structs import Evaluation, compute_node_class
from nomad_tpu.structs.structs import EvalStatusComplete

from helpers import wait_for  # noqa: E402


def _build_fleet(n):
    """Deterministic fleet: stable IDs and strictly distinct capacities
    so binpack scores differ by far more than the tie-break noise and
    placement argmaxes are reproducible across servers."""
    nodes = []
    for i in range(n):
        node = mock.node()
        node.ID = f"node-{i:03d}"
        node.Name = f"node-{i:03d}"
        node.Resources.CPU = 4000 + 100 * i
        node.Reserved = None
        compute_node_class(node)
        nodes.append(node)
    return nodes


def _storm_job(jid, count=3, prio=50):
    job = mock.job()
    job.ID = jid
    job.Name = jid
    job.Priority = prio
    tg = job.TaskGroups[0]
    tg.Count = count
    task = tg.Tasks[0]
    task.Resources.CPU = 100
    task.Resources.MemoryMB = 32
    task.Resources.DiskMB = 10
    task.Resources.Networks = []
    task.Services = []
    if task.LogConfig is not None:
        task.LogConfig.MaxFiles = 1
        task.LogConfig.MaxFileSizeMB = 1
    return job


def _placements_with_scores(srv, eval_ids):
    """{alloc.Name: (NodeID, winning score)} over every eval's allocs."""
    out = {}
    for eid in eval_ids:
        for a in srv.state.allocs_by_eval(eid):
            score = None
            if a.Metrics is not None and a.Metrics.Scores:
                score = max(a.Metrics.Scores.values())
            out[a.Name] = (a.NodeID, score)
    return out


def _assert_same_placements(a, b):
    """Same alloc set, same nodes, same scores — scores compared within
    the per-server tie-break noise (make_noise_vec, <= 1e-3), which
    exists precisely to spread ties and differs between servers while
    the argmax (the fleet's distinct capacities dominate) does not."""
    assert sorted(a) == sorted(b)
    for name in a:
        node_a, score_a = a[name]
        node_b, score_b = b[name]
        assert node_a == node_b, (name, a[name], b[name])
        if score_a is not None and score_b is not None:
            assert abs(score_a - score_b) < 5e-3, (name, a[name], b[name])
        else:
            assert score_a == score_b, (name, a[name], b[name])


def _run_storm_sync(federation):
    """Fixed-order storm drained synchronously by one worker (no live
    threads -> no timing nondeterminism). Returns (placements, order)."""
    srv = Server(ServerConfig(num_schedulers=0, federation=federation,
                              min_heartbeat_ttl=24 * 3600.0,
                              heartbeat_grace=24 * 3600.0))
    srv.establish_leadership()
    try:
        for node in _build_fleet(10):
            srv.node_register(node)
        eval_of = {}
        for i in range(8):
            eval_of[srv.job_register(_storm_job(f"job-{i}"))[0]] = \
                f"job-{i}"
        w = Worker(srv.raft, srv.eval_broker, srv.plan_queue,
                   srv.blocked_evals, srv.tindex)
        w.fed_source = srv.fed_source
        order = []
        seen = set()
        for _ in range(len(eval_of) * 3):
            if not w.process_one(timeout=0.05):
                break
            for eid, jid in eval_of.items():
                e = srv.state.eval_by_id(eid)
                if (e is not None and e.Status == EvalStatusComplete
                        and eid not in seen):
                    seen.add(eid)
                    order.append(jid)
        for eid, jid in eval_of.items():
            e = srv.state.eval_by_id(eid)
            assert e is not None and e.Status == EvalStatusComplete, \
                (jid, e)
        return _placements_with_scores(srv, list(eval_of)), order
    finally:
        srv.shutdown()


def _run_storm_pipelined(federation, n_jobs=12):
    """The same deterministic storm through the LIVE served path
    (pipelined worker windows, plan applier, commit)."""
    srv = Server(ServerConfig(num_schedulers=1, scheduler_window=8,
                              federation=federation,
                              min_heartbeat_ttl=24 * 3600.0,
                              heartbeat_grace=24 * 3600.0))
    srv.establish_leadership()
    try:
        for node in _build_fleet(10):
            srv.node_register(node)
        eval_ids = [srv.job_register(_storm_job(f"job-{i}"))[0]
                    for i in range(n_jobs)]
        assert wait_for(
            lambda: all(
                (e := srv.state.eval_by_id(eid)) is not None
                and e.Status == EvalStatusComplete for eid in eval_ids),
            timeout=30,
            msg="pipelined federation storm completes")
        return _placements_with_scores(srv, eval_ids)
    finally:
        srv.shutdown()


FED = FederationConfig(enabled=True)


class TestFollowerSnapshotOracle:
    """Gate 1: snapshot-source scheduling == fresh-snapshot oracle."""

    def test_sync_storm_matches_leader_oracle(self):
        fed, order_fed = _run_storm_sync(FED)
        oracle, order_oracle = _run_storm_sync(None)
        _assert_same_placements(fed, oracle)
        assert order_fed == order_oracle

    def test_pipelined_storm_matches_leader_oracle(self):
        fed = _run_storm_pipelined(FED)
        oracle = _run_storm_pipelined(None)
        _assert_same_placements(fed, oracle)

    def test_source_actually_shared(self):
        """The federated storm must actually exercise snapshot reuse —
        otherwise gate 1 proves nothing about follower snapshots."""
        srv = Server(ServerConfig(num_schedulers=0, federation=FED))
        srv.establish_leadership()
        try:
            for node in _build_fleet(4):
                srv.node_register(node)
            eids = [srv.job_register(_storm_job(f"job-{i}", count=1))[0]
                    for i in range(6)]
            w = Worker(srv.raft, srv.eval_broker, srv.plan_queue,
                       srv.blocked_evals, srv.tindex)
            w.fed_source = srv.fed_source
            for _ in range(12):
                if not w.process_one(timeout=0.05):
                    break
            for eid in eids:
                e = srv.state.eval_by_id(eid)
                assert e is not None \
                    and e.Status == EvalStatusComplete
            stats = srv.fed_source.stats()
            assert stats["Reused"] > 0, stats
        finally:
            srv.shutdown()


class TestStaleSnapshotRedelivery:
    """Gate 2: a deliberately-staled snapshot's plan is rejected and the
    eval redelivered exactly once."""

    def test_stale_plan_rejected_then_redelivered_once(self):
        fed = FederationConfig(enabled=True, reject_after_s=2.0)
        srv = Server(ServerConfig(num_schedulers=0, federation=fed))
        srv.establish_leadership()
        try:
            for node in _build_fleet(4):
                srv.node_register(node)
            job = _storm_job("stale-job", count=3)
            eid, _, _ = srv.job_register(job)
            # Pin a snapshot that CONTAINS the job but was "born" far
            # past the staleness bound: the worker will happily build a
            # plan from it, and the applier must reject that plan.
            srv.fed_source.pin(srv.state.snapshot(),
                               born=time.monotonic() - 10.0)
            w = Worker(srv.raft, srv.eval_broker, srv.plan_queue,
                       srv.blocked_evals, srv.tindex)
            w.fed_source = srv.fed_source

            rejected_before = srv.plan_applier.stats["rejected"]
            assert w.process_one(timeout=0.5)  # delivery #1: rejected
            assert srv.plan_applier.stats["rejected"] \
                == rejected_before + 1
            ev = srv.state.eval_by_id(eid)
            assert ev is None or ev.Status != EvalStatusComplete
            assert not srv.state.allocs_by_eval(eid), \
                "a stale-rejected plan must commit nothing"

            # Heal: the redelivered eval places against a fresh snapshot.
            srv.fed_source.unpin()
            assert w.process_one(timeout=5.0)  # delivery #2: places
            ev = srv.state.eval_by_id(eid)
            assert ev is not None and ev.Status == EvalStatusComplete
            allocs = srv.state.allocs_by_eval(eid)
            assert len(allocs) == 3  # exactly Count — no duplicates
            assert len({a.Name for a in allocs}) == 3
            # Exactly once: nothing left to deliver.
            assert not w.process_one(timeout=0.2)
        finally:
            srv.shutdown()

    def test_stale_error_is_typed(self):
        with pytest.raises(StaleSnapshotError):
            raise StaleSnapshotError("x")


class TestDisabledBitIdentity:
    """Gate 3: federation=None == pre-federation path, and
    enabled=False is indistinguishable from None."""

    def test_none_matches_disabled_config(self):
        none_p, none_o = _run_storm_sync(None)
        off_p, off_o = _run_storm_sync(FederationConfig(enabled=False))
        _assert_same_placements(none_p, off_p)
        assert none_o == off_o

    def test_disabled_internals_disarmed(self):
        srv = Server(ServerConfig(num_schedulers=0))
        srv.establish_leadership()
        try:
            assert srv.fed_source is None
            assert srv.fed_health is None
            for node in _build_fleet(2):
                srv.node_register(node)
            eid, _, _ = srv.job_register(_storm_job("plain", count=1))
            # No release floor, no Region stamp: the broker and the
            # eval look exactly as they did pre-federation.
            assert srv.eval_broker.release_floor(eid) is None
            ev = srv.state.eval_by_id(eid)
            assert ev is not None and ev.Region == ""
            assert srv.eval_broker.foreign_parked() == []
        finally:
            srv.shutdown()

    def test_enabled_stamps_region_and_floor(self):
        srv = Server(ServerConfig(num_schedulers=0, region="west",
                                  federation=FED))
        srv.establish_leadership()
        try:
            for node in _build_fleet(2):
                srv.node_register(node)
            job = _storm_job("fed-plain", count=1)
            job.Region = ""  # mock jobs pre-stamp "global"
            eid, _, _ = srv.job_register(job)
            assert job.Region == "west"  # _default_region helper
            ev = srv.state.eval_by_id(eid)
            assert ev is not None and ev.Region == "west"
            floor = srv.eval_broker.release_floor(eid)
            assert floor is not None and floor >= ev.ModifyIndex
        finally:
            srv.shutdown()


class TestRegionRouting:
    """A foreign-region eval parks instead of entering a local ready
    queue — this region has no nodes for it."""

    def test_foreign_eval_parked_never_dequeued(self):
        srv = Server(ServerConfig(num_schedulers=0, region="east",
                                  federation=FED))
        srv.establish_leadership()
        try:
            from nomad_tpu.structs import generate_uuid
            from nomad_tpu.structs.structs import (
                EvalStatusPending,
                JobTypeService,
            )

            foreign = Evaluation(
                ID=generate_uuid(), Priority=50, Type=JobTypeService,
                TriggeredBy="job-register", JobID="west-job",
                Region="west", Status=EvalStatusPending)
            srv.eval_broker.enqueue(foreign)
            assert [e.ID for e in srv.eval_broker.foreign_parked()] \
                == [foreign.ID]
            got, _ = srv.eval_broker.dequeue([JobTypeService],
                                             timeout=0.1)
            assert got is None
            assert srv.eval_broker.stats.TotalReady == 0
        finally:
            srv.shutdown()
